"""Device route engine: the fused route step wired into the serving path.

This is the piece that makes the TPU program THE broker hot path instead of
a side-car demo: it compiles the live routing state (Router filter universe +
Broker subscriber/shared-group membership) into the fused device tables
(models.router_engine), runs `route_step`/`route_step_shapes` over publish
micro-batches, and consumes the `RouteResult` into actual session deliveries
— replacing the reference's per-message publish path
(emqx_broker.erl:199-308: match_routes → dispatch fold → shared pick).

Serving is staged so the asyncio event loop never blocks on the device
(round-2 weak #3): `prepare()` (loop: tokenize+encode), `dispatch()`
(executor thread: the jitted step — on a dispatch relay this is the slow,
blocking call), `materialize()` (executor thread: device→host readbacks),
`finish()` (loop: consume RouteResult rows into session deliveries).
`route_batch()` remains the synchronous composition for callers without a
pipeline (publish_batch, tests, warmup).

Snapshot/consistency model (SURVEY.md §7 hard-part 1, "mutable trie on
immutable arrays"):

- The compiled tables are an immutable snapshot; mutations keep flowing into
  the authoritative host dicts and are *tracked* relative to the snapshot:
  - a filter whose subscriber membership changed since the build is DIRTY —
    its fan-out segment on device is stale, so its deliveries come from the
    live host dict instead (correct for adds, removes and opts changes);
  - a filter added since the build lands in the DEVICE-RESIDENT DELTA
    OVERLAY (ISSUE 4, ops/delta.py): a small linear-matcher table fused
    into the route programs, so it is matched AND delivered on device in
    the same dispatch. The host delta trie remains the fallback for
    filters the overlay cannot hold (overlay program class still
    warming, row overflow past the top class, deeper than max_levels) —
    those match host-side as before, counted by
    `routing.device.host_delta`. With `EMQX_TPU_DELTA_OVERLAY=0` /
    `broker.delta_overlay=false` EVERY delta filter takes that host
    path (the pre-overlay behavior, the A/B baseline);
  - a (filter, group) shared slot that changed is dirty likewise; a group
    added to a built filter is dispatched host-side until the next rebuild.
- The full rebuild is demoted to a rare **compaction** (overlay row
  overflow / delete-tombstone ratio / built-filter membership churn past
  `rebuild_threshold` — see _compaction_reason), recompiled **in the
  background, double-buffered** (round-2 weak #7): the router/broker
  state is captured in cooperative chunks on the loop — incrementally,
  from the previous build's capture plus the touched-filter journal,
  instead of re-walking the world — compiled + uploaded + warm-jitted
  off the loop, and swapped in atomically once no dispatched batch is
  outstanding. Mutations during the build are journaled and replayed
  against the new snapshot at swap, so no churn is lost and serving
  never stalls on a rebuild.

Delivery attribution: device fan-out rows for one message are the
concatenation of per-filter CSR segments in match order, so the host walks
`matches[i]` and slices `rows[i]` by the *built* segment lengths — clean
filters deliver straight from device rows (packed opts unpacked on the fly),
no host dict walk. Messages flagged overflow/too-deep fall back to the full
host path (emqx_router.erl:136-141 short-circuit analog).

Shared subscriptions: device picks (ops.shared cursors) drive delivery for
EVERY strategy (round_robin / random / hash_* / sticky), clustered or
not. Under a cluster the snapshot's member list is the CLUSTER-WIDE
membership (emqx_shared_sub:pick semantics over all nodes' members,
emqx_shared_sub.erl:239-268): local members carry their subopts, remote
members ride as reserved-range sids (>= _REMOTE_SID_BASE) that index a
host-side (origin, remote_sid) list — a remote pick is forwarded with the
same directed shared.deliver_fwd RPC the host path uses
(emqx_shared_sub.erl dispatch's cross-node SubPid ! send). Sticky rides
the cursor state reinterpreted as an affinity pointer (seeded by
capture_shared, never advanced on device); only RE-picking after a
member death is feedback-dependent and runs host-side via the consume
fallback (emqx_shared_sub.erl:269-283). A remote join/leave dirties the
slot (store watcher → note_member_change) so the group serves host-side
until the next rebuild.
"""

from __future__ import annotations

import itertools
import os
import time
import zlib
from typing import Optional

import numpy as np

from emqx_tpu.broker.deliver import DEFERRED, OPT_TABLE, LaneCounts
from emqx_tpu.broker.match_cache import DEFAULT_CAPACITY, MatchCache
from emqx_tpu.broker.message import Message
from emqx_tpu.ops.compact import csr_slices
from emqx_tpu.ops import intern as I
from emqx_tpu.utils import topic as T

_PACKED_KEYS = {"qos", "nl", "rap", "rh"}

# reuse layers in front of the device match (both host-tunable without a
# restart of anything but the node):
#   EMQX_TPU_DEDUP=0        disables in-window unique-topic dedup AND the
#                           cached dispatch variant that rides on it (the
#                           cross-batch cache has no vehicle without it)
#   EMQX_TPU_MATCH_CACHE=N  cross-batch match-cache capacity in unique
#                           topics; 0 disables the cache layer only
#                           (in-window dedup still engages)
def resolve_dedup(configured=None) -> bool:
    """The one dedup-knob resolution: config (``broker.topic_dedup``)
    beats ``EMQX_TPU_DEDUP`` beats default-on. ``=0`` disables
    in-window unique-topic dedup AND the cached dispatch variant that
    rides on it — the ISSUE-2 A/B baseline."""
    if configured is not None:
        return bool(configured)
    return os.environ.get("EMQX_TPU_DEDUP", "1") \
        not in ("0", "false", "off")


def resolve_match_cache_size(configured=None) -> int:
    """The one match-cache-capacity resolution: config
    (``broker.match_cache_size``) beats ``EMQX_TPU_MATCH_CACHE`` beats
    the built-in ``DEFAULT_CAPACITY``. 0 disables the cache layer only
    (in-window dedup still engages)."""
    if configured is not None:
        return int(configured)
    env = os.environ.get("EMQX_TPU_MATCH_CACHE")
    return int(env) if env is not None else DEFAULT_CAPACITY


def resolve_compact_readback(configured=None) -> bool:
    """The one compact-readback resolution: config
    (``broker.compact_readback``) beats ``EMQX_TPU_COMPACT_READBACK``
    beats default-on. ``=0`` restores dense-plane readback exactly —
    the ISSUE-3 A/B baseline the acceptance criteria compare."""
    if configured is not None:
        return bool(configured)
    return os.environ.get("EMQX_TPU_COMPACT_READBACK", "1") \
        not in ("0", "false", "off")


def resolve_delta_overlay(configured=None) -> bool:
    """The one delta-overlay resolution: config
    (``broker.delta_overlay``) beats ``EMQX_TPU_DELTA_OVERLAY`` beats
    default-on. ``=0`` restores host-trie fallback + full O(N)
    recaptures at the rebuild threshold exactly — the ISSUE-4 churn
    A/B baseline."""
    if configured is not None:
        return bool(configured)
    return os.environ.get("EMQX_TPU_DELTA_OVERLAY", "1") \
        not in ("0", "false", "off")


def resolve_subscription_covering(configured=None) -> bool:
    """The one subscription-covering resolution: config
    (``broker.subscription_covering``) beats ``EMQX_TPU_COVERING``
    beats default-on. ``=0`` restores the full-set match exactly — the
    ISSUE-18 A/B baseline (twin-tested bit-identical on delivery
    counts and per-session order)."""
    if configured is not None:
        return bool(configured)
    return os.environ.get("EMQX_TPU_COVERING", "1") \
        not in ("0", "false", "off")


# module-level one-shot resolutions: engines read these when their
# config leaves a knob unset (tests monkeypatch them directly, and
# parallel/serving.py imports the compact/delta/covering set for the
# mesh)
_ENV_DEDUP = resolve_dedup()
_ENV_COMPACT = resolve_compact_readback()
_ENV_DELTA = resolve_delta_overlay()
_ENV_COVERING = resolve_subscription_covering()


def resolve_rebuild_threshold(configured=None) -> int:
    """The one rebuild-threshold resolution: config beats
    EMQX_TPU_REBUILD_THRESHOLD beats the built-in 256. The env knob lets
    deployments tune churn tolerance without a config edit (mirroring
    the EMQX_TPU_* family above); it must be a positive integer —
    anything else is a deployment error worth failing loudly on."""
    if configured is not None:
        return int(configured)
    env = os.environ.get("EMQX_TPU_REBUILD_THRESHOLD")
    if env is None:
        return 256
    try:
        val = int(env)
    except ValueError:
        raise ValueError(
            f"EMQX_TPU_REBUILD_THRESHOLD={env!r} is not an integer")
    if val <= 0:
        raise ValueError(
            f"EMQX_TPU_REBUILD_THRESHOLD must be > 0, got {val}")
    return val


_snapshot_ids = itertools.count(1)

# delta-overlay capacity ladder (ISSUE 4): pow2 row classes so the jit
# signature of the fused delta programs stays stable while the overlay
# grows; beyond the top class the oldest _OVERLAY_MAX delta filters
# keep their device rows and the rest serve host-side until the
# compaction the overflow triggers completes. Fan-out is a fixed
# per-row budget (sub rows = rows * _DELTA_FAN_PER_ROW) so membership
# growth inside a class never retraces; a delta filter with more
# subscribers (or rich subopts) keeps its MATCH on device and delivers
# through the host dict instead.
_DELTA_CLASSES = (16, 128, 512)
_OVERLAY_MAX = _DELTA_CLASSES[-1]
_DELTA_FAN_PER_ROW = 8
_DELTA_MATCH_CAP = 16
_DELTA_FANOUT_CAP = 64


def _topic_keys(enc: np.ndarray, lens: np.ndarray,
                dollar: np.ndarray) -> np.ndarray:
    """[N, L] interned rows + [N] lens + [N] is_dollar -> [N] void16 keys.

    Two independent 64-bit folds over the level ids (vectorized down the
    batch axis), finalized with the lens and the '$'-root flag — 128 bits
    per topic, the dedup/cache identity. Interned ids are stable for the
    process lifetime (ops/intern.py only ever appends), so equal keys
    mean equal device inputs; distinct unseen words all encode to UNKNOWN
    and are identical to the device anyway. Collision posture matches
    ops/shapes.py's 2x32-bit path hashes, two levels up: ~2^-128 per key
    pair, negligible against the cache's bounded live set."""
    n = enc.shape[0]
    h1 = np.full(n, 0x9E3779B97F4A7C15, np.uint64)
    h2 = np.full(n, 0xC2B2AE3D27D4EB4F, np.uint64)
    m1 = np.uint64(0x100000001B3)
    m2 = np.uint64(0xFF51AFD7ED558CCD)
    for level in range(enc.shape[1]):
        w = enc[:, level].astype(np.uint64)
        h1 = (h1 ^ (w + np.uint64(level * 0x9E3779B1 + 1))) * m1
        h2 = (h2 ^ (w * m1 + np.uint64(level + 1))) * m2
    fin = lens.astype(np.uint64) * np.uint64(2) + dollar.astype(np.uint64)
    h1 = (h1 ^ fin) * m2
    h2 = (h2 ^ (fin * m1)) * m1
    h1 ^= h1 >> np.uint64(29)
    h2 ^= h2 >> np.uint64(31)
    return np.ascontiguousarray(
        np.stack([h1, h2], axis=1)).view("V16").reshape(-1)


class _CachePlan:
    """Device-side inputs of one deduplicated (optionally cache-backed)
    dispatch: the compacted miss lanes, the host-filled base rows, and
    the scatter/gather indexing that rebuilds full window width."""

    __slots__ = ("miss_topics", "miss_lens", "miss_dollar", "base_m",
                 "base_c", "base_o", "miss_pos", "inv", "Bm", "n_miss",
                 "n_hit", "base_dm", "base_dc", "base_do")

    def __init__(self, miss_topics, miss_lens, miss_dollar, base_m,
                 base_c, base_o, miss_pos, inv, Bm, n_miss, n_hit):
        self.miss_topics = miss_topics
        self.miss_lens = miss_lens
        self.miss_dollar = miss_dollar
        self.base_m = base_m
        self.base_c = base_c
        self.base_o = base_o
        self.miss_pos = miss_pos
        self.inv = inv
        self.Bm = Bm
        self.n_miss = n_miss
        self.n_hit = n_hit
        # delta-overlay base rows (overlay ROW-index space; filled only
        # when the window fuses the overlay — ISSUE 4)
        self.base_dm = None
        self.base_dc = None
        self.base_do = None


class _CacheInfo:
    """Post-materialize cache population: (key, flat lane) per unique
    topic the cache did not have, pinned to the dispatching snapshot.
    `version` pins the match-cache's delta version at plan time: an
    overlay insert/delete while this window was in flight makes its
    readback rows stale (they predate the filter change), so put_many
    drops the batch on a version mismatch — the delta-aware analog of
    the snapshot-id check."""

    __slots__ = ("sid", "inserts", "version")

    def __init__(self, sid, inserts, version=None):
        self.sid = sid
        self.inserts = inserts
        self.version = version


class _CsrRes:
    """Host side of one compacted readback (ISSUE 3): the CSR planes
    materialize transferred instead of the dense result planes, plus the
    always-small dense overflow/occur planes consume needs anyway.
    finish_sub dispatches on this type vs the dense 8-tuple."""

    __slots__ = ("off", "c3", "pay", "overflow", "occur")

    def __init__(self, off, c3, pay, overflow, occur):
        self.off = off            # [W, B+1] combined payload offsets
        self.c3 = c3              # [W, B, 3] (match, fanout, shared)
        self.pay = pay            # [W, P] flat payload
        self.overflow = overflow  # [W, B] host-fallback lanes
        self.occur = occur        # [W, G] cursor writeback input


class _Overlay:
    """One immutable VERSION of the delta overlay (ISSUE 4): the device
    DeltaTables plus the host-side index consume/plan need. Handles pin
    the version they dispatched against, so an overlay refresh mid-batch
    can neither re-index an in-flight decode nor swap the arrays under a
    dispatch — the same pinning discipline as `_Built`."""

    __slots__ = ("dev", "fid_set", "row_of", "seg_of", "hostfan",
                 "version", "cap", "n")

    def __init__(self, dev, fid_set, row_of, seg_of, hostfan, version,
                 cap, n):
        self.dev = dev            # device DeltaTables (row class `cap`)
        self.fid_set = fid_set    # frozenset of delta fids in the table
        self.row_of = row_of      # fid -> overlay row index
        self.seg_of = seg_of      # fid -> device fan-row segment length
        self.hostfan = hostfan    # fids delivering host-side (rich/big)
        self.version = version    # overlay clock stamp at build
        self.cap = cap            # row class (jit signature component)
        self.n = n                # live rows


class _DeltaRes:
    """Dense host views of one window's delta-overlay planes."""

    __slots__ = ("fids", "counts", "moverflow", "rows", "opts",
                 "overflow")

    def __init__(self, fids, counts, moverflow, rows, opts, overflow):
        self.fids = fids          # [W, B, Dm] delta fids
        self.counts = counts      # [W, B]
        self.moverflow = moverflow  # [W, B] match-capacity overflow
        self.rows = rows          # [W, B, Dc]
        self.opts = opts          # [W, B, Dc]
        self.overflow = overflow  # [W, B] combined (match | fan-out)


class _DeltaCsr:
    """CSR host views of one window's delta planes (same payload layout
    as the main CSR with an empty shared family — csr_slices decodes
    both), plus the always-small dense count/overflow planes."""

    __slots__ = ("off", "c3", "pay", "counts", "moverflow", "overflow")

    def __init__(self, off, c3, pay, counts, moverflow, overflow):
        self.off = off
        self.c3 = c3
        self.pay = pay
        self.counts = counts
        self.moverflow = moverflow
        self.overflow = overflow


def _pack_opts(opts: dict) -> int:
    return ((int(opts.get("qos", 0)) & 0x3)
            | ((1 if opts.get("nl") else 0) << 2)
            | ((1 if opts.get("rap") else 0) << 3)
            | ((int(opts.get("rh", 0)) & 0x3) << 4))


def _unpack_opts(b: int) -> dict:
    return {"qos": b & 0x3, "nl": (b >> 2) & 1, "rap": (b >> 3) & 1,
            "rh": (b >> 4) & 0x3}


def _is_rich(opts: dict) -> bool:
    """Subopts that the packed byte cannot carry (v5 subscription ids etc.)
    force the filter onto the host dict path."""
    return any(k not in _PACKED_KEYS and k != "share" and v is not None
               for k, v in opts.items())


def _next_pow2(x: int) -> int:
    return 1 << max(2, (x - 1).bit_length())


# device member ids at/above this are remote refs: they index the built
# snapshot's remote_members list instead of a local session row (int32-safe;
# local sids are small dense ints)
_REMOTE_SID_BASE = 1 << 30


def capture_shared(broker, f: str) -> dict:
    """Per-filter shared-group capture for a device snapshot (used by the
    single-chip engine AND the mesh ShardedRouteServer).

    Standalone: the local SharedGroup members with their subopts.
    Clustered: the CLUSTER-WIDE membership (cluster._members — the
    same sorted (origin, sid) view the host pick uses), with local
    members carrying subopts and remote members captured as
    ((origin, sid), None) refs that the build turns into
    reserved-range device sids. Remote-only groups known purely via
    replication are captured too — every device-supported strategy's
    pick runs on device regardless of where members live (reference
    semantics: emqx_shared_sub.erl:239-268 + replicated group routes
    :312-320).

    For the `sticky` strategy the returned cursor is the sticky member's
    INDEX in the members list (establishing affinity on the first
    capture if none exists) — the device kernel reinterprets the cursor
    as the affinity pointer and never advances it (ops.shared).

    Sticky-seeding invariant (ADVICE r5): establishing affinity is the
    ONE write this otherwise read-only capture performs (grp.sticky /
    cluster._shared_sticky), and it is IDEMPOTENT by construction —
    it only runs when no live member holds affinity, and every writer
    derives the same deterministic value from the same source
    (members[0] of the insertion-ordered members dict standalone;
    refs[0] of cluster._members' SORTED (origin, sid) view clustered).
    Two captures racing on different threads (a sync rebuild on a
    route_batch(wait=True) thread vs a loop-side chunked capture)
    therefore converge on the same member: the race is benign, the
    seeded snapshots agree, and re-running capture never moves an
    established affinity (the `not in` guards below). Do not replace
    the guarded writes with unconditional ones — that is what keeps
    concurrent captures convergent."""
    cluster = broker.cluster
    sticky_mode = broker.shared_strategy == "sticky"
    local = broker.shared.get(f) or {}
    if cluster is None:
        out = {}
        for g, grp in local.items():
            if not grp.members:
                continue
            members = list(grp.members.items())
            cursor = grp.cursor
            if sticky_mode:
                if grp.sticky not in grp.members:
                    grp.sticky = members[0][0]   # establish affinity
                cursor = next(i for i, (sid, _) in enumerate(members)
                              if sid == grp.sticky)
            out[g] = (members, cursor)
        return out
    names = set(local) | cluster._groups_by_real.get(f, set())
    me = cluster.rpc.node
    out = {}
    for g in sorted(names):
        grp = local.get(g)
        members = []
        refs = []                      # (origin, sid) per kept member
        for origin, sid in cluster._members(broker, f, g):
            if origin == me:
                opts = grp.members.get(sid) if grp else None
                if opts is not None:
                    members.append((sid, opts))
                    refs.append((origin, sid))
            else:
                members.append(((origin, sid), None))
                refs.append((origin, sid))
        if not members:
            continue
        cursor = grp.cursor if grp else 0
        if sticky_mode:
            want = cluster._shared_sticky.get((f, g))
            if want not in refs:
                want = refs[0]         # establish cluster-wide affinity
                cluster._shared_sticky[(f, g)] = want
            cursor = refs.index(want)
        out[g] = (members, cursor)
    return out


class _CoverState:
    """Host-side subscription-covering companion of one snapshot
    (ISSUE 18): the covering-set HostTrie + root encodings answer "is
    this new filter covered?" on the subscribe path, and the numpy
    CoverTables mirror backs the expansion-CSR APPEND region (a
    covered new filter becomes an append + small device upload, not a
    rebuild). n_roots/n_covered feed stats()'s reduction factor."""

    __slots__ = ("trie", "root_words", "roots", "ct", "app_used",
                 "level_cap", "n_roots", "n_covered", "incomplete")

    def __init__(self, roots, ct, level_cap, n_covered, incomplete):
        self.roots = roots            # root fid array (covering set)
        self.trie = None              # HostTrie over roots, built
        self.root_words = None        # lazily on the first append try
        self.ct = ct                  # numpy CoverTables (host mirror)
        self.app_used = 0             # append-region rows consumed
        self.level_cap = level_cap    # vwords width (append depth gate)
        self.n_roots = len(roots)
        self.n_covered = n_covered
        self.incomplete = incomplete  # detection-overflow filter count


class _Built:
    """One compiled snapshot (host-side indexes of the device tables)."""

    __slots__ = ("fid_of", "fid_filter", "seg_len", "slot_of", "slot_key",
                 "n_slots", "backend", "remote_members", "seg_np",
                 "fid_shared", "fid_rich", "sid", "match_width", "cover")

    def __init__(self):
        self.fid_of: dict[str, int] = {}
        self.fid_filter: list[str] = []
        self.seg_len: list[int] = []
        self.slot_of: dict[tuple, int] = {}       # (filter, group) -> slot
        self.slot_key: list[tuple] = []           # slot -> (filter, group)
        self.n_slots = 0
        # remote shared members: device sid _REMOTE_SID_BASE+i -> (origin,
        # remote_sid); consume forwards picks for these over RPC
        self.remote_members: list[tuple] = []
        self.backend = "trie"
        # snapshot identity: the match-cache key space (match rows are a
        # pure function of (sid, topic) — see broker/match_cache.py)
        self.sid = next(_snapshot_ids)
        # width of one match row ([B, match_width] out of the match
        # stage): shape capacity for the shapes backend, match_cap for
        # the trie NFA — the cache's row width for this snapshot
        self.match_width = 0
        # vectorized-consume companions (set once at build):
        self.seg_np = np.zeros(0, np.int64)       # seg_len as an array
        self.fid_shared = np.zeros(0, bool)       # fid has shared groups
        self.fid_rich = np.zeros(0, bool)         # fid has rich subopts
        # subscription covering (ISSUE 18): _CoverState when this
        # snapshot matched the covering set only, else None. With
        # covering on, seg_np/fid_shared/fid_rich are padded to
        # filter_cap so APPENDED fids (cover-set churn) index safely.
        self.cover: Optional[_CoverState] = None


class _Handle:
    """One in-flight dispatched WINDOW of 1..W publish micro-batches
    (prepare → dispatch → materialize → finish_sub per batch). A single
    batch is a window of 1 — one unified device path. Host-side metadata
    pins the snapshot the dispatch ran against; the engine defers
    snapshot swaps until no handle is outstanding. `refs` counts the
    attached sub-batches: the handle releases (outstanding--) when every
    sub has been finished or abandoned."""

    __slots__ = ("subs", "built", "dev_shared", "enc", "res", "np_res",
                 "np_counts", "error", "refs", "t0", "plan", "cache_info",
                 "pcap", "cres", "delta", "dres", "dcres", "np_delta",
                 "trace", "sub_traces")

    def __init__(self, subs, built, dev_shared):
        self.subs = subs          # list of (msgs, words_list, too_long)
        self.built = built
        self.dev_shared = dev_shared
        self.res = None       # device RouteResult, fields [W, ...]
        self.np_res = None    # host views: dense tuple OR _CsrRes
        self.np_counts = None  # match_counts [W, B] (cache population)
        self.error = None
        self.refs = len(subs)
        self.t0 = None        # consumer-side window processing start
        self.plan = None      # _CachePlan: dedup/cached dispatch inputs
        self.cache_info = None  # _CacheInfo: rows to insert post-readback
        self.pcap = None      # payload class: CSR-compact this dispatch
        self.cres = None      # device CompactPlanes (set by dispatch)
        self.delta = None     # _Overlay this dispatch fused (ISSUE 4)
        self.dres = None      # device DeltaPlanes (set by dispatch)
        self.dcres = None     # device delta CompactPlanes
        self.np_delta = None  # host views: _DeltaRes or _DeltaCsr
        self.trace = 0        # flight-recorder trace id (ISSUE 7):
        #                       the LEAD entry's window trace — rides
        #                       the StepTraceAnnotation so the device
        #                       timeline joins the host one
        self.sub_traces = None  # per-sub-batch trace ids (fused windows)


class DeviceRouteEngine:
    def __init__(self, node, *, rebuild_threshold: Optional[int] = None,
                 max_levels: int = 16, frontier_cap: int = 16,
                 match_cap: int = 64, fanout_cap: int = 128,
                 slot_cap: int = 16, shape_cap: int = 32,
                 match_cache_size: Optional[int] = None,
                 dedup: Optional[bool] = None,
                 compact_readback: Optional[bool] = None,
                 delta_overlay: Optional[bool] = None,
                 subscription_covering: Optional[bool] = None,
                 supervisor=None, ledger=None,
                 dispatch_depth: Optional[int] = None):
        self.node = node
        self.broker = node.broker
        self.router = node.broker.router
        self.rebuild_threshold = resolve_rebuild_threshold(
            rebuild_threshold)
        self.max_levels = max_levels
        self.frontier_cap = frontier_cap
        self.match_cap = match_cap
        self.fanout_cap = fanout_cap
        self.slot_cap = slot_cap
        self.shape_cap = shape_cap

        self.intern = I.InternTable()
        self._built: Optional[_Built] = None
        self._tables = None            # device RouterTables/ShapeRouterTables
        self._cursors = None           # device [G]
        self.dirty_filters: set[str] = set()
        self.dirty_slots: set[tuple] = set()
        self.new_slots_by_filter: dict[str, set[str]] = {}
        # hostside-mask memo (ISSUE 5 satellite): _fast_deliver used to
        # rebuild fid_rich + dirty-scatter on EVERY batch while any
        # filter was dirty; the mask only changes when the dirty set or
        # the snapshot does, so it is memoized on (snapshot id, dirty
        # version) — the version bumps on subscribe/unsubscribe churn
        # (_mark_dirty), never per batch
        self._dirty_ver = 0
        self._hostside_memo: Optional[tuple] = None
        from emqx_tpu.ops.trie import HostTrie
        self._delta_trie = HostTrie()
        self._delta_filter: dict[int, str] = {}
        self._delta_fid_of: dict[str, int] = {}
        self._next_delta_fid = 0

        # per-filter cluster shared-group union, invalidated on membership
        # change (avoids per-message set unions on the consume path)
        self._cluster_groups_cache: dict[str, tuple] = {}
        # compile-class readiness: the BATCHER only routes a batch to
        # the device when its (W, Bp) class is known-warm for the current
        # snapshot signature — an in-path XLA compile stalls live
        # traffic for seconds (observed: 5s+ first-QoS1-ack under a
        # cold-start flood). Classes become warm via background warm
        # tasks or any successful dispatch (route_batch warmups).
        self._warm_classes: set = set()      # {(sig, W, Bp[, Bm])}
        self._extra_classes: set = set()     # non-standard (W, Bp) wanted
        # cached-dispatch (W, Bp, Bm) classes the serving path asked for:
        # demand-driven (a dedup plan whose class is cold falls back to
        # the plain warm program and registers here), warmed by the same
        # background thread as the standard ladder
        self._wanted_cached: set = set()
        self._cur_sig: tuple = ()
        self._fuse_warm_task = None
        # background rebuild machinery (round-2 weak #7)
        self._outstanding = 0          # dispatched-but-unfinished handles
        self._journal: Optional[list] = None   # churn while a build runs
        self._building = False
        self._pending_swap = None      # (built, tables, cursors, rich)
        self._rebuild_task = None

        # reuse layers (ISSUE 2 tentpole): in-window unique-topic dedup
        # and the cross-batch snapshot-keyed match cache. Config beats
        # env beats default; cache size 0 / dedup False disable a layer.
        if dedup is None:
            dedup = _ENV_DEDUP
        if match_cache_size is None:
            match_cache_size = resolve_match_cache_size()
        self.dedup = bool(dedup)
        self._match_cache: Optional[MatchCache] = \
            MatchCache(match_cache_size, node.metrics) \
            if (self.dedup and match_cache_size > 0) else None

        # CSR readback compaction (ISSUE 3 tentpole): materialize ships
        # offsets + actual entries instead of the padded result planes.
        # Config beats env beats default-on; payload capacity quantizes
        # onto _PAYLOAD_MULTS * Bp classes sized by a peak-biased EWMA
        # of recent window totals, with a dense-readback fallback when a
        # window outgrows its class (row_overflow).
        if compact_readback is None:
            compact_readback = _ENV_COMPACT
        self.compact_readback = bool(compact_readback)
        self._pay_ewma: dict[int, float] = {}   # Bp -> peak entry total
        # compact (W, Bp[, Bm], P[, Cd]) classes the serving path asked
        # for, warmed by the same background thread as the cached ladder
        self._wanted_compact: set = set()

        # delta overlay (ISSUE 4 tentpole): post-snapshot filters match
        # ON DEVICE via a small linear overlay table fused into the
        # route programs, instead of host-routing until the next full
        # rebuild. Config beats env beats default-on.
        if delta_overlay is None:
            delta_overlay = _ENV_DELTA
        self.delta_overlay = bool(delta_overlay)
        self._overlay: Optional[_Overlay] = None  # current serving table

        # subscription covering (ISSUE 18 tentpole): the snapshot match
        # tables hold only the COVERING set; a fused expansion CSR
        # (ops/cover) re-expands matched covers after the match stage.
        # Config beats env beats default-on; =0 builds the full set.
        if subscription_covering is None:
            subscription_covering = _ENV_COVERING
        self.subscription_covering = bool(subscription_covering)
        # new filters that could NOT ride the expansion-CSR append path
        # (they cover others / nothing covers them): they serve through
        # the overlay, but each one left in place erodes the covering
        # reduction — past a budget the snapshot recompacts
        # (_compaction_reason "covering")
        self._cover_churn = 0

        # double-buffered window pipeline (ISSUE 9 tentpole): at
        # dispatch_depth >= 2 the serving dispatch (a) threads cursors
        # through the DONATING program twins so the ping-pong buffers
        # reuse HBM (models.router_engine.donating), and (b) starts the
        # device→host transfers of every readback plane at dispatch
        # return (copy_to_host_async-style), so materialize is
        # consume-on-arrival under the next window's dispatch. Depth 1
        # restores the pre-ISSUE-9 programs and synchronous readback
        # exactly — the A/B baseline. Config beats env beats default 2.
        from emqx_tpu.broker.batcher import resolve_dispatch_depth
        self.dispatch_depth = resolve_dispatch_depth(dispatch_depth)
        self._pipelined = self.dispatch_depth > 1
        self._overlay_stale = False     # journal entries pending apply
        self._overlay_clock = 0         # monotonic overlay mutation clock
        self._overlay_uncovered = 0     # live delta filters NOT in the
                                        # overlay (too deep / past cap)
        # fid -> clock of its last MEMBERSHIP change: an overlay version
        # older than the entry has stale fan rows for that fid, so
        # consume delivers it host-side (the overlay's dirty_filters)
        self._fid_member_clock: dict[int, int] = {}
        self._wanted_delta: set = set()  # (W, Bp, Cd) plain delta classes
        # journal-driven incremental capture (ISSUE 4): the previous
        # build's capture + the set of filters touched since it — a
        # compaction refreshes only the touched filters instead of
        # re-walking the world (see _capture_state_incremental)
        self._last_capture = None
        self._touched: set[str] = set()
        self._built_deleted: set[str] = set()  # snapshot tombstones
        self._enc_cache: dict[str, list] = {}  # filter -> interned words
        # columnar-ingress burst pre-encode (ISSUE 11): one vectorized
        # native intern pass over a read burst's unique topics, consumed
        # by prepare_window's gather path. Guarded by the intern-table
        # length — intern ids are append-only, so an unchanged length
        # proves the cached rows are what a fresh encode would produce
        # (a filter word interned between burst and window would turn a
        # cached UNKNOWN stale — the guard drops the whole memo then).
        self._burst_enc = None          # (idx: dict, enc, lens, dollar,
                                        #  too_long, intern_len)

        # fault-domain supervision (ISSUE 6): injection points at every
        # stage boundary, breaker-gated degradation (the reuse layers
        # stand down at rung 1, the whole device path at rung 2 — the
        # batcher reads the rung), contained cache/overlay/swap faults.
        # None (knob off) restores the pre-ISSUE-6 unwind exactly.
        self.sup = supervisor if supervisor is not None \
            else getattr(node, "supervisor", None)
        if self.sup is not None:
            self.sup.register_probe("dispatch", self._probe_dispatch)
            self.sup.register_probe("materialize",
                                    self._probe_materialize)

        # HBM ledger (ISSUE 8): every persistent device allocation this
        # engine makes — snapshot tables/cursors, per-version delta
        # overlays — registers through _hold; dispatch handles pin the
        # window clock for the stale-pin sentinel. None (knob off)
        # restores the untracked behavior exactly.
        self.ledger = ledger if ledger is not None \
            else getattr(node, "hbm_ledger", None)

        # wire change notifications
        self.router.on_route_change = self.note_route_change
        self.broker.device_engine = self
        tele = getattr(node, "pipeline_telemetry", None)
        if tele is not None:
            tele.rebuild_state_fn = self.rebuild_state

    # ---- churn tracking -------------------------------------------------
    def staleness(self) -> int:
        """Distinct stale entities vs the snapshot (filters/slots serving
        host-side) — the rebuild trigger. A set-size measure, so repeated
        churn on one filter counts once and the subscribe path's double
        notification (route change + member change) cannot double-count.
        With the delta overlay on (ISSUE 4), post-snapshot filters are
        matched AND delivered on device, so they no longer count toward
        the full-rebuild trigger — overlay overflow and the snapshot
        tombstone ratio trigger compactions instead
        (_compaction_reason). DELETED built filters likewise move to
        the tombstone-ratio trigger: a tombstone costs a slow-path
        consume only for messages that still match it (it delivers
        nothing), so under rolling subscribe/unsubscribe churn it must
        not drip the churn counter over the threshold — that would
        recreate exactly the rebuild cadence the overlay exists to
        demote."""
        base = (len(self.dirty_filters) + len(self.dirty_slots)
                + sum(len(v) for v in self.new_slots_by_filter.values()))
        if self.delta_overlay:
            base -= len(self._built_deleted)    # ⊆ dirty_filters
            # delta filters the overlay CANNOT hold (deeper than
            # max_levels, or past the top row class) serve host-side
            # and disable the fast consume — they must keep counting
            # toward the rebuild trigger exactly like the overlay-off
            # path, or one deep filter would degrade every message's
            # consume forever with nothing ever healing it
            base += self._overlay_uncovered
        else:
            base += len(self._delta_filter)
        return base

    def journal_depth(self) -> int:
        """Filters touched since the last capture — the incremental
        compaction's pending work (exported via the rebuild telemetry
        section)."""
        return len(self._touched)

    def _mark_dirty(self, f: str) -> None:
        """dirty_filters.add with the hostside-memo version bump (only
        on actual growth — the subscribe path's double notification
        must not churn the memo key twice for one event)."""
        if f not in self.dirty_filters:
            self.dirty_filters.add(f)
            self._dirty_ver += 1

    def _hostside_mask(self, b) -> np.ndarray:
        """Per-fid host-side delivery mask of snapshot `b` (rich subopts
        OR dirty membership), memoized on (snapshot id, dirty version).
        Invalidated by subscribe/unsubscribe churn and snapshot swaps,
        not per batch."""
        if not self.dirty_filters:
            return b.fid_rich
        key = (b.sid, self._dirty_ver)
        memo = self._hostside_memo
        if memo is not None and memo[0] == key:
            return memo[1]
        hs = b.fid_rich.copy()
        for f in self.dirty_filters:
            fid = b.fid_of.get(f)
            if fid is not None:
                hs[fid] = True
        self._hostside_memo = (key, hs)
        return hs

    def _enc_filter(self, f: str) -> list:
        """Interned level ids of a filter, memoized across builds: word
        ids are append-only for the process lifetime (ops/intern.py), so
        the encoding never goes stale and the compaction path reuses the
        previous build's work instead of re-tokenizing the universe."""
        w = self._enc_cache.get(f)
        if w is None:
            w = self._enc_cache[f] = self.intern.encode_filter(
                T.tokens(f))
        return w

    def _overlay_changed(self, words, deleted_fid=None) -> None:
        """Bookkeeping shared by delta insert and delete: bump the
        overlay clock, mark the table stale, and make the match cache
        delta-aware — drop exactly the cached topics the changed filter
        matches (host-side check over the stored encoded topics) plus
        bump the cache's delta version so in-flight readbacks that
        predate this change cannot insert stale rows."""
        self._overlay_clock += 1
        self._overlay_stale = True
        if deleted_fid is not None:
            self._fid_member_clock.pop(deleted_fid, None)
        cache = self._match_cache
        if cache is not None:
            from emqx_tpu.ops.delta import np_filter_match
            cache.bump_delta_version()
            if len(cache):
                cache.drop_where(
                    self._built.sid if self._built else None,
                    lambda encs, lens, dols: np_filter_match(
                        words, encs, lens, dols))

    def note_route_change(self, topic_filter: str, added: bool) -> None:
        """Router filter-universe change (local subscribe path and
        cluster-replicated remote routes both land here)."""
        if self._journal is not None:
            self._journal.append(("route", topic_filter, added))
        self._touched.add(topic_filter)
        removed_words = None
        if not added:
            # read the memo BEFORE evicting it: the delete path below
            # needs the encoding and must not re-tokenize per delete
            # under rolling unsubscribe churn
            removed_words = self._enc_cache.pop(topic_filter, None)
        if self._built is None:
            return
        if added:
            if topic_filter in self._built.fid_of:
                self._mark_dirty(topic_filter)
                self._built_deleted.discard(topic_filter)
            elif topic_filter not in self._delta_fid_of:
                words = self._enc_filter(topic_filter)
                if self._try_cover_append(topic_filter, words):
                    return
                fid = self._next_delta_fid
                self._next_delta_fid += 1
                self._delta_trie.insert(words, fid)
                self._delta_filter[fid] = topic_filter
                self._delta_fid_of[topic_filter] = fid
                if self.delta_overlay:
                    self._overlay_changed(words)
        else:
            if topic_filter in self._built.fid_of:
                self._mark_dirty(topic_filter)
                self._built_deleted.add(topic_filter)
            fid = self._delta_fid_of.pop(topic_filter, None)
            if fid is not None:
                words = removed_words if removed_words is not None \
                    else self.intern.encode_filter(T.tokens(topic_filter))
                self._delta_trie.delete(words)
                self._delta_filter.pop(fid, None)
                if self.delta_overlay:
                    self._overlay_changed(words, deleted_fid=fid)

    def note_member_change(self, real: str, group: Optional[str]) -> None:
        """Broker membership change (subscribe/unsubscribe/opts update)."""
        if self._journal is not None:
            self._journal.append(("member", real, group))
        self._touched.add(real)
        self._cluster_groups_cache.pop(real, None)
        if self._built is None:
            return
        if group is None:
            if real in self._built.fid_of:
                self._mark_dirty(real)
            elif self.delta_overlay:
                fid = self._delta_fid_of.get(real)
                if fid is not None:
                    # overlay fan rows for this fid are stale: versions
                    # at/below this clock deliver it host-side until the
                    # next overlay apply refreshes the row (match rows
                    # are membership-independent — no cache action)
                    self._overlay_clock += 1
                    self._fid_member_clock[fid] = self._overlay_clock
                    self._overlay_stale = True
        else:
            if (real, group) in self._built.slot_of:
                self.dirty_slots.add((real, group))
            elif real in self._built.fid_of:
                self.new_slots_by_filter.setdefault(real, set()).add(group)
            # delta filters' shared groups dispatch host-side via the
            # consume sweep over live broker.shared — nothing to track

    # ---- subscription covering: cover-set churn (ISSUE 18) --------------
    def _cover_index(self, b) -> "_CoverState":
        """The snapshot's host covering index (HostTrie over the roots
        + their encodings), built lazily on the first append attempt —
        the steady-state serving path never needs it, so builds don't
        pay O(roots) host-dict construction up front."""
        cs = b.cover
        if cs.trie is None:
            from emqx_tpu.ops.trie import HostTrie
            t = HostTrie()
            rw: dict[int, list] = {}
            for fid in cs.roots:
                w = self._enc_filter(b.fid_filter[int(fid)])
                t.insert(w, int(fid))
                rw[int(fid)] = w
            cs.trie, cs.root_words = t, rw
        return cs

    def _try_cover_append(self, f: str, words: list) -> bool:
        """Cover-set churn fast path: a NEW filter covered by a built
        covering root becomes an expansion-CSR append — a spare padded
        fid + a small device upload of the append region — instead of
        an overlay row or a rebuild. The appended fid matches on device
        from the next dispatch (sorted after every built filter, which
        is exactly where the covering-off twin's overlay rows deliver)
        and delivers host-side through the fid_rich path (its padded
        SubTable segment is empty, so device fan-out ships nothing for
        it). Returns False → the caller takes the overlay path, which
        is always correct; a False on an *eligible* snapshot counts
        toward the "covering" compaction reason (uncovered new filters
        erode the covering reduction until a recompaction)."""
        b = self._built
        if b is None or b.cover is None or self._tables is None \
                or not self.subscription_covering:
            return False
        m = self.node.metrics
        cs = b.cover
        ct = cs.ct
        if (len(words) > cs.level_cap
                or cs.app_used >= ct.app_root.shape[0]
                or len(b.fid_filter) >= len(b.seg_np)):
            self._cover_churn += 1
            m.inc("routing.cover.append_rejects")
            return False
        cs = self._cover_index(b)
        from emqx_tpu.ops.cover import host_covering_roots, rank_base
        roots = host_covering_roots(cs.trie, cs.root_words, words,
                                    f.startswith("$"))
        if not roots:
            self._cover_churn += 1
            m.inc("routing.cover.append_rejects")
            return False

        fid = len(b.fid_filter)
        k = cs.app_used
        ct.app_root[k] = min(roots)
        ct.app_fid[k] = fid
        # dense order rank past every built filter's: appends deliver
        # in arrival order after the snapshot set, mirroring the
        # off-twin's overlay order (see build_cover_tables ranking)
        ct.app_key[k] = np.int32(rank_base(ct) + k)
        ct.app_words[k, :len(words)] = words
        ct.app_lens[k] = len(words)
        cs.app_used += 1
        b.fid_of[f] = fid
        b.fid_filter.append(f)
        b.seg_len.append(0)
        b.fid_rich[fid] = True       # deliver via the live broker dict
        self._dirty_ver += 1         # hostside-mask memo must refresh

        # upload ONLY the append-region leaves (same shapes → no
        # retrace, warm classes stay valid); in-flight handles keep the
        # old immutable arrays, so the swap is safe mid-pipeline
        import jax
        if b.backend == "shapes":
            dev_cover = self._tables.shapes.cover
        else:
            dev_cover = self._tables.trie.cover
        put = self._hold("cover_csr", jax.device_put(
            (ct.app_root, ct.app_fid, ct.app_key, ct.app_words,
             ct.app_lens)), owner=f"sid{b.sid}")
        dev_cover = dev_cover._replace(
            app_root=put[0], app_fid=put[1], app_key=put[2],
            app_words=put[3], app_lens=put[4])
        if b.backend == "shapes":
            self._tables = self._tables._replace(
                shapes=self._tables.shapes._replace(cover=dev_cover))
        else:
            self._tables = self._tables._replace(
                trie=self._tables.trie._replace(cover=dev_cover))

        # match-cache invalidation walks the EXPANDED set: cached
        # topics that match the NEW covered filter (a member of the
        # expanded result, never of the covering match set) must drop
        # so their next dispatch includes the appended fid; the delta
        # version bump keeps in-flight readbacks from re-inserting
        # pre-append rows
        cache = self._match_cache
        if cache is not None:
            from emqx_tpu.ops.delta import np_filter_match
            cache.bump_delta_version()
            if len(cache):
                cache.drop_where(
                    b.sid,
                    lambda encs, lens, dols: np_filter_match(
                        words, encs, lens, dols))
        m.inc("routing.cover.appends")
        return True

    # ---- snapshot compile ----------------------------------------------
    def _observe_rebuild(self, stage: str, t0: float) -> None:
        tele = getattr(self.node, "pipeline_telemetry", None)
        if tele is not None:
            tele.observe_rebuild(stage, time.perf_counter() - t0)

    def rebuild(self) -> None:
        """Compile router+broker state into fresh device tables and swap,
        synchronously (first build / callers without a loop). The background
        path is `maybe_background_rebuild`. Reuses the previous build's
        capture + the touched-filter journal when the overlay machinery
        is on (the incremental-compaction path — see
        _capture_state_incremental)."""
        t0 = time.perf_counter()
        if self._can_capture_incremental():
            capture = self._capture_state_incremental()
        else:
            capture = self._capture_state_sync()
        self._observe_rebuild("capture", t0)
        t0 = time.perf_counter()
        result = self._build_from_capture(capture)
        self._observe_rebuild("build", t0)
        t0 = time.perf_counter()
        self._apply_build(result, journal=())
        self._observe_rebuild("swap", t0)

    def _capture_shared(self, f: str) -> dict:
        return capture_shared(self.broker, f)

    def _note_captured(self, capture) -> None:
        """A capture just completed: it becomes the incremental
        baseline. Called from every capture path BEFORE mutations racing
        the build can land (those re-enter _touched via note_*)."""
        if self.delta_overlay:
            self._last_capture = capture

    def _can_capture_incremental(self) -> bool:
        return self.delta_overlay and self._last_capture is not None

    def _incremental_refresh_set(self) -> set:
        """Filters the incremental capture must re-walk: everything
        touched since the last capture, plus every shared-group filter
        (old and new) — shared captures carry CURSOR state that advances
        on every dispatch without a note_* notification, so reusing a
        stale shared capture would reset round-robin rotation at each
        compaction. Shared filters are a small slice of the universe, so
        this keeps the capture o(touched + shared), never O(N)."""
        refresh = set(self._touched)
        self._touched = set()   # re-touches during the capture re-add
        _e, _w, _subs, shared0 = self._last_capture
        refresh |= set(shared0)
        refresh |= set(self.broker.shared)
        return refresh

    def _apply_refresh(self, subs: dict, shared: dict, fs) -> None:
        """Refresh one chunk of filters from live state into the capture
        dicts (shared by the sync and async incremental captures)."""
        broker = self.broker
        for f in fs:
            s = broker.subs.get(f)
            if s:
                subs[f] = list(s.items())
            else:
                subs.pop(f, None)
            cap = self._capture_shared(f)
            if cap:
                shared[f] = cap
            else:
                shared.pop(f, None)

    def _capture_state_incremental(self):
        """Journal-driven capture (ISSUE 4): start from the previous
        build's capture and re-walk ONLY the filters touched since (plus
        the shared set — see _incremental_refresh_set), instead of the
        full O(N) state walk. The filter universe lists are re-snapshotted
        live (two atomic C calls); _build_from_capture keys everything
        else off them, so filters added/removed since the baseline are
        picked up/dropped by construction."""
        router = self.router
        exact, wild = list(router.exact), list(router.wildcards)
        _e, _w, subs0, shared0 = self._last_capture
        subs, shared = dict(subs0), dict(shared0)
        self._apply_refresh(subs, shared, self._incremental_refresh_set())
        capture = (exact, wild, subs, shared)
        self._note_captured(capture)
        return capture

    async def _capture_state_incremental_async(self, chunk: int = 1024):
        """Chunked incremental capture (the background-compaction
        flavor): same refresh set, yielding between chunks; mutations
        landing mid-capture re-enter _touched AND the build journal, so
        they converge at swap exactly like the full capture's races."""
        import asyncio
        router = self.router
        exact, wild = list(router.exact), list(router.wildcards)
        _e, _w, subs0, shared0 = self._last_capture
        subs, shared = dict(subs0), dict(shared0)
        refresh = sorted(self._incremental_refresh_set())
        for i in range(0, len(refresh), chunk):
            self._apply_refresh(subs, shared, refresh[i:i + chunk])
            await asyncio.sleep(0)
        capture = (exact, wild, subs, shared)
        self._note_captured(capture)
        return capture

    def _capture_state_sync(self):
        """Point-in-time copy of the routing state (sync, may stall)."""
        broker, router = self.broker, self.router
        self._touched = set()
        exact, wild = list(router.exact), list(router.wildcards)
        filters = exact + wild
        subs = {f: list(broker.subs[f].items())
                for f in filters if broker.subs.get(f)}
        shared = {}
        for f in filters:
            cap = self._capture_shared(f)
            if cap:
                shared[f] = cap
        capture = (exact, wild, subs, shared)
        self._note_captured(capture)
        return capture

    async def _capture_state_async(self, chunk: int = 1024):
        """Chunked capture: yields to the loop between chunks so serving
        continues; mutations landing mid-capture are journaled and replayed
        at swap, so a half-captured filter at worst serves host-side.
        (Sorting — O(n log n) over every filter string — happens on the
        build thread, not here: list() of a set is a single atomic C call.)
        """
        import asyncio
        broker, router = self.broker, self.router
        self._touched = set()
        exact, wild = list(router.exact), list(router.wildcards)
        filters = exact + wild
        subs: dict = {}
        shared: dict = {}
        for i in range(0, len(filters), chunk):
            for f in filters[i:i + chunk]:
                s = broker.subs.get(f)
                if s:
                    subs[f] = list(s.items())
                cap = self._capture_shared(f)
                if cap:
                    shared[f] = cap
            await asyncio.sleep(0)
        capture = (exact, wild, subs, shared)
        self._note_captured(capture)
        return capture

    def _build_from_capture(self, capture):
        """Compile a captured state into device tables (loop-free: safe on
        an executor thread). Returns (built, dev_tables, cursors_np, rich)
        or None when the filter set is empty."""
        import jax

        from emqx_tpu.models.router_engine import (RouterTables,
                                                   ShapeRouterTables)
        from emqx_tpu.ops.fanout import build_subtable
        from emqx_tpu.ops.shapes import ShapeCapacityError, build_shape_tables
        from emqx_tpu.ops.trie import build_tables

        exact, wild, subs_cap, shared_cap = capture
        filters = sorted(exact) + sorted(wild)
        if not filters:
            return None

        b = _Built()
        b.fid_of = {f: i for i, f in enumerate(filters)}
        b.fid_filter = filters
        n = len(filters)
        # memoized encodings (ISSUE 4): a compaction re-encodes only
        # filters it has never seen, not the universe
        words = [self._enc_filter(f) for f in filters]
        L = max(1, max(len(w) for w in words))
        rows = np.zeros((n, L), np.int32)
        lens = np.zeros(n, np.int64)
        for i, w in enumerate(words):
            rows[i, :len(w)] = w
            lens[i] = len(w)

        normal: dict[int, list] = {}
        filter_slots: dict[int, list] = {}
        shared_members: dict[int, list] = {}
        cursors0: list[int] = []
        rich: set[str] = set()
        seg_len = [0] * n
        for f, fid in b.fid_of.items():
            subs = subs_cap.get(f)
            if subs:
                entries = []
                for sid, opts in subs:
                    if _is_rich(opts):
                        rich.add(f)
                    entries.append((sid, _pack_opts(opts)))
                normal[fid] = entries
                seg_len[fid] = len(entries)
            for g in sorted(shared_cap.get(f, {})):
                members_raw, cursor = shared_cap[f][g]
                slot = len(b.slot_key)
                b.slot_of[(f, g)] = slot
                b.slot_key.append((f, g))
                members = []
                for sid, opts in members_raw:
                    if isinstance(sid, tuple):
                        # remote member ref: reserve a device sid that
                        # indexes remote_members; opts live on its node
                        dev_sid = _REMOTE_SID_BASE + len(b.remote_members)
                        b.remote_members.append(sid)
                        members.append((dev_sid, 0))
                        continue
                    if _is_rich(opts):
                        rich.add(f)
                    members.append((sid, _pack_opts(opts)))
                shared_members[slot] = members
                filter_slots.setdefault(fid, []).append(slot)
                cursors0.append(cursor)
        b.seg_len = seg_len
        b.n_slots = len(b.slot_key)
        b.seg_np = np.asarray(seg_len, np.int64)
        b.fid_shared = np.zeros(max(1, n), bool)
        for fid in filter_slots:
            b.fid_shared[fid] = True
        b.fid_rich = np.zeros(max(1, n), bool)
        for f in rich:
            b.fid_rich[b.fid_of[f]] = True

        # pow2 capacity classes: recompile only when a class grows
        filter_cap = _next_pow2(n)

        # subscription covering (ISSUE 18 tentpole): detect cover
        # relations over the interned columnar table and shrink the
        # match set to the ROOTS (uncovered filters); the expansion CSR
        # re-expands matched covers after the match stage (ops/cover).
        # Disabled when nothing is covered (zero overhead, the tables
        # stay cover-free) or when a filter is too deep for the int32
        # order key — always correct, covering is a pure optimization.
        from emqx_tpu.ops import cover as cover_mod
        cover_np = None
        cover_state = None
        sub_ids = None                 # fids the match tables hold
        cover_shapes = False
        if self.subscription_covering and n >= 2 \
                and L <= cover_mod.MAX_KEY_LEVELS:
            dollar = np.fromiter((f.startswith("$") for f in filters),
                                 bool, n)
            covs, inc = cover_mod.detect_covers(rows, lens, dollar)
            owner = cover_mod.assign_owners(covs, inc)
            covered = np.flatnonzero(owner >= 0)
            if len(covered):
                # backend choice is free: the expansion stage re-sorts
                # every candidate by the per-filter order key, and two
                # DISTINCT filters matching the same topic always carry
                # distinct keys (equal key + same topic forces equal
                # literals), so the expanded row reproduces the off
                # twin's order whatever backend matched the roots. Pick
                # the ORDER KEY family and row width from what the off
                # twin would run (shapes iff the FULL set fits the
                # shape cap — its row is the full set's shape width),
                # but match the roots under shapes whenever the ROOT
                # subset fits: that is the covering win on populations
                # whose full diversity overflows the shape cap into
                # the trie
                roots_pre = np.flatnonzero(owner < 0)
                ns_full = cover_mod.full_shape_count(rows, lens)
                ns_root = cover_mod.full_shape_count(
                    rows[roots_pre], lens[roots_pre])
                cover_shapes = L <= 20 and ns_root <= self.shape_cap
                if cover_shapes and ns_full <= self.shape_cap:
                    keys = cover_mod.shape_order_keys(rows, lens)
                    out_w = 1 << max(0, (ns_full - 1).bit_length())
                else:
                    keys = cover_mod.trie_order_keys(rows, lens)
                    out_w = self.match_cap
                cand_cap = min(4096, _next_pow2(max(256, 4 * out_w)))
                cover_np = cover_mod.build_cover_tables(
                    rows, lens, owner, keys, fid_cap=filter_cap,
                    out_width=out_w, cand_cap=cand_cap)
                sub_ids = np.flatnonzero(owner < 0)
                cover_state = _CoverState(
                    sub_ids, cover_np, L, len(covered), int(inc.sum()))
                # pad the consume companions to filter_cap: cover-set
                # churn APPENDS fids past n (spare padded SubTable rows
                # deliver host-side via fid_rich), and the consume walk
                # indexes these arrays by matched fid
                pad = filter_cap - n
                b.seg_np = np.concatenate(
                    [b.seg_np, np.zeros(pad, np.int64)])
                b.fid_shared = np.concatenate(
                    [b.fid_shared[:n], np.zeros(pad, bool)])
                b.fid_rich = np.concatenate(
                    [b.fid_rich[:n], np.zeros(pad, bool)])
        b.cover = cover_state

        total_subs = sum(seg_len)
        total_members = sum(len(m) for m in shared_members.values())
        subs_tbl = build_subtable(
            filter_cap, normal, filter_slots, shared_members,
            slot_cap=_next_pow2(max(1, b.n_slots)),
            sub_rows_cap=_next_pow2(max(1, total_subs)),
            fs_rows_cap=_next_pow2(max(1, b.n_slots)),
            member_rows_cap=_next_pow2(max(1, total_members)))

        tables = None
        if cover_np is not None:
            # covering path: match tables over the ROOT subset, with
            # the roots keeping their original dense fids (SubTable /
            # fan-out CSR / consume indexing are untouched — covered
            # fids simply never leave the match stage un-expanded)
            roots = sub_ids
            if cover_shapes:
                st = build_shape_tables(rows[roots], lens[roots],
                                        filter_ids=roots,
                                        shape_cap=self.shape_cap)
                tables = ShapeRouterTables(shapes=st, subs=subs_tbl)
                b.backend = "shapes"
                # the EXPANDED row is padded to the FULL set's shape
                # width, so the cache/compact/consume row width matches
                # the covering-off twin's exactly
                b.match_width = int(cover_np.out_pad.shape[0])
            else:
                node_cap = _next_pow2(
                    max(256, 2 * (int(lens[roots].sum()) + 1)))
                trie = build_tables(rows[roots], lens[roots],
                                    filter_ids=roots,
                                    node_capacity=node_cap,
                                    slot_capacity=4 * node_cap)
                tables = RouterTables(trie=trie, subs=subs_tbl)
                b.backend = "trie"
                b.match_width = self.match_cap
        if tables is None and L <= 20:
            try:
                st = build_shape_tables(rows, lens, shape_cap=self.shape_cap)
                tables = ShapeRouterTables(shapes=st, subs=subs_tbl)
                b.backend = "shapes"
                b.match_width = int(st.shape_plus_mask.shape[0])
            except ShapeCapacityError:
                tables = None
        if tables is None:
            node_cap = _next_pow2(max(256, 2 * (int(lens.sum()) + 1)))
            trie = build_tables(rows, lens, node_capacity=node_cap,
                                slot_capacity=4 * node_cap)
            tables = RouterTables(trie=trie, subs=subs_tbl)
            b.backend = "trie"
            b.match_width = self.match_cap

        cur = np.zeros(max(1, len(cursors0)), np.int32)
        if cursors0:
            cur[:len(cursors0)] = cursors0
        dev_tables = self._hold("snapshot_tables", jax.device_put(tables),
                                owner=f"sid{b.sid}")
        dev_cursors = self._hold("snapshot_cursors", jax.device_put(cur))
        if cover_np is not None:
            # expansion-CSR buffers ride their own ledger category
            # ("cover_csr") so the HBM report prices covering separately
            # from the match tables; attached post-put so the
            # snapshot_tables category does not double-count the leaves
            dev_cover = self._hold("cover_csr", jax.device_put(cover_np),
                                   owner=f"sid{b.sid}")
            if b.backend == "shapes":
                dev_tables = dev_tables._replace(
                    shapes=dev_tables.shapes._replace(cover=dev_cover))
            else:
                dev_tables = dev_tables._replace(
                    trie=dev_tables.trie._replace(cover=dev_cover))
        return b, dev_tables, dev_cursors, rich

    def _hold(self, category: str, tree, owner: Optional[str] = None):
        """Register a persistent device allocation with the HBM ledger
        (ISSUE 8); identity passthrough when the ledger is off."""
        if self.ledger is not None:
            return self.ledger.hold(category, tree, owner=owner)
        return tree

    def _apply_build(self, result, journal) -> None:
        """Swap a finished build in and rebase churn tracking onto it by
        replaying the journal of mutations that happened during the build."""
        if self.sup is not None:
            # ISSUE 6 injection point: a swap failure is contained by
            # _try_swap / poll_rebuild — serving stays on the old
            # snapshot + host deltas (whose churn tracking is still
            # current: journaled note_* calls also ran live against it)
            self.sup.fire("snapshot_swap")
        self._reset_deltas()
        if result is None:
            self._built = None
            self._tables = None
            self._cursors = None
            self._cur_sig = ()
        else:
            b, tables, cursors, _rich = result
            self._built = b
            self._tables = tables
            self._cursors = cursors
            self._cur_sig = self._tables_sig(tables) \
                if b.backend == "shapes" else ()
            # evict warmth of superseded signatures (unbounded set
            # otherwise under churn); a re-warm for a returning capacity
            # class is a jit-cache hit, not a fresh trace
            self._warm_classes = {e for e in self._warm_classes
                                  if e[0] == self._cur_sig}
            # demand for cached classes resets with the snapshot too:
            # classes still in use re-register on their next plan, and
            # stale ones must not be background-recompiled after every
            # swap for the rest of the process lifetime
            self._wanted_cached.clear()
            self._wanted_compact.clear()
            self._wanted_delta.clear()
        # match-cache invalidation: wholesale, HERE — and, with the
        # delta overlay on, at overlay inserts/deletes where ONLY the
        # cached topics matching the changed filter drop
        # (_overlay_changed; ISSUE 4's delta-aware invalidation).
        # Invariant: within one snapshot's lifetime the MAIN device
        # tables are immutable — subscription churn marks filters/slots
        # dirty and those deliver host-side against the PINNED snapshot
        # (the dirty/delta scheme above), so a cached MAIN row can never
        # go stale between swaps; the cached DELTA rows are kept exact
        # by the selective drop + the put-side delta-version check. The
        # id check inside the cache then makes serving rows across
        # snapshot ids structurally impossible.
        if self._match_cache is not None:
            self._match_cache.attach(
                self._built.sid if self._built is not None else None)
        # replay churn that raced the build: journaled note_* calls are
        # idempotent against the fresh snapshot (worst case marks a filter
        # that the build already captured as dirty — correct, just host-side
        # until the next rebuild)
        for entry in journal:
            if entry[0] == "route":
                self.note_route_change(entry[1], entry[2])
            else:
                self.note_member_change(entry[1], entry[2])
        self.node.metrics.inc("routing.device.rebuilds")

    def _reset_deltas(self) -> None:
        from emqx_tpu.ops.trie import HostTrie
        self._cluster_groups_cache = {}
        self.dirty_filters = set()
        self._dirty_ver += 1
        self._hostside_memo = None
        self.dirty_slots = set()
        self.new_slots_by_filter = {}
        self._delta_trie = HostTrie()
        self._delta_filter = {}
        self._delta_fid_of = {}
        self._next_delta_fid = 0
        self._built_deleted = set()
        # the fresh snapshot subsumes every overlay row: reset the
        # overlay (version monotonicity rides the clock, which is NOT
        # reset — in-flight handles pinned to an old overlay keep their
        # consistent view)
        self._overlay = None
        self._overlay_stale = False
        self._overlay_uncovered = 0
        self._fid_member_clock = {}
        self._cover_churn = 0   # the fresh snapshot re-detected covers

    def _compaction_reason(self) -> Optional[str]:
        """Why the current snapshot should recompile, or None.

        Overlay off: the pre-ISSUE-4 policy — distinct stale entities
        (incl. every delta filter) past the threshold. Overlay on: delta
        filters serve on device, so the full rebuild is demoted to a
        rare COMPACTION triggered by (a) overlay row overflow, (b) the
        snapshot's delete-tombstone ratio — deleted built filters still
        burn match work and dirty-set checks every batch, or (c)
        membership churn on built filters/slots (still host-side) past
        the threshold."""
        if self._built is None:
            return None
        if not self.delta_overlay:
            return "churn" if self.staleness() >= self.rebuild_threshold \
                else None
        if len(self._delta_filter) > _OVERLAY_MAX:
            return "overflow"
        dead = len(self._built_deleted)
        if dead >= 64 and 2 * dead >= len(self._built.fid_filter):
            return "tombstones"
        if self._cover_churn >= 64:
            # new COVERING filters (or uncovered ones) that could not
            # ride the expansion-CSR append path serve through the
            # overlay; each erodes the covering reduction, so past a
            # budget the snapshot recompacts and re-detects covers
            return "covering"
        if self.staleness() >= self.rebuild_threshold:
            return "churn"
        return None

    def _count_compaction(self, reason: str) -> None:
        m = self.node.metrics
        m.inc("routing.device.compactions")
        m.inc(f"routing.device.compaction.{reason}")

    # ---- background rebuild (double-buffered, round-2 weak #7) ----------
    def poll_rebuild(self) -> None:
        """The one rebuild policy, called on the batch cadence: a small
        first build runs inline (milliseconds — the first batch already
        rides the device); a big first build or a compaction trigger
        (_compaction_reason) runs double-buffered in the background."""
        if self._building:
            return
        if self.sup is not None:
            # supervision tick rides the batch cadence like the rebuild
            # policy: launch any due half-open probes (off-path)
            self.sup.poll()
        if self._built is None:
            n = len(self.router.exact) + len(self.router.wildcards)
            if n == 0:
                return
            if self.sup is not None and not self.sup.rebuild_enabled():
                return      # swap breaker open: host-route until probed
            if n <= 4096 or not self.maybe_background_rebuild():
                if self.sup is None:
                    self.rebuild()
                    return
                try:
                    self.rebuild()
                except Exception as e:  # noqa: BLE001 — contained
                    # first-build fault (ISSUE 6): serving stays
                    # host-side (no snapshot → prepare returns None)
                    # until the snapshot_swap breaker's probe re-admits
                    # rebuild attempts
                    self.sup.note_fault("snapshot_swap", e)
                    self.node.metrics.inc(
                        "routing.device.rebuild_failed")
        else:
            reason = self._compaction_reason()
            if reason is not None and self.maybe_background_rebuild():
                self._count_compaction(reason)

    def maybe_background_rebuild(self, executor=None) -> bool:
        """Kick a background rebuild when churn crossed a compaction
        trigger. Returns True when one is running/queued after the call.
        Requires a running loop; sync callers use rebuild()."""
        import asyncio
        if self._building:
            return True
        if self.sup is not None and not self.sup.rebuild_enabled():
            # snapshot_swap breaker open (ISSUE 6): no rebuild attempts
            # until the half-open probe succeeds — the old snapshot +
            # host deltas keep serving correctly meanwhile
            return False
        if self._built is not None \
                and self._compaction_reason() is None:
            return False
        if self._built is None \
                and not (self.router.exact or self.router.wildcards):
            return False    # nothing to compile yet
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return False
        self._building = True
        self._journal = []
        from emqx_tpu.broker.supervise import guard_task
        self._rebuild_task = guard_task(
            loop.create_task(self._background_rebuild(executor)),
            "device-rebuild", self.node.metrics)
        return True

    async def _background_rebuild(self, executor=None) -> None:
        import asyncio
        loop = asyncio.get_running_loop()
        try:
            t0 = time.perf_counter()
            if self._can_capture_incremental():
                capture = await self._capture_state_incremental_async()
            else:
                capture = await self._capture_state_async()
            self._observe_rebuild("capture", t0)
            t0 = time.perf_counter()
            result = await loop.run_in_executor(
                executor, self._build_from_capture, capture)
            self._observe_rebuild("build", t0)
            if result is not None:
                t0 = time.perf_counter()
                await loop.run_in_executor(executor, self._warm_compile,
                                           result)
                self._observe_rebuild("warm", t0)
            self._pending_swap = (result,)   # 1-tuple: result may be None
            self._try_swap()
        except Exception:
            import logging
            logging.getLogger("emqx.device").exception(
                "background snapshot rebuild failed; serving stays on the "
                "old snapshot + host deltas")
            self._journal = None
            self._building = False
            self._pending_swap = None
            self.node.metrics.inc("routing.device.rebuild_failed")

    def _warm_compile(self, result) -> None:
        """Pre-jit the route step for the new tables' shapes across the
        common (window, batch) classes, so neither the swap nor a later
        first-use of a bigger class stalls serving on an XLA
        trace/compile (tracing holds the GIL even on an executor thread;
        cached compiles don't)."""
        import contextlib

        import jax

        from emqx_tpu.models.router_engine import (route_step,
                                                   route_window_full)
        from emqx_tpu.ops.shared import STRATEGY_ROUND_ROBIN
        tele = getattr(self.node, "pipeline_telemetry", None)
        b, tables, cursors, _rich = result
        strat = np.int32(STRATEGY_ROUND_ROBIN)
        for Wp, Bp in self._STD_CLASSES:
            if Wp > 1 and b.backend != "shapes":
                continue    # trie backend never fuses: (8, Bp) would
                            # just redundantly re-run the (1, Bp) step
            ctx = tele.compile_context(f"warm W{Wp}xB{Bp}") \
                if tele is not None else contextlib.nullcontext()
            enc = np.zeros((Wp, Bp, self.max_levels), np.int32)
            lens = np.zeros((Wp, Bp), np.int32)
            dollar = np.zeros((Wp, Bp), bool)
            mh = np.zeros((Wp, Bp), np.int32)
            with ctx:
                # warm the program the serving path will actually
                # dispatch (the donating twin at depth >= 2) with a
                # throwaway cursors buffer — never the live one, which
                # the twin would donate away (_warm_cursors)
                if b.backend == "shapes":
                    r = self._rt(route_window_full)(
                        tables, self._warm_cursors(cursors), enc, lens,
                        dollar, mh, strat,
                        fanout_cap=self.fanout_cap,
                        slot_cap=self.slot_cap)
                else:
                    r = self._rt(route_step)(
                        tables, self._warm_cursors(cursors), enc[0],
                        lens[0], dollar[0], mh[0], strat,
                        frontier_cap=self.frontier_cap,
                        match_cap=self.match_cap,
                        fanout_cap=self.fanout_cap,
                        slot_cap=self.slot_cap)
                jax.block_until_ready(r.match_counts)
        if b.backend == "shapes":
            # this snapshot's classes are warm: once IT is serving, the
            # batcher may dispatch/fuse (readiness is per shape
            # signature, so an old snapshot still serving cannot run
            # into cold shapes)
            sig = self._tables_sig(tables)
            for Wp, Bp in self._STD_CLASSES:
                self._warm_classes.add((sig, Wp, Bp))

    def _try_swap(self) -> None:
        """Apply a finished background build if no dispatch is in flight
        (handles pin the snapshot they were dispatched against)."""
        if not self._building or self._pending_swap is None \
                or self._outstanding > 0:
            return
        (result,) = self._pending_swap
        journal = self._journal or ()
        self._pending_swap = None
        self._journal = None
        self._building = False
        t0 = time.perf_counter()
        if self.sup is None:
            self._apply_build(result, journal)
        else:
            try:
                self._apply_build(result, journal)
            except Exception as e:  # noqa: BLE001 — contained domain
                # swap fault (ISSUE 6): the old snapshot keeps serving
                # (its dirty/delta tracking ran live during the build,
                # so dropping the failed result loses nothing); the
                # breaker gates further rebuild attempts until a probe
                self.sup.note_fault("snapshot_swap", e)
                self.node.metrics.inc("routing.device.rebuild_failed")
                self._observe_rebuild("swap", t0)
                return
            self.sup.note_ok("snapshot_swap")
        self._observe_rebuild("swap", t0)

    # ---- supervision probes (ISSUE 6: off-the-serving-path health
    #      checks the half-open breaker runs on an executor thread) ----
    def _probe_dispatch(self) -> None:
        """End-to-end health check of the dispatch stage: run the plain
        route program over an all-pad batch against the live tables —
        the same shape the demand-warm calls already execute from
        executor threads, so thread-safety and jit-cache behavior are
        identical. Matches nothing, advances nothing (the probe's
        new_cursors are dropped; an all-pad batch has zero occur)."""
        if self._built is None or self._tables is None:
            return      # nothing to probe: vacuous health
        import jax

        from emqx_tpu.models import router_engine as RE
        from emqx_tpu.ops.shared import STRATEGY_ROUND_ROBIN
        Bp = self._STD_CLASSES[0][1]
        enc = np.zeros((1, Bp, self.max_levels), np.int32)
        z = np.zeros((1, Bp), np.int32)
        zb = np.zeros((1, Bp), bool)
        strat = np.int32(STRATEGY_ROUND_ROBIN)
        # ISSUE 9: at dispatch_depth >= 2 the serving dispatch DONATES
        # the live cursors buffer, so the probe must not hand it to a
        # concurrent call — it probes with a throwaway device buffer
        # (the probe's cursor state is discarded anyway); the PLAIN
        # program is kept deliberately (off-path; a cold compile here
        # never stalls serving)
        cur = self._warm_cursors(self._cursors)
        if self._built.backend == "shapes":
            r = RE.route_window_full(self._tables, cur, enc,
                                     z, zb, z, strat,
                                     fanout_cap=self.fanout_cap,
                                     slot_cap=self.slot_cap)
        else:
            r = RE.route_step(self._tables, cur, enc[0], z[0],
                              zb[0], z[0], strat,
                              frontier_cap=self.frontier_cap,
                              match_cap=self.match_cap,
                              fanout_cap=self.fanout_cap,
                              slot_cap=self.slot_cap)
        jax.block_until_ready(r.match_counts)

    def _probe_materialize(self) -> None:
        """Health check of the readback stage: one small device→host
        transfer proves the link."""
        import jax.numpy as jnp
        np.asarray(jnp.zeros((8,), jnp.int32))

    # ---- the serving path ----------------------------------------------
    def device_shared_active(self) -> bool:
        """Device picks serve all device-supported strategies, clustered
        or standalone — the snapshot holds the cluster-wide membership
        with remote members as forwardable refs (round-4: previously
        groups with remote members fell back to host dispatch; round-2
        before that, ANY cluster disabled the whole on-device path)."""
        from emqx_tpu.ops.shared import STRATEGIES
        return self.broker.shared_strategy in STRATEGIES

    def _host_shared_dispatch(self, f: str, gname: str, msg) -> bool:
        """One group's host-side dispatch: cluster-wide pick under a
        cluster, local strategy pick standalone."""
        broker = self.broker
        if broker.cluster is not None:
            return broker.cluster._dispatch_one_group(broker, f, gname,
                                                      msg)
        g = broker.shared.get(f, {}).get(gname)
        return bool(g and g.members
                    and broker._shared_pick_deliver(gname, f, g, msg))

    # ---- delta overlay (ISSUE 4) ----------------------------------------
    def _overlay_class(self, n: int) -> int:
        for c in _DELTA_CLASSES:
            if n <= c:
                return c
        return _DELTA_CLASSES[-1]

    @staticmethod
    def _delta_payload_cap(Bp: int) -> int:
        """Delta CSR payload class, a fixed multiple of Bp (so it adds
        no warm-class dimension): overlay matches are sparse — most
        lanes match zero post-snapshot filters — so one entry per lane
        of headroom covers realistic churn; a window that still outgrows
        it reads the dense delta planes of the same dispatch."""
        return max(64, Bp)

    def _overlay_sync(self) -> None:
        """Apply pending journal entries to the overlay (see
        _overlay_sync_inner for the mechanics). Under supervision
        (ISSUE 6) this is the overlay_apply fault domain: a raising
        apply is CONTAINED — the overlay stays stale and its filters
        serve through the host delta trie (exactly the pre-overlay
        fallback, counted by routing.device.host_delta) while the
        breaker opens toward rung 1. Without supervision the exception
        propagates out of prepare (the pre-ISSUE-6 behavior: the whole
        group host-routes via the batcher's produce catch)."""
        if not self.delta_overlay or not self._overlay_stale:
            return
        sup = self.sup
        if sup is None:
            self._overlay_sync_inner()
            return
        try:
            sup.fire("overlay_apply")
            self._overlay_sync_inner()
        except Exception as e:  # noqa: BLE001 — contained fault domain
            sup.note_fault("overlay_apply", e)
        else:
            sup.note_ok("overlay_apply")

    def _overlay_sync_inner(self) -> None:
        """Rebuild the small host table from the live delta dicts and
        upload a fresh DeltaTables version. The table is a few hundred
        rows of numpy — microseconds, safe on the loop; the EXPENSIVE
        part (the fused program compile for a new row class) is
        demand-warmed off the serving path like the cached/compact
        ladders (_gate_delta). Versions are immutable: in-flight
        handles keep the table they dispatched with, and per-fid
        membership staleness is judged against the pinned version's
        clock stamp at consume."""
        t0 = time.perf_counter()
        from emqx_tpu.ops.delta import build_delta_tables
        live = sorted(self._delta_filter.items())   # fid order = age
        entries = []
        fid_set = set()
        row_of: dict[int, int] = {}
        seg_of: dict[int, int] = {}
        hostfan: set[int] = set()
        for fid, f in live:
            if len(entries) >= _OVERLAY_MAX:
                break       # overflow: the rest host-route until the
                            # compaction this state has already triggered
            words = self._enc_filter(f)
            if len(words) > self.max_levels:
                continue    # too deep for the device planes: host path
            fan = []
            subs = self.broker.subs.get(f)
            host_side = False
            if subs:
                if len(subs) > _DELTA_FAN_PER_ROW:
                    host_side = True    # oversized fan-out: match on
                else:                   # device, deliver via host dict
                    for sid, opts in subs.items():
                        if _is_rich(opts):
                            host_side = True
                            break
                        fan.append((sid, _pack_opts(opts)))
            if host_side:
                fan = []
                hostfan.add(fid)
            row_of[fid] = len(entries)
            seg_of[fid] = len(fan)
            fid_set.add(fid)
            entries.append((words, fid, fan))
        self._overlay_uncovered = len(live) - len(fid_set)
        if not entries:
            self._overlay = None
            self._overlay_stale = False
            return
        cap = self._overlay_class(len(entries))
        dt = build_delta_tables(entries, row_cap=cap,
                                level_cap=self.max_levels,
                                fan_per_row=_DELTA_FAN_PER_ROW)
        import jax
        # each overlay version is its own ledgered allocation: pinned
        # versions show up as distinct owners until their handles drain
        dev = self._hold("delta_overlay", jax.device_put(dt),
                         owner=f"v{self._overlay_clock}")
        self._overlay = _Overlay(dev, frozenset(fid_set), row_of, seg_of,
                                 hostfan, self._overlay_clock, cap,
                                 len(entries))
        self._overlay_stale = False
        self.node.metrics.inc("routing.device.delta_applies")
        self._observe_rebuild("delta_apply", t0)

    def _gate_delta(self, Wp: int, Bp: int,
                    gate_cold: bool) -> Optional[_Overlay]:
        """Choose + warm-gate the overlay for one dispatch. Returns the
        pinned _Overlay, or None to dispatch WITHOUT the fused overlay
        (overlay off/empty, or its class is cold on the serving path —
        the pre-overlay host fallback stays correct meanwhile and the
        routing.device.host_delta counter measures exactly that gap)."""
        if not self.delta_overlay:
            return None
        self._overlay_sync()
        ov = self._overlay
        if ov is None:
            return None
        key = (self._cur_sig, Wp, Bp, f"d{ov.cap}")
        if gate_cold and key not in self._warm_classes:
            self._wanted_delta.add((Wp, Bp, ov.cap))
            self._kick_class_warm()
            self.node.metrics.inc("routing.device.cold_delta_class")
            return None
        return ov

    def _delta_pending(self, ov: Optional[_Overlay]) -> bool:
        """True when some live delta filter is NOT served by `ov` (no
        overlay this dispatch, or filters landed/overflowed past it) —
        consume must then run the host delta trie for the uncovered
        remainder and the vectorized fast path stands down."""
        if not self._delta_filter:
            return False
        if ov is None:
            return True
        return not self._delta_filter.keys() <= ov.fid_set

    def prepare(self, msgs: list[Message], gate_cold: bool = True):
        """Stage 1 (event loop): encode ONE micro-batch (window of 1)."""
        return self.prepare_window([msgs], gate_cold=gate_cold)

    def _plan_window(self, b, enc4, len4, dol4, gate_cold: bool,
                     ov: Optional[_Overlay] = None):
        """Dedup + match-cache analysis for one encoded window.

        Collapses the [Wp, Bp] lanes to unique encoded topics (padding
        lanes all share one sentinel key, so under-filled fused windows
        still win), consults the snapshot-keyed cache for each unique
        topic, and compacts the remainder into a miss sub-batch whose
        size is quantized onto the SAME pow2 batch-class ladder the warm
        machinery already compiles.

        Returns (plan, cache_info): `plan` is the cached-dispatch device
        input set (None = dispatch the plain program), `cache_info` the
        post-readback insert list (kept even when the plan is rejected —
        the plain path's readback must still seed the cache, or a cold
        hot-set would never start hitting)."""
        Wp, Bp, L = enc4.shape
        if b.backend != "shapes" and Wp > 1:
            # trie never fuses, so a multi-batch trie window only exists
            # for direct callers — no plan, and no point paying the
            # hash/unique analysis either
            return None, None
        if Wp == 1 and Bp <= self._STD_CLASSES[0][1]:
            # a single window at the smallest batch class can never
            # engage (Bm floors at that same class, so Bm < Bp is
            # impossible): skip the whole analysis — trickle traffic
            # must not pay hashing/unique/lookup for zero possible
            # payoff (measured 0.88x at batch 64 otherwise)
            return None, None
        n_lanes = Wp * Bp
        encf = enc4.reshape(n_lanes, L)
        lenf = len4.reshape(n_lanes)
        dolf = dol4.reshape(n_lanes)
        keys_v = _topic_keys(encf, lenf, dolf)
        uniq, first_idx, inv = np.unique(keys_v, return_index=True,
                                         return_inverse=True)
        Bu = len(uniq)
        pad_u = lenf[first_idx] == 0          # [Bu] the sentinel pad lane
        real = int((lenf > 0).sum())
        uniq_real = Bu - int(pad_u.sum())
        if Bu > Bp:
            # window more diverse than the Bp-wide unique arrays can
            # hold: dedup would not pay anyway — plain dispatch
            return None, None
        cache = self._match_cache
        keys = [None if pad_u[u] else uniq[u].tobytes()
                for u in range(Bu)]
        # the cache lookup runs before the engage decision by necessity
        # (the miss count IS the decision input), and misses must seed
        # the cache even from plain-dispatched windows or a cold hot-set
        # would never start hitting; the base rows themselves are only
        # materialized once the plan engages
        hit_rows: list = [None] * Bu
        miss_u: list[int] = []
        inserts: list[tuple] = []
        if cache is not None:
            rows = cache.get_many(b.sid,
                                  [k for k in keys if k is not None])
            it = iter(rows)
            for u, k in enumerate(keys):
                if k is None:
                    continue
                row = next(it)
                if row is not None and ov is not None:
                    # delta-fused dispatch: a usable hit must carry the
                    # overlay base triple (rows inserted from a window
                    # that dispatched without the overlay store None
                    # there) and its fids must map into the pinned
                    # table (deleted fids are swept by the delta-aware
                    # invalidation, so a miss here is a transient race,
                    # not a leak)
                    if len(row) < 6 or row[3] is None or not all(
                            int(df) in ov.row_of for df in row[3]
                            if df >= 0):
                        row = None
                if row is None:
                    miss_u.append(u)
                    inserts.append((k, int(first_idx[u])))
                else:
                    hit_rows[u] = row
        else:
            miss_u = [u for u in range(Bu) if keys[u] is not None]
        info = _CacheInfo(
            b.sid, inserts,
            cache.delta_version if cache is not None
            and self.delta_overlay else None) if inserts else None
        n_miss = len(miss_u)
        n_hit = uniq_real - n_miss
        Bm = self._batch_class(max(1, n_miss))
        # engage only when the deduplicated dispatch removes real match
        # work: the miss sub-batch quantizes to a SMALLER class than the
        # full batch, or a fused window (whose plain match would run Wp
        # full-width batches). Hits alone don't qualify — at Bm == Bp
        # the match runs the same lane count either way and the cached
        # program would only add gather overhead (and pointless warm
        # traces for its class).
        if not (Bm < Bp or Wp > 1):
            return None, info
        dsuf = (f"d{ov.cap}",) if ov is not None else ()
        dC = ov.cap if ov is not None else None
        if gate_cold \
                and (self._cur_sig, Wp, Bp, Bm) + dsuf \
                not in self._warm_classes:
            # serving path: a cold cached (W, Bp, Bm[, dC]) class would
            # stall on an in-path XLA compile — dispatch the warm plain
            # program instead and let the background warm bring the
            # class online (same policy as batch_class_warm; trie
            # classes are keyed under the empty signature)
            self._wanted_cached.add((Wp, Bp, Bm, dC))
            self._kick_class_warm()
            self.node.metrics.inc("routing.device.cold_cached_class")
            return None, info
        base_m = np.full((Bp, b.match_width), -1, np.int32)
        base_c = np.zeros(Bp, np.int32)
        base_o = np.zeros(Bp, bool)
        base_dm = base_dc = base_do = None
        if ov is not None:
            base_dm = np.full((Bp, _DELTA_MATCH_CAP), -1, np.int32)
            base_dc = np.zeros(Bp, np.int32)
            base_do = np.zeros(Bp, bool)
        for u, row in enumerate(hit_rows):
            if row is not None:
                base_m[u] = row[0]
                base_c[u] = row[1]
                base_o[u] = row[2]
                if ov is not None:
                    # cached delta triples are FID-space (stable across
                    # overlay row reassignments); map onto the pinned
                    # table's row indices for the device-side merge
                    dm = row[3]
                    for j, df in enumerate(dm):
                        if df >= 0:
                            base_dm[u, j] = ov.row_of[int(df)]
                    base_dc[u] = row[4]
                    base_do[u] = row[5]
        miss_topics = np.full((Bm, L), I.PAD, np.int32)
        miss_lens = np.zeros(Bm, np.int32)
        miss_dollar = np.zeros(Bm, bool)
        # pad = Bp (out of range for the [Bp]-wide base arrays): dropped
        # by the device scatter. NOT -1 — jax wraps negative indices
        # before the bounds check, which would clobber unique row Bp-1
        # with the empty pad match whenever Bu == Bp
        miss_pos = np.full(Bm, Bp, np.int32)
        if n_miss:
            src = first_idx[miss_u]
            miss_topics[:n_miss] = encf[src]
            miss_lens[:n_miss] = lenf[src]
            miss_dollar[:n_miss] = dolf[src]
            miss_pos[:n_miss] = miss_u
        plan = _CachePlan(miss_topics, miss_lens, miss_dollar, base_m,
                          base_c, base_o, miss_pos,
                          inv.reshape(Wp, Bp).astype(np.int32), Bm,
                          n_miss, n_hit)
        plan.base_dm, plan.base_dc, plan.base_do = base_dm, base_dc, \
            base_do
        # telemetry is recorded ONLY for engaged plans, so the exported
        # dedup ratio / hit rate describe match work actually removed
        # from dispatches — not lookups whose window went plain (those
        # would inflate the attribution the counters exist to ground)
        tele = getattr(self.node, "pipeline_telemetry", None)
        if tele is not None and real:
            tele.record_dedup(real, uniq_real)
        if cache is not None:
            cache.count_lookups(n_hit, n_miss)
        return plan, info

    # window sub-batch count classes: each (W, Bp) pair is one XLA
    # compile; quantizing W the same way as the batch axis keeps the
    # compile count bounded (empty padding sub-batches match nothing)
    _W_CLASSES = (1, 8)

    @staticmethod
    def _tables_sig(tables) -> tuple:
        """Shape signature of a device table pytree: the jit cache key's
        shape component. Fusion readiness is tracked PER SIGNATURE — a
        snapshot whose capacity classes differ from the warmed one would
        otherwise cold-compile the window program on the serving path."""
        import jax
        return tuple(tuple(x.shape) for x in jax.tree.leaves(tables))

    def max_fuse(self) -> int:
        """How many batches the serving path may fuse per dispatch right
        now: 1 until the CURRENT snapshot's fused window class is warm,
        then the largest class. Trie-backend snapshots never fuse (no
        window program — sequential dispatch amortizes nothing)."""
        W, Bp = self._STD_CLASSES[-1]
        if self._built is None or self._built.backend != "shapes" \
                or (self._cur_sig, W, Bp) not in self._warm_classes:
            return 1
        return W

    def _batch_class(self, n_msgs: int) -> int:
        """Quantize a batch size onto the standard Bp ladder (derived
        from _STD_CLASSES), or the next pow2 beyond it."""
        for _w, Bp in self._STD_CLASSES:
            if _w == 1 and n_msgs <= Bp:
                return Bp
        return _next_pow2(n_msgs)

    def batch_class_warm(self, n_msgs: int) -> bool:
        """True when a single batch of n_msgs would dispatch into an
        already-compiled (1, Bp) class for the CURRENT snapshot — the
        batcher routes host-side (and kicks the background warm)
        otherwise, so serving never stalls on an XLA compile."""
        if self._built is None:
            return False
        if self._built.backend != "shapes":
            # trie backend has no background warm path for every class;
            # first use compiles in-path as it always has (rare fallback)
            return True
        Bp = self._batch_class(n_msgs)
        if (self._cur_sig, 1, Bp) in self._warm_classes:
            return True
        if Bp > self._STD_CLASSES[-1][1]:
            # oversized batch class (max_publish_batch > 1024): queue it
            # for the background warm, or it would be locked out forever
            self._extra_classes.add((1, Bp))
        return False

    _STD_CLASSES = ((1, 64), (1, 256), (1, 1024), (8, 1024))

    # payload classes are multiples of the batch class Bp (entries per
    # message budget): 8 covers trickle fan-out, 32 the fan-out ≤ ~10
    # regime the motivation targets, 128 heavy fan-out. Beyond 128 the
    # compacted payload approaches the dense planes and compaction stops
    # paying — the chooser returns None (dense readback).
    _PAYLOAD_MULTS = (8, 32, 128)

    def _dense_msg_entries(self, b=None) -> int:
        """Dense readback cost per message lane in int32-equivalent
        entries: match plane + fan rows/opts + shared slot/row/opts."""
        b = b or self._built
        return b.match_width + 2 * self.fanout_cap + 3 * self.slot_cap

    def _choose_payload_cap(self, Bp: int) -> Optional[int]:
        """Payload class for a (·, Bp) dispatch, or None for dense.

        Sized by a peak-biased EWMA of recent per-window-row entry
        totals (adopts an upward sample outright, decays slowly — see
        _note_payload) with 2x headroom, quantized onto the
        _PAYLOAD_MULTS * Bp ladder so the compile-class count stays
        bounded. A window that still outgrows its class falls back to
        the dense readback of the SAME dispatch (row_overflow), so an
        undershoot costs bytes, never correctness."""
        if not self.compact_readback or self._built is None:
            return None
        dense = self._dense_msg_entries()
        mults = [m for m in self._PAYLOAD_MULTS if m < dense]
        if not mults:
            return None         # tiny caps: nothing to compact away
        ew = self._pay_ewma.get(Bp)
        if ew is None:
            # no traffic measured at this class yet: start mid-ladder
            # (the first window's offsets seed the EWMA either way)
            return mults[min(1, len(mults) - 1)] * Bp
        for m in mults:
            if m * Bp >= 2.0 * ew:
                return m * Bp
        return None             # sustained heavy fan-out: dense wins

    def _note_payload(self, Bp: int, totals: np.ndarray) -> None:
        """Feed the EWMA from one window's actual per-row entry totals
        (read from the offsets plane — available on the overflow
        fallback too, which is exactly when learning matters most)."""
        s = float(totals.max()) if totals.size else 0.0
        ew = self._pay_ewma.get(Bp)
        # peak-biased: adopt growth immediately (the next window must
        # not overflow again), decay shrinkage slowly (a lull must not
        # trigger a class downshift and an overflow on the next burst)
        self._pay_ewma[Bp] = s if (ew is None or s > ew) \
            else 0.8 * ew + 0.2 * s

    def _gate_compact(self, Wp: int, Bp: int, plan, gate_cold: bool,
                      ov: Optional[_Overlay] = None) -> Optional[int]:
        """Choose + warm-gate the payload class for one dispatch.
        Returns the class, or None to read back dense (compaction off,
        unprofitable, or the class is cold on the serving path)."""
        pcap = self._choose_payload_cap(Bp)
        if pcap is None:
            return None
        dsuf = (f"d{ov.cap}",) if ov is not None else ()
        key = (self._cur_sig, Wp, Bp) \
            + ((plan.Bm,) if plan is not None else ()) + dsuf \
            + (f"c{pcap}",)
        if gate_cold and key not in self._warm_classes:
            # same policy as the cached ladder: a cold compact class
            # would stall serving on an in-path XLA compile — dispatch
            # with the dense readback and let the background warm bring
            # the class online
            self._wanted_compact.add(
                (Wp, Bp, plan.Bm if plan is not None else None, pcap,
                 ov.cap if ov is not None else None))
            self._kick_class_warm()
            self.node.metrics.inc("routing.device.cold_compact_class")
            return None
        return pcap

    @staticmethod
    def _class_key(sig, Wp, Bp, Bm=None, dC=None, P=None) -> tuple:
        """The one warm-class key layout: (sig, W, Bp[, Bm][, dN][, cP])
        — dedup miss class, delta-overlay row class and compact payload
        class are each optional program dimensions."""
        return ((sig, Wp, Bp)
                + ((Bm,) if Bm is not None else ())
                + ((f"d{dC}",) if dC is not None else ())
                + ((f"c{P}",) if P is not None else ()))

    def _kick_class_warm(self) -> None:
        """Warm every standard (W, Bp) class AND every demand-registered
        cached / delta-overlay / compact program class the CURRENT
        snapshot is missing, off the serving path. Re-kicks after a
        failure and after any swap to unwarmed capacity classes. The
        standard ladder is shapes-only (trie compiles its plain step
        in-path, as ever); cached/delta/compact classes warm for BOTH
        backends — the gates hold each program variant back until its
        class is warm."""
        import asyncio
        if self._fuse_warm_task is not None or self._built is None:
            return
        backend = self._built.backend
        ck = self._class_key
        missing = []
        if backend == "shapes":
            wanted = self._STD_CLASSES + tuple(sorted(self._extra_classes))
            missing = [(W, Bp) for W, Bp in wanted
                       if (self._cur_sig, W, Bp) not in self._warm_classes]
        delta_missing = [
            e for e in sorted(self._wanted_delta)
            if ck(self._cur_sig, e[0], e[1], dC=e[2])
            not in self._warm_classes]
        cached_missing = [
            e for e in sorted(self._wanted_cached,
                              key=lambda e: (e[0], e[1], e[2], e[3] or 0))
            if ck(self._cur_sig, e[0], e[1], Bm=e[2], dC=e[3])
            not in self._warm_classes]
        compact_missing = [
            e for e in sorted(self._wanted_compact,
                              key=lambda e: (e[0], e[1], e[2] or 0, e[3],
                                             e[4] or 0))
            if ck(self._cur_sig, e[0], e[1], Bm=e[2], dC=e[4], P=e[3])
            not in self._warm_classes]
        if not missing and not delta_missing and not cached_missing \
                and not compact_missing:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        tables, cursors = self._tables, self._cursors
        match_width = self._built.match_width
        sig = self._cur_sig

        tele = getattr(self.node, "pipeline_telemetry", None)

        def warm():
            import contextlib

            import jax

            from emqx_tpu.models.router_engine import (
                route_step_cached, route_step_delta,
                route_step_delta_cached, route_window_cached,
                route_window_delta, route_window_delta_cached,
                route_window_full)
            from emqx_tpu.ops.delta import empty_delta_tables
            from emqx_tpu.ops.shared import STRATEGY_ROUND_ROBIN
            strat = np.int32(STRATEGY_ROUND_ROBIN)
            rt = self._rt

            def wc():
                # fresh throwaway cursors per program call: the
                # donating twins consume their input (_warm_cursors)
                return self._warm_cursors(cursors)

            def dummy_delta(dC):
                # shapes are all that matter for the trace; an all-empty
                # table of the class is the cheapest valid instance
                return empty_delta_tables(dC, self.max_levels,
                                          fan_per_row=_DELTA_FAN_PER_ROW)

            def ctx_of(label):
                return tele.compile_context(label) if tele is not None \
                    else contextlib.nullcontext()

            for Wp, Bp in missing:
                enc = np.zeros((Wp, Bp, self.max_levels), np.int32)
                z = np.zeros((Wp, Bp), np.int32)
                with ctx_of(f"warm W{Wp}xB{Bp}"):
                    r = rt(route_window_full)(
                        tables, wc(), enc, z, np.zeros((Wp, Bp), bool),
                        z, strat, fanout_cap=self.fanout_cap,
                        slot_cap=self.slot_cap)
                    jax.block_until_ready(r.match_counts)
                self._warm_classes.add((sig, Wp, Bp))
            # demand-driven delta-overlay classes (ISSUE 4): each
            # (W, Bp, dC) is one fused program; the serving path keeps
            # the host delta fallback until its class lands here
            for Wp, Bp, dC in delta_missing:
                dt = dummy_delta(dC)
                enc = np.zeros((Wp, Bp, self.max_levels), np.int32)
                z = np.zeros((Wp, Bp), np.int32)
                zb = np.zeros((Wp, Bp), bool)
                with ctx_of(f"warm W{Wp}xB{Bp}d{dC}"):
                    if backend == "shapes":
                        r = rt(route_window_delta)(
                            tables, dt, wc(), enc, z, zb, z, strat,
                            fanout_cap=self.fanout_cap,
                            slot_cap=self.slot_cap,
                            delta_match_cap=_DELTA_MATCH_CAP,
                            delta_fanout_cap=_DELTA_FANOUT_CAP)
                    else:   # trie delta dispatches are single-batch
                        r = rt(route_step_delta)(
                            tables, dt, wc(), enc[0], z[0], zb[0],
                            z[0], strat, frontier_cap=self.frontier_cap,
                            match_cap=self.match_cap,
                            fanout_cap=self.fanout_cap,
                            slot_cap=self.slot_cap,
                            delta_match_cap=_DELTA_MATCH_CAP,
                            delta_fanout_cap=_DELTA_FANOUT_CAP)
                    jax.block_until_ready(r.res.match_counts)
                self._warm_classes.add(ck(sig, Wp, Bp, dC=dC))
            # demand-driven cached-dispatch classes: the serving path
            # registered every (W, Bp, Bm[, dC]) a dedup plan wanted and
            # fell back to the plain program meanwhile
            for Wp, Bp, Bm, dC in cached_missing:
                args = (np.full((Bm, self.max_levels), I.PAD, np.int32),
                        np.zeros(Bm, np.int32), np.zeros(Bm, bool),
                        np.full((Bp, match_width), -1, np.int32),
                        np.zeros(Bp, np.int32), np.zeros(Bp, bool))
                dargs = () if dC is None else (
                    np.full((Bp, _DELTA_MATCH_CAP), -1, np.int32),
                    np.zeros(Bp, np.int32), np.zeros(Bp, bool))
                pos = (np.full(Bm, Bp, np.int32),)   # pad = Bp: dropped
                label = f"warm W{Wp}xB{Bp}mB{Bm}" \
                    + (f"d{dC}" if dC is not None else "")
                with ctx_of(label):
                    if backend == "shapes":
                        inv = np.zeros((Wp, Bp), np.int32)
                        mh = np.zeros((Wp, Bp), np.int32)
                        if dC is None:
                            r = rt(route_window_cached)(
                                tables, wc(), *args, *pos, inv, mh,
                                strat, fanout_cap=self.fanout_cap,
                                slot_cap=self.slot_cap)
                        else:
                            r = rt(route_window_delta_cached)(
                                tables, dummy_delta(dC), wc(), *args,
                                *dargs, *pos, inv, mh, strat,
                                fanout_cap=self.fanout_cap,
                                slot_cap=self.slot_cap,
                                delta_match_cap=_DELTA_MATCH_CAP,
                                delta_fanout_cap=_DELTA_FANOUT_CAP).res
                    else:
                        # trie plans are single-batch (Wp == 1)
                        inv = np.zeros(Bp, np.int32)
                        mh = np.zeros(Bp, np.int32)
                        kw = dict(frontier_cap=self.frontier_cap,
                                  match_cap=self.match_cap,
                                  fanout_cap=self.fanout_cap,
                                  slot_cap=self.slot_cap)
                        if dC is None:
                            r = rt(route_step_cached)(
                                tables, wc(), *args, *pos, inv, mh,
                                strat, **kw)
                        else:
                            r = rt(route_step_delta_cached)(
                                tables, dummy_delta(dC), wc(), *args,
                                *dargs, *pos, inv, mh, strat, **kw,
                                delta_match_cap=_DELTA_MATCH_CAP,
                                delta_fanout_cap=_DELTA_FANOUT_CAP).res
                    jax.block_until_ready(r.match_counts)
                self._warm_classes.add(ck(sig, Wp, Bp, Bm=Bm, dC=dC))
            # demand-driven compact-readback classes (ISSUE 3): each
            # (W, Bp[, Bm][, dC], P) is one program; the serving path
            # reads back dense until its class lands here
            from emqx_tpu.models.router_engine import (
                route_step_cached_compact, route_step_compact,
                route_step_delta_cached_compact, route_step_delta_compact,
                route_window_cached_compact, route_window_delta_compact,
                route_window_delta_cached_compact,
                route_window_full_compact)
            for Wp, Bp, Bm, P, dC in compact_missing:
                label = f"warm W{Wp}xB{Bp}" \
                    + (f"mB{Bm}" if Bm is not None else "") \
                    + (f"d{dC}" if dC is not None else "") + f"c{P}"
                dkw = dict(delta_match_cap=_DELTA_MATCH_CAP,
                           delta_fanout_cap=_DELTA_FANOUT_CAP,
                           d_payload_cap=self._delta_payload_cap(Bp))
                with ctx_of(label):
                    if Bm is None:
                        enc = np.zeros((Wp, Bp, self.max_levels),
                                       np.int32)
                        z = np.zeros((Wp, Bp), np.int32)
                        zb = np.zeros((Wp, Bp), bool)
                        if backend == "shapes":
                            if dC is None:
                                r = rt(route_window_full_compact)(
                                    tables, wc(), enc, z, zb, z,
                                    strat, fanout_cap=self.fanout_cap,
                                    slot_cap=self.slot_cap,
                                    payload_cap=P)
                            else:
                                r = rt(route_window_delta_compact)(
                                    tables, dummy_delta(dC), wc(),
                                    enc, z, zb, z, strat,
                                    fanout_cap=self.fanout_cap,
                                    slot_cap=self.slot_cap,
                                    payload_cap=P, **dkw)
                        else:   # trie compact plans are single-batch
                            kw = dict(frontier_cap=self.frontier_cap,
                                      match_cap=self.match_cap,
                                      fanout_cap=self.fanout_cap,
                                      slot_cap=self.slot_cap,
                                      payload_cap=P)
                            if dC is None:
                                r = rt(route_step_compact)(
                                    tables, wc(), enc[0], z[0],
                                    zb[0], z[0], strat, **kw)
                            else:
                                r = rt(route_step_delta_compact)(
                                    tables, dummy_delta(dC), wc(),
                                    enc[0], z[0], zb[0], z[0], strat,
                                    **kw, **dkw)
                    else:
                        args = (np.full((Bm, self.max_levels), I.PAD,
                                        np.int32),
                                np.zeros(Bm, np.int32),
                                np.zeros(Bm, bool),
                                np.full((Bp, match_width), -1, np.int32),
                                np.zeros(Bp, np.int32),
                                np.zeros(Bp, bool))
                        dargs = () if dC is None else (
                            np.full((Bp, _DELTA_MATCH_CAP), -1,
                                    np.int32),
                            np.zeros(Bp, np.int32), np.zeros(Bp, bool))
                        pos = (np.full(Bm, Bp, np.int32),)
                        if backend == "shapes":
                            inv = np.zeros((Wp, Bp), np.int32)
                            mh = np.zeros((Wp, Bp), np.int32)
                            if dC is None:
                                r = rt(route_window_cached_compact)(
                                    tables, wc(), *args, *pos, inv,
                                    mh, strat,
                                    fanout_cap=self.fanout_cap,
                                    slot_cap=self.slot_cap,
                                    payload_cap=P)
                            else:
                                r = rt(
                                    route_window_delta_cached_compact)(
                                    tables, dummy_delta(dC), wc(),
                                    *args, *dargs, *pos, inv, mh,
                                    strat, fanout_cap=self.fanout_cap,
                                    slot_cap=self.slot_cap,
                                    payload_cap=P, **dkw)
                        else:
                            inv = np.zeros(Bp, np.int32)
                            mh = np.zeros(Bp, np.int32)
                            kw = dict(frontier_cap=self.frontier_cap,
                                      match_cap=self.match_cap,
                                      fanout_cap=self.fanout_cap,
                                      slot_cap=self.slot_cap,
                                      payload_cap=P)
                            if dC is None:
                                r = rt(route_step_cached_compact)(
                                    tables, wc(), *args, *pos, inv,
                                    mh, strat, **kw)
                            else:
                                r = rt(route_step_delta_cached_compact)(
                                    tables, dummy_delta(dC), wc(),
                                    *args, *dargs, *pos, inv, mh,
                                    strat, **kw, **dkw)
                    jax.block_until_ready(r.compact.offsets)
                self._warm_classes.add(
                    ck(sig, Wp, Bp, Bm=Bm, dC=dC, P=P))

        async def run():
            try:
                await loop.run_in_executor(None, warm)
            except Exception:  # noqa: BLE001 — classes stay cold, retry
                import logging
                logging.getLogger("emqx.device").exception(
                    "class warm-compile failed; affected classes stay "
                    "host-routed until the next attempt")
            finally:
                self._fuse_warm_task = None

        from emqx_tpu.broker.supervise import guard_task
        self._fuse_warm_task = guard_task(loop.create_task(run()),
                                          "device-class-warm",
                                          self.node.metrics)


    def preencode_burst(self, topics: list) -> None:
        """ISSUE 11: intern a read burst's topics in ONE vectorized
        native pass (split + hash + id-probe in C over the unique
        strings), memoized for prepare_window's encode. The memo is
        replaced wholesale per burst (no growth) and is only consumed
        while the intern table length is unchanged — intern ids are
        append-only, so equal length proves bit-identical encodings."""
        from emqx_tpu.ops.match import encode_topics_str
        uniq = list(dict.fromkeys(topics))
        try:
            enc, lens, dollar, too_long = encode_topics_str(
                self.intern, uniq, self.max_levels)
        except Exception:  # noqa: BLE001 — a failed pre-encode only
            self._burst_enc = None        # means the window re-encodes
            return
        self._burst_enc = ({t: i for i, t in enumerate(uniq)},
                           enc, lens, dollar, too_long,
                           len(self.intern))

    def _encode_publish_batch(self, topics: list):
        """One batch's topic encode: the burst memo's vectorized gather
        when every topic pre-encoded under the current intern length,
        else the normal one-native-call path (bit-identical outputs
        either way — the memo IS a cache of that call)."""
        from emqx_tpu.ops.match import encode_topics_str
        be = self._burst_enc
        if be is not None and be[5] == len(self.intern):
            idx_map, enc, lens, dollar, too_long = be[:5]
            idxs = [idx_map.get(t, -1) for t in topics]
            if -1 not in idxs:
                return (enc[idxs], lens[idxs], dollar[idxs],
                        too_long[idxs])
        return encode_topics_str(self.intern, topics, self.max_levels)

    def prepare_window(self, lives: list[list[Message]],
                       gate_cold: bool = True):
        """Stage 1 (event loop): encode 1..W micro-batches as one fused
        dispatch window (models.router_engine.route_window_full). The
        per-dispatch cost — dominant on high-latency links — is paid
        once for the whole window. When dedup is on, the window is also
        compacted to unique topics + match-cache hits (_plan_window) so
        the dispatch runs the NFA/shape hash only on miss lanes.

        `gate_cold=False` (sync callers: route_batch, tests, warmup)
        lets a cold cached class compile in-path instead of falling back
        to the plain program.

        Returns a _Handle, or None when the engine has no snapshot to
        serve (caller routes host-side; a background rebuild may be
        warming up).
        """
        self.poll_rebuild()
        if self._built is None or not lives:
            return None
        self._kick_class_warm()
        b = self._built
        subs = []
        encs = []
        Bp = 64
        for msgs in lives:
            # one native call per batch (split+hash+probe in C) — or
            # the burst memo's gather when submit_burst pre-encoded
            # this burst's topics (ISSUE 11); word lists are tokenized
            # lazily in _consume_one only when the delta-trie path
            # actually needs them
            enc, lens, dollar, too_long = self._encode_publish_batch(
                [m.topic for m in msgs])
            subs.append((msgs, None, too_long))
            encs.append((enc, lens, dollar))
            Bp = max(Bp, self._batch_class(len(msgs)))
        if len(lives) > 1:
            # fused windows run ONLY in the warmed (W, Bp) top standard
            # class: any other pair would cold-compile on the serving
            # path (padding compute is the price of never stalling)
            Bp = max(Bp, self._STD_CLASSES[-1][1])
        for Wp in self._W_CLASSES:
            if len(lives) <= Wp:
                break
        else:
            Wp = _next_pow2(len(lives))
        W = len(lives)
        enc4 = np.full((Wp, Bp, self.max_levels), I.PAD, np.int32)
        len4 = np.zeros((Wp, Bp), np.int32)
        dol4 = np.zeros((Wp, Bp), bool)
        for k, (enc, lens, dollar) in enumerate(encs):
            n = enc.shape[0]
            enc4[k, :n] = enc
            len4[k, :n] = lens
            dol4[k, :n] = dollar
        h = _Handle(subs, b, self.device_shared_active())
        h.enc = (enc4, len4, dol4)
        seq_trie = b.backend != "shapes" and Wp > 1
        # degradation ladder rung 1 (ISSUE 6): with the cache_insert or
        # overlay_apply breaker open, the reuse layers stand down and
        # this window dispatches the PLAIN program — device-plain is
        # the middle rung between full-featured and host-trie
        degraded = self.sup is not None and not self.sup.reuse_enabled()
        if not seq_trie and not degraded:
            # delta overlay for this dispatch (None = host fallback for
            # post-snapshot filters, exactly the pre-overlay behavior).
            # The sequential multi-batch trie window has no single fused
            # program to hang the overlay on — rare direct-caller path.
            h.delta = self._gate_delta(Wp, Bp, gate_cold)
        if self.dedup and not degraded:
            h.plan, h.cache_info = self._plan_window(b, enc4, len4, dol4,
                                                     gate_cold, h.delta)
        if not degraded and not (seq_trie and h.plan is None):
            # CSR readback class for this dispatch (None = dense). The
            # excluded case is the rare plain multi-batch trie window,
            # which dispatches sequential steps and stacks host-side —
            # no single fused program to hang the compaction on.
            h.pcap = self._gate_compact(Wp, Bp, h.plan, gate_cold,
                                        h.delta)
        self._outstanding += 1
        if self.ledger is not None:
            # pin sentinel (ISSUE 8): this handle pins the snapshot —
            # a pin outliving pin_warn_windows prepared windows fires
            # the stale-pin warning (counter + hook + recorder event)
            self.ledger.note_window()
            self.ledger.pin(id(h), h)
        self.node.metrics.inc("routing.device.windows")
        self.node.metrics.inc("routing.device.window_subs", W)
        b = self._built
        if b is not None and b.cover is not None:
            # windows matched against the covering set (expansion fused
            # after the match stage), and the per-window match-work
            # saved: covered filters the root match never visited
            self.node.metrics.inc("pipeline.cover.windows")
            self.node.metrics.inc("pipeline.cover.filters_skipped",
                                  b.cover.n_covered)
        tele = getattr(self.node, "pipeline_telemetry", None)
        if tele is not None:
            # batch occupancy per shape class: how much of the padded
            # (Wp, Bp) program each dispatch actually fills — low fill
            # means padding compute dominates (shrink the window /
            # batch class), high fill means the class is saturated
            for msgs in lives:
                tele.record_occupancy(f"b{Bp}", len(msgs) / Bp)
            if Wp > 1:
                tele.record_occupancy(f"w{Wp}", W / Wp)
        return h

    # ---- device-side tracing (SURVEY §5.1 mapping) -------------------
    def start_device_trace(self, log_dir: str) -> bool:
        """Begin a jax.profiler trace capturing the device-side route
        steps (each dispatch is annotated as one profiler step, so the
        trace decomposes device execution from host/relay time). Returns
        False when the backend has no profiler support."""
        import jax
        try:
            jax.profiler.start_trace(log_dir)
            self._tracing = True
            return True
        except Exception:  # noqa: BLE001 — relay backends may lack it
            return False

    def stop_device_trace(self) -> None:
        import jax
        if getattr(self, "_tracing", False):
            self._tracing = False
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass

    # ---- ISSUE 9: donation + async readback helpers ---------------------
    def _rt(self, fn):
        """The serving-path variant of a fused route program: at
        dispatch_depth >= 2 the cursors slot is DONATED (the ping-pong
        cursor buffers reuse HBM across windows; the output is
        re-adopted under the snapshot identity guard in
        _dispatch_inner). Depth 1 returns the plain program — the
        pre-ISSUE-9 jit cache, bit-exact. The warm passes resolve
        through this SAME chooser, so the program a class warms is the
        program the serving path dispatches."""
        if not self._pipelined:
            return fn
        from emqx_tpu.models.router_engine import donating
        return donating(fn)

    def _warm_cursors(self, cursors):
        """Cursors argument for off-serving-path calls (class warms,
        pre-swap warms): at dispatch_depth >= 2 the serving programs
        donate their cursors slot, so a warm must never hand over a
        live buffer — it passes a throwaway device_put zeros of the
        same shape instead. Device-array inputs share the jit-cache
        entry with the serving call's (device_put arrays and jit
        outputs key identically; numpy inputs do NOT — measured), so
        the warm still covers the serving class. Depth 1 passes the
        live cursors through untouched, pre-ISSUE-9 exact. Reading
        .shape is safe even when a racing dispatch already donated the
        buffer away (aval metadata survives deletion)."""
        if not self._pipelined:
            return cursors
        import jax
        # hbm: transient — donated away by the warm call it feeds
        return jax.device_put(np.zeros(cursors.shape, np.int32))

    def _readback_planes(self, h) -> list:
        """The device arrays materialize will transfer for this handle
        — exactly those, so the async start never wastes link bandwidth
        on planes the CSR compaction made redundant (a later overflow
        fallback to the dense planes still transfers synchronously;
        correctness never depends on the prefetch)."""
        out = []
        res, cp = h.res, h.cres
        dp, dcp = h.dres, h.dcres
        if dp is not None:
            out += [dp.counts, dp.moverflow, dp.overflow]
            if dcp is not None:
                out += [dcp.offsets, dcp.counts3, dcp.row_overflow,
                        dcp.payload]
            else:
                out += [dp.fids, dp.rows, dp.opts]
        if cp is not None:
            out += [cp.offsets, cp.counts3, cp.row_overflow, cp.payload,
                    res.overflow, res.occur]
        else:
            out += [res.matches, res.rows, res.opts, res.shared_sids,
                    res.shared_rows, res.shared_opts, res.overflow,
                    res.occur]
            if h.cache_info is not None and self._match_cache is not None:
                out.append(res.match_counts)
        return out

    def _start_readback(self, h) -> None:
        """ISSUE 9: start the device→host transfer of every plane
        materialize will read, AT DISPATCH RETURN — the readback
        crosses the link while dispatch(W+1) computes, and materialize
        becomes consume-on-arrival. The in-flight result buffers
        register with the HBM ledger under `pipeline_buffers` (they are
        pinned HBM for up to dispatch_depth windows; release is
        automatic when the handle dies). Backends without async copies
        keep the synchronous transfer in materialize — the prefetch is
        an overlap optimization, never a correctness input."""
        if self.ledger is not None:
            self._hold("pipeline_buffers",
                       (h.res, h.cres, h.dres, h.dcres))
        for a in self._readback_planes(h):
            try:
                a.copy_to_host_async()
            except AttributeError:
                return      # backend has no async copy: sync readback
            except Exception:  # noqa: BLE001 — best-effort prefetch
                return

    def dispatch(self, h) -> None:
        """Stage 2 (executor thread): run the jitted route step. On a
        dispatch relay this blocks on HTTP; on co-located hardware it is an
        async enqueue — either way it is off the event loop. Under an
        active jax.profiler trace every dispatch is one annotated step.
        The span lands in the `dispatch` stage histogram — or
        `dispatch_cached` for a deduplicated/cache-backed dispatch, so
        the cached-vs-uncached match latency split is directly
        comparable in the exported percentiles; any jit-cache miss
        inside it is attributed to this window's (W, B[, Bm]) class as
        an IN-PATH recompile (the kind the warm gates exist to
        prevent)."""
        tele = getattr(self.node, "pipeline_telemetry", None)
        stage = "dispatch" if h.plan is None else "dispatch_cached"
        t0 = time.perf_counter()
        try:
            if tele is not None:
                Wp, Bp = h.enc[0].shape[0], h.enc[0].shape[1]
                label = f"dispatch W{Wp}xB{Bp}" if h.plan is None \
                    else f"dispatch W{Wp}xB{Bp}mB{h.plan.Bm}cached"
                with tele.compile_context(label):
                    self._dispatch_annotated(h)
            else:
                self._dispatch_annotated(h)
            if self._pipelined and h.res is not None:
                # ISSUE 9: start the async readback while this thread
                # still owns the dispatch slot — the transfer hides
                # under the NEXT window's dispatch
                self._start_readback(h)
        finally:
            if tele is not None:
                tele.observe_stage(stage, time.perf_counter() - t0)
            self._rec_span(h.trace, stage, t0, track="dispatch",
                           meta={"W": h.enc[0].shape[0],
                                 "B": h.enc[0].shape[1]})

    def _dispatch_annotated(self, h) -> None:
        if getattr(self, "_tracing", False):
            import jax
            # the step_num IS the window's flight-recorder trace id
            # (ISSUE 7): a jax.profiler capture's device timeline joins
            # the host-side Perfetto dump on the same key. Windows with
            # no trace (knob off) keep the old private counter.
            step = h.trace
            if not step:
                self._step_num = getattr(self, "_step_num", 0) + 1
                step = self._step_num
            with jax.profiler.StepTraceAnnotation("route_step",
                                                  step_num=step):
                self._dispatch_inner(h)
        else:
            self._dispatch_inner(h)

    def _msg_hashes(self, msgs, strat_id) -> list[int]:
        from emqx_tpu.ops.shared import (STRATEGY_HASH_CLIENT,
                                         STRATEGY_HASH_TOPIC,
                                         STRATEGY_ROUND_ROBIN)
        if strat_id == STRATEGY_HASH_TOPIC:
            return [zlib.crc32(m.topic.encode()) & 0x7FFFFFFF
                    for m in msgs]
        if strat_id == STRATEGY_HASH_CLIENT:
            return [zlib.crc32((m.from_ or "").encode()) & 0x7FFFFFFF
                    for m in msgs]
        if strat_id == STRATEGY_ROUND_ROBIN:
            return [0] * len(msgs)
        return [(id(m) >> 4) & 0x7FFFFFFF for m in msgs]  # random

    def _dispatch_inner(self, h) -> None:
        """Select + run the fused program for this window: the plain
        step/window, with up to three optional fused dimensions — dedup
        plan (ISSUE 2), CSR readback (ISSUE 3), delta overlay
        (ISSUE 4) — each independently warm-gated at prepare."""
        if self.sup is not None:
            # ISSUE 6 injection point: an exception here propagates to
            # the batcher's consumer, which notes the fault, replays the
            # window host-side and advances the dispatch breaker; a hang
            # is caught by the consumer's watchdog deadline
            self.sup.fire("dispatch")
        from emqx_tpu.models import router_engine as RE
        from emqx_tpu.ops.shared import (STRATEGIES, STRATEGY_ROUND_ROBIN)
        broker = self.broker
        # pin the table/cursor pair ONCE for this whole dispatch: a
        # watchdog timeout (ISSUE 6) abandons the handle while this
        # thread is still running, which releases the swap gate — a
        # zombie dispatch must neither mix old and new tables mid-call
        # nor clobber the new snapshot's cursors with a late write (the
        # identity guard at the end, mirroring the mesh's `_builts is
        # h.built` discipline in parallel/serving.py)
        tables, cursors = self._tables, self._cursors
        sig = self._cur_sig
        enc4, len4, dol4 = h.enc
        Wp, Bp = enc4.shape[0], enc4.shape[1]
        strat_id = STRATEGIES.get(broker.shared_strategy,
                                  STRATEGY_ROUND_ROBIN)
        msg_hash = np.zeros((Wp, Bp), np.int32)
        for k, (msgs, _w, _t) in enumerate(h.subs):
            msg_hash[k, :len(msgs)] = self._msg_hashes(msgs, strat_id)
        strat = np.int32(strat_id)
        p, P, ov = h.plan, h.pcap, h.delta
        dC = ov.cap if ov is not None else None
        shapes = h.built.backend == "shapes"
        kw = dict(fanout_cap=self.fanout_cap, slot_cap=self.slot_cap)
        if not shapes:
            kw.update(frontier_cap=self.frontier_cap,
                      match_cap=self.match_cap)
        dkw = {} if ov is None else dict(
            delta_match_cap=_DELTA_MATCH_CAP,
            delta_fanout_cap=_DELTA_FANOUT_CAP)
        ckw = {} if P is None else dict(payload_cap=P)
        if P is not None and ov is not None:
            ckw["d_payload_cap"] = self._delta_payload_cap(Bp)

        if not shapes and p is None and ov is None and P is None:
            # plain trie: no window variant — dispatch sub-batches
            # sequentially and stack (rare path: >SHAPE_CAP distinct
            # shapes with every fused dimension disabled or cold)
            import jax.numpy as jnp
            outs = []
            step_fn = self._rt(RE.route_step)
            for k in range(Wp):
                r = step_fn(tables, cursors, enc4[k],
                            len4[k], dol4[k], msg_hash[k], strat,
                            **kw)
                cursors = r.new_cursors
                outs.append(r)
            if self._tables is tables:   # no swap raced this dispatch
                # adopted cursors are fresh jit outputs, not the held
                # device_put array — re-register so the ledger's
                # cursor bytes track the LIVE array across dispatches
                self._cursors = self._hold("snapshot_cursors", cursors)
            h.res = type(outs[0])(*[jnp.stack([getattr(o, f)
                                              for o in outs])
                                    for f in outs[0]._fields])
            return

        if p is not None:
            # deduplicated dispatch: match only the miss lanes, merge
            # with the cache-hit base rows, scatter back to window width
            # before the cursor-dependent post stage (trie plans are
            # single-batch: _plan_window guarantees Wp == 1 there)
            base = (p.miss_topics, p.miss_lens, p.miss_dollar,
                    p.base_m, p.base_c, p.base_o)
            dbase = () if ov is None else (p.base_dm, p.base_dc,
                                           p.base_do)
            tail = (p.miss_pos, p.inv if shapes else p.inv[0],
                    msg_hash if shapes else msg_hash[0], strat)
            if ov is not None:
                fn = (RE.route_window_delta_cached_compact
                      if P is not None
                      else RE.route_window_delta_cached) if shapes else \
                    (RE.route_step_delta_cached_compact if P is not None
                     else RE.route_step_delta_cached)
                out = self._rt(fn)(tables, ov.dev, cursors, *base,
                                   *dbase, *tail, **kw, **dkw, **ckw)
            else:
                fn = (RE.route_window_cached_compact if P is not None
                      else RE.route_window_cached) if shapes else \
                    (RE.route_step_cached_compact if P is not None
                     else RE.route_step_cached)
                out = self._rt(fn)(tables, cursors, *base, *tail,
                                   **kw, **ckw)
            self.node.metrics.inc("routing.device.cached_windows")
            warm_key = self._class_key(sig, Wp, Bp, Bm=p.Bm,
                                       dC=dC, P=P)
        else:
            args4 = (enc4, len4, dol4, msg_hash) if shapes else \
                (enc4[0], len4[0], dol4[0], msg_hash[0])
            if ov is not None:
                fn = (RE.route_window_delta_compact if P is not None
                      else RE.route_window_delta) if shapes else \
                    (RE.route_step_delta_compact if P is not None
                     else RE.route_step_delta)
                out = self._rt(fn)(tables, ov.dev, cursors, *args4,
                                   strat, **kw, **dkw, **ckw)
            else:
                fn = (RE.route_window_full_compact if P is not None
                      else RE.route_window_full) if shapes else \
                    RE.route_step_compact   # plain trie without P
                                            # returned above
                out = self._rt(fn)(tables, cursors, *args4, strat,
                                   **kw, **ckw)
            warm_key = self._class_key(sig, Wp, Bp, dC=dC,
                                       P=P)

        # unwrap the result family; every remaining variant is
        # window-shaped except the bare cached trie step
        if isinstance(out, RE.CompactDeltaRouteResult):
            res = out.dres.res
            h.dres = out.dres.dp
            h.cres = out.compact
            h.dcres = out.d_compact
        elif isinstance(out, RE.DeltaRouteResult):
            res = out.res
            h.dres = out.dp
        elif isinstance(out, RE.CompactRouteResult):
            res = out.res
            h.cres = out.compact
        else:
            res = out
            if not shapes and p is not None:
                import jax.numpy as jnp
                res = type(res)(*[jnp.stack([getattr(res, f)])
                                  for f in res._fields])
        if self._tables is tables:   # no swap raced this dispatch
            self._cursors = self._hold("snapshot_cursors",
                                       res.new_cursors[-1])
        self._warm_classes.add(warm_key)
        h.res = res

    def _materialize_delta(self, h) -> int:
        """Read back the delta-overlay planes (when this dispatch fused
        the overlay): the small count/overflow planes always, plus
        either the delta CSR payload or — on delta payload overflow, or
        without a payload class — the dense fid/row/opts planes of the
        same program. Returns the transferred byte count (billed into
        the window's readback bucket by the caller)."""
        dp = h.dres
        if dp is None:
            return 0
        counts = np.asarray(dp.counts)
        mov = np.asarray(dp.moverflow)
        ovf = np.asarray(dp.overflow)
        nbytes = counts.nbytes + mov.nbytes + ovf.nbytes
        dcp = h.dcres
        if dcp is not None:
            off = np.asarray(dcp.offsets)
            c3 = np.asarray(dcp.counts3)
            rovf = np.asarray(dcp.row_overflow)
            nbytes += off.nbytes + c3.nbytes + rovf.nbytes
            if rovf.any():
                self.node.metrics.inc(
                    "routing.device.delta_compact_overflow")
                dcp = None      # dense delta planes below
            else:
                pay = np.asarray(dcp.payload)
                nbytes += pay.nbytes
                h.np_delta = _DeltaCsr(off, c3, pay, counts, mov, ovf)
                return nbytes
        fids = np.asarray(dp.fids)
        rows = np.asarray(dp.rows)
        opts = np.asarray(dp.opts)
        nbytes += fids.nbytes + rows.nbytes + opts.nbytes
        h.np_delta = _DeltaRes(fids, counts, mov, rows, opts, ovf)
        return nbytes

    def _delta_cache_fields(self, h, lane: int, Bp: int) -> tuple:
        """Fields 3.. of a match-cache row under the delta overlay:
        (delta fids, delta count, MATCH-level delta overflow, encoded
        topic, len, is_dollar) — the overlay base triple in FID space
        (stable across overlay row reassignment) plus the topic encoding
        the delta-aware invalidation matches against. Empty () with the
        overlay knob off, so the pre-overlay 3-tuple rows (and their
        tests) are bit-exact."""
        if not self.delta_overlay:
            return ()
        enc4, len4, dol4 = h.enc
        w, bb = divmod(lane, Bp)
        topic = (enc4[w, bb].copy(), int(len4[w, bb]),
                 bool(dol4[w, bb]))
        nd = h.np_delta
        if nd is None:
            if self._delta_filter:
                # overlay exists but this dispatch ran without it (cold
                # class): the delta part of this topic is UNKNOWN — a
                # None marker keeps the main row usable while making the
                # row ineligible as a cached delta base (_plan_window)
                return (None, 0, False) + topic
            dm = np.full(_DELTA_MATCH_CAP, -1, np.int32)
            return (dm, 0, False) + topic
        if isinstance(nd, _DeltaCsr):
            o = int(nd.off[w, bb])
            cm = int(nd.c3[w, bb, 0])
            dm = np.full(_DELTA_MATCH_CAP, -1, np.int32)
            dm[:cm] = nd.pay[w, o:o + cm]
        else:
            dm = nd.fids[w, bb].copy()
        return (dm, int(nd.counts[w, bb]), bool(nd.moverflow[w, bb])) \
            + topic

    def materialize(self, h) -> None:
        """Stage 3 (executor thread): blocking device→host readbacks.
        Every field is [W, ...] (window-stacked). Also the match-cache
        population point: the rows for this window's cache-missed unique
        topics come straight out of the readback the consume stage needs
        anyway — no extra device round trip.

        With a payload class attached (h.cres — ISSUE 3) the transfer is
        the CSR planes (offsets + counts3 + flat payload) plus the small
        overflow/occur planes, instead of the padded match/fan-out/shared
        planes: >90% of the dense transfer is `-1` padding at low
        fan-out. A window whose entries outgrew its payload class reads
        the dense planes of the SAME dispatch instead (they are outputs
        of the same fused program — the fallback re-dispatches nothing).
        Both paths meter actual transferred bytes into the
        pipeline.readback.* counters all four exporters carry."""
        tele = getattr(self.node, "pipeline_telemetry", None)
        metrics = self.node.metrics
        t0 = time.perf_counter()
        corrupt = None
        if self.sup is not None:
            # ISSUE 6 injection point (executor thread): exceptions
            # propagate to the consumer (fault noted + window replayed
            # host-side), hangs are caught by its watchdog deadline,
            # and "corrupt" shape-corrupts the readback below — the
            # consume stage then blows up exactly like a real
            # wrong-shape transfer would, and the supervisor's replay
            # path must recover the window
            corrupt = self.sup.fire("materialize", corrupt_ok=True)
        res = h.res
        cp = h.cres
        delta_bytes = self._materialize_delta(h)
        csr_probe_bytes = 0
        if cp is not None:
            off = np.asarray(cp.offsets)
            c3 = np.asarray(cp.counts3)
            rovf = np.asarray(cp.row_overflow)
            # EWMA learns from the offsets either way — on the overflow
            # fallback the totals are exactly what resizes the class up
            self._note_payload(off.shape[1] - 1, off[:, -1])
            if rovf.any():
                metrics.inc("routing.device.compact_overflow")
                # the CSR probe planes already crossed the link; bill
                # them to the dense window below or the exported
                # reduction overstates exactly the overflowing workloads
                csr_probe_bytes = off.nbytes + c3.nbytes + rovf.nbytes
                h.cres = None           # dense readback below
            else:
                overflow = np.asarray(res.overflow)
                occur = np.asarray(res.occur)
                pay = np.asarray(cp.payload)
                h.np_res = _CsrRes(off, c3, pay, overflow, occur)
                metrics.inc("pipeline.readback.bytes.compact",
                            off.nbytes + c3.nbytes + pay.nbytes
                            + overflow.nbytes + occur.nbytes
                            + delta_bytes)
                metrics.inc("pipeline.readback.windows.compact")
                info = h.cache_info
                if info is not None and self._match_cache is not None:
                    # cache population from the CSR view: a reconstructed
                    # row is the hole-compacted valid prefix + -1 pad.
                    # Equivalent to the dense row by the hole-insensitivity
                    # contract (ops/compact.py): fan-out/shared expansion
                    # and consume only see valid entries in order, and the
                    # stored count cm == match_counts for both backends.
                    mw = h.built.match_width
                    Bp = off.shape[1] - 1
                    o_flat = overflow.reshape(-1)
                    items = []
                    for key, lane in info.inserts:
                        w, bb = divmod(lane, Bp)
                        cm = int(c3[w, bb, 0])
                        row = np.full(mw, -1, np.int32)
                        row[:cm] = pay[w, off[w, bb]:off[w, bb] + cm]
                        items.append((key, (row, cm, bool(o_flat[lane]))
                                      + self._delta_cache_fields(h, lane,
                                                                 Bp)))
                    self._cache_put(info.sid, items,
                                    version=info.version)
                if corrupt:
                    self._corrupt_readback(h)
                if tele is not None:
                    tele.observe_stage("materialize",
                                       time.perf_counter() - t0)
                self._rec_span(h.trace, "materialize", t0,
                               track="materialize")
                return
        h.np_res = (np.asarray(res.matches), np.asarray(res.rows),
                    np.asarray(res.opts), np.asarray(res.shared_sids),
                    np.asarray(res.shared_rows), np.asarray(res.shared_opts),
                    np.asarray(res.overflow), np.asarray(res.occur))
        dense_bytes = sum(a.nbytes for a in h.np_res) + csr_probe_bytes \
            + delta_bytes
        info = h.cache_info
        if info is not None and self._match_cache is not None:
            # the match_counts readback is only paid when there are rows
            # to insert — consume never reads it, so windows with no
            # cache work skip the extra [W, B] transfer entirely
            h.np_counts = np.asarray(res.match_counts)
            dense_bytes += h.np_counts.nbytes
            matches, overflow = h.np_res[0], h.np_res[6]
            Bp = matches.shape[1]
            mw = matches.shape[-1]
            mflat = matches.reshape(-1, mw)
            cflat = h.np_counts.reshape(-1)
            oflat = overflow.reshape(-1)
            # overflow cached as the COMBINED flag (match|fanout|slot):
            # all three are pure functions of (snapshot, topic), and
            # post_match re-ORs the fan-out/slot parts, so the merged
            # result stays bit-identical to a cold match
            self._cache_put(
                info.sid,
                [(k, (mflat[i].copy(), int(cflat[i]), bool(oflat[i]))
                  + self._delta_cache_fields(h, i, Bp))
                 for k, i in info.inserts], version=info.version)
        metrics.inc("pipeline.readback.bytes.dense", dense_bytes)
        metrics.inc("pipeline.readback.windows.dense")
        if corrupt:
            self._corrupt_readback(h)
        if tele is not None:
            tele.observe_stage("materialize", time.perf_counter() - t0)
        self._rec_span(h.trace, "materialize", t0, track="materialize")

    def _rec_span(self, trace_id: int, name: str, t0: float, *,
                  track: str, parent: int = 0, meta=None) -> None:
        """Record one [t0, now] span on the flight recorder (no-op
        when tracing is off or the window carries no trace)."""
        rec = getattr(self.node, "flight_recorder", None)
        if rec is not None and trace_id:
            rec.record(trace_id, name, t0, time.perf_counter(),
                       track=track, parent=parent, meta=meta)

    def _corrupt_readback(self, h) -> None:
        """Apply the injected corrupt-shape fault: truncate the window
        axis of the host views so the consume stage fails exactly like
        a real wrong-shape readback (an IndexError at the first plane
        access) — the supervisor's window replay must then re-route the
        window host-side with zero loss."""
        nr = h.np_res
        if isinstance(nr, _CsrRes):
            h.np_res = _CsrRes(nr.off[:0], nr.c3[:0], nr.pay[:0],
                               nr.overflow[:0], nr.occur[:0])
        elif nr is not None:
            h.np_res = tuple(a[:0] for a in nr)

    def _cache_put(self, sid, items, version=None) -> None:
        """Match-cache population with the cache_insert fault domain
        (ISSUE 6): under supervision a raising insert is CONTAINED —
        the cache is an optimization, so a cache bug must cost the
        reuse layer (breaker opens → rung 1, plain dispatches), never
        the window. Without supervision the exception propagates out of
        materialize exactly as before (dispatch_failed → host
        fallback)."""
        cache = self._match_cache
        if cache is None:
            return
        sup = self.sup
        if sup is None:
            cache.put_many(sid, items, version=version)
            return
        try:
            sup.fire("cache_insert")
            cache.put_many(sid, items, version=version)
        except Exception as e:  # noqa: BLE001 — contained fault domain
            sup.note_fault("cache_insert", e)
        else:
            sup.note_ok("cache_insert")

    def finish_sub(self, h, k: int, defer: bool = True) -> list[int]:
        """Stage 4 (event loop): consume sub-batch k of the window into
        deliveries. Releases one handle reference (deferred to plan
        completion when the lanes own the deliveries — the snapshot
        swap gate must cover in-flight lane work).

        The clean common case — local node, no delta/dirty filters, no
        shared involvement for the message — is consumed by ONE
        vectorized pre-pass over the whole sub-batch
        (_consume_batch_fast): the per-message Python walk over
        match/fan-out rows used to cost more than the entire host route
        (24ms vs 22ms per 1024-batch at 50k filters), which made the
        device unable to win e2e no matter how fast the chip was.

        With the delivery lanes active (ISSUE 5; `defer=True` and a
        DeliveryLanePool on the node), this stage only BUILDS the
        delivery plan: clean messages' rows are bucketed into
        session-affine lanes, slow messages become ordered closures
        behind the plan's barrier, and the returned LaneCounts is
        back-filled when the plan completes (the `deliver` stage
        histogram then measures plan construction; the delivery time
        itself lands in the per-lane deliver_lane{i} histograms).
        `defer=False` (sync callers: route_batch/finish) keeps the
        inline consume — counts are final on return."""
        tele = getattr(self.node, "pipeline_telemetry", None)
        t0 = time.perf_counter()
        plan = None
        deferred = False
        try:
            nr = h.np_res
            msgs, words_list, too_long = h.subs[k]
            b = h.built
            csr = isinstance(nr, _CsrRes)
            if csr:
                overflow_k, occur_k = nr.overflow[k], nr.occur[k]
            else:
                (matches, rows, opts, shared_sids, shared_rows,
                 shared_opts, overflow, occur) = nr
                overflow_k, occur_k = overflow[k], occur[k]
            nd = h.np_delta
            d_counts_k = None
            if nd is not None:
                # a delta-plane overflow (match cap or fan cap) means
                # the message's post-snapshot matches are incomplete:
                # full host fallback, same contract as the main planes
                overflow_k = overflow_k | nd.overflow[k]
                d_counts_k = nd.counts[k]
            pending = self._delta_pending(h.delta)
            if h.dev_shared and b.n_slots:
                self._writeback_cursors(occur_k, b)
            metrics = self.node.metrics
            broker = self.broker
            if defer:
                pool = getattr(self.node, "deliver_lanes", None)
                if pool is not None and pool.active():
                    plan = pool.new_plan(msgs)  # None without a loop
                    if plan is not None:
                        plan.routed_device = True
                        # causal propagation (ISSUE 7): the plan
                        # carries its sub-batch's trace, so lane items
                        # record against the right window — and KEEP it
                        # across a lane-worker restart (queue items
                        # hold the plan, the plan holds the trace)
                        plan.trace = h.sub_traces[k] \
                            if h.sub_traces and k < len(h.sub_traces) \
                            else h.trace
            if csr:
                fast = self._consume_batch_fast_csr(
                    msgs, nr.off[k], nr.c3[k], nr.pay[k], too_long,
                    overflow_k, h.dev_shared, b, d_counts_k, pending,
                    plan=plan)
            else:
                fast = self._consume_batch_fast(
                    msgs, matches[k], rows[k], opts[k], shared_sids[k],
                    too_long, overflow_k, h.dev_shared, b, d_counts_k,
                    pending, plan=plan)
            dev_shared, ov = h.dev_shared, h.delta
            counts: list[int] = []
            for i, msg in enumerate(msgs):
                f_i = fast[i]
                if f_i is DEFERRED:
                    counts.append(0)      # back-filled at plan finalize
                    continue
                if f_i is not None:
                    counts.append(f_i)
                    continue
                if plan is not None:
                    # slow path under lanes: an ordered closure behind
                    # the plan's barrier — it runs with every prior
                    # fast delivery done and nothing overtaking, the
                    # exact interleaving of the inline loop
                    counts.append(0)
                    plan.add_slow(i, self._make_slow_fn(
                        h, k, i, msg, b, csr, nr, nd, words_list,
                        too_long, overflow_k, dev_shared, ov, pending))
                    continue
                if too_long[i] or overflow_k[i]:
                    metrics.inc("routing.device.host_fallback")
                    counts.append(broker._route(
                        msg, self.router.match(msg.topic)))
                    continue
                if csr:
                    # per-message CSR views: the valid entries of every
                    # plane in order, no pad — _consume_one's walk is
                    # layout-agnostic (it skips -1 and slices fan rows
                    # by the built segment lengths, which the payload's
                    # fan section concatenates exactly)
                    row6 = csr_slices(nr.off[k], nr.c3[k], nr.pay[k], i)
                else:
                    row6 = (matches[k][i], rows[k][i], opts[k][i],
                            shared_sids[k][i], shared_rows[k][i],
                            shared_opts[k][i])
                drow = None
                if nd is not None:
                    if isinstance(nd, _DeltaCsr):
                        drow = csr_slices(nd.off[k], nd.c3[k],
                                          nd.pay[k], i)[:3]
                    else:
                        drow = (nd.fids[k][i], nd.rows[k][i],
                                nd.opts[k][i])
                counts.append(self._consume_one(
                    msg, *row6,
                    words_list[i] if words_list is not None else None,
                    h.dev_shared, b, drow=drow, ov=h.delta,
                    pending=pending))
            metrics.inc("routing.device.batches")
            if plan is not None:
                out = LaneCounts(counts)
                out.plan = plan
                plan.target = out
                # the handle stays pinned until the lanes finish: slow
                # closures read live engine state against this snapshot,
                # and _try_swap must not rebase it under them
                plan.add_done_callback(lambda: self._release_one(h))
                pool.submit(plan)
                deferred = True
                return out
            return counts
        finally:
            if tele is not None:
                tele.observe_stage("deliver", time.perf_counter() - t0)
            self._rec_span(h.sub_traces[k]
                           if h.sub_traces and k < len(h.sub_traces)
                           else h.trace,
                           "deliver", t0, track="consume")
            if not deferred:
                self._release_one(h)

    def _make_slow_fn(self, h, k: int, i: int, msg, b, csr, nr, nd,
                      words_list, too_long, overflow_k, dev_shared,
                      ov, pending):
        """Build the deferred slow-path consume for one message (runs
        behind the plan barrier; the handle is pinned until then)."""
        def run() -> int:
            if too_long[i] or overflow_k[i]:
                self.node.metrics.inc("routing.device.host_fallback")
                return self.broker._route(
                    msg, self.router.match(msg.topic))
            if csr:
                row6 = csr_slices(nr.off[k], nr.c3[k], nr.pay[k], i)
            else:
                row6 = (nr[0][k][i], nr[1][k][i], nr[2][k][i],
                        nr[3][k][i], nr[4][k][i], nr[5][k][i])
            drow = None
            if nd is not None:
                if isinstance(nd, _DeltaCsr):
                    drow = csr_slices(nd.off[k], nd.c3[k],
                                      nd.pay[k], i)[:3]
                else:
                    drow = (nd.fids[k][i], nd.rows[k][i],
                            nd.opts[k][i])
            return self._consume_one(
                msg, *row6,
                words_list[i] if words_list is not None else None,
                dev_shared, b, drow=drow, ov=ov, pending=pending)
        return run

    def _consume_batch_fast(self, msgs, m_k, r_k, o_k, ss_k, too_long,
                            overflow_k, dev_shared: bool, b,
                            d_counts_k=None, pending: bool = False,
                            plan=None):
        """Vectorized consume for provably-clean messages. Returns a list
        with per-message delivery counts, or None where the slow path
        must run. Clean requires, globally: standalone node (no cluster
        forward / cluster group sweep), no delta filters beyond the
        fused overlay (`pending`), no post-snapshot shared groups; per
        message: no too-long/overflow, no dirty/rich matched filter, no
        delta-overlay match, and no shared involvement (no device slot
        matched; no matched filter with host shared groups)."""
        if (self.broker.cluster is not None or pending
                or self.new_slots_by_filter):
            return [None] * len(msgs)
        B = len(msgs)
        mask = m_k[:B] >= 0
        mi = np.nonzero(mask)[0]
        fids = m_k[:B][mask]
        shared_any = (ss_k[:B] >= 0).any(axis=1)

        def fetch(row_msg, col):
            return r_k[row_msg, col], o_k[row_msg, col]

        return self._fast_deliver(msgs, mi, fids, too_long, overflow_k,
                                  shared_any, fetch, dev_shared, b,
                                  d_counts_k, plan=plan)

    def _consume_batch_fast_csr(self, msgs, off_k, c3_k, pay_k, too_long,
                                overflow_k, dev_shared: bool, b,
                                d_counts_k=None, pending: bool = False,
                                plan=None):
        """_consume_batch_fast over one window row's CSR planes: same
        clean-message proof and the same vectorized delivery walk, with
        the 2-D plane gathers replaced by flat payload gathers at each
        message's family base offsets."""
        if (self.broker.cluster is not None or pending
                or self.new_slots_by_filter):
            return [None] * len(msgs)
        B = len(msgs)
        cm = c3_k[:B, 0].astype(np.int64)
        cf = c3_k[:B, 1].astype(np.int64)
        cs = c3_k[:B, 2]
        base = off_k[:B].astype(np.int64)
        total_m = int(cm.sum())
        mi = np.repeat(np.arange(B), cm)
        if total_m:
            mcum = np.cumsum(cm) - cm
            fids = pay_k[np.arange(total_m) - np.repeat(mcum, cm)
                         + np.repeat(base, cm)]
        else:
            fids = np.zeros(0, np.int32)
        shared_any = cs[:B] > 0
        fbase = base + cm           # fan rows start, per message
        obase = base + cm + cf      # fan opts start, per message

        def fetch(row_msg, col):
            return (pay_k[fbase[row_msg] + col],
                    pay_k[obase[row_msg] + col])

        return self._fast_deliver(msgs, mi, fids, too_long, overflow_k,
                                  shared_any, fetch, dev_shared, b,
                                  d_counts_k, plan=plan)

    @staticmethod
    def _attribute_rows(mi_f, fids_f, seg, total: int):
        """Row attribution shared by the inline loop and the lane plan:
        within each message the fan-out rows are the concatenation of
        per-filter CSR segments in match order. Returns (row_msg, col,
        row_fid) — for every fan-out row, its message index, its column
        within that message's fan-out, and the filter it came from."""
        csum = np.cumsum(seg) - seg                # global exclusive
        starts = np.flatnonzero(np.r_[True, mi_f[1:] != mi_f[:-1]])
        base = np.repeat(csum[starts], np.diff(np.r_[starts,
                                                     mi_f.size]))
        within = csum - base                       # offset inside msg
        row_msg = np.repeat(mi_f, seg)
        ar = np.arange(total)
        row_local = ar - np.repeat(csum, seg)
        col = np.repeat(within, seg) + row_local
        row_fid = np.repeat(fids_f, seg)
        return row_msg, col, row_fid

    def _fast_deliver(self, msgs, mi, fids, too_long, overflow_k,
                      shared_any, fetch, dev_shared: bool, b,
                      d_counts_k=None, plan=None):
        """Shared tail of the vectorized fast consume (dense and CSR):
        per-message clean proof, row attribution, and delivery. `mi`/
        `fids` list every valid match (message index, filter id) in
        match order; `fetch(row_msg, col)` gathers the (sid, packed
        opts) of fan-out entry `col` within message `row_msg`.

        With `plan` attached (ISSUE 5: deliver lanes active) this stops
        looping entirely: the gathered (row_msg, sid, opt, fid) arrays
        are handed to the plan, which buckets them into session-affine
        lane slices — delivery (and the no-subscriber bookkeeping for
        these messages) then overlaps the next window's dispatch.
        `plan=None` is the inline A/B baseline (deliver_lanes=0 or no
        running loop): the per-row loop below, unchanged semantics."""
        broker = self.broker
        B = len(msgs)
        # per-fid host-side mask, memoized on (snapshot, dirty version)
        hostside = self._hostside_mask(b)

        slow = np.asarray(too_long[:B]) | (overflow_k[:B] != 0)
        if d_counts_k is not None:
            # overlay-matched messages walk the slow path (delta fan-out
            # is per-filter segmented like the main rows, but mixing the
            # two fid spaces into one vectorized gather isn't worth the
            # complexity for the churn tail — only DELTA-matched lanes
            # pay, everything else stays fast)
            slow |= d_counts_k[:B] > 0
        if fids.size:
            np.logical_or.at(slow, mi, hostside[fids] | b.fid_shared[fids])
        if dev_shared:
            slow |= shared_any

        out: list = [None] * B
        fast_ok = ~slow
        if not fast_ok.any():
            return out
        keep = fast_ok[mi]
        mi_f, fids_f = mi[keep], fids[keep]
        seg = b.seg_np[fids_f]
        total = int(seg.sum())
        if plan is not None:
            # lane hand-off: one gather pass, zero Python per-row work
            # here — the lanes deliver these messages off this stage
            fast_idx = np.flatnonzero(fast_ok)
            plan.register_fast(fast_idx)
            if total:
                row_msg, col, row_fid = self._attribute_rows(
                    mi_f, fids_f, seg, total)
                sid, opt = fetch(row_msg, col)
                valid = sid >= 0
                plan.add_rows(row_msg[valid], sid[valid], opt[valid],
                              row_fid[valid], b.fid_filter)
            for i in fast_idx.tolist():
                out[i] = DEFERRED
            return out
        counts = np.zeros(B, np.int64)
        delivered = 0
        if total:
            row_msg, col, row_fid = self._attribute_rows(
                mi_f, fids_f, seg, total)
            sid, opt = fetch(row_msg, col)
            valid = sid >= 0
            fid_filter = b.fid_filter
            deliver = broker._deliver
            # the 64-entry OPT_TABLE replaces the old per-call
            # opt_cache (ISSUE 5 satellite); the dict copy stays on
            # this inline path because _deliver plants the dict into
            # the delivered copy's headers — the lane path instead
            # shares the frozen table entry through the DeliveryView
            for bi, s, ob, fd in zip(row_msg[valid].tolist(),
                                     sid[valid].tolist(),
                                     opt[valid].tolist(),
                                     row_fid[valid].tolist()):
                if deliver(s, fid_filter[fd], msgs[bi],
                           dict(OPT_TABLE[ob & 0x3F])):
                    counts[bi] += 1
                    delivered += 1
        if delivered:
            self.node.metrics.inc("messages.routed.device", delivered)
        metrics = self.node.metrics
        hooks = broker.hooks
        for i in np.flatnonzero(fast_ok).tolist():
            n = int(counts[i])
            if n == 0 and not msgs[i].is_sys:
                metrics.inc("messages.dropped")
                metrics.inc("messages.dropped.no_subscribers")
                hooks.run("message.dropped", (msgs[i], "no_subscribers"))
            out[i] = n
        return out

    def finish(self, h) -> list[int]:
        """Stage 4 for single-batch callers (route_batch): window of 1.
        Sync callers need final counts on return, so the consume stays
        inline (the lanes serve the pipelined path via finish_sub)."""
        return self.finish_sub(h, 0, defer=False)

    def _release_one(self, h) -> None:
        """Drop one sub-batch reference; the handle releases at zero."""
        if h is None or h.built is None:
            return
        h.refs -= 1
        if h.refs <= 0:
            h.built = None
            self._outstanding -= 1
            if self.ledger is not None:
                self.ledger.unpin(id(h))
            if self._building:
                self._try_swap()

    def abandon(self, h) -> None:
        """Release a handle ENTIRELY (error path: the caller falls back
        to the host route for every remaining sub-batch). Idempotent.

        At dispatch_depth >= 2 the failed dispatch may have DONATED the
        live cursors buffer before dying (jax invalidates donated
        inputs at call time, success or not) and the adoption at the
        end of _dispatch_inner never ran — without a reseed every
        subsequent device dispatch would hit 'Array has been deleted'
        until a snapshot swap happened to replace _cursors, permanently
        degrading a static-subscription node to the host rung. The
        reseed costs one round-robin fairness reset (same class of blip
        as a swap racing a dispatch), never correctness."""
        if h is not None and h.built is not None:
            h.refs = 0
            h.built = None
            self._outstanding -= 1
            if self.ledger is not None:
                self.ledger.unpin(id(h))
            if self._building:
                self._try_swap()
        if self._pipelined:
            cur = self._cursors
            try:
                deleted = cur is not None and cur.is_deleted()
            except Exception:  # noqa: BLE001 — non-jax placeholder
                deleted = False
            if deleted:
                import jax
                self._cursors = self._hold(
                    "snapshot_cursors",
                    # hbm: reseed — the donating call consumed the
                    # buffer and the failure path skipped adoption
                    jax.device_put(np.zeros(cur.shape, np.int32)))

    def route_batch(self, msgs: list[Message]) -> Optional[list[int]]:
        """Route+deliver a micro-batch through the fused device step,
        synchronously (publish_batch / tests / warmup). The pipelined
        serving path drives the four stages separately via PublishBatcher.

        Returns per-message delivery counts, or None when the engine has no
        tables to serve (caller falls back to the host path).
        """
        # a sync rebuild must honor the handle pin: swapping _tables while
        # the batcher has a dispatch in flight on the dispatch thread would
        # hand that dispatch the new tables under the old _Built metadata
        # (outstanding > 0 implies a snapshot exists, so serving stale +
        # host deltas meanwhile is always correct)
        if self._outstanding == 0 \
                and (self._built is None
                     or (not self._building
                         and self._compaction_reason() is not None)):
            if self._built is not None:
                self._count_compaction(self._compaction_reason())
            self.rebuild()
        # sync callers compile in-path by design — let a cold cached
        # class trace instead of bouncing to the plain program
        h = self.prepare(msgs, gate_cold=False)
        if h is None:
            return None
        try:
            self.dispatch(h)
            self.materialize(h)
        except Exception:
            self.abandon(h)
            raise
        return self.finish(h)

    def _writeback_cursors(self, occur: np.ndarray, b=None) -> None:
        """Mirror device round-robin cursor advances into the host
        SharedGroup state so the host path and the next rebuild stay fair."""
        if self.broker.shared_strategy != "round_robin":
            return
        b = b or self._built
        for slot in np.flatnonzero(occur[:b.n_slots]):
            f, gname = b.slot_key[slot]
            g = self.broker.shared.get(f, {}).get(gname)
            if g is not None and g.members:
                # for mixed local/remote groups this folds the device's
                # full-membership advance onto the local cursor — an
                # approximation that keeps the host fallback fair, not a
                # correctness input (the device cursor itself is
                # authoritative while the snapshot serves)
                g.cursor = (g.cursor + int(occur[slot])) % len(g.members)

    def _consume_one(self, msg, m_row, r_row, o_row, ss_row, sr_row, so_row,
                     words, dev_shared: bool, b=None, drow=None, ov=None,
                     pending: bool = False) -> int:
        """Turn one message's RouteResult rows into deliveries.

        `drow` = (delta fids, delta fan rows, delta fan opts) when the
        dispatch fused the delta overlay `ov` (ISSUE 4): post-snapshot
        filters deliver straight from the device planes; `pending`
        marks live delta filters the overlay does NOT cover (just
        subscribed / overflowed / too deep) — only those still walk the
        host trie, and overlay-covered fids are skipped there so nothing
        delivers twice."""
        broker = self.broker
        metrics = self.node.metrics
        b = b or self._built
        n = 0
        matched: list[str] = []
        off = 0
        for fid in m_row:
            if fid < 0:
                continue
            f = b.fid_filter[fid]
            seg = b.seg_len[fid]
            matched.append(f)
            # rich-ness is snapshot state: read it from the handle's
            # pinned _Built (fid_rich), never from engine-level state —
            # one source of truth shared with the vectorized fast path
            if f in self.dirty_filters or b.fid_rich[fid]:
                n += broker.dispatch(f, msg)
            else:
                for k in range(off, off + seg):
                    sid = int(r_row[k])
                    if sid < 0:
                        continue
                    if broker._deliver(sid, f, msg,
                                       _unpack_opts(int(o_row[k]))):
                        n += 1
                        metrics.inc("messages.routed.device")
            off += seg

        # filters added since the snapshot (ISSUE 4): the fused overlay
        # planes deliver them from device rows; only uncovered filters
        # (no overlay this dispatch, overlay overflow, too-deep) walk
        # the host trie — the routing.device.host_delta counter measures
        # exactly those host-side deliveries (the pre-overlay behavior)
        if ov is not None and drow is not None:
            d_fids, d_rows, d_opts = drow
            doff = 0
            for raw in d_fids:
                dfid = int(raw)
                if dfid < 0:
                    continue
                seg = ov.seg_of.get(dfid, 0)
                f = self._delta_filter.get(dfid)
                if f is None:       # deleted while this batch flew
                    doff += seg
                    continue
                matched.append(f)
                if dfid in ov.hostfan \
                        or self._fid_member_clock.get(dfid, -1) \
                        > ov.version:
                    # rich/oversized fan-out, or membership changed
                    # after this overlay version was built: the match
                    # stands, delivery comes from the live host dict
                    n += broker.dispatch(f, msg)
                else:
                    for j in range(doff, doff + seg):
                        sid = int(d_rows[j])
                        if sid < 0:
                            continue
                        if broker._deliver(sid, f, msg,
                                           _unpack_opts(int(d_opts[j]))):
                            n += 1
                            metrics.inc("messages.routed.device")
                doff += seg
        if self._delta_filter and (ov is None or pending):
            if words is None:   # prepare defers tokenization (native
                words = T.tokens(msg.topic)[:self.max_levels]  # encode)
            ids = self.intern.encode_topic(words)
            dol = words[0].startswith("$") if words else False
            host_hit = False
            for dfid in self._delta_trie.match(ids, dol):
                if ov is not None and dfid in ov.fid_set:
                    continue    # the overlay planes already served it
                f = self._delta_filter.get(dfid)
                if f is None:
                    continue
                matched.append(f)
                n += broker.dispatch(f, msg)
                host_hit = True
            if host_hit:
                metrics.inc("routing.device.host_delta")

        # shared subscriptions
        if dev_shared:
            handled: set[tuple] = set()
            for k, slot in enumerate(ss_row):
                if slot < 0:
                    continue
                f, gname = b.slot_key[slot]
                handled.add((f, gname))
                if (f, gname) in self.dirty_slots:
                    if self._host_shared_dispatch(f, gname, msg):
                        n += 1
                    continue
                sid = int(sr_row[k])
                if sid >= _REMOTE_SID_BASE:
                    # device picked a remote member: directed forward,
                    # the host path's cross-node dispatch with the pick
                    # already done on device
                    cluster = broker.cluster
                    if cluster is not None:
                        origin, rsid = \
                            b.remote_members[sid - _REMOTE_SID_BASE]
                        cluster._spawn_fwd(
                            origin, "shared.deliver_fwd",
                            [f, gname, rsid, msg.to_wire()],
                            key=msg.topic)
                        n += 1
                        metrics.inc("messages.routed.device")
                        metrics.inc("messages.routed.device.remote_shared")
                    elif self._host_shared_dispatch(f, gname, msg):
                        # cluster torn down since the build: host decides
                        n += 1
                elif sid >= 0:
                    if broker._deliver(
                            sid, f, msg,
                            dict(_unpack_opts(int(so_row[k])),
                                 share=gname)):
                        n += 1
                        metrics.inc("messages.routed.device")
                    else:
                        # re-dispatch ONLY when the picked member is
                        # actually gone (in-flight churn window) or the
                        # ack protocol is on — a nack from a live member
                        # with dispatch_ack off is final, matching the
                        # host pick's semantics (for sticky the re-pick
                        # is also where affinity re-homes,
                        # emqx_shared_sub.erl:269-283)
                        grp = broker.shared.get(f, {}).get(gname)
                        gone = grp is None or sid not in grp.members
                        if (gone or broker.shared_dispatch_ack) and \
                                self._host_shared_dispatch(f, gname,
                                                           msg):
                            n += 1
            cluster = broker.cluster
            for f in matched:
                # groups created after the snapshot on matched filters
                for gname in self.new_slots_by_filter.get(f, ()):
                    if (f, gname) in handled:
                        continue
                    handled.add((f, gname))
                    if self._host_shared_dispatch(f, gname, msg):
                        n += 1
                # delta filters' groups (host dispatch covers them all)
                if f in self._delta_fid_of:
                    for gname in list(broker.shared.get(f, {})):
                        if (f, gname) not in handled:
                            handled.add((f, gname))
                            if self._host_shared_dispatch(f, gname, msg):
                                n += 1
                if cluster is not None:
                    # groups excluded from the snapshot (remote members)
                    # and remote-only groups known via replication;
                    # cached per filter — membership changes invalidate
                    groups = self._cluster_groups_cache.get(f)
                    if groups is None:
                        groups = tuple(
                            set(broker.shared.get(f, ()))
                            | cluster._groups_by_real.get(f, set()))
                        self._cluster_groups_cache[f] = groups
                    for gname in groups:
                        if (f, gname) in handled:
                            continue
                        handled.add((f, gname))
                        if self._host_shared_dispatch(f, gname, msg):
                            n += 1
        else:
            n += broker._dispatch_shared(msg, matched)

        if broker.cluster:
            n += broker.cluster.forward(msg, matched)
        if n == 0 and not msg.is_sys:
            metrics.inc("messages.dropped")
            metrics.inc("messages.dropped.no_subscribers")
            broker.hooks.run("message.dropped", (msg, "no_subscribers"))
        return n

    def rebuild_state(self) -> dict:
        """Live rebuild/overlay gauges for the telemetry snapshot's
        `rebuild` section (PipelineTelemetry.rebuild_state_fn): counts
        ride the Metrics registry; these are the point-in-time values a
        counter can't carry."""
        ov = self._overlay
        return {
            "journal_depth": self.journal_depth(),
            "building": self._building,
            "staleness": self.staleness(),
            "tombstones": len(self._built_deleted),
            "delta_overlay": self.delta_overlay,
            "overlay_rows": ov.n if ov is not None else 0,
            "overlay_class": ov.cap if ov is not None else 0,
            "overlay_version": ov.version if ov is not None else None,
            "overlay_uncovered": self._overlay_uncovered,
            "delta_filters": len(self._delta_filter),
        }

    def stats(self) -> dict:
        b = self._built
        ov = self._overlay
        return {
            "built": b is not None,
            "backend": b.backend if b else None,
            "filters": len(b.fid_filter) if b else 0,
            "shared_slots": b.n_slots if b else 0,
            "churn": self.staleness(),
            "dirty_filters": len(self.dirty_filters),
            "delta_filters": len(self._delta_filter),
            "building": self._building,
            "outstanding": self._outstanding,
            "dedup": self.dedup,
            "match_cache": self._match_cache.stats()
            if self._match_cache is not None else None,
            "compact_readback": self.compact_readback,
            "dispatch_depth": self.dispatch_depth,
            "payload_ewma": {k: round(v, 1)
                             for k, v in self._pay_ewma.items()},
            "delta_overlay": self.delta_overlay,
            "overlay": {"rows": ov.n, "class": ov.cap,
                        "version": ov.version,
                        "hostfan": len(ov.hostfan)}
            if ov is not None else None,
            "journal_depth": self.journal_depth(),
            "subscription_covering": self.subscription_covering,
            "cover": {"roots": b.cover.n_roots,
                      "covered": b.cover.n_covered,
                      "appends": b.cover.app_used,
                      "incomplete": b.cover.incomplete,
                      "reduction": round(
                          (b.cover.n_roots + b.cover.n_covered)
                          / max(1, b.cover.n_roots), 2)}
            if b is not None and b.cover is not None else None,
        }
