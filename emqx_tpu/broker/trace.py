"""Window-causal flight recorder for the device route pipeline (ISSUE 7).

PR 1's stage histograms aggregate away exactly what the device-e2e gap
diagnosis needs: CAUSALITY (which admit fed which dispatch fed which
delivery) and OVERLAP (how much dispatch(W+1) actually hides
materialize(W), and where the bubbles sit). This module is the causal
layer under the histograms:

- **Window traces**: every publish window gets a trace id minted at
  batcher admit (`FlightRecorder.new_trace`) and propagated through the
  whole five-stage pipeline — batch_form, dispatch (the id rides the
  ``jax.profiler.StepTraceAnnotation`` so the device timeline joins the
  host one), materialize, plan construction, the delivery lanes, down
  to settle. Supervise replays KEEP the window's original trace id and
  link the replay as a child span (the causal chain survives the
  degradation ladder); lane-worker restarts keep the plan's trace
  (queue items carry the plan, the plan carries the trace).
- **Sampled per-message spans** ride the window trace: one in
  ``EMQX_TPU_TRACE_SAMPLE`` messages records its own enqueue→settle
  span with its topic, so tail latency decomposes per message, not
  just per batch.
- **The flight recorder**: spans land in a lock-free bounded ring
  buffer — always on at window granularity, negligible overhead
  (one ``itertools.count`` bump + one list-slot store per span under
  the GIL; no locks, no allocation beyond the span record). The ring
  retains the last ``cap`` spans, so it is dumpable POST-MORTEM after
  a wedge or a breaker trip: ``GET /api/v5/pipeline/trace?format=
  perfetto``, ``FlightRecorder.dump(path)``, or
  ``tools/trace_report.py`` on a saved dump.
- **The overlap/bubble analyzer** (`analyze_spans`): per-window stage
  occupancy, the dispatch↔materialize overlap fraction (how much of
  window W's readback the next window's dispatch hid), and gap
  attribution — every uncovered interval inside a window is billed to
  ``host_stall`` (waiting on the loop / the dispatch thread / the
  consumer), ``device_stall`` (waiting on the device or the readback
  pool) or ``lane_backpressure`` (waiting on the delivery lanes), with
  the top bubbles named per window.

Knobs: ``broker.trace`` / ``EMQX_TPU_TRACE`` (config beats env beats
default-on; ``=0`` restores the pre-ISSUE-7 behavior exactly — no
recorder object anywhere, zero hot-path cost), ``broker.trace_sample``
/ ``EMQX_TPU_TRACE_SAMPLE`` (per-message sampling 1-in-N, default 256,
0 disables message spans), ``broker.trace_ring`` (span capacity,
default 4096).

Exported three ways: the Chrome trace-event JSON above (loadable in
Perfetto / chrome://tracing), the ``trace`` section of
`PipelineTelemetry.snapshot()` (fanned through $SYS / Prometheus /
StatsD counters / `GET /api/v5/pipeline/stats`), and the
``trace.spans`` / ``trace.windows`` / ``trace.dropped`` counters in
the shared Metrics registry.
"""

from __future__ import annotations

import itertools
import json
import os
import time
from collections import defaultdict
from typing import Optional

SCHEMA = "emqx_tpu.trace/v1"

# trace id 0 is the node scope: events that belong to no single window
# (breaker trips, rung changes, lane-worker restarts)
NODE_TRACE = 0

# gap attribution: an uncovered interval inside a window is billed by
# the span that ENDS the gap — what the window was waiting FOR
_GAP_ATTR = {
    "dispatch": "host_stall",        # formed, waiting for the dispatch
    "dispatch_cached": "host_stall",  # thread / a pipeline slot
    "batch_form": "host_stall",
    "host_route": "host_stall",
    "deliver": "host_stall",         # readback done, consumer busy
    "materialize": "device_stall",   # dispatched, device/readback pending
    "replay": "host_stall",
    "settle": "host_stall",
}
_LANE_ATTR = "lane_backpressure"
BUBBLE_CLASSES = ("host_stall", "device_stall", "lane_backpressure")


def resolve_trace(configured=None) -> bool:
    """The one tracing-knob resolution: config (``broker.trace``) beats
    ``EMQX_TPU_TRACE`` beats default-on. ``=0`` restores the
    pre-ISSUE-7 behavior exactly (no recorder anywhere) — the A/B
    baseline the shape-equivalence test compares."""
    if configured is not None:
        return bool(configured)
    return os.environ.get("EMQX_TPU_TRACE", "1") \
        not in ("0", "false", "off")


def resolve_trace_sample(configured=None) -> int:
    """Per-message span sampling: one in N messages records its own
    enqueue→settle span. Config (``broker.trace_sample``) beats
    ``EMQX_TPU_TRACE_SAMPLE`` beats the built-in 256. 0 disables
    message spans (window spans stay on)."""
    if configured is None:
        configured = os.environ.get("EMQX_TPU_TRACE_SAMPLE", "256")
    n = int(configured)
    if n < 0:
        raise ValueError(f"trace_sample must be >= 0, got {n}")
    return n


class Span:
    """One recorded span: a (trace, name, track, [t0, t1]) interval in
    the shared perf_counter time base. ``t0 == t1`` is an instant event
    (replay, rung_change, lane_restart). ``parent_id`` links causal
    children (a replay's host_route is a child of the replay span,
    which is a child of the window root)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "track",
                 "t0", "t1", "meta", "slot")

    def __init__(self, trace_id, span_id, parent_id, name, track,
                 t0, t1, meta, slot=0):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.track = track
        self.t0 = t0
        self.t1 = t1
        self.meta = meta
        self.slot = slot    # ring write cursor at record time

    @property
    def dur(self) -> float:
        return max(0.0, self.t1 - self.t0)


class FlightRecorder:
    """Lock-free bounded span ring + the export/analysis surfaces.

    Thread-safety: ``record`` runs on the event loop AND the dispatch/
    read executor threads concurrently. Each writer claims a unique
    monotonic slot via ``itertools.count().__next__`` (atomic under the
    GIL) and stores into its own ring index — no lock, no torn reads
    (readers snapshot the buffer list and sort by span id). The
    recorded/dropped accounting is derived from the slot numbers in
    the ring at read time, so writers share no mutable counter.
    """

    def __init__(self, metrics=None, *, cap: int = 4096,
                 sample: Optional[int] = None):
        self.cap = max(16, int(cap))
        self.metrics = metrics
        self.sample = resolve_trace_sample(sample) \
            if not isinstance(sample, int) else max(0, sample)
        self._buf: list = [None] * self.cap
        self._slot = itertools.count()       # unique write cursor
        self._ids = itertools.count(1)       # trace + span ids
        self._msg_tick = itertools.count()   # message-sampling clock
        self.windows = 0                     # traces minted (approximate)
        # one shared time base for every span: ts in exports are
        # relative to epoch_perf; epoch_wall anchors them to wall clock
        self.epoch_perf = time.perf_counter()
        self.epoch_wall = time.time()

    # ---- recording (hot path) -------------------------------------------
    def new_trace(self) -> int:
        """Mint one window trace id (batcher admit)."""
        self.windows += 1
        if self.metrics is not None:
            self.metrics.inc("trace.windows")
        return next(self._ids)

    def record(self, trace_id: int, name: str, t0: float, t1: float, *,
               track: str = "pipeline", parent: int = 0,
               meta: Optional[dict] = None) -> int:
        """Record one span; returns its span id (for child linking)."""
        sid = next(self._ids)
        slot = next(self._slot)
        i = slot % self.cap
        if self.metrics is not None:
            self.metrics.inc("trace.spans")
            if self._buf[i] is not None:
                self.metrics.inc("trace.dropped")
        self._buf[i] = Span(trace_id, sid, parent, name, track,
                            t0, t1, meta, slot)
        return sid

    def event(self, trace_id: int, name: str, *,
              track: str = "events", parent: int = 0,
              meta: Optional[dict] = None) -> int:
        """Record one instant event (replay, rung change, restart)."""
        now = time.perf_counter()
        return self.record(trace_id, name, now, now, track=track,
                           parent=parent, meta=meta)

    def sample_hit(self) -> bool:
        """One global sampling decision per message: True one-in-
        ``sample`` calls (0 = never)."""
        if self.sample <= 0:
            return False
        return next(self._msg_tick) % self.sample == 0

    # ---- reading --------------------------------------------------------
    def spans(self) -> list[Span]:
        """Snapshot the ring, oldest first (span ids are monotone)."""
        return sorted((s for s in list(self._buf) if s is not None),
                      key=lambda s: s.span_id)

    def recorded(self) -> int:
        """Total spans ever recorded — derived from the highest write
        cursor present in the ring at read time, so concurrent writers
        need no shared read-modify-write on the hot path (a plain
        counter store races: a preempted writer's stale store would
        regress it). Exact once writers are quiescent; a consistent
        lower bound mid-flight (overwrites only raise slot numbers)."""
        return max((s.slot for s in list(self._buf) if s is not None),
                   default=-1) + 1

    def dropped(self) -> int:
        return max(0, self.recorded() - self.cap)

    def state(self) -> dict:
        return {"cap": self.cap, "recorded": self.recorded(),
                "dropped": self.dropped(), "sample": self.sample,
                "windows": self.windows}

    # ---- Chrome trace-event / Perfetto export ---------------------------
    def to_chrome(self, spans: Optional[list[Span]] = None) -> dict:
        """The ring as a Chrome trace-event document (Perfetto /
        chrome://tracing loadable): one process ``emqx_tpu pipeline``,
        one thread track per span track (batcher / dispatch /
        materialize / consume / lane{i} / messages / events), complete
        (``X``) events for real spans and instant (``i``) events for
        the zero-duration ones, args carrying the causal ids so
        `analyze_chrome` round-trips. The device timeline joins on the
        ``trace_id`` arg: the engine annotates every dispatch with
        ``StepTraceAnnotation("route_step", step_num=<trace id>)``, so
        a jax.profiler capture of the same run keys its device steps
        by the same ids."""
        if spans is None:
            spans = self.spans()
        pid = 1
        tids: dict[str, int] = {}
        events: list[dict] = [{
            "ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": "emqx_tpu pipeline"}}]

        def tid_of(track: str) -> int:
            t = tids.get(track)
            if t is None:
                t = tids[track] = len(tids) + 1
                events.append({"ph": "M", "pid": pid, "tid": t,
                               "name": "thread_name",
                               "args": {"name": track}})
            return t

        for sp in spans:
            args = {"trace_id": sp.trace_id, "span_id": sp.span_id}
            if sp.parent_id:
                args["parent_id"] = sp.parent_id
            if sp.meta:
                args.update(sp.meta)
            ev = {"name": sp.name, "cat": "pipeline", "pid": pid,
                  "tid": tid_of(sp.track),
                  "ts": round((sp.t0 - self.epoch_perf) * 1e6, 3),
                  "args": args}
            if sp.t1 > sp.t0:
                ev["ph"] = "X"
                ev["dur"] = round((sp.t1 - sp.t0) * 1e6, 3)
            else:
                ev["ph"] = "i"
                ev["s"] = "t"
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"schema": SCHEMA,
                              "epoch_wall": self.epoch_wall,
                              "dropped": self.dropped()}}

    def dump(self, path: str) -> str:
        """Write the Perfetto-loadable dump (post-mortem surface)."""
        doc = self.to_chrome()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path

    # ---- analysis -------------------------------------------------------
    def analyze(self, *, top: int = 3, per_window: int = 8) -> dict:
        return analyze_spans(self.spans(), top=top,
                             per_window=per_window)

    def snapshot_section(self) -> dict:
        """The ``trace`` section of `PipelineTelemetry.snapshot()`:
        ring state + the aggregate overlap/bubble analysis (per-window
        rows capped so $SYS payloads stay bounded)."""
        out = {"schema": SCHEMA, "ring": self.state()}
        a = self.analyze(per_window=4)
        for k in ("windows", "overlap", "stage_occupancy", "bubbles",
                  "last_windows"):
            if k in a:
                out[k] = a[k]
        return out


# ---- the overlap/bubble analyzer (pure functions, reusable offline) ----

def _union_and_gaps(intervals: list[tuple], w0: float, w1: float):
    """Merge [t0, t1, name] intervals clipped to [w0, w1]; return
    (covered_seconds, gaps) where each gap is (g0, g1, next_name) —
    the name of the span that ENDS the gap (what was being waited on).
    The trailing gap (after the last span) carries next_name=None."""
    ivs = sorted((max(w0, a), min(w1, b), n)
                 for a, b, n in intervals if b > a)
    covered = 0.0
    gaps = []
    cur = w0
    for a, b, n in ivs:
        if a > cur:
            gaps.append((cur, a, n))
        if b > cur:
            covered += b - max(cur, a)
            cur = b
    if w1 > cur:
        gaps.append((cur, w1, None))
    return covered, gaps


def _attr_of(next_name: Optional[str], has_lanes: bool) -> str:
    if next_name is None:
        # trailing gap: the window sat settled-pending — on the lanes
        # when the trace shows lane work, else on the host consumer
        return _LANE_ATTR if has_lanes else "host_stall"
    if next_name.startswith("lane"):
        return _LANE_ATTR
    return _GAP_ATTR.get(next_name, "host_stall")


def analyze_spans(spans: list, *, top: int = 3,
                  per_window: int = 8) -> dict:
    """Per-window occupancy + bubbles and the global dispatch↔
    materialize overlap, from any span list (the live ring, or one
    reconstructed from a Perfetto dump by `analyze_chrome`).

    Returns::

        {"windows": N,
         "overlap": {"dispatch_materialize": 0.42,
                     "materialize_s": ..., "overlapped_s": ...},
         "stage_occupancy": {stage: {"total_s":, "mean_frac":}},
         "bubbles": {"host_stall_s":, "device_stall_s":,
                     "lane_backpressure_s":, "total_s":,
                     "top": [[label, seconds], ...]},
         "last_windows": [{"trace_id":, "span_s":, "stages": {...},
                           "bubbles": [[attr, s], ...]}, ...]}
    """
    by_trace: dict[int, list] = defaultdict(list)
    dispatches: list[tuple] = []
    materializes: list[tuple] = []
    for sp in spans:
        if sp.trace_id > NODE_TRACE:
            by_trace[sp.trace_id].append(sp)
        if sp.name in ("dispatch", "dispatch_cached") and sp.t1 > sp.t0:
            dispatches.append((sp.t0, sp.t1, sp.trace_id))
        elif sp.name == "materialize" and sp.t1 > sp.t0:
            materializes.append((sp.t0, sp.t1, sp.trace_id))

    # dispatch↔materialize overlap: how much of each window's readback
    # was hidden under ANOTHER window's dispatch (the double-buffering
    # win ROADMAP item 1 is tuned against). Fraction of total
    # materialize seconds covered by a different trace's dispatch.
    dispatches.sort()
    materializes.sort()
    mat_s = 0.0
    hidden_s = 0.0
    lo = 0
    for m0, m1, mtid in materializes:
        mat_s += m1 - m0
        # both lists are time-sorted: a dispatch ending at or before
        # this m0 can never cover this or any LATER materialize, so
        # the scan start only moves forward — amortized O(D+M) where
        # a full rescan per materialize is O(D*M) (analyze runs inside
        # snapshot() on the event loop, on every $SYS tick)
        while lo < len(dispatches) and dispatches[lo][1] <= m0:
            lo += 1
        cover: list[tuple] = []
        for j in range(lo, len(dispatches)):
            d0, d1, dtid = dispatches[j]
            if d0 >= m1:
                break
            if dtid == mtid or d1 <= m0:
                continue
            cover.append((max(d0, m0), min(d1, m1), ""))
        covered, _g = _union_and_gaps(cover, m0, m1)
        hidden_s += covered
    overlap = {}
    if materializes:
        overlap = {
            "dispatch_materialize": round(hidden_s / mat_s, 4)
            if mat_s else 0.0,
            "materialize_s": round(mat_s, 6),
            "overlapped_s": round(hidden_s, 6),
        }

    stage_tot: dict[str, float] = defaultdict(float)
    stage_frac: dict[str, list] = defaultdict(list)
    bubble_tot: dict[str, float] = dict.fromkeys(BUBBLE_CLASSES, 0.0)
    win_rows = []
    for tid in sorted(by_trace):
        sps = by_trace[tid]
        # the window interval: admit (first span start) → settle (last
        # span end); instant events bound it too (a replay marks time)
        w0 = min(s.t0 for s in sps)
        w1 = max(s.t1 for s in sps)
        span_s = w1 - w0
        if span_s <= 0:
            continue
        has_lanes = any(s.track.startswith("lane")
                        or s.name in ("lane_admit", "lane_drain")
                        for s in sps)
        stages: dict[str, float] = defaultdict(float)
        ivs = []
        for s in sps:
            if s.t1 <= s.t0 or s.name in ("window", "message"):
                continue    # events and roll-up spans don't cover work
            stages[s.name] += s.dur
            ivs.append((s.t0, s.t1, s.name))
        for name, d in stages.items():
            stage_tot[name] += d
            stage_frac[name].append(d / span_s)
        _covered, gaps = _union_and_gaps(ivs, w0, w1)
        attrs: dict[str, float] = defaultdict(float)
        for g0, g1, nxt in gaps:
            attrs[_attr_of(nxt, has_lanes)] += g1 - g0
        for k, v in attrs.items():
            bubble_tot[k] = bubble_tot.get(k, 0.0) + v
        win_rows.append({
            "trace_id": tid,
            "span_s": round(span_s, 6),
            "stages": {k: round(v, 6) for k, v in stages.items()},
            "bubbles": [[k, round(v, 6)] for k, v in
                        sorted(attrs.items(), key=lambda kv: -kv[1])
                        ][:top],
        })

    out: dict = {"windows": len(win_rows)}
    if overlap:
        out["overlap"] = overlap
    if stage_tot:
        out["stage_occupancy"] = {
            k: {"total_s": round(v, 6),
                "mean_frac": round(sum(stage_frac[k])
                                   / len(stage_frac[k]), 4)}
            for k, v in stage_tot.items()}
    bub_total = sum(bubble_tot.values())
    if win_rows:
        out["bubbles"] = {
            **{f"{k}_s": round(v, 6) for k, v in bubble_tot.items()},
            "total_s": round(bub_total, 6),
            "top": [[k, round(v, 6)] for k, v in
                    sorted(bubble_tot.items(), key=lambda kv: -kv[1])
                    if v > 0][:top],
        }
        out["last_windows"] = win_rows[-per_window:]
    return out


def analyze_chrome(doc: dict, *, top: int = 3,
                   per_window: int = 0) -> dict:
    """Rebuild spans from a Chrome trace-event dump (`to_chrome` /
    `FlightRecorder.dump`) and run the same analyzer —
    ``tools/trace_report.py``'s offline entry. per_window=0 keeps
    every window row (the offline report wants them all)."""
    spans = []
    # tid -> track from the thread_name metadata events: the analyzer's
    # lane attribution keys on span.track (has_lanes), so the offline
    # path must reconstruct it or lane_backpressure silently degrades
    # to host_stall on the very dump the post-mortem reads
    tracks: dict[tuple, str] = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            tracks[(ev.get("pid"), ev.get("tid"))] = \
                (ev.get("args") or {}).get("name", "")
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") not in ("X", "i"):
            continue
        args = ev.get("args") or {}
        if "trace_id" not in args:
            continue
        t0 = float(ev.get("ts", 0)) / 1e6
        t1 = t0 + float(ev.get("dur", 0)) / 1e6
        meta = {k: v for k, v in args.items()
                if k not in ("trace_id", "span_id", "parent_id")}
        spans.append(Span(int(args["trace_id"]),
                          int(args.get("span_id", 0)),
                          int(args.get("parent_id", 0)),
                          ev.get("name", ""),
                          tracks.get((ev.get("pid"), ev.get("tid")),
                                     ""), t0, t1,
                          meta or None))
    spans.sort(key=lambda s: (s.t0, s.span_id))
    n_windows = len({s.trace_id for s in spans if s.trace_id > 0})
    return analyze_spans(spans, top=top,
                         per_window=per_window or max(1, n_windows))
