"""Broker node: the composition root bundling all subsystems.

Parity: the emqx application + emqx_sup supervision tree
(apps/emqx/src/emqx_sup.erl:64-79) — here a plain object graph assembled at
boot, since asyncio tasks replace the supervised process tree. Also carries
the facade API the reference exports from emqx.erl:25-52
(subscribe/publish/topics/hook/...).
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional

from emqx_tpu.broker.alarm import AlarmManager
from emqx_tpu.broker.banned import Banned
from emqx_tpu.broker.cm import ConnectionManager
from emqx_tpu.broker.monitor import OsMon
from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker.message import Message
from emqx_tpu.broker.metrics import Metrics, Stats
from emqx_tpu.broker.pubsub import Broker
from emqx_tpu.broker.router import Router


class Node:
    def __init__(self, config: Optional[dict] = None, *,
                 use_device: Optional[bool] = None,
                 name: str = "emqx_tpu@127.0.0.1"):
        from emqx_tpu.broker.config import Config
        self.name = name
        self.config = config if hasattr(config, "get_zone") else Config(config)
        from emqx_tpu.utils.logger import setup_from_config
        setup_from_config(self.config.get("log") or {})
        self.hooks = Hooks()
        self.metrics = Metrics()
        self.stats = Stats()
        perf = self.config.get("broker") or {}
        if use_device is None:
            # default-on: the fused device route step IS the serving path
            # wherever a jax device exists (real TPU or the CPU backend)
            use_device = bool(perf.get("device_route", True))
        from emqx_tpu.broker.telemetry import PipelineTelemetry
        slow_ms = perf.get("slow_batch_threshold_ms", 250)
        self.pipeline_telemetry = PipelineTelemetry(
            self.metrics, hooks=self.hooks,
            slow_batch_s=(slow_ms / 1000.0) if slow_ms else None,
            track_compiles=use_device)
        # rebuild threshold: config beats EMQX_TPU_REBUILD_THRESHOLD
        # beats the built-in default (one resolution shared by the host
        # router and both device engines)
        from emqx_tpu.broker.device_engine import resolve_rebuild_threshold
        rebuild_threshold = resolve_rebuild_threshold(
            perf.get("rebuild_threshold"))
        # double-buffered window pipeline depth (ISSUE 9): one
        # resolution shared by the batcher's settle ring and both
        # engines' donation/async-readback gates. broker.dispatch_depth
        # / EMQX_TPU_DISPATCH_DEPTH, config beats env beats default 2;
        # =1 restores the synchronous pre-ISSUE-9 loop exactly.
        from emqx_tpu.broker.batcher import resolve_dispatch_depth
        dispatch_depth = resolve_dispatch_depth(
            perf.get("dispatch_depth"))
        # columnar zero-copy PUBLISH ingress (ISSUE 11): one resolution
        # for the whole layer — the native burst decode in the codec,
        # the channel's burst hand-off, the batcher's submit_burst and
        # the sharded acceptor lanes all read these two node attributes.
        # broker.columnar_ingress / EMQX_TPU_COLUMNAR_INGRESS, config
        # beats env beats default-on; =0 restores the per-packet ingress
        # path EXACTLY (single accept loop, parser.feed, per-packet
        # handle_in, no `ingress` telemetry section).
        from emqx_tpu.broker.connection import (resolve_columnar_ingress,
                                                resolve_ingress_lanes)
        self.columnar_ingress = resolve_columnar_ingress(
            perf.get("columnar_ingress"))
        self.ingress_lanes = resolve_ingress_lanes(
            perf.get("ingress_lanes")) if self.columnar_ingress else 1
        self.router = Router(
            use_device=use_device,
            rebuild_threshold=rebuild_threshold,
            device_min_batch=perf.get("device_min_batch", 4))
        self.broker = Broker(
            router=self.router, hooks=self.hooks, metrics=self.metrics,
            shared_strategy=perf.get("shared_subscription_strategy",
                                     "round_robin"),
            shared_dispatch_ack=perf.get("shared_dispatch_ack_enabled",
                                         False))
        self.device_engine = None
        self.publish_batcher = None
        # window-causal flight recorder (ISSUE 7): trace ids minted at
        # batcher admit ride the whole pipeline (dispatch/materialize/
        # replay/lanes/settle) into a bounded span ring — always on at
        # window granularity, dumpable post-mortem (GET /api/v5/
        # pipeline/trace?format=perfetto). broker.trace /
        # EMQX_TPU_TRACE =0 restores the pre-ISSUE-7 behavior exactly
        # (self.flight_recorder stays None everywhere).
        self.flight_recorder = None
        mc = perf.get("multichip") or {}
        from emqx_tpu.broker.trace import FlightRecorder, resolve_trace
        if resolve_trace(perf.get("trace")) \
                and (use_device or mc.get("enable")):
            self.flight_recorder = FlightRecorder(
                self.metrics, cap=perf.get("trace_ring", 4096),
                sample=perf.get("trace_sample"))
            self.pipeline_telemetry.recorder = self.flight_recorder
        # fault-domain supervision (ISSUE 6): the per-node supervision
        # tree every pipeline stage plugs into — fault injection points,
        # per-stage circuit breakers driving the degradation ladder
        # (device+cache+delta → device-plain → host-trie), the window
        # journal and the stage watchdogs. broker.supervise /
        # EMQX_TPU_SUPERVISE =0 restores the pre-ISSUE-6 ad-hoc unwind
        # behavior exactly (self.supervisor stays None everywhere).
        self.supervisor = None
        from emqx_tpu.broker.supervise import (PipelineSupervisor,
                                               resolve_supervise)
        if resolve_supervise(perf.get("supervise")) \
                and (use_device or mc.get("enable")):
            self.supervisor = PipelineSupervisor(
                self.metrics, telemetry=self.pipeline_telemetry,
                threshold=perf.get("supervise_threshold"))
            self.pipeline_telemetry.supervise_state_fn = \
                self.supervisor.state
            # rung changes / trips / restarts land in the flight
            # recorder as node-scope events (trace id 0) — the causal
            # timeline shows WHEN the ladder moved relative to the
            # windows that tripped it
            self.supervisor.recorder = self.flight_recorder
        # HBM ledger (ISSUE 8): per-category accounting of persistent
        # device allocations (snapshot tables/cursors, delta-overlay
        # versions, mesh shard tables) + the stale-pin sentinel. Both
        # engines register their device_put sites through it;
        # telemetry.snapshot() gains the `memory` section all four
        # exporters publish. broker.hbm_ledger / EMQX_TPU_HBM_LEDGER
        # =0 restores the untracked behavior exactly (self.hbm_ledger
        # stays None everywhere).
        self.hbm_ledger = None
        from emqx_tpu.broker.hbm_ledger import (HbmLedger,
                                                resolve_hbm_ledger)
        if resolve_hbm_ledger(perf.get("hbm_ledger")) \
                and (use_device or mc.get("enable")):
            self.hbm_ledger = HbmLedger(
                self.metrics,
                pin_warn_windows=perf.get("pin_warn_windows"),
                hooks=self.hooks, recorder=self.flight_recorder)
            self.pipeline_telemetry.ledger = self.hbm_ledger
            self.stats.register_stats_fun(self.hbm_ledger.stats_fun)
        # end-to-end latency SLO observatory (ISSUE 13): per-message
        # ingress→routed / ingress→delivered percentiles keyed by
        # (qos, path), the SLO burn engine and breach exemplars.
        # Stamps start at frame decode (mqtt/frame), ride Message
        # through the batcher/host paths, and are recorded at settle.
        # broker.latency_observatory / EMQX_TPU_LATENCY =0 restores the
        # pre-ISSUE-13 observable behavior (self.latency_observatory
        # stays None everywhere: no `latency` snapshot section, REST
        # 404; the frame-decode stamp itself stays on — see the
        # resolver docstring).
        # Deliberately NOT gated on use_device: the host-only twin
        # measures the same e2e legs (path `host`).
        self.latency_observatory = None
        from emqx_tpu.broker.latency import (LatencyObservatory,
                                             resolve_latency_observatory)
        if resolve_latency_observatory(perf.get("latency_observatory")):
            self.latency_observatory = LatencyObservatory(
                self.metrics, hooks=self.hooks,
                recorder=self.flight_recorder,
                objective_ms=perf.get("slo_route_p99_ms"))
            self.pipeline_telemetry.observatory = self.latency_observatory
            self.broker.latency_obs = self.latency_observatory
        # adaptive overload protection (ISSUE 14): the graded load-shed
        # ladder (normal → elevated → overload → critical) polled on
        # the housekeeping tick, fed by signals that already exist —
        # batcher queue/journal depth, lane backpressure, SLO burn,
        # HBM pressure, event-loop lag — arming ordered shed actions
        # per grade (sampling clamp → dispatch-depth shrink + retained
        # defer + CONNECT 0x97 → QoS0 shed + top-offender disconnect).
        # broker.overload / EMQX_TPU_OVERLOAD =0 restores the
        # pre-ISSUE-14 behavior exactly (self.overload_governor stays
        # None everywhere: no `overload` snapshot section, REST 404).
        # Deliberately NOT gated on use_device: a host-only node
        # overloads the same way (its queue/burn signals still exist).
        self.overload_governor = None
        from emqx_tpu.broker.overload import (OverloadGovernor,
                                              resolve_overload)
        if resolve_overload(perf.get("overload")):
            self.overload_governor = OverloadGovernor(
                self, self.metrics, hooks=self.hooks,
                recorder=self.flight_recorder)
            self.pipeline_telemetry.overload_state_fn = \
                self.overload_governor.state
        # session-affine delivery lanes (ISSUE 5): the overlapped egress
        # stage both engines' consume hands plans to. 0 lanes (config
        # broker.deliver_lanes / env EMQX_TPU_DELIVER_LANES) restores
        # the inline delivery loop exactly — the A/B baseline.
        self.deliver_lanes = None
        from emqx_tpu.broker.deliver import (DeliveryLanePool,
                                             resolve_deliver_lanes)
        n_lanes = resolve_deliver_lanes(perf.get("deliver_lanes"))
        if n_lanes > 0 and (use_device or mc.get("enable")):
            self.deliver_lanes = DeliveryLanePool(
                self.broker, self.metrics, hooks=self.hooks,
                telemetry=self.pipeline_telemetry, n_lanes=n_lanes,
                depth=perf.get("deliver_lane_depth", 8),
                supervisor=self.supervisor)
            self.pipeline_telemetry.deliver_state_fn = \
                self.deliver_lanes.state
            self.stats.register_stats_fun(self.deliver_lanes.stats_fun)
        if mc.get("enable"):
            # multichip serving mode: route through a dp×route device
            # mesh (parallel.serving) instead of the single-chip engine;
            # same PublishBatcher protocol, so channels are none the wiser
            from emqx_tpu.broker.batcher import PublishBatcher
            from emqx_tpu.parallel.serving import ShardedRouteServer
            self.device_engine = ShardedRouteServer(
                self, n_devices=mc.get("devices"), dp=mc.get("dp"),
                fanout_cap=perf.get("device_fanout_cap", 128),
                slot_cap=perf.get("device_slot_cap", 16),
                max_batch=mc.get("max_batch", 256),
                compact_readback=perf.get("compact_readback"),
                # churn knob (ISSUE 4): the mesh's churn path is already
                # incremental (per-shard compaction) — the knob is
                # accepted for config parity and surfaced in stats
                delta_overlay=perf.get("delta_overlay"),
                supervisor=self.supervisor,
                dispatch_depth=dispatch_depth,
                # device-to-device exchange stage (ISSUE 15):
                # broker.device_exchange / EMQX_TPU_EXCHANGE =0
                # restores host gather/merge exactly
                device_exchange=perf.get("device_exchange"),
                # subscription covering A/B knob (ISSUE 18; None =
                # EMQX_TPU_COVERING / default-on)
                subscription_covering=perf.get("subscription_covering"))
            self.publish_batcher = PublishBatcher(
                self, self.device_engine,
                window_us=perf.get("batch_window_us", 200),
                max_batch=mc.get("max_batch", 256),
                device_min_batch=perf.get("device_min_batch", 4),
                dispatch_depth=dispatch_depth)
        elif use_device:
            from emqx_tpu.broker.batcher import PublishBatcher
            from emqx_tpu.broker.device_engine import DeviceRouteEngine
            self.device_engine = DeviceRouteEngine(
                self,
                rebuild_threshold=rebuild_threshold,
                fanout_cap=perf.get("device_fanout_cap", 128),
                slot_cap=perf.get("device_slot_cap", 16),
                # device-match reuse layers (None = env / built-in
                # default; see EMQX_TPU_MATCH_CACHE / EMQX_TPU_DEDUP)
                match_cache_size=perf.get("match_cache_size"),
                dedup=perf.get("topic_dedup"),
                # CSR readback compaction A/B knob (ISSUE 3; None =
                # EMQX_TPU_COMPACT_READBACK / default-on)
                compact_readback=perf.get("compact_readback"),
                # delta-overlay A/B knob (ISSUE 4; None =
                # EMQX_TPU_DELTA_OVERLAY / default-on)
                delta_overlay=perf.get("delta_overlay"),
                # subscription covering A/B knob (ISSUE 18; None =
                # EMQX_TPU_COVERING / default-on)
                subscription_covering=perf.get("subscription_covering"),
                supervisor=self.supervisor,
                dispatch_depth=dispatch_depth)
            self.publish_batcher = PublishBatcher(
                self, self.device_engine,
                window_us=perf.get("batch_window_us", 200),
                max_batch=perf.get("max_publish_batch", 1024),
                device_min_batch=perf.get("device_min_batch", 4),
                dispatch_depth=dispatch_depth)
        self.cm = ConnectionManager()
        self.cm.broker = self.broker
        self.banned = Banned()
        aconf = self.config.get("alarm") or {}
        self.alarms = AlarmManager(
            self.hooks, size_limit=aconf.get("size_limit", 1000),
            validity_period=aconf.get("validity_period", 86400))
        self.os_mon = OsMon(self.alarms,
                            self.config.get("sysmon", "os") or {})
        self.stats.register_stats_fun(self.broker.stats_fun)
        self.stats.register_stats_fun(self.cm.stats_fun)
        self.listeners: list = []
        self._apps: list = []      # started feature apps (retainer, ...)
        self._timer_task: Optional[asyncio.Task] = None

    # ---- config-file boot (emqx_machine_app load_config_files +
    #      emqx_listeners:start) ----
    @classmethod
    def from_config_file(cls, path: str, **kw) -> "Node":
        from emqx_tpu.broker.config import Config
        return cls(Config.load_file(path), **kw)

    async def start_listeners(self) -> list:
        """Start every listener configured under `listeners`
        (emqx_listeners.erl:91,126-138: tcp/ssl esockd, ws/wss cowboy)."""
        from emqx_tpu.broker.connection import Listener
        from emqx_tpu.broker.ws import WsListener
        for name, lc in (self.config.get("listeners") or {}).items():
            if not lc.get("enabled", True):
                continue
            ltype = lc.get("type", "tcp")
            ssl_opts = lc.get("ssl") \
                if ltype in ("ssl", "wss") or "ssl" in lc else None
            if ltype in ("ssl", "wss") and not ssl_opts:
                # never silently downgrade a TLS listener to plaintext
                raise ValueError(
                    f"listener {name!r} is type {ltype} but has no ssl "
                    f"block")
            common = dict(bind=lc.get("bind", "0.0.0.0"),
                          port=int(lc.get("port", 0)),
                          zone=lc.get("zone"),
                          max_connections=int(
                              lc.get("max_connections", 1024000)),
                          ssl_opts=ssl_opts)
            if ltype in ("ws", "wss"):
                lst = WsListener(self, path=lc.get("path", "/mqtt"),
                                 **common)
            elif ltype in ("tcp", "ssl"):
                lst = Listener(self, name=f"{ltype}:{name}", **common)
            elif ltype == "quic":
                from emqx_tpu.quic import QuicListener
                ssl_opts = lc.get("ssl") or {}
                if not ssl_opts.get("certfile") or \
                        not ssl_opts.get("keyfile"):
                    raise ValueError(
                        f"quic listener {name!r} needs ssl.certfile and "
                        f"ssl.keyfile")
                common.pop("ssl_opts", None)
                lst = QuicListener(self, certfile=ssl_opts["certfile"],
                                   keyfile=ssl_opts["keyfile"], **common)
            else:
                raise ValueError(f"unknown listener type {ltype!r}")
            await lst.start()
            self.listeners.append(lst)
        return self.listeners

    async def start_dashboard(self):
        """Boot the mgmt REST API + web dashboard from config (the
        reference's emqx_dashboard http listener, default port 18083).
        Opt-in: requires a `dashboard` config section; disable with
        `dashboard.enable = false`. The full /api/v5 surface and the
        single-file UI share one server; everything except the UI page
        and /api/v5/login sits behind the admin token/basic auth."""
        dc = self.config.get("dashboard") or {}
        if not dc or dc.get("enable") is False:
            return None
        from emqx_tpu.apps.dashboard import DashboardAdmin, register_api
        from emqx_tpu.mgmt import Mgmt, make_api
        lc = (dc.get("listeners") or {}).get("http") or {}
        cluster = getattr(self.broker, "cluster", None)
        admin = DashboardAdmin(self)
        mgmt = Mgmt(self, cluster)
        srv = make_api(self, mgmt, cluster=cluster,
                       host=str(lc.get("bind", "127.0.0.1")),
                       port=int(lc.get("port", 18083)))
        srv.auth_check = admin.auth_check
        register_api(srv, self, admin, mgmt)
        await srv.start()
        self.dashboard_server = srv
        return srv

    async def start_apps(self) -> list:
        """Boot every feature app the config declares (retainer, delayed,
        rewrite, rule engine, authn/authz chains, exhook) — the release
        application-start analog. See apps/boot.py for the surface."""
        from emqx_tpu.apps.boot import start_apps
        return await start_apps(self)

    async def start_gateways(self) -> list:
        """Boot protocol gateways from the `gateway` config section
        (emqx_gateway.erl loads gateway.stomp/mqttsn/coap/lwm2m/exproto
        blocks the same way). Each block: enable (default true) + the
        gateway's own options (bind/port/...)."""
        from emqx_tpu.gateway.registry import GatewayRegistry
        reg = getattr(self, "gateway_registry", None)
        if reg is None:
            reg = GatewayRegistry.with_builtins(self)
        started = []
        for name, conf in (self.config.get("gateway") or {}).items():
            if not isinstance(conf, dict) or conf.get("enable") is False:
                continue
            started.append(await reg.load(name, conf))
        return started

    async def stop_gateways(self) -> None:
        reg = getattr(self, "gateway_registry", None)
        if reg is not None:
            for name in list(reg._instances):
                await reg.unload(name)

    async def stop_listeners(self) -> None:
        for lst in self.listeners:
            await lst.stop()
        self.listeners.clear()
        await self.stop_gateways()
        srv = getattr(self, "dashboard_server", None)
        if srv is not None:
            await srv.stop()
            self.dashboard_server = None
        # resources created by the config boot (DB-backed authn/authz):
        # close their pools + health loop or their sockets outlive the node
        mgr = getattr(self, "resources", None)
        if mgr is not None:
            mgr.stop_health_checks()
            for rid in list(mgr.instances):
                await mgr.remove(rid)

    # ---- periodic housekeeping (the reference's per-subsystem timers:
    #      session expiry, retained expiry scan, delayed fire, stats) ----
    def sweep(self) -> None:
        """One housekeeping pass; also callable directly from tests."""
        self.cm.sweep_expired_sessions()
        self.banned.tick()
        self.alarms.tick()
        self.os_mon.tick()
        if self.overload_governor is not None:
            # overload governor poll (ISSUE 14): grade transitions and
            # shed arming ride the housekeeping cadence — BEFORE the
            # app ticks, so the retainer's deferred-replay drain sees
            # the post-recovery flags on the same tick
            self.overload_governor.poll()
        self.stats.sample()
        for app in self._apps:
            tick = getattr(app, "tick", None)
            if tick is not None:
                tick()

    async def _housekeeping(self, interval: float) -> None:
        while True:
            await asyncio.sleep(interval)
            self.sweep()

    def start_timers(self, interval: float = 1.0) -> None:
        if self._timer_task is None:
            if self.overload_governor is not None:
                # the loop-lag probe measures cadence drift against
                # this interval (poll later than interval ⇒ the loop
                # was wedged in callbacks for the difference)
                self.overload_governor.poll_interval_s = interval
            from emqx_tpu.broker.supervise import guard_task
            self._timer_task = guard_task(
                asyncio.ensure_future(self._housekeeping(interval)),
                "node-housekeeping", self.metrics)

    def stop_timers(self) -> None:
        if self._timer_task is not None:
            self._timer_task.cancel()
            self._timer_task = None

    # ---- facade (emqx.erl) ----
    def publish(self, msg: Message) -> int:
        return self.broker.publish(msg)

    async def publish_async(self, msg: Message) -> int:
        """The channel PUBLISH entry: batched through the device route
        pipeline when enabled, else the host per-message path."""
        if self.publish_batcher is not None:
            return await self.publish_batcher.submit(msg)
        return await self.broker.publish_async(msg)

    def publish_nowait(self, msg: Message) -> bool:
        """Fire-and-forget PUBLISH (QoS0 path): pipelines into the batch
        window without serializing the caller's read loop. Returns False
        when not accepted (no batcher, or backpressure bound hit) — the
        caller must `await publish_async` instead, which both preserves
        per-publisher ordering and stalls an overloading read loop."""
        if self.publish_batcher is not None:
            return self.publish_batcher.enqueue(msg)
        return False

    def topics(self) -> list[str]:
        return self.router.topics()

    def hook(self, name: str, action, priority: int = 0) -> None:
        self.hooks.add(name, action, priority)

    def unhook(self, name: str, action_or_tag) -> None:
        self.hooks.delete(name, action_or_tag)

    def run_hook(self, name: str, args: tuple = ()) -> None:
        self.hooks.run(name, args)

    def register_app(self, app: Any) -> Any:
        """Attach a feature app (retainer, delayed, rule engine, ...)."""
        self._apps.append(app)
        return app

    def get_app(self, cls) -> Optional[Any]:
        for a in self._apps:
            if isinstance(a, cls):
                return a
        return None
