"""Publish micro-batcher: the cross-connection batching window + pipeline.

The reference amortizes per-packet costs with `{active, N}` socket reads
inside ONE connection (emqx_connection.erl:111,454-464 — SURVEY.md P10);
the TPU design needs batching ACROSS connections so the fused device route
step sees a real batch. This is that window: channels submit PUBLISHes here
and await their delivery counts; a producer task accumulates messages for at
most `window_us` (or until `max_batch`), runs the `message.publish` hook
fold per message (concurrently — exhook gRPC etc. stay async), then routes
the batch.

Round-2 rework (VERDICT weak #2/#3/#4):

- **Non-blocking**: device dispatch and device→host readback run on executor
  threads (DeviceRouteEngine.dispatch/materialize); the event loop only does
  the cheap encode (prepare) and the delivery walk (finish). A slow relay
  round-trip no longer freezes every connection.
- **Pipelined**: up to `pipeline_depth` dispatched batches are in flight;
  a consumer task completes them strictly in FIFO order, so per-publisher
  ordering holds even when device- and host-routed batches interleave
  (host batches ride the same in-order queue and are routed at consume
  time, never early).
- **Adaptive with live probes both ways**: the device/host choice compares
  measured EWMA costs. The host cost is refreshed by an ACTIVE probe every
  `host_probe_every` device batches (round 2's estimator starved: under
  steady device load the host was never sampled and `device_bypassed`
  could not fire); the device cost is re-probed every `_PROBE_EVERY`
  bypassed batches so a transiently slow device is not written off forever.
  Pipelined device cost is sampled as completion-to-completion time (the
  amortized rate the pipeline actually delivers), not the full round-trip.

Round-10 rework (ISSUE 9 tentpole) — the **double-buffered window
pipeline**: at ``dispatch_depth >= 2`` the consumer becomes a bounded
in-flight settle ring. Each dispatched window's remaining stages
(await dispatch → launch + await materialize) run in their OWN task the
moment the window is admitted from the FIFO queue, up to
``dispatch_depth`` windows concurrently — so dispatch(W+1) runs while
materialize(W) is still crossing the link, and with the engine's async
readback (start-transfer at dispatch return) materialize is
consume-on-arrival. Settle order stays STRICTLY FIFO (the ring head is
always completed first), so per-publisher ordering and the journal
discipline are bit-identical to the synchronous loop. Knob:
``broker.dispatch_depth`` / ``EMQX_TPU_DISPATCH_DEPTH`` (config beats
env beats default 2); ``=1`` restores the pre-ISSUE-9 synchronous
consumer EXACTLY — same code path, same jit programs (no cursor
donation), the A/B baseline. Supervision: each in-flight window's
stage awaits are bounded by the watchdog deadlines INDEPENDENTLY (one
stage task per window), and a mid-pipeline death replays exactly the
journaled windows it touched through the host rung.

Ordering: submissions are FIFO; batches complete in arrival order; within a
batch messages are consumed in order — MQTT's per-publisher-per-topic
ordering is preserved end to end.
"""

from __future__ import annotations

import asyncio
import os
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from emqx_tpu.broker.message import Message

# re-probe the device path after this many consecutive host-routed
# batches, so a transiently slow device (cold compile, relay hiccup)
# is not written off forever
_PROBE_EVERY = 64


def resolve_dispatch_depth(configured=None) -> int:
    """The one dispatch-depth resolution (ISSUE 9): config
    (``broker.dispatch_depth``) beats ``EMQX_TPU_DISPATCH_DEPTH`` beats
    the built-in 2. ``=1`` restores the synchronous consumer loop (and
    the non-donating jit programs) exactly — the A/B baseline every
    depth-twin test compares. Must be a positive integer; anything else
    is a deployment error worth failing loudly on."""
    if configured is None:
        env = os.environ.get("EMQX_TPU_DISPATCH_DEPTH")
        if env is None:
            return 2
        configured = env
    try:
        val = int(configured)
    except (TypeError, ValueError):
        raise ValueError(
            f"EMQX_TPU_DISPATCH_DEPTH={configured!r} is not an integer")
    if val < 1:
        raise ValueError(
            f"EMQX_TPU_DISPATCH_DEPTH must be >= 1, got {val}")
    return val


class PublishBatcher:
    def __init__(self, node, engine, *, window_us: int = 200,
                 max_batch: int = 1024, device_min_batch: int = 4,
                 max_pending: Optional[int] = None,
                 pipeline_depth: int = 8, host_probe_every: int = 32,
                 window_fuse: int = 8,
                 dispatch_depth: Optional[int] = None):
        self.node = node
        self.engine = engine
        # pipeline telemetry (stage spans / occupancy / decisions) — a
        # Node always carries one; tolerate bare test harness nodes
        self.tele = getattr(node, "pipeline_telemetry", None)
        # fault-domain supervision (ISSUE 6): the consumer's watchdog
        # deadlines, the window journal, and the device/host ladder
        # gate all hang off this. None (knob off / bare test nodes)
        # restores the pre-ISSUE-6 unwind behavior exactly.
        self.sup = getattr(node, "supervisor", None)
        # window-causal flight recorder (ISSUE 7): every window's trace
        # id is minted HERE at admit and rides the entry dict through
        # dispatch/materialize/replay/lanes to settle. None (knob off /
        # bare test nodes) restores the pre-ISSUE-7 behavior exactly.
        self.rec = getattr(node, "flight_recorder", None)
        # latency SLO observatory (ISSUE 13): per-message ingress→
        # routed / ingress→delivered recording at settle, keyed by the
        # window's (qos, path) attribution. None (knob off / bare test
        # nodes) restores the pre-ISSUE-13 behavior exactly.
        self.obs = getattr(node, "latency_observatory", None)
        # overload governor (ISSUE 14): at grade critical the
        # shed_qos0 action drops QoS0 PUBLISHes HERE, at admit — QoS1/2
        # are never shed (at-least-once intent honored, per-session
        # order preserved). None (knob off / bare test nodes) restores
        # the pre-ISSUE-14 admit paths exactly. One plain attribute
        # read per message when armed; zero reads when gov is None.
        self.gov = getattr(node, "overload_governor", None)
        # the most recent window's trace id (0 before any window):
        # overload shed events land on this trace so the causal
        # timeline shows the ladder moving between the windows
        self.last_trace = 0
        self.window_s = window_us / 1e6
        self.max_batch = max_batch
        self.device_min_batch = device_min_batch
        self.pipeline_depth = pipeline_depth
        # ISSUE 9: how many dispatched windows may run their remaining
        # stages (dispatch-await + materialize) concurrently ahead of
        # their FIFO settle turn. 1 = the pre-ISSUE-9 synchronous
        # consumer, bit-exact (the legacy code path below).
        self.dispatch_depth = resolve_dispatch_depth(dispatch_depth)
        self.host_probe_every = host_probe_every
        # under sustained load, up to this many consecutive batches fuse
        # into ONE device dispatch (route_window_full) — the per-dispatch
        # cost is paid once per window, the same amortization bench.py
        # measures with BENCH_FUSE
        self.window_fuse = max(1, min(window_fuse, 8))
        # fusion slow-start (congestion-control shaped): the width grows
        # x2 per successfully completed window and resets to 1 whenever
        # the chooser bypasses — early windows stay small so a slow
        # device is discovered after ~1 batch of regret, not 8
        self._fuse_cwnd = 1
        # fire-and-forget backpressure bound: beyond this, enqueue() refuses
        # and the caller must await submit() (stalling its read loop)
        self.max_pending = max_pending or 8 * max_batch
        self._queue: deque = deque()
        self._task: Optional[asyncio.Task] = None
        self._consumer: Optional[asyncio.Task] = None
        self._inflight: Optional[asyncio.Queue] = None
        # one dispatch thread keeps device dispatches ordered (the engine
        # threads cursors batch-to-batch); readbacks overlap on their own
        self._dispatch_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="route-dispatch")
        self._read_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="route-read")
        # adaptive device/host choice: EWMAs of measured cost. On
        # co-located hardware the fused device step wins from tiny
        # batches; behind a high-latency dispatch relay the host path
        # wins until batches amortize the round trip — measure, don't
        # assume (SURVEY §7 hard-part 2's adaptive micro-batching).
        self._dev_batch_s: Optional[float] = None    # per device batch
        self._host_msg_s: Optional[float] = None     # per host message
        self._dev_spike = 0       # consecutive-outlier streaks (_ewma)
        self._host_spike = 0
        # PUBLISH→route latency reservoir (BASELINE.md's p99<2ms
        # criterion is judged on this: oldest-enqueue → batch completion,
        # which upper-bounds every message in the batch). _q_times
        # parallels _queue so the submit/enqueue tuple shape is untouched.
        self._q_times: deque = deque()
        self.route_lat: deque = deque(maxlen=8192)
        self._since_probe = 0         # host batches since last device try
        self._since_host_probe = 0    # device batches since last host probe
        self._last_dev_done: Optional[float] = None
        self._consuming = False       # consumer mid-entry (fast-path gate)

    # ---- producer side --------------------------------------------------
    def _shed_qos0(self, msg: Message) -> bool:
        """ISSUE 14: True when the overload governor's shed_qos0 action
        is armed AND this message is QoS0 — the message is dropped at
        admit (counted; the publisher owes no ack, so nothing hangs).
        QoS1/2 NEVER pass this gate."""
        gov = self.gov
        if gov is not None and gov.shed_qos0 and msg.qos == 0:
            gov.count_qos0_shed()
            return True
        return False

    async def submit(self, msg: Message) -> int:
        """Queue one PUBLISH; resolves to its delivery count."""
        if self._shed_qos0(msg):
            return 0
        fut = asyncio.get_running_loop().create_future()
        self._queue.append((msg, fut))
        self._q_times.append(time.perf_counter())
        self._kick()
        return await fut

    def enqueue(self, msg: Message) -> bool:
        """Fire-and-forget submit (QoS0: the publisher owes no ack, so one
        connection can pipeline publishes into a single batch window).
        Returns False when the queue is over the backpressure bound — the
        caller must fall back to awaiting submit()."""
        if self._shed_qos0(msg):
            return True      # accepted-and-shed: no fallback submit
        if len(self._queue) >= self.max_pending:
            return False
        self._queue.append((msg, None))
        self._q_times.append(time.perf_counter())
        self._kick()
        return True

    def submit_burst(self, rows: list) -> dict:
        """Columnar-ingress hand-off (ISSUE 11): append a whole read
        burst's messages to the batch queue in one pass. `rows` is
        [(Message, needs_count)], in publisher frame order — the queue
        is FIFO, so per-publisher order is preserved by construction.

        QoS0 rows (needs_count=False) ride WITHOUT per-message futures,
        like enqueue(); QoS1/2 rows get futures that resolve through
        the existing window journal / settle machinery. One timestamp
        covers the burst (its rows entered together), one _kick wakes
        the producer, and the burst's unique topics are interned in one
        vectorized native pass (engine.preencode_burst) so the window
        encode later hits a warm gather instead of per-window probes.

        Returns {row_index: future} for every row the caller must
        await: all QoS>=1 rows, plus the burst's LAST row when the
        queue crossed max_pending — awaiting it stalls the read loop,
        the same backpressure a refused enqueue() exerts."""
        loop = asyncio.get_running_loop()
        futs: dict = {}
        q = self._queue
        qt = self._q_times
        now = time.perf_counter()
        over = len(q) + len(rows) > self.max_pending
        last = len(rows) - 1
        for i, (msg, need) in enumerate(rows):
            if not need and self._shed_qos0(msg):
                # ISSUE 14: QoS0 rows shed at admit never enter the
                # queue; QoS1/2 rows (need=True) always do. Relative
                # order of the surviving rows is the row order.
                continue
            fut = None
            if need or (over and i == last):
                fut = loop.create_future()
                futs[i] = fut
            q.append((msg, fut))
            qt.append(now)
        eng = self.engine
        if eng is not None and rows:
            pre = getattr(eng, "preencode_burst", None)
            if pre is not None:
                pre([m.topic for m, _n in rows])
        self._kick()
        return futs

    def _kick(self) -> None:
        if self._inflight is None:
            self._inflight = asyncio.Queue(maxsize=self.pipeline_depth)
        from emqx_tpu.broker.supervise import guard_task
        if self._task is None or self._task.done():
            self._task = guard_task(
                asyncio.get_running_loop().create_task(self._produce()),
                "batcher-produce", self.node.metrics)
        if self._consumer is None or self._consumer.done():
            self._consumer = guard_task(
                asyncio.get_running_loop().create_task(self._consume()),
                "batcher-consume", self.node.metrics)

    async def stop(self) -> None:
        for t in (self._task, self._consumer):
            if t is not None and not t.done():
                t.cancel()
                try:
                    await t
                except asyncio.CancelledError:
                    pass
        # fail anything still queued/in flight so publishers unblock
        err = RuntimeError("publish batcher stopped")
        while self._queue:
            _m, fut = self._queue.popleft()
            if fut is not None and not fut.done():
                fut.set_exception(err)
        self._q_times.clear()
        if self._inflight is not None:
            while not self._inflight.empty():
                entry = self._inflight.get_nowait()
                if entry.get("eof"):
                    continue
                for _m, fut in entry["batch"]:
                    if fut is not None and not fut.done():
                        fut.set_exception(err)
                if entry.get("handle") is not None:
                    self.engine.abandon(entry["handle"])
                if self.sup is not None:
                    self.sup.journal_settle(entry.get("wid"))
        self._task = None
        self._consumer = None

    def close(self) -> None:
        self._dispatch_pool.shutdown(wait=False)
        self._read_pool.shutdown(wait=False)

    # ---- producer: form batches, choose path, dispatch ------------------
    async def _produce(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            while self._queue:
                # adaptive window: the first message opened it; give
                # concurrent connections one short beat to pile on unless
                # already full
                if len(self._queue) < self.max_batch and self.window_s > 0:
                    await asyncio.sleep(self.window_s)
                def form_entry(cap=None):
                    limit = min(self.max_batch, cap) if cap else \
                        self.max_batch
                    batch = []
                    rec = self.rec
                    sampled = None
                    t_enq = self._q_times[0] if self._q_times else \
                        time.perf_counter()
                    while self._queue and len(batch) < limit:
                        batch.append(self._queue.popleft())
                        tq = self._q_times.popleft()
                        if rec is not None and rec.sample_hit():
                            # sampled per-message span (ISSUE 7): this
                            # message records its own enqueue→settle
                            # interval on the window trace
                            if sampled is None:
                                sampled = []
                            sampled.append((len(batch) - 1, tq))
                    now = time.perf_counter()
                    if self.tele is not None:
                        # enqueue stage: oldest-message queue wait before
                        # its batch formed (upper-bounds the batch)
                        self.tele.observe_stage("enqueue", now - t_enq)
                    entry = {"batch": batch, "handle": None, "sub": 0,
                             "dispatch_fut": None, "live": None,
                             "live_idx": None, "t_enq": t_enq}
                    if rec is not None:
                        # the window's trace id, minted at admit; the
                        # enqueue span doubles as the root every later
                        # span parents to
                        tid = rec.new_trace()
                        entry["trace"] = tid
                        self.last_trace = tid
                        entry["root_span"] = rec.record(
                            tid, "enqueue", t_enq, now, track="batcher",
                            meta={"batch": len(batch)})
                        if sampled:
                            entry["trace_msgs"] = sampled
                    if self.sup is not None:
                        # window journal (ISSUE 6): the window is
                        # journaled the moment it is admitted to the
                        # pipeline — its (message, publisher-future)
                        # batch by reference — and settled when its
                        # counts resolve. A stage death mid-window
                        # replays exactly this manifest through the
                        # next ladder rung.
                        entry["wid"] = self.sup.journal_admit(batch)
                    return entry

                group = [form_entry()]
                try:
                    await self._fold_hooks(group[0])
                    if self.engine is not None:
                        # churn check rides the batch cadence: a threshold
                        # crossing kicks the background double-buffered
                        # rebuild even when batches are too small for the
                        # device path
                        self.engine.poll_rebuild()
                    if self.sup is not None:
                        # supervision tick rides the same cadence: due
                        # half-open probes launch here even when every
                        # breaker gates the engine paths shut (the
                        # probes ARE the way back up the ladder)
                        self.sup.poll()
                    live0 = group[0]["live"]
                    # the device/host DECISION runs on the first batch
                    # alone, BEFORE any fusion — a host probe (or bypass)
                    # then costs one batch at host speed, never a whole
                    # fused window
                    dispatched = False
                    use_device = (bool(live0) and self.engine is not None
                                  and len(live0) >= self.device_min_batch)
                    if use_device and self.sup is not None \
                            and not self.sup.allow_device():
                        # ladder rung 2 (ISSUE 6): the dispatch or
                        # materialize breaker is open — this window
                        # routes through the host trie; the half-open
                        # probe (off-path) steps the ladder back up
                        self.node.metrics.inc(
                            "routing.device.supervised_bypass")
                        use_device = False
                    if use_device \
                            and not self.engine.batch_class_warm(
                                len(live0)):
                        # the class would cold-compile in the dispatch
                        # path: route host-side and let the background
                        # warm bring the device online (observed: 5s+
                        # first-ack latency under a cold-start flood)
                        self.engine._kick_class_warm()
                        self.node.metrics.inc("routing.device.cold_class")
                        use_device = False
                    use_device = use_device \
                        and self._device_worth_it(len(live0))
                    if use_device:
                        # window fusion: sustained backlog folds further
                        # batches into the SAME device dispatch — capped
                        # at the largest already-compiled window class
                        # (a cold window compile would stall serving)
                        # and the slow-start width
                        # fusion runs only in the warmed (8, Bstd)
                        # class: a FIRST batch beyond the largest
                        # standard class (max_publish_batch > Bstd and a
                        # deep backlog) dispatches as a single window via
                        # its extra class, but ordinary batches still
                        # fuse — so raising max_publish_batch for burst
                        # headroom does not silently disable fusion
                        b_std = self.engine._STD_CLASSES[-1][1]
                        fuse_cap = 1 if len(live0) > b_std else \
                            min(self.window_fuse,
                                self.engine.max_fuse(),
                                self._fuse_cwnd)
                        while (len(group) < fuse_cap
                               and len(self._queue)
                               >= self.device_min_batch):
                            # later sub-batches must stay inside the
                            # window class too
                            e2 = form_entry(cap=b_std)
                            await self._fold_hooks(e2)
                            group.append(e2)
                    lives = [e["live"] for e in group if e["live"]]
                    if use_device and lives:
                        handle = self.engine.prepare_window(lives)
                        if handle is None:
                            # the device path was CHOSEN but declined
                            # (mid-rebuild, gated swap): these entries
                            # route host-side as the host_fallback
                            # latency series, not plain host — a
                            # rebuild storm shows up as its own tail
                            for e in group:
                                e["fallback"] = True
                        if handle is not None:
                            dispatched = True
                            k = 0
                            first_live = None
                            for e in group:
                                if not e["live"]:
                                    continue
                                e["handle"] = handle
                                e["sub"] = k
                                if first_live is None:
                                    first_live = e
                                k += 1
                            # probe cadence counts SUB-BATCHES, so
                            # fusion does not stretch the host-refresh
                            # interval 8x
                            self._since_host_probe += len(lives)
                            self._since_probe = 0   # device just tried
                            if self.rec is not None:
                                # causal propagation (ISSUE 7): the
                                # fused dispatch records under the LEAD
                                # entry's trace; per-sub traces ride
                                # sub_traces so deliver/lane spans land
                                # on their own window, and fused
                                # followers link to the lead
                                handle.trace = \
                                    first_live.get("trace", 0)
                                handle.sub_traces = [
                                    e.get("trace", 0) for e in group
                                    if e["live"]]
                                for e in group:
                                    if e["live"] and e is not first_live \
                                            and "trace" in e:
                                        self.rec.event(
                                            e["trace"], "fused",
                                            track="batcher",
                                            parent=e.get("root_span", 0),
                                            meta={"lead": handle.trace})
                            first_live["dispatch_fut"] = \
                                loop.run_in_executor(
                                    self._dispatch_pool,
                                    self.engine.dispatch, handle)
                    if not dispatched:
                        self._since_probe += 1
                    if self.tele is not None:
                        if dispatched:
                            # cached = the dedup/match-cache program took
                            # this window (engine attached a plan): the
                            # device/device_cached decision split lets
                            # BENCH rounds attribute throughput moves to
                            # the reuse rate (mesh handles carry no plan
                            # — the mesh bypasses the cache).
                            # device_compact = plain program with the CSR
                            # readback attached; a cached window may ALSO
                            # be compact — routing.device.compact_windows
                            # (incremented at materialize) is the
                            # authoritative compact count, this split
                            # stays the routing-decision view
                            # device_delta = the dispatch fused the
                            # churn overlay (ISSUE 4) — takes precedence
                            # in the split so churn-window throughput is
                            # attributable to the overlay engaging
                            if getattr(handle, "delta", None) is not None:
                                path = "device_delta"
                            elif getattr(handle, "plan", None) \
                                    is not None:
                                path = "device_cached"
                            elif getattr(handle, "pcap", None) \
                                    is not None:
                                path = "device_compact"
                            else:
                                path = "device"
                            self.tele.record_decision(path, len(lives))
                        else:
                            # a fused group can fall back whole (e.g.
                            # prepare_window returned None mid-rebuild):
                            # every entry in it is a host batch
                            self.tele.record_decision("host", len(group))
                            for e in group:
                                self.tele.record_occupancy(
                                    "host",
                                    len(e["batch"]) / self.max_batch)
                except asyncio.CancelledError:
                    for e in group:
                        self._fail_entry(
                            e, RuntimeError("publish batcher stopped"))
                    raise
                except Exception as e:
                    for en in group:
                        en["error"] = e
                if len(group) == 1 and group[0]["handle"] is None \
                        and self._inflight.empty() and not self._consuming:
                    # trickle fast path: nothing in flight ahead of us, so
                    # the host route runs inline — no pipeline hop, p99 at
                    # trickle rates stays where the pre-pipeline drain had
                    # it (SURVEY §7 hard-part 2's dedicated small-batch
                    # path)
                    try:
                        await self._complete_host(group[0])
                    except asyncio.CancelledError:
                        # now cancellable mid-completion (chunked yields),
                        # and this entry is in neither the queue nor the
                        # pipeline — fail it or its publishers strand
                        self._fail_entry(
                            group[0],
                            RuntimeError("publish batcher stopped"))
                        raise
                    continue
                for gi, entry in enumerate(group):
                    try:
                        # FIFO hand-off; blocks when pipeline_depth
                        # batches are in flight (backpressure up to
                        # enqueue()/submit())
                        await self._inflight.put(entry)
                    except asyncio.CancelledError:
                        # stop() cancelled us mid-put: these entries are
                        # in neither the queue nor the pipeline — fail
                        # them here or their publishers hang and the
                        # handle leaks
                        for e in group[gi:]:
                            self._fail_entry(
                                e, RuntimeError("publish batcher stopped"))
                        raise
            # queue drained: park the consumer too, then re-check — a
            # publish that landed while we were suspended on this put would
            # otherwise sit unprocessed (_kick sees a live task and won't
            # restart us)
            await self._inflight.put({"eof": True})
            if not self._queue:
                return

    def _fail_entry(self, entry: dict, err: Exception) -> None:
        for _m, fut in entry["batch"]:
            if fut is not None and not fut.done():
                fut.set_exception(err)
        if entry.get("handle") is not None:
            self.engine.abandon(entry["handle"])
            entry["handle"] = None
        if self.sup is not None:
            # failed ≠ lost silently: the futures above carry the error
            # to their publishers, so the journal entry is accounted for
            self.sup.journal_settle(entry.get("wid"))

    async def _fold_hooks(self, entry: dict) -> None:
        """message.publish hook fold, concurrently across the batch."""
        t0 = time.perf_counter()
        broker = self.node.broker
        batch = entry["batch"]
        if not broker.hooks.lookup("message.publish"):
            # empty hook chain (the common ingest-bound deployment): a
            # fold would return every message unchanged — skip the
            # per-message coroutine fan-out, but keep one scheduling
            # point (the gather was an await; background warms and
            # readbacks rely on the producer yielding between windows)
            await asyncio.sleep(0)
            folded = [m for m, _f in batch]
        else:
            folded = await asyncio.gather(*[
                broker.hooks.run_fold_async("message.publish", (), m)
                for m, _f in batch])
        live_idx: list[int] = []
        live: list[Message] = []
        for i, m in enumerate(folded):
            if m is None or m.get_header("allow_publish") is False:
                continue
            broker.metrics.inc("messages.publish")
            live_idx.append(i)
            live.append(m)
        entry["live"] = live
        entry["live_idx"] = live_idx
        if self.tele is not None:
            self.tele.observe_stage("batch_form",
                                    time.perf_counter() - t0)
        if self.rec is not None and "trace" in entry:
            self.rec.record(entry["trace"], "batch_form", t0,
                            time.perf_counter(), track="batcher",
                            parent=entry.get("root_span", 0))

    # ---- consumer: complete batches strictly in order --------------------
    async def _complete_host(self, entry: dict, routed=None) -> None:
        """Route an entry host-side (or publish a device result) and
        resolve its futures. Raises nothing. Yields every 64 routed
        messages — a 1024-message host fallback otherwise stalls the
        whole event loop for tens of ms. Safe against reordering: the
        trickle caller runs in the producer task (nothing can enqueue
        behind it while it awaits) and the consumer is strictly
        sequential."""
        batch = entry["batch"]
        counts = [0] * len(batch)
        tele = self.tele
        rec = self.rec
        obs = self.obs
        tid = entry.get("trace") if rec is not None else None
        path = "host" if routed is None else "device"
        # latency path attribution (ISSUE 13): the fine-grained series
        # key. The coarse `path` above keeps its two historical values
        # (trace window meta, record_total meta) — the observatory's
        # five-way split is its own dimension.
        if routed is not None:
            lpath = "device_cached" \
                if getattr(entry.get("handle"), "plan", None) is not None \
                else "device"
        elif entry.get("replayed"):
            lpath = "replay"
        elif entry.get("fallback") or entry.get("handle") is not None:
            lpath = "host_fallback"
        else:
            lpath = "host"
        try:
            if "error" in entry:
                raise entry["error"]
            live, live_idx = entry["live"], entry["live_idx"]
            if routed is None and live:
                # deliver lanes first (ISSUE 5): a host-routed batch
                # delivers inline on the loop, so it must wait out any
                # lane-queued device deliveries — otherwise a host batch
                # could overtake an earlier device batch for the same
                # session and break the per-publisher FIFO this
                # consumer exists to preserve
                pool = getattr(self.node, "deliver_lanes", None)
                if pool is not None and pool.busy():
                    t_d = time.perf_counter()
                    await pool.drain()
                    if tid is not None:
                        # a real wait on the lanes: the
                        # lane-backpressure bubble, named
                        rec.record(tid, "lane_drain", t_d,
                                   time.perf_counter(), track="batcher",
                                   parent=entry.get("root_span", 0))
                t0 = time.perf_counter()
                routed = []
                broker = self.node.broker
                for j, m in enumerate(live):
                    if tele is not None and j % 32 == 0:
                        # sampled host match split: the host-side
                        # decomposition of the device program's match
                        # stage (1-in-32 keeps the hot loop cheap)
                        tm = time.perf_counter()
                        mt = broker.router.match(m.topic)
                        tele.observe_stage("host_match",
                                           time.perf_counter() - tm)
                    else:
                        mt = broker.router.match(m.topic)
                    routed.append(broker._route(m, mt))
                    if j % 64 == 63:
                        await asyncio.sleep(0)
                span = time.perf_counter() - t0
                if tele is not None:
                    tele.observe_stage("host_route", span)
                if tid is not None:
                    # a replayed window's host re-route is a CHILD of
                    # its replay span — the original trace id is kept
                    # (ISSUE 7 satellite: causality survives the
                    # degradation ladder)
                    rec.record(tid, "host_route", t0,
                               time.perf_counter(), track="host",
                               parent=entry.get("replay_span")
                               or entry.get("root_span", 0))
                self._host_msg_s, self._host_spike = _ewma(
                    self._host_msg_s, span / len(live),
                    self._host_spike)
                # a host completion breaks the device completion chain:
                # the next device sample must be a full round-trip, not
                # completion-to-completion across this host batch
                self._last_dev_done = None
            if obs is not None and live:
                # ingress→routed (ISSUE 13): the route result for every
                # live message is in hand — device windows arrive here
                # with `routed` precomputed (finish_sub just returned),
                # host/fallback/replay rungs just finished the trie
                # walk. Only socket-ingress messages carry a stamp.
                t_ns = time.perf_counter_ns()
                tr = entry.get("trace", 0)
                for m in live:
                    ing = m.ingress_ns
                    if ing:
                        obs.record_routed(m, lpath, (t_ns - ing) / 1e9,
                                          trace=tr)
            def _settle() -> None:
                if live:
                    for j, i in enumerate(live_idx):
                        counts[i] = routed[j]
                for i, (_m, fut) in enumerate(batch):
                    if fut is not None and not fut.done():
                        fut.set_result(counts[i])
                if self.sup is not None:
                    self.sup.journal_settle(entry.get("wid"))
                if obs is not None and live:
                    # ingress→delivered (ISSUE 13): _settle runs when
                    # the deliveries are written — inline for host
                    # batches, via the DeliveryPlan done-callback when
                    # the PR 5 lanes own the walk
                    t_ns = time.perf_counter_ns()
                    for m in live:
                        ing = m.ingress_ns
                        if ing:
                            obs.record_delivered(m, lpath,
                                                 (t_ns - ing) / 1e9)
                # PUBLISH→route latency sample: oldest enqueue →
                # completion (covers both host- and device-routed
                # entries — the device path funnels through here with
                # `routed` precomputed)
                t_enq = entry.get("t_enq")
                if t_enq is not None:
                    total = time.perf_counter() - t_enq
                    self.route_lat.append(total)
                    if tele is not None:
                        tele.record_total(total, batch=len(batch),
                                          path=path)
                if tid is not None:
                    now = time.perf_counter()
                    w0 = entry.get("t_enq") or now
                    # the window roll-up span (admit → settle) + the
                    # sampled per-message enqueue→settle spans
                    rec.record(tid, "window", w0, now, track="window",
                               meta={"path": path,
                                     "batch": len(batch)})
                    for i, tq in entry.get("trace_msgs", ()):
                        m = batch[i][0]
                        rec.record(tid, "message", tq, now,
                                   track="messages",
                                   parent=entry.get("root_span", 0),
                                   meta={"topic": m.topic,
                                         "qos": m.qos})

            # deliver-lane hand-off (ISSUE 5): a LaneCounts carries the
            # in-flight DeliveryPlan — publisher futures resolve when
            # the lanes finish delivering (counts are placeholders
            # until then), while THIS consumer moves on to the next
            # window. That is the overlap the egress stage buys; the
            # completion chain itself stays FIFO via the lane queues.
            plan = getattr(routed, "plan", None)
            if plan is not None and not plan.done:
                plan.add_done_callback(_settle)
            else:
                _settle()
        except Exception as e:  # route failure must not hang publishers
            for _m, fut in batch:
                if fut is not None and not fut.done():
                    fut.set_exception(e)
            if self.sup is not None:
                self.sup.journal_settle(entry.get("wid"))

    async def _consume(self) -> None:
        if self.dispatch_depth > 1:
            # ISSUE 9 tentpole: the bounded in-flight settle ring —
            # stages run ahead per window, settle stays FIFO
            await self._consume_pipelined()
            return
        loop = asyncio.get_running_loop()
        while True:
            entry = await self._inflight.get()
            if entry.get("eof"):
                if self._park_ok():
                    return
                continue
            self._consuming = True
            try:
                routed = None
                if entry.get("handle") is not None and "error" not in entry:
                    routed = await self._complete_device(entry, loop)
                await self._complete_host(entry, routed)
            except asyncio.CancelledError:
                self._fail_entry(entry,
                                 RuntimeError("publish batcher stopped"))
                raise
            except Exception as e:
                # a failing deliver callback / hook must neither hang the
                # batch's publishers nor kill the consumer task
                self._fail_entry(entry, e)
            finally:
                self._consuming = False

    def _park_ok(self) -> bool:
        """True when the consumer may park (queue drained, producer
        done) — the legacy loop's eof exit condition, shared by the
        pipelined ring."""
        return self._inflight.empty() and not self._queue \
            and (self._task is None or self._task.done())

    async def _run_stages(self, entry: dict, loop) -> bool:
        """The in-flight stage task of ONE dispatched window (ISSUE 9):
        await its dispatch, then launch + await its materialize — ahead
        of the window's FIFO settle turn, concurrently with up to
        dispatch_depth-1 other windows' stage tasks. Returns False
        (handle abandoned, fault noted, replay counted) when the window
        must fall back to the host rung at settle; the error handling is
        the depth-1 consumer's, verbatim, so the supervision contract —
        per-window watchdog deadlines, breaker advancement, journal
        replay — is identical per in-flight window."""
        handle = entry["handle"]
        handle.t0 = time.perf_counter()
        try:
            if self.sup is None:
                try:
                    await entry["dispatch_fut"]
                    await loop.run_in_executor(
                        self._read_pool, self.engine.materialize, handle)
                except Exception as e:
                    self.engine.abandon(handle)
                    self.node.metrics.inc(
                        "routing.device.dispatch_failed")
                    self._note_replay_span(entry, "device",
                                           type(e).__name__)
                    return False
                return True
            if not await self._await_stage(entry["dispatch_fut"],
                                           "dispatch", handle, entry):
                return False
            mat = loop.run_in_executor(
                self._read_pool, self.engine.materialize, handle)
            return await self._await_stage(mat, "materialize", handle,
                                           entry)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # stage machinery itself failed
            self.engine.abandon(handle)
            self.node.metrics.inc("routing.device.dispatch_failed")
            self._note_replay_span(entry, "device", type(e).__name__)
            return False

    async def _consume_pipelined(self) -> None:
        """Depth-N in-flight settle ring (ISSUE 9 tentpole).

        Admission: entries pop from the FIFO queue into the ring; a
        DISPATCHING entry (it owns a window's dispatch_fut) starts its
        stage task immediately, and admission pauses once
        ``dispatch_depth`` such windows are in flight (host batches and
        fused-window followers admit freely — they pin no extra device
        buffers). Settle: strictly the ring head, so completion order —
        and therefore per-publisher delivery order, lane drains, and
        journal settles — is bit-identical to the synchronous loop; only
        WHEN dispatch/materialize run moves. A stage task that failed
        (timeout / fault / injected chaos) already abandoned its handle
        and noted the fault; its window (and independently any other
        in-flight window the same death took down) replays through the
        host rung at its own settle turn — zero QoS>=1 loss, FIFO
        preserved."""
        from emqx_tpu.broker.supervise import guard_task
        loop = asyncio.get_running_loop()
        ring: deque = deque()
        eof_seen = False
        try:
            while True:
                while not eof_seen:
                    if ring:
                        # count LIVE stage tasks only: a window whose
                        # stages finished but which still waits its
                        # FIFO settle turn no longer occupies a
                        # pipeline slot — counting it would serialize
                        # admission behind the settle loop and collapse
                        # the effective depth to ~1 under load
                        in_flight = sum(
                            1 for e in ring
                            if e.get("stage_task") is not None
                            and not e["stage_task"].done())
                        if in_flight >= self.dispatch_depth:
                            break
                        try:
                            entry = self._inflight.get_nowait()
                        except asyncio.QueueEmpty:
                            break
                    else:
                        entry = await self._inflight.get()
                    if entry.get("eof"):
                        if ring:
                            # drain the ring first, then re-check the
                            # park condition (the producer already
                            # exited after this eof)
                            eof_seen = True
                            break
                        if self._park_ok():
                            return
                        continue
                    if entry.get("handle") is not None \
                            and entry.get("dispatch_fut") is not None \
                            and "error" not in entry:
                        entry["stage_task"] = guard_task(
                            loop.create_task(
                                self._run_stages(entry, loop)),
                            "batcher-window-stages", self.node.metrics)
                    ring.append(entry)
                    # the trickle fast path must not overtake ring
                    # entries: anything in the ring means "mid-consume"
                    self._consuming = True
                if not ring:
                    continue
                entry = ring.popleft()
                # pipelined-cost sampling hint: more windows behind us
                # means the completion-to-completion sample is the
                # amortized rate (same rule as the depth-1 queue check)
                entry["_pipeline_busy"] = bool(ring)
                try:
                    routed = None
                    if entry.get("handle") is not None \
                            and "error" not in entry:
                        routed = await self._complete_device(entry, loop)
                    await self._complete_host(entry, routed)
                except asyncio.CancelledError:
                    self._fail_entry(
                        entry, RuntimeError("publish batcher stopped"))
                    raise
                except Exception as e:
                    self._fail_entry(entry, e)
                finally:
                    self._consuming = bool(ring)
                if eof_seen and not ring:
                    eof_seen = False
                    if self._park_ok():
                        return
        except asyncio.CancelledError:
            err = RuntimeError("publish batcher stopped")
            for e in ring:
                st = e.get("stage_task")
                if st is not None and not st.done():
                    st.cancel()
                self._fail_entry(e, err)
            self._consuming = False
            raise

    async def _complete_device(self, entry: dict, loop) -> Optional[list]:
        """Await dispatch + readback off-loop, consume on-loop. Returns the
        per-live-message counts, or None to fall back to the host path.
        Window entries after the first reuse the already-materialized
        handle (FIFO adjacency guarantees the dispatching entry ran).

        Supervision (ISSUE 6): each stage await is bounded by the
        supervisor's watchdog deadline (p99-derived) — a hang trips the
        stage's breaker and replays the window host-side instead of
        wedging this consumer; stage exceptions are attributed to their
        fault domain; a consume failure (e.g. a corrupt readback)
        likewise replays instead of failing the window's publishers.
        Without a supervisor the pre-ISSUE-6 behavior is bit-exact:
        unbounded awaits, one catch-all host fallback for dispatch/
        materialize, consume errors fail the entry."""
        handle = entry["handle"]
        sub = entry.get("sub", 0)
        n_subs = len(handle.subs)
        sup = self.sup
        st = entry.get("stage_task")
        if st is not None:
            # pipelined mode (ISSUE 9): the window's dispatch/
            # materialize ran (watchdog-bounded) in its own in-flight
            # stage task — settle just collects the verdict
            try:
                ok = await st
            except asyncio.CancelledError:
                raise
            except Exception:  # guard_task already logged it
                ok = False
            if not ok:
                return None
        elif entry["dispatch_fut"] is not None:
            handle.t0 = time.perf_counter()
            if sup is None:
                try:
                    await entry["dispatch_fut"]
                    await loop.run_in_executor(
                        self._read_pool, self.engine.materialize, handle)
                except Exception as e:
                    self.engine.abandon(handle)
                    self.node.metrics.inc(
                        "routing.device.dispatch_failed")
                    self._note_replay_span(entry, "device",
                                           type(e).__name__)
                    return None
            else:
                if not await self._await_stage(
                        entry["dispatch_fut"], "dispatch", handle,
                        entry):
                    return None
                mat = loop.run_in_executor(
                    self._read_pool, self.engine.materialize, handle)
                if not await self._await_stage(mat, "materialize",
                                               handle, entry):
                    return None
        if handle.built is None or handle.np_res is None:
            # the window's dispatching entry failed/abandoned earlier
            return None
        if sup is None:
            counts = self.engine.finish_sub(handle, sub)
        else:
            try:
                counts = self.engine.finish_sub(handle, sub)
            except Exception as e:
                # consume died mid-window (corrupt readback / decode
                # bug): abandon the pinned snapshot and replay the
                # journaled window through the next rung — the host
                # path below re-routes every message, so QoS≥1 loses
                # nothing and per-session order holds (the host
                # completion drains the lanes first)
                self.engine.abandon(handle)
                sup.note_fault("materialize", e)
                sup.note_replay()
                self.node.metrics.inc("routing.device.dispatch_failed")
                self._note_replay_span(entry, "consume",
                                       type(e).__name__)
                return None
        pool = getattr(self.node, "deliver_lanes", None)
        if pool is not None and pool.active():
            # backpressure: too many plans queued in the delivery lanes
            # stalls THIS consumer, which fills _inflight, which blocks
            # the producer's put, which bounces submit()/enqueue() —
            # a blocked lane therefore stalls publishers instead of
            # buffering (or dropping) deliveries unboundedly
            t_a = time.perf_counter()
            await pool.admit()
            if self.rec is not None and "trace" in entry \
                    and time.perf_counter() - t_a > 5e-4:
                # only a REAL wait is a lane-backpressure bubble worth
                # a span; the no-wait fast path stays unrecorded
                self.rec.record(entry["trace"], "lane_admit", t_a,
                                time.perf_counter(), track="batcher",
                                parent=entry.get("root_span", 0))
        done = time.perf_counter()
        if sub == n_subs - 1:
            if sup is not None:
                # one healthy window resets the stage breakers'
                # consecutive-fault counters
                sup.note_ok("dispatch")
                sup.note_ok("materialize")
            # ONE cost sample per WINDOW, divided by its width — sampling
            # per entry would count the near-instant later subs of a
            # window as full batches and drag the EWMA to ~zero (the
            # chooser then never bypasses a slow device).  Pipelined cost
            # = completion-to-completion when the pipeline was busy; full
            # latency otherwise.
            if self._last_dev_done is not None \
                    and (not self._inflight.empty()
                         or entry.get("_pipeline_busy")):
                sample = (done - self._last_dev_done) / n_subs
            else:
                sample = (done - (handle.t0 or done)) / n_subs
            self._last_dev_done = done
            self._dev_batch_s, self._dev_spike = _ewma(
                self._dev_batch_s, sample, self._dev_spike)
            # slow-start growth: this window completed, widen the next
            self._fuse_cwnd = min(8, max(2, 2 * n_subs))
        return counts

    async def _await_stage(self, fut, stage: str, handle,
                           entry: Optional[dict] = None) -> bool:
        """Await one off-loop stage under the supervisor's watchdog
        deadline. Returns False (handle abandoned, fault noted, replay
        counted — caller falls back to the host rung) on timeout or
        stage exception; True on success. The deadline derives from the
        stage histogram's p99, so a legitimately-slow relay link earns
        a proportionally longer leash (supervise.deadline)."""
        sup = self.sup
        try:
            await asyncio.wait_for(fut, sup.deadline(stage))
        except asyncio.CancelledError:
            raise
        except asyncio.TimeoutError:
            # the executor thread may still be wedged inside the stage —
            # the breaker keeps further windows off the device while it
            # is; this consumer moves on instead of wedging with it
            self.engine.abandon(handle)
            self.node.metrics.inc("routing.device.dispatch_failed")
            sup.note_stall(stage)
            sup.note_replay()
            self._note_replay_span(entry, stage, "stall")
            return False
        except Exception as e:
            self.engine.abandon(handle)
            self.node.metrics.inc("routing.device.dispatch_failed")
            sup.note_fault(stage, e)
            sup.note_replay()
            self._note_replay_span(entry, stage, type(e).__name__)
            return False
        return True

    def _note_replay_span(self, entry: Optional[dict], stage: str,
                          kind: str) -> None:
        """ISSUE 7 satellite: a window re-routed through the host rung
        KEEPS its original trace id; the replay itself is linked as a
        child span of the window root, and the host_route that follows
        parents to the replay — the causal chain survives the
        supervise replay."""
        if entry is not None:
            # latency path attribution (ISSUE 13): a supervised journal
            # replay lands in the `replay` series, an unsupervised
            # device failure in `host_fallback` — independent of the
            # flight-recorder knob below
            entry["replayed" if self.sup is not None
                  else "fallback"] = True
        rec = self.rec
        if rec is None or entry is None or "trace" not in entry:
            return
        entry["replay_span"] = rec.event(
            entry["trace"], "replay", track="batcher",
            parent=entry.get("root_span", 0),
            meta={"stage": stage, "kind": kind})

    def lat_percentiles(self) -> Optional[dict]:
        """PUBLISH→route latency percentiles (ms) over the reservoir."""
        if not self.route_lat:
            return None
        s = sorted(self.route_lat)
        return {
            "p50_ms": round(s[len(s) // 2] * 1000, 3),
            "p99_ms": round(
                s[min(len(s) - 1, int(len(s) * 0.99))] * 1000, 3),
            "samples": len(s),
        }

    def _device_worth_it(self, n: int) -> bool:
        """Measured-cost routing choice with active probes BOTH ways: the
        device is re-tried every _PROBE_EVERY host batches, and the host is
        re-sampled every host_probe_every device batches (otherwise the host
        estimate starves under steady device load and the bypass can never
        engage — round-2 weak #2). The decision runs on the FIRST batch of
        a prospective window (n = its live count) before any fusion;
        _dev_batch_s is the amortized per-sub-batch completion cost, so the
        single-sub-batch comparison is the per-sub-batch comparison."""
        if self._dev_batch_s is None:
            return True      # optimistic: measure the device first
        if self._host_msg_s is None \
                or self._since_host_probe >= self.host_probe_every:
            # active host probe: route this one host-side to seed/refresh
            # the estimate (costs one batch at host speed). Without it the
            # host cost is never measured under steady device load and the
            # bypass can never engage (round-2 weak #2). Counters reset at
            # DECISION time — resetting at consume time would turn one
            # scheduled probe into a pipeline_depth-long probe burst.
            self._since_host_probe = 0
            return False
        if self._since_probe >= _PROBE_EVERY:
            self._since_probe = 0
            return True
        if self._dev_batch_s <= n * self._host_msg_s:
            return True
        self.node.metrics.inc("routing.device.bypassed")
        self._fuse_cwnd = 1      # re-enter fusion carefully next time
        return False


def _ewma(cur: Optional[float], sample: float, streak: int = 0,
          alpha: float = 0.2) -> tuple[Optional[float], int]:
    """Cost estimate: pessimize fast — but not on ONE bad sample. A first
    sample >3x the estimate is DISCARDED (estimate unchanged) and arms the
    outlier streak; a second consecutive >3x sample — still measured
    against the same un-drifted baseline — is a sustained slowdown and is
    adopted outright. A lone spike (GC pause, one relay hiccup) can no
    longer rewrite a path's cost and misroute traffic for up to
    _PROBE_EVERY batches; a real 3x+ slowdown is adopted on its second
    window. A wrongly-pessimized estimate still self-corrects: the active
    probes re-measure both paths on a bounded cadence.
    Returns (estimate, outlier_streak)."""
    if cur is None:
        return sample, 0
    if sample > 3 * cur:
        if streak >= 1:
            return sample, streak + 1
        return cur, 1
    return (1 - alpha) * cur + alpha * sample, 0
