"""Publish micro-batcher: the cross-connection batching window.

The reference amortizes per-packet costs with `{active, N}` socket reads
inside ONE connection (emqx_connection.erl:111,454-464 — SURVEY.md P10);
the TPU design needs batching ACROSS connections so the fused device route
step sees a real batch. This is that window: channels submit PUBLISHes here
and await their delivery counts; a drain task accumulates messages for at
most `window_us` (or until `max_batch`), runs the `message.publish` hook
fold per message (concurrently — exhook gRPC etc. stay async), then routes
the batch:

- batches >= `device_min_batch` with a built device snapshot go through
  DeviceRouteEngine.route_batch (the fused match+fanout+shared step);
- small batches take the host per-message path — the dedicated small-batch
  path of SURVEY.md §7 hard-part 2, keeping p99 low at trickle rates.

The drain task lives only while the queue is non-empty (spawned by submit,
exits when drained), so an idle broker holds no background task.

Ordering: submissions are FIFO; the drain processes whole batches in
arrival order, and within a batch messages are consumed in order, so MQTT's
per-publisher-per-topic ordering is preserved.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Optional

from emqx_tpu.broker.message import Message

# re-probe the device path after this many consecutive host-routed
# batches, so a transiently slow device (cold compile, relay hiccup)
# is not written off forever
_PROBE_EVERY = 64


class PublishBatcher:
    def __init__(self, node, engine, *, window_us: int = 200,
                 max_batch: int = 1024, device_min_batch: int = 4,
                 max_pending: Optional[int] = None):
        self.node = node
        self.engine = engine
        self.window_s = window_us / 1e6
        self.max_batch = max_batch
        self.device_min_batch = device_min_batch
        # fire-and-forget backpressure bound: beyond this, enqueue() refuses
        # and the caller must await submit() (stalling its read loop)
        self.max_pending = max_pending or 8 * max_batch
        self._queue: deque = deque()
        self._task: Optional[asyncio.Task] = None
        # adaptive device/host choice: EWMAs of measured cost. On
        # co-located hardware the fused device step wins from tiny
        # batches; behind a high-latency dispatch relay the host path
        # wins until batches amortize the round trip — measure, don't
        # assume (SURVEY §7 hard-part 2's adaptive micro-batching).
        self._dev_batch_s: Optional[float] = None    # per device batch
        self._host_msg_s: Optional[float] = None     # per host message
        self._since_probe = 0

    # ---- producer side --------------------------------------------------
    async def submit(self, msg: Message) -> int:
        """Queue one PUBLISH; resolves to its delivery count."""
        fut = asyncio.get_running_loop().create_future()
        self._queue.append((msg, fut))
        self._kick()
        return await fut

    def enqueue(self, msg: Message) -> bool:
        """Fire-and-forget submit (QoS0: the publisher owes no ack, so one
        connection can pipeline publishes into a single batch window).
        Returns False when the queue is over the backpressure bound — the
        caller must fall back to awaiting submit()."""
        if len(self._queue) >= self.max_pending:
            return False
        self._queue.append((msg, None))
        self._kick()
        return True

    def _kick(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._drain())

    async def stop(self) -> None:
        if self._task is not None and not self._task.done():
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        self._task = None

    # ---- drain loop (alive only while the queue is non-empty) -----------
    async def _drain(self) -> None:
        while self._queue:
            # adaptive window: the first message opened it; give concurrent
            # connections one short beat to pile on unless already full
            if len(self._queue) < self.max_batch and self.window_s > 0:
                await asyncio.sleep(self.window_s)
            batch = []
            while self._queue and len(batch) < self.max_batch:
                batch.append(self._queue.popleft())
            try:
                await self._process(batch)
            except Exception as e:  # route failure must not hang publishers
                for _m, fut in batch:
                    if fut is not None and not fut.done():
                        fut.set_exception(e)

    async def _process(self, batch: list) -> None:
        broker = self.node.broker
        # message.publish hook fold, concurrently across the batch
        folded = await asyncio.gather(*[
            broker.hooks.run_fold_async("message.publish", (), m)
            for m, _f in batch])
        live_idx: list[int] = []
        live: list[Message] = []
        for i, m in enumerate(folded):
            if m is None or m.get_header("allow_publish") is False:
                continue
            broker.metrics.inc("messages.publish")
            live_idx.append(i)
            live.append(m)

        counts = [0] * len(batch)
        if live:
            routed = None
            if (self.engine is not None
                    and len(live) >= self.device_min_batch
                    and self._device_worth_it(len(live))):
                t0 = time.perf_counter()
                routed = self.engine.route_batch(live)
                if routed is not None:
                    self._dev_batch_s = _ewma(
                        self._dev_batch_s, time.perf_counter() - t0)
                    self._since_probe = 0
            if routed is None:
                t0 = time.perf_counter()
                routed = [broker._route(m, broker.router.match(m.topic))
                          for m in live]
                self._host_msg_s = _ewma(
                    self._host_msg_s,
                    (time.perf_counter() - t0) / len(live))
                self._since_probe += 1
            for j, i in enumerate(live_idx):
                counts[i] = routed[j]
        for i, (_m, fut) in enumerate(batch):
            if fut is not None and not fut.done():
                fut.set_result(counts[i])

    def _device_worth_it(self, n: int) -> bool:
        """Measured-cost routing choice; optimistic until both EWMAs
        exist, periodic re-probe so estimates track the environment."""
        if self._dev_batch_s is None or self._host_msg_s is None:
            return True
        if self._since_probe >= _PROBE_EVERY:
            return True
        if self._dev_batch_s <= n * self._host_msg_s:
            return True
        self.node.metrics.inc("routing.device.bypassed")
        return False


def _ewma(cur: Optional[float], sample: float,
          alpha: float = 0.2) -> float:
    if cur is None:
        return sample
    # clamp wild outliers (a cold compile inside a sample) so one spike
    # does not dominate the estimate
    return (1 - alpha) * cur + alpha * min(sample, 5 * cur)
