"""Banned-client table + flapping detection.

Parity: apps/emqx/src/emqx_banned.erl (mnesia table keyed by
{clientid|username|peerhost, Value} with until-timestamp, checked during
CONNECT) and emqx_flapping.erl (connect/disconnect churn within a window
→ auto-ban, emqx_flapping.erl:69-72).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

WHO_KINDS = ("clientid", "username", "peerhost")


@dataclass
class BanEntry:
    kind: str
    value: str
    by: str = "admin"
    reason: str = ""
    at: float = field(default_factory=time.time)
    until: Optional[float] = None        # epoch seconds; None = forever

    def expired(self, now: Optional[float] = None) -> bool:
        return self.until is not None and (now or time.time()) >= self.until


class Banned:
    def __init__(self):
        self._t: dict[tuple[str, str], BanEntry] = {}

    def create(self, kind: str, value: str, *, by: str = "admin",
               reason: str = "", duration: Optional[float] = None) -> BanEntry:
        if kind not in WHO_KINDS:
            raise ValueError(f"bad ban kind {kind!r}")
        e = BanEntry(kind, value, by=by, reason=reason,
                     until=None if duration is None
                     else time.time() + duration)
        self._t[(kind, value)] = e
        return e

    def delete(self, kind: str, value: str) -> bool:
        return self._t.pop((kind, value), None) is not None

    def look_up(self, kind: str, value: str) -> Optional[BanEntry]:
        e = self._t.get((kind, value))
        if e is not None and e.expired():
            del self._t[(kind, value)]
            return None
        return e

    def check(self, clientinfo: dict) -> bool:
        """True if the connecting client is banned (emqx_banned:check/1)."""
        peer = clientinfo.get("peername")
        probes = (("clientid", clientinfo.get("clientid")),
                  ("username", clientinfo.get("username")),
                  ("peerhost", peer[0] if peer else None))
        return any(v is not None and self.look_up(k, str(v)) is not None
                   for k, v in probes)

    def all(self) -> list[BanEntry]:
        self.expire()
        return list(self._t.values())

    def expire(self) -> int:
        now = time.time()
        stale = [k for k, e in self._t.items() if e.expired(now)]
        for k in stale:
            del self._t[k]
        return len(stale)

    def tick(self) -> None:
        self.expire()


class FlappingDetect:
    """client.connected/disconnected hook pair counting churn per client.

    Parity: emqx_flapping.erl — a client exceeding `max_count`
    disconnects within `window_time` seconds is banned for `ban_time`.
    """

    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        c = dict(node.config.get("flapping_detect") or {})
        c.update(conf or {})
        self.enable = c.get("enable", False)
        self.max_count = int(c.get("max_count", 15))
        self.window = float(c.get("window_time", 60))
        self.ban_time = float(c.get("ban_time", 300))
        self._hits: dict[str, list[float]] = {}

    def load(self) -> "FlappingDetect":
        if self.enable:
            self.node.hooks.add("client.disconnected",
                                self.on_client_disconnected, tag="flapping")
        return self

    def unload(self) -> None:
        self.node.hooks.delete("client.disconnected", "flapping")

    def on_client_disconnected(self, clientinfo: dict, reason) -> None:
        cid = clientinfo.get("clientid")
        if not cid:
            return
        now = time.monotonic()
        hits = self._hits.setdefault(cid, [])
        hits.append(now)
        cutoff = now - self.window
        while hits and hits[0] < cutoff:
            hits.pop(0)
        if len(hits) >= self.max_count:
            del self._hits[cid]
            self.node.banned.create(
                "clientid", cid, by="flapping_detect",
                reason=f"flapping: {self.max_count} disconnects in "
                       f"{self.window}s", duration=self.ban_time)
            self.node.metrics.inc("client.flapping.banned")

    def tick(self) -> None:
        """Housekeeping: drop clientids whose newest disconnect left the
        window — otherwise one timestamp list leaks per clientid ever
        disconnected."""
        cutoff = time.monotonic() - self.window
        stale = [cid for cid, hits in self._hits.items()
                 if not hits or hits[-1] < cutoff]
        for cid in stale:
            del self._hits[cid]
