"""The pubsub engine: subscribe/unsubscribe/publish/dispatch.

Parity: emqx_broker.erl (publish/1 :199-209, dispatch/2 :282-308,
subscriber tables :96-109) + emqx_shared_sub.erl (group strategies :62-67,
pick :239-268). Host-side engine over the Router; the device fused path
(models.router_engine.route_step) serves the bulk micro-batch pipeline,
while this engine is the authoritative per-message semantics.

Subscribers are registered as deliver callbacks keyed by an integer
subscriber id (the "session row" of the device tables); the reference's
`SubPid ! {deliver,...}` becomes `subscriber.deliver(filter, msg)`.
"""

from __future__ import annotations

import random
import time
import zlib
from dataclasses import dataclass, field
from typing import Callable, Optional, Protocol

from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker.message import Message
from emqx_tpu.broker.metrics import Metrics
from emqx_tpu.broker.router import Router
from emqx_tpu.utils import topic as T

SHARED_STRATEGIES = ("random", "round_robin", "sticky", "hash_clientid",
                     "hash_topic")


class Subscriber(Protocol):
    def deliver(self, topic_filter: str, msg: Message) -> bool:
        """Deliver one routed message; False = nack (shared redispatch).

        `msg` is either a full Message copy (the host/inline paths —
        `_deliver` below) or a `broker.deliver.DeliveryView` (the
        ISSUE-5 delivery-lane fast path): a copy-on-write view sharing
        the routed message's payload/headers with `subopts` overlaid.
        Both quack the same; treat the delivered message's `subopts`
        as frozen (views share one 64-entry unpacked-subopts table).

        Subscribers MAY also implement
        `deliver_batch(items: list[tuple[str, Message]]) -> int`
        (all-or-none accept; returns len(items) or 0): the delivery
        lanes coalesce a same-session run of messages into one call so
        the session accept + socket drain amortize across the run.
        Without it, the lanes fall back to per-message deliver()."""


@dataclass
class SharedGroup:
    members: dict[int, dict] = field(default_factory=dict)  # sid -> subopts
    cursor: int = 0                 # round_robin position
    sticky: Optional[int] = None    # sticky member


def _hash(s: str) -> int:
    return zlib.crc32(s.encode())


class Broker:
    def __init__(self, router: Optional[Router] = None,
                 hooks: Optional[Hooks] = None,
                 metrics: Optional[Metrics] = None,
                 shared_strategy: str = "round_robin",
                 shared_dispatch_ack: bool = False):
        self.router = router or Router()
        self.hooks = hooks or Hooks()
        self.metrics = metrics or Metrics()
        self.shared_strategy = shared_strategy
        self.shared_dispatch_ack = shared_dispatch_ack

        # set by cluster.ClusterNode when this broker joins a cluster:
        # replicates routes/shared-members and forwards cross-node
        self.cluster = None
        # set by DeviceRouteEngine: membership-churn listener for the
        # compiled device snapshot
        self.device_engine = None
        # set by Node when the latency observatory (ISSUE 13) is on:
        # the per-message host publish path (no batcher — pure host
        # nodes, gateways awaiting publish_async directly) records its
        # ingress→routed/delivered spans here; the batcher-owned paths
        # record at batch settle instead, never both for one message
        self.latency_obs = None

        self._subscribers: dict[int, Subscriber] = {}
        self._sub_meta: dict[int, str] = {}     # sid -> clientid
        self._pub_tasks: set = set()            # in-flight publish_soon
        # filter -> {sid -> subopts}  (emqx_subscriber + emqx_suboption)
        self.subs: dict[str, dict[int, dict]] = {}
        # real filter -> {group -> SharedGroup} (emqx_shared_subscription),
        # indexed by filter so dispatch only touches matched groups
        self.shared: dict[str, dict[str, SharedGroup]] = {}
        self._next_sid = 0

    # ---- subscriber registry ----
    def register(self, subscriber: Subscriber, clientid: str = "") -> int:
        sid = self._next_sid
        self._next_sid += 1
        self._subscribers[sid] = subscriber
        self._sub_meta[sid] = clientid
        return sid

    def unregister(self, sid: int) -> None:
        self._subscribers.pop(sid, None)
        self._sub_meta.pop(sid, None)

    def swap_subscriber(self, sid: int, subscriber: Subscriber) -> None:
        """Re-point an existing sid at a new deliver target (used when a
        connection detaches, leaving its persistent session parked, and
        when it re-attaches — the reference instead keeps the channel
        process alive in 'disconnected' state)."""
        self._subscribers[sid] = subscriber

    # ---- subscribe / unsubscribe (emqx_broker:subscribe/3 :115-162) ----
    def subscribe(self, sid: int, topic_filter: str,
                  subopts: Optional[dict] = None) -> None:
        real, opts = T.parse(topic_filter, dict(subopts or {}))
        group = opts.get("share")
        if group:
            g = self.shared.setdefault(real, {}).setdefault(
                group, SharedGroup())
            g.members[sid] = opts
            if len(g.members) == 1:
                self.router.add_route(real)
            if self.cluster:
                self.cluster.shared_join(real, group, sid)
            if self.device_engine:
                self.device_engine.note_member_change(real, group)
        else:
            fsubs = self.subs.setdefault(real, {})
            fsubs[sid] = opts
            if len(fsubs) == 1:
                self.router.add_route(real)
                if self.cluster:
                    self.cluster.local_route_add(real)
            if self.device_engine:
                self.device_engine.note_member_change(real, None)

    def unsubscribe(self, sid: int, topic_filter: str) -> bool:
        real, opts = T.parse(topic_filter)
        group = opts.get("share")
        if group:
            groups = self.shared.get(real)
            g = groups.get(group) if groups else None
            if not g or sid not in g.members:
                return False
            del g.members[sid]
            if g.sticky == sid:
                g.sticky = None
            if self.cluster:
                self.cluster.shared_leave(real, group, sid)
            if self.device_engine:
                self.device_engine.note_member_change(real, group)
            if not g.members:
                del groups[group]
                if not groups:
                    del self.shared[real]
                if not self._has_any_sub(real):
                    self._route_del(real)
            return True
        fsubs = self.subs.get(real)
        if not fsubs or sid not in fsubs:
            return False
        del fsubs[sid]
        if not fsubs:
            del self.subs[real]
            if not self._has_any_sub(real):
                self._route_del(real)
        if self.device_engine:
            self.device_engine.note_member_change(real, None)
        return True

    def _route_del(self, real: str) -> None:
        """Remove the local route; under a cluster the filter stays in the
        local trie while any remote node still routes it (the reference's
        per-node #route rows — emqx_router.erl:77-86)."""
        if self.cluster:
            self.cluster.local_route_del(real)
        else:
            self.router.delete_route(real)

    def _has_any_sub(self, real: str) -> bool:
        if self.subs.get(real):
            return True
        return any(g.members for g in self.shared.get(real, {}).values())

    def subscriber_down(self, sid: int) -> None:
        """Clean every subscription of a dead subscriber
        (emqx_broker_helper DOWN cleanup, emqx_broker.erl:330-347)."""
        for f in [f for f, m in self.subs.items() if sid in m]:
            self.unsubscribe(sid, f)
        for real, groups in list(self.shared.items()):
            for group in [gn for gn, g in groups.items()
                          if sid in g.members]:
                self.unsubscribe(sid, f"$share/{group}/{real}")
        self.unregister(sid)

    # ---- publish (emqx_broker:publish/1 :199-209) ----
    def publish(self, msg: Message) -> int:
        """Run message.publish hooks, route, dispatch. Returns deliveries.

        A hook setting allow_publish=false (delayed interception, rule-engine
        republish guards) stops routing quietly — the reference just returns
        [] without counting a drop (emqx_broker.erl:203-208).

        Async message.publish callbacks (exhook gRPC) are skipped on this
        sync path; client publishes go through publish_async which awaits
        them (the reference blocks the channel process there)."""
        msg = self.hooks.run_fold("message.publish", (), msg)
        if msg is None or msg.get_header("allow_publish") is False:
            return 0
        self.metrics.inc("messages.publish")
        return self._route(msg, self.router.match(msg.topic))

    async def publish_async(self, msg: Message) -> int:
        """publish/1 with awaited message.publish callbacks — the channel's
        per-client PUBLISH path, where a slow extension blocks only this
        client like the reference's channel process."""
        msg = await self.hooks.run_fold_async("message.publish", (), msg)
        if msg is None or msg.get_header("allow_publish") is False:
            return 0
        self.metrics.inc("messages.publish")
        n = self._route(msg, self.router.match(msg.topic))
        obs = self.latency_obs
        if obs is not None and msg.ingress_ns:
            # ISSUE 13, batcher-less host path: routing and delivery
            # are one inline walk, so both legs share the settle clock
            s = (time.perf_counter_ns() - msg.ingress_ns) / 1e9
            obs.record_routed(msg, "host", s)
            obs.record_delivered(msg, "host", s)
        return n

    def publish_soon(self, msg: Message) -> None:
        """Fire-and-forget publish from sync code paths (will messages,
        gateway datagrams, rule republish): schedules publish_async so
        async extension hooks (exhook) still see the message; falls back
        to the sync path when no loop is running. Tasks are strongly held
        until done — the loop only keeps weak refs and GC could otherwise
        drop an in-flight publish."""
        import asyncio
        try:
            task = asyncio.get_running_loop().create_task(
                self.publish_async(msg))
        except RuntimeError:
            self.publish(msg)
            return
        self._pub_tasks.add(task)
        task.add_done_callback(self._pub_tasks.discard)
        from emqx_tpu.broker.supervise import guard_task
        guard_task(task, "publish-soon", self.metrics)

    def publish_batch(self, msgs: list[Message]) -> list[int]:
        """Micro-batched publish: one device route step for the whole batch
        (the {active,N}-window analog, SURVEY.md P10)."""
        live: list[Message] = []
        for m in msgs:
            mm = self.hooks.run_fold("message.publish", (), m)
            if mm is None or mm.get_header("allow_publish") is False:
                live.append(None)
            else:
                self.metrics.inc("messages.publish")
                live.append(mm)
        idx = [i for i, m in enumerate(live) if m is not None]
        counts = [0] * len(msgs)
        routed = None
        if self.device_engine is not None and idx:
            routed = self.device_engine.route_batch([live[i] for i in idx])
        if routed is None:
            matched = self.router.match_batch([live[i].topic for i in idx])
            routed = [self._route(live[i], matched[j])
                      for j, i in enumerate(idx)]
        for j, i in enumerate(idx):
            counts[i] = routed[j]
        return counts

    def _route(self, msg: Message, filters: list[str]) -> int:
        n = 0
        for f in filters:
            n += self.dispatch(f, msg)
        n += self._dispatch_shared(msg, filters)
        if self.cluster:
            n += self.cluster.forward(msg, filters)
        if n == 0 and not msg.is_sys:
            self.metrics.inc("messages.dropped")
            self.metrics.inc("messages.dropped.no_subscribers")
            self.hooks.run("message.dropped", (msg, "no_subscribers"))
        return n

    # ---- dispatch (emqx_broker:dispatch/2 :282-308) ----
    def dispatch(self, topic_filter: str, msg: Message) -> int:
        n = 0
        for sid, subopts in list(self.subs.get(topic_filter, {}).items()):
            if self._deliver(sid, topic_filter, msg, subopts):
                n += 1
        return n

    def _deliver(self, sid: int, topic_filter: str, msg: Message,
                 subopts: dict) -> bool:
        # the per-subscriber copy + header plant is the ordering-safe
        # inline baseline (deliver_lanes=0 A/B anchor); the ISSUE-5 lane
        # fast path replaces it with a copy-on-write DeliveryView and
        # batches the metric/hook tail per lane slice (broker/deliver.py)
        sub = self._subscribers.get(sid)
        if sub is None:
            return False
        m = msg.copy()
        m.headers["subopts"] = subopts
        ok = sub.deliver(topic_filter, m)
        if ok:
            self.metrics.inc("messages.delivered")
            self.hooks.run("message.delivered", (self._sub_meta.get(sid), m))
        return bool(ok)

    # ---- shared dispatch (emqx_shared_sub:dispatch :120-135) ----
    def _dispatch_shared(self, msg: Message, filters: list[str]) -> int:
        if self.cluster:
            return self.cluster.dispatch_shared(self, msg, filters)
        n = 0
        for real in filters:
            for group, g in list(self.shared.get(real, {}).items()):
                if g.members and self._shared_pick_deliver(group, real, g,
                                                           msg):
                    n += 1
        return n

    def _shared_pick_deliver(self, group: str, real: str, g: SharedGroup,
                             msg: Message) -> bool:
        """Pick per strategy; on nack retry remaining members (failover,
        emqx_shared_sub.erl:120-135)."""
        order = self._pick_order(group, real, g, msg)
        for k, sid in enumerate(order):
            opts = g.members.get(sid)
            if opts is None:
                continue
            if self._deliver(sid, real, msg, dict(opts, share=group)):
                if self.shared_strategy == "sticky":
                    g.sticky = sid
                return True
            if not self.shared_dispatch_ack:
                return False   # without ack protocol, first pick is final
        return False

    def _pick_order(self, group: str, real: str, g: SharedGroup,
                    msg: Message) -> list[int]:
        sids = list(g.members)
        s = self.shared_strategy
        if s == "sticky" and g.sticky in g.members:
            first = g.sticky
        elif s == "round_robin":
            # pick-then-advance: first registered member gets the first
            # message, matching the device kernel (ops.shared.pick_members)
            # and the reference's counter start (emqx_shared_sub.erl:284-290)
            first = sids[g.cursor % len(sids)]
            g.cursor = (g.cursor + 1) % len(sids)
        elif s == "hash_clientid":
            first = sids[_hash(msg.from_) % len(sids)]
        elif s == "hash_topic":
            first = sids[_hash(msg.topic) % len(sids)]
        else:
            first = sids[random.randrange(len(sids))]
        rest = [x for x in sids if x != first]
        random.shuffle(rest)
        return [first] + rest

    # ---- introspection (emqx.erl facade: topics/subscriptions/subscribers) ----
    def subscriptions(self, sid: int) -> list[tuple[str, dict]]:
        out = [(f, m[sid]) for f, m in self.subs.items() if sid in m]
        out += [(f"$share/{grp}/{real}", g.members[sid])
                for real, groups in self.shared.items()
                for grp, g in groups.items() if sid in g.members]
        return out

    def subscribers(self, topic_filter: str) -> list[int]:
        return list(self.subs.get(topic_filter, {}))

    def subscription_count(self) -> int:
        return (sum(len(m) for m in self.subs.values()) +
                self.shared_subscription_count())

    def shared_subscription_count(self) -> int:
        return sum(len(g.members) for groups in self.shared.values()
                   for g in groups.values())

    def stats_fun(self, stats) -> None:
        """Parity: emqx_broker:stats_fun/0."""
        stats.setstat("topics.count", self.router.route_count(), "topics.max")
        stats.setstat("subscribers.count",
                      sum(len(m) for m in self.subs.values()),
                      "subscribers.max")
        stats.setstat("subscriptions.count", self.subscription_count(),
                      "subscriptions.max")
        stats.setstat("subscriptions.shared.count",
                      self.shared_subscription_count(),
                      "subscriptions.shared.max")
