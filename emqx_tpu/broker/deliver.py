"""Parallel fan-out delivery lanes: the session-affine egress stage.

ISSUE 5 tentpole. PR 2-4 made match, readback and churn device-fast,
but every delivery still funneled through one serial Python loop on the
consume side (`DeviceRouteEngine._fast_deliver` row-by-row into
`Broker._deliver`), with a `msg.copy()` + headers-dict mutation + hook
dispatch per subscriber — at the north-star fan-out (deliveries/s >>
matches/s) egress was the hard ceiling, and it blocked the next
window's finish. This module turns deliver into its own overlapped
pipeline stage:

- **DeliveryPlan**: the vectorized delivery plan of one consumed
  sub-batch. The engine's row-attribution gather already produces
  `(row_msg, sid, opt, fid)` arrays; the plan buckets them by
  `sid % n_lanes` with ONE stable argsort pass (secondary key `sid`, so
  same-session deliveries are contiguous for coalescing) and hands each
  lane a contiguous slice. A session always hashes to the same lane,
  so per-session FIFO — the MQTT ordering invariant — holds by
  construction. Slow-path messages (shared groups, rich subopts,
  delta-matched, dirty filters, host fallbacks) ride the SAME plan as
  ordered closures behind an all-lanes barrier: every lane finishes its
  fast slices first, exactly one worker runs the slow closures in batch
  order, and no lane proceeds past the barrier meanwhile — the
  per-session interleaving is bit-identical to the inline loop
  (fast rows first, then slow rows, per window).

- **DeliveryLanePool**: a small pool of asyncio lane workers (config
  `broker.deliver_lanes` / env `EMQX_TPU_DELIVER_LANES`, default
  `min(4, cpus)`; `=0` restores the inline loop exactly — the A/B
  baseline) consuming per-lane queues. The batcher's consume stage
  submits the plan and returns, so delivery overlaps the next window's
  dispatch/materialize (which run on executor threads and release the
  GIL in XLA / the relay HTTP client); `admit()` bounds outstanding
  plans and propagates backpressure to the batcher's `_inflight` queue,
  and `drain()` serializes host-routed batches behind in-flight lane
  work so device/host interleaving cannot reorder a session's stream.

- **DeliveryView**: the copy-on-write per-delivery message. Replaces
  the per-subscriber `msg.copy()` + `headers["subopts"]` mutation with
  one small object sharing the frozen payload/topic/headers of the
  routed message and overlaying `subopts`; the first write (set_header
  / set_flag / update_expiry) materializes private dicts, and `copy()`
  yields a real, independent `Message` — so downstream enrichment
  (session._enrich) is untouched. Metric/hook bookkeeping
  (`messages.delivered`, `message.delivered`) is batched per lane
  slice instead of per row; same-session runs within a slice coalesce
  into one `deliver_batch()` call (one session accept + one socket
  drain) when the subscriber supports it.

Ordering contract (what the property tests pin): for every session,
the delivered sequence under `deliver_lanes=N` is identical to the
inline `deliver_lanes=0` sequence. Within a window the inline order is
"all fast rows, then slow messages in batch order"; lanes reproduce it
with the slice-then-barrier queueing above, and windows serialize
per-lane because plans enqueue in consume (FIFO) order.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Callable, Optional

import numpy as np

log = logging.getLogger("emqx.deliver")

# a fast-path message whose deliveries were handed to the lanes: the
# consume loop must not treat it as "needs the slow path" (None) nor as
# a settled count (int) — the plan's finalize writes the real count
DEFERRED = object()


def _unpack_opts(b: int) -> dict:
    return {"qos": b & 0x3, "nl": (b >> 2) & 1, "rap": (b >> 3) & 1,
            "rh": (b >> 4) & 0x3}


# The packed subopts word is 6 bits (qos:2 | nl:1 | rap:1 | rh:2), so
# there are exactly 64 distinct unpacked dicts — precompute them all
# once instead of re-unpacking (and re-dict-copying) per delivery.
# CONTRACT: these dicts are FROZEN — every consumer treats delivered
# subopts as read-only (session._enrich only reads; dispatch paths that
# need to extend them build a new dict, e.g. dict(opts, share=g)).
OPT_TABLE = tuple(_unpack_opts(b) for b in range(64))


def resolve_deliver_lanes(configured=None) -> int:
    """The one deliver-lanes resolution: config beats
    EMQX_TPU_DELIVER_LANES beats the built-in min(4, cpus). 0 disables
    the lanes (the inline-loop A/B baseline); negatives are a
    deployment error worth failing loudly on."""
    if configured is not None:
        val = int(configured)
    else:
        env = os.environ.get("EMQX_TPU_DELIVER_LANES")
        if env is None:
            return min(4, os.cpu_count() or 1)
        try:
            val = int(env)
        except ValueError:
            raise ValueError(
                f"EMQX_TPU_DELIVER_LANES={env!r} is not an integer")
    if val < 0:
        raise ValueError(f"deliver_lanes must be >= 0, got {val}")
    return val


class _ViewHeaders:
    """Read-through headers mapping of a DeliveryView: the base
    message's headers with `subopts` overlaid, no dict built. Writing
    through it materializes the view's private headers dict first
    (copy-on-write)."""

    __slots__ = ("_v",)

    def __init__(self, view: "DeliveryView"):
        self._v = view

    def _own(self):
        h = self._v._headers
        return h if h is not None else None

    def get(self, key, default=None):
        h = self._v._headers
        if h is not None:
            return h.get(key, default)
        if key == "subopts":
            return self._v._subopts
        return self._v._base_headers.get(key, default)

    def __getitem__(self, key):
        h = self._v._headers
        if h is not None:
            return h[key]
        if key == "subopts":
            return self._v._subopts
        return self._v._base_headers[key]

    def __contains__(self, key):
        h = self._v._headers
        if h is not None:
            return key in h
        return key == "subopts" or key in self._v._base_headers

    def __setitem__(self, key, val):
        self._v._materialize_headers()[key] = val

    def pop(self, key, *a):
        return self._v._materialize_headers().pop(key, *a)

    def setdefault(self, key, default=None):
        return self._v._materialize_headers().setdefault(key, default)

    def update(self, *a, **kw):
        self._v._materialize_headers().update(*a, **kw)

    def __delitem__(self, key):
        del self._v._materialize_headers()[key]

    def popitem(self):
        return self._v._materialize_headers().popitem()

    def clear(self):
        self._v._materialize_headers().clear()

    def _as_dict(self) -> dict:
        h = self._v._headers
        if h is not None:
            return dict(h)
        d = dict(self._v._base_headers)
        d["subopts"] = self._v._subopts
        return d

    def items(self):
        return self._as_dict().items()

    def keys(self):
        return self._as_dict().keys()

    def values(self):
        return self._as_dict().values()

    def copy(self) -> dict:
        return self._as_dict()

    def __iter__(self):
        return iter(self._as_dict())

    def __len__(self):
        return len(self._as_dict())

    def __eq__(self, other):
        if isinstance(other, _ViewHeaders):
            other = other._as_dict()
        return self._as_dict() == other

    def __repr__(self):
        return repr(self._as_dict())


class DeliveryView:
    """Copy-on-write per-delivery message: shares the routed message's
    payload/topic/flags/headers and overlays `subopts` — the lightweight
    replacement for `msg.copy()` + `headers["subopts"] = subopts` on
    the lane fast path. Message-API compatible: reads delegate, the
    first write materializes a private dict, `copy()` returns a real
    independent Message (so session._enrich keeps working unchanged).

    Copy-on-write boundary: mutations through the Message API
    (set_flag / set_header / headers[...] / update_expiry) are
    isolated; the `flags` and `extra` dicts read through to the BASE
    message until a set_flag materializes — a consumer that mutates
    `msg.flags`/`msg.extra` by direct dict access would write the
    routed message every subscriber shares. No in-repo consumer does
    (session enrichment copies first; hooks read), and delivered
    messages are read-only by the Subscriber protocol contract
    (pubsub.py) — `copy()` first if you must mutate beyond the API."""

    __slots__ = ("topic", "payload", "qos", "from_", "id", "ts", "extra",
                 "_base_flags", "_base_headers", "_subopts", "_flags",
                 "_headers")

    def __init__(self, msg, subopts: dict):
        self.topic = msg.topic
        self.payload = msg.payload
        self.qos = msg.qos
        self.from_ = msg.from_
        self.id = msg.id
        self.ts = msg.ts
        self.extra = msg.extra
        self._base_flags = msg.flags
        self._base_headers = msg.headers
        self._subopts = subopts
        self._flags = None
        self._headers = None

    # -- copy-on-write materialization --
    def _materialize_headers(self) -> dict:
        if self._headers is None:
            h = dict(self._base_headers)
            h["subopts"] = self._subopts
            self._headers = h
        return self._headers

    def _materialize_flags(self) -> dict:
        if self._flags is None:
            self._flags = dict(self._base_flags)
        return self._flags

    @property
    def headers(self):
        if self._headers is not None:
            return self._headers
        return _ViewHeaders(self)

    @property
    def flags(self):
        return self._flags if self._flags is not None else self._base_flags

    # -- Message API parity (emqx_tpu.broker.message.Message) --
    def get_flag(self, name: str, default: bool = False) -> bool:
        return bool(self.flags.get(name, default))

    def set_flag(self, name: str, val: bool = True) -> "DeliveryView":
        self._materialize_flags()[name] = val
        return self

    @property
    def retain(self) -> bool:
        return self.get_flag("retain")

    @property
    def dup(self) -> bool:
        return self.get_flag("dup")

    @property
    def is_sys(self) -> bool:
        return self.get_flag("sys") or self.topic.startswith("$SYS/")

    def get_header(self, name: str, default=None):
        if self._headers is not None:
            return self._headers.get(name, default)
        if name == "subopts":
            return self._subopts
        return self._base_headers.get(name, default)

    def set_header(self, name: str, val) -> "DeliveryView":
        self._materialize_headers()[name] = val
        return self

    def expiry_interval(self) -> Optional[int]:
        props = self.get_header("properties") or {}
        return props.get("message_expiry_interval")

    def is_expired(self) -> bool:
        from emqx_tpu.broker.message import now_ms
        exp = self.expiry_interval()
        if exp is None:
            return False
        return now_ms() > self.ts + exp * 1000

    def update_expiry(self) -> "DeliveryView":
        from emqx_tpu.broker.message import now_ms
        exp = self.expiry_interval()
        if exp is not None:
            remaining = max(1, exp - (now_ms() - self.ts) // 1000)
            props = dict(self.get_header("properties") or {})
            props["message_expiry_interval"] = int(remaining)
            self.set_header("properties", props)
        return self

    def copy(self):
        from emqx_tpu.broker.message import Message
        if self._headers is not None:
            headers = dict(self._headers)
        else:
            headers = dict(self._base_headers)
            headers["subopts"] = self._subopts
        return Message(topic=self.topic, payload=self.payload,
                       qos=self.qos, from_=self.from_,
                       flags=dict(self.flags), headers=headers,
                       id=self.id, ts=self.ts, extra=dict(self.extra))

    def to_map(self) -> dict:
        from emqx_tpu.broker.message import base62_encode
        return {
            "id": base62_encode(self.id), "topic": self.topic,
            "qos": self.qos, "from": self.from_,
            "payload": self.payload, "flags": dict(self.flags),
            "timestamp": self.ts, "retain": self.retain,
        }

    def to_wire(self) -> dict:
        return self.copy().to_wire()

    def __repr__(self):
        return (f"DeliveryView(topic={self.topic!r}, qos={self.qos}, "
                f"from_={self.from_!r})")


class DeliveryPlan:
    """One consumed sub-batch's delivery work: fast rows destined for
    the lanes plus slow-path closures behind the barrier. `counts[i]`
    accumulates message i's successful deliveries; `target` (the
    LaneCounts list the engine returned to the batcher) is back-filled
    at finalize, and done-callbacks fire last (publisher futures,
    handle release)."""

    __slots__ = ("pool", "msgs", "counts", "fast_idx", "slow_items",
                 "filters", "_chunks", "routed_device", "pending",
                 "done", "target", "_cbs", "s_midx", "s_sid", "s_opt",
                 "s_fid", "_barrier_left", "_barrier_evt", "trace")

    def __init__(self, pool: "DeliveryLanePool", msgs: list):
        self.pool = pool
        self.msgs = msgs
        self.counts = np.zeros(len(msgs), np.int64)
        self.fast_idx: list[int] = []
        self.slow_items: list[tuple[int, Callable[[], int]]] = []
        self.filters = None         # fid -> topic-filter string
        self._chunks: list[tuple] = []
        self.routed_device = False
        self.pending = 0            # outstanding lane parts
        self.done = False
        self.target = None          # LaneCounts to back-fill
        self._cbs: list[Callable[[], None]] = []
        self.s_midx = self.s_sid = self.s_opt = self.s_fid = None
        self._barrier_left = 0
        self._barrier_evt: Optional[asyncio.Event] = None
        # flight-recorder trace id (ISSUE 7): set by the engine from
        # its window handle; lane work records against it, and it
        # SURVIVES a lane-worker restart because the queue items carry
        # the plan (the causal context is data, not task state)
        self.trace = 0

    # -- building (engine consume stage, event loop) --
    def register_fast(self, indices) -> None:
        """Mark message indices whose deliveries the lanes own (their
        no-subscriber drop bookkeeping moves to finalize)."""
        self.fast_idx.extend(int(i) for i in indices)

    def add_rows(self, midx, sid, opt, fid, filters) -> None:
        """One vectorized chunk of fast deliveries: parallel arrays of
        (message index, session id, packed opts, filter id) plus the
        fid -> filter-string table they index (the pinned snapshot's
        `fid_filter` for the single-chip engine; a plan-local list for
        the mesh)."""
        if self.filters is None:
            self.filters = filters
        elif self.filters is not filters:
            # shouldn't happen (one snapshot per plan) — remap defensively
            base = len(self.filters)
            self.filters = list(self.filters) + list(filters)
            fid = np.asarray(fid) + base
        self._chunks.append((np.asarray(midx, np.int64),
                             np.asarray(sid, np.int64),
                             np.asarray(opt, np.int64),
                             np.asarray(fid, np.int64)))

    def add_rows_py(self, msg_idx: int, rows: list[tuple]) -> None:
        """Python-built fast rows for one message (mesh consume):
        `rows` is [(sid, packed_opt, filter_string)]. Appends to a
        plan-local filter table."""
        if not rows:
            return
        if self.filters is None:
            self.filters = []
        base = len(self.filters)
        n = len(rows)
        midx = np.full(n, msg_idx, np.int64)
        sid = np.fromiter((r[0] for r in rows), np.int64, n)
        opt = np.fromiter((r[1] for r in rows), np.int64, n)
        fidx = np.arange(base, base + n, dtype=np.int64)
        self.filters.extend(r[2] for r in rows)
        self._chunks.append((midx, sid, opt, fidx))

    def add_slow(self, msg_idx: int, fn: Callable[[], int]) -> None:
        """A message the fast path cannot prove clean: `fn` runs the
        ordering-safe inline consume for it (behind the barrier) and
        returns its delivery count."""
        self.slow_items.append((msg_idx, fn))

    def add_done_callback(self, cb: Callable[[], None]) -> None:
        if self.done:
            cb()
        else:
            self._cbs.append(cb)

    # -- completion (lane workers, event loop) --
    def _finish_part(self) -> None:
        self.pending -= 1
        if self.pending <= 0 and not self.done:
            self._finalize()

    def _finalize(self) -> None:
        self.done = True
        pool = self.pool
        if self.target is not None:
            counts = self.counts
            for i in range(len(self.msgs)):
                self.target[i] = int(counts[i])
        # no-subscriber bookkeeping for lane-owned messages (the slow
        # closures did their own inside the inline consume)
        metrics = pool.metrics
        hooks = pool.hooks
        for i in self.fast_idx:
            if self.counts[i] == 0 and not self.msgs[i].is_sys:
                metrics.inc("messages.dropped")
                metrics.inc("messages.dropped.no_subscribers")
                if hooks is not None:
                    hooks.run("message.dropped",
                              (self.msgs[i], "no_subscribers"))
        for cb in self._cbs:
            try:
                cb()
            except Exception:  # noqa: BLE001 — one waiter must not
                log.exception("delivery-plan callback failed")  # stall
        self._cbs = []
        pool._plan_done(self)


class LaneCounts(list):
    """finish_sub's return value when the lanes own the deliveries: a
    plain list of per-message counts (placeholders until the plan
    completes) carrying the plan so the batcher can defer publisher
    futures with `plan.add_done_callback`."""

    plan: DeliveryPlan


_PARK = ("park",)


class DeliveryLanePool:
    """N session-affine delivery lanes on the event loop.

    Why asyncio tasks and not threads: every subscriber callback
    (channel -> session -> asyncio transport write) is loop-affine, so
    thread workers would need a lock per session; loop tasks keep the
    single-writer discipline for free, and the OVERLAP the stage buys
    is with the device dispatch/materialize stages, which run on
    executor threads and release the GIL inside XLA / the relay HTTP
    round trip. The lanes also amortize per-row Python: one view object
    instead of a Message copy, coalesced same-session drains, and
    per-slice (not per-row) metric/hook bookkeeping.
    """

    def __init__(self, broker, metrics, *, hooks=None, telemetry=None,
                 n_lanes: int = 4, depth: int = 8, supervisor=None):
        self.broker = broker
        self.metrics = metrics
        self.hooks = hooks
        self.telemetry = telemetry
        # fault-domain supervision (ISSUE 6): the lane_deliver breaker
        # gates active() (open → the engines deliver inline, the rung
        # below the lanes), slice faults are contained + retried, dead
        # workers are restarted by the drain/admit watchdogs. None
        # restores the pre-ISSUE-6 behavior exactly.
        self.sup = supervisor
        self.n_lanes = n_lanes
        # max outstanding PLANS (consumed sub-batches) before admit()
        # blocks the batcher's consumer — the backpressure bound
        self.depth = max(1, depth)
        self._loop = None
        self._queues: list[asyncio.Queue] = []
        self._workers: list[Optional[asyncio.Task]] = []
        self._wake: Optional[asyncio.Event] = None
        self._gate: Optional[asyncio.Event] = None
        self._paused = False
        self._live_plans = 0
        self._plans: list[DeliveryPlan] = []     # in-flight, FIFO
        self._lane_items: list[int] = [0] * n_lanes  # real work per lane
        # same-sid coalescing yields one drain per run; chunk big slices
        # so one huge fan-out cannot monopolize the loop between yields.
        # 2048 rows ≈ 1-2ms of delivery per burst — well under the
        # pipeline's loop-stall budget — while finer chunks measurably
        # thrash (sweep on a 2-cpu box: 512→310k, 2048→556k, 8192→378k
        # deliveries/s at lanes=4: too-fine interleaving rotates lanes'
        # working sets through cache per yield)
        self._chunk = 2048

    # ---- lifecycle ------------------------------------------------------
    def active(self) -> bool:
        if self.n_lanes <= 0:
            return False
        if self.sup is None or self.sup.lanes_enabled():
            return True
        # lane_deliver breaker open: stop taking NEW plans only once the
        # in-flight lane work has drained — an immediate inline fallback
        # could deliver a session's newer message while its older rows
        # are still queued on a lane (per-session FIFO violation). Plans
        # admitted here still ride the ordered lane queues; the
        # consumer's windows are sequential, so once busy() goes false
        # the lanes are empty and the inline fallback is order-safe.
        return self.busy()

    def ensure_loop(self) -> bool:
        """(Re)start the workers on the CURRENT running loop. Tests run
        several event loops against one Node; workers from a dead loop
        are discarded and fresh queues built — plans never span loops
        (drain() runs before a loop winds down in every serving path)."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return False
        if loop is not self._loop:
            orphans = [p for p in self._plans if not p.done]
            self._plans = []
            self._loop = loop
            self._queues = [asyncio.Queue() for _ in range(self.n_lanes)]
            self._workers = [None] * self.n_lanes
            self._wake = asyncio.Event()
            self._gate = asyncio.Event()
            if not self._paused:
                self._gate.set()
            self._lane_items = [0] * self.n_lanes
            # plans stranded by a torn-down loop (tests run several
            # loops against one node) must still finalize: their
            # callbacks release pinned snapshot handles — leaking one
            # would block every future swap on this engine
            self._live_plans = len(orphans)
            for p in orphans:
                p.pending = 0
                p._finalize()
        for i in range(self.n_lanes):
            w = self._workers[i]
            if w is None or w.done():
                from emqx_tpu.broker.supervise import guard_task
                self._workers[i] = guard_task(
                    loop.create_task(self._worker(i)),
                    f"deliver-lane{i}", self.metrics)
        return True

    def pause(self) -> None:
        """Quiesce the lanes (tests, shutdown drains): queued plans stay
        queued; resume() releases them."""
        self._paused = True
        if self._gate is not None:
            self._gate.clear()

    def resume(self) -> None:
        self._paused = False
        if self._gate is not None:
            self._gate.set()

    # ---- plan intake (engine consume stage) -----------------------------
    def new_plan(self, msgs: list) -> Optional[DeliveryPlan]:
        if not self.active() or not self.ensure_loop():
            return None
        return DeliveryPlan(self, msgs)

    def submit(self, plan: DeliveryPlan) -> None:
        """Bucket the plan's fast rows into session-affine lane slices
        (one stable argsort: primary sid % n_lanes, secondary sid — so
        a session's rows stay in arrival order AND contiguous for the
        coalesced drain) and enqueue; slow closures ride behind an
        all-lanes barrier. Returns immediately — this is the overlap."""
        # workers may have parked since new_plan() — the barrier needs
        # every lane live, so re-arm them before enqueuing anything
        self.ensure_loop()
        parts = 0
        slices = []
        if plan._chunks:
            if len(plan._chunks) == 1:
                midx, sid, opt, fid = plan._chunks[0]
            else:
                midx = np.concatenate([c[0] for c in plan._chunks])
                sid = np.concatenate([c[1] for c in plan._chunks])
                opt = np.concatenate([c[2] for c in plan._chunks])
                fid = np.concatenate([c[3] for c in plan._chunks])
            plan._chunks = []
            lane = sid % self.n_lanes
            # stable single-key argsort: lane-major, sid-minor, original
            # order within a sid (sids are < 2^31 — broker sid counter)
            order = np.argsort((lane << np.int64(31)) | sid,
                               kind="stable")
            # plain lists for the delivery walk: per-row numpy scalar
            # indexing costs ~3x a list index in the hot loop
            plan.s_midx = midx[order].tolist()
            plan.s_sid = sid[order].tolist()
            plan.s_opt = opt[order].tolist()
            plan.s_fid = fid[order].tolist()
            lanes_sorted = lane[order]
            bounds = np.searchsorted(lanes_sorted,
                                     np.arange(self.n_lanes + 1))
            for ln in range(self.n_lanes):
                lo, hi = int(bounds[ln]), int(bounds[ln + 1])
                if lo == hi:
                    continue
                parts += 1
                slices.append((ln, lo, hi))
            self.metrics.inc("pipeline.deliver.rows", len(order))
        if plan.slow_items:
            parts += 1
            plan._barrier_left = self.n_lanes
            plan._barrier_evt = asyncio.Event()
        # all fallible work is done: go live, then enqueue (put_nowait
        # on unbounded queues cannot raise — a half-enqueued plan would
        # wedge drain()/admit() forever)
        plan.pending = parts
        self._live_plans += 1
        for ln, lo, hi in slices:
            self._lane_items[ln] += 1
            self._queues[ln].put_nowait(("slice", plan, lo, hi))
        if plan.slow_items:
            # the barrier holds EVERY lane: the slow closures run with
            # all prior fast deliveries done and nothing overtaking —
            # the ordering-safe serialization the inline loop had
            for ln, q in enumerate(self._queues):
                self._lane_items[ln] += 1
                q.put_nowait(("barrier", plan))
        self.metrics.inc("pipeline.deliver.plans")
        if parts == 0:
            plan._finalize()
        else:
            self._plans.append(plan)

    def _plan_done(self, plan: DeliveryPlan) -> None:
        try:
            self._plans.remove(plan)
        except ValueError:
            pass    # zero-part plans finalize before tracking
        self._live_plans -= 1
        if self._wake is not None:
            self._wake.set()
        if self._live_plans == 0:
            # park the workers: idle tasks pending at loop teardown
            # would otherwise warn "task was destroyed" on every test
            for q in self._queues:
                q.put_nowait(_PARK)

    # ---- flow control (batcher consume stage) ---------------------------
    async def admit(self) -> None:
        """Backpressure: block while more than `depth` plans are
        outstanding. Called by the batcher after enqueuing a plan — the
        stall propagates to its `_inflight` queue and from there to
        submit()/enqueue(), instead of dropping or buffering unboundedly."""
        if self._wake is None or self._live_plans <= self.depth:
            return
        self.metrics.inc("pipeline.deliver.backpressure_waits")
        while self._live_plans > self.depth:
            self._wake.clear()
            await self._wait_wake()

    async def drain(self) -> None:
        """Wait for every outstanding plan to finish delivering. Host-
        routed batches call this before delivering inline, so a host
        batch can never overtake lane-queued deliveries for a session
        (the device/host FIFO contract the batcher's consumer enforces
        extends through the lanes)."""
        if self._wake is None:
            return
        if self._loop is not asyncio.get_running_loop():
            # drain on a NEW loop (tests tear loops down under a live
            # node): rebind first — ensure_loop force-finalizes plans
            # stranded on the dead loop, releasing their pinned
            # snapshot handles, so this drain returns instead of
            # waiting forever on a wake event nobody can set
            self.ensure_loop()
        while self._live_plans > 0:
            self._wake.clear()
            await self._wait_wake()

    async def _wait_wake(self) -> None:
        """One bounded wait on lane progress. With a supervisor
        (ISSUE 6) the wait is a lane-queue watchdog: a deadline expiry
        counts a stall, RESTARTS any dead lane workers (their queues
        are intact, so a revived worker drains in order — the
        crashed-lane recovery contract) and advances the lane_deliver
        breaker, instead of wedging the caller forever on a queue
        nobody is consuming."""
        sup = self.sup
        if sup is None:
            await self._wake.wait()
            return
        try:
            await asyncio.wait_for(self._wake.wait(),
                                   sup.deadline("lane_deliver"))
        except asyncio.TimeoutError:
            sup.note_stall("lane_deliver")
            if self._revive_workers():
                sup.note_restart("lane_worker")

    def _revive_workers(self) -> int:
        """Restart dead lane workers on the current loop (the stall
        watchdog's recovery arm; ensure_loop does the same lazily at
        the next plan intake). Queues are untouched — a restarted
        worker picks up exactly where the dead one stopped, in order."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return 0
        if loop is not self._loop:
            return 0
        revived = 0
        from emqx_tpu.broker.supervise import guard_task
        for i, w in enumerate(self._workers):
            if w is None or w.done():
                self._workers[i] = guard_task(
                    loop.create_task(self._worker(i)),
                    f"deliver-lane{i}", self.metrics)
                revived += 1
        return revived

    def busy(self) -> bool:
        return self._live_plans > 0

    def queued_items(self) -> int:
        return sum(self._lane_items)

    def lane_depth(self) -> int:
        """Deepest lane (pending work items) right now — the exported
        gauge (park sentinels are housekeeping, not work: excluded)."""
        return max(self._lane_items, default=0)

    # ---- telemetry ------------------------------------------------------
    def state(self) -> dict:
        return {
            "lanes": self.n_lanes,
            "depth_limit": self.depth,
            "live_plans": self._live_plans,
            "queued_items": self.queued_items(),
            "lane_depth": self.lane_depth(),
            "paused": self._paused,
        }

    def stats_fun(self, stats) -> None:
        """Registered on Node.stats: the point-in-time lane-depth gauge
        every exporter carries (Prometheus gauge family, StatsD |g,
        $SYS stats/)."""
        stats.setstat("pipeline.deliver.lane_depth", self.lane_depth())
        stats.setstat("pipeline.deliver.live_plans", self._live_plans)

    # ---- lane workers ---------------------------------------------------
    async def _worker(self, lane: int) -> None:
        q = self._queues[lane]
        tele = self.telemetry
        while True:
            item = await q.get()
            if item[0] == "park":
                if self._live_plans == 0 and q.empty():
                    return
                continue
            t0 = time.perf_counter()
            worked = True
            try:
                if not self._gate.is_set():
                    try:
                        await self._gate.wait()
                    except asyncio.CancelledError:
                        # dying while HOLDING a popped item: surrender
                        # it (lost-but-accounted) or its plan's part
                        # leaks and every future drain wedges on work
                        # nobody owns — the gap the ISSUE-6 lane
                        # watchdog test exposed
                        self._surrender(item)
                        raise
                t0 = time.perf_counter()   # gate wait is not lane work
                if item[0] == "slice":
                    _k, plan, lo, hi = item
                    try:
                        try:
                            if self.sup is not None:
                                # ISSUE 6 injection point: a lane
                                # worker failing mid-slice must be
                                # contained, not a silent task death
                                self.sup.fire("lane_deliver")
                            await self._run_slice(plan, lane, lo, hi)
                            if self.sup is not None:
                                self.sup.note_ok("lane_deliver")
                        except Exception as e:  # noqa: BLE001
                            if self.sup is None:
                                raise   # pre-ISSUE-6: the task dies
                            # real delivery faults are contained PER
                            # CHUNK inside _run_slice; reaching here
                            # means the slice failed BEFORE any
                            # delivery (the injection point, chunk-
                            # boundary code), so a whole-slice retry
                            # cannot duplicate
                            self.sup.note_fault("lane_deliver", e)
                            try:
                                # re-run CHUNKED (cooperative yields) —
                                # one flat _deliver_rows over a huge
                                # slice would monopolize the loop, the
                                # exact stall the chunking prevents
                                await self._run_slice(plan, lane,
                                                      lo, hi)
                            except Exception:  # noqa: BLE001
                                log.exception(
                                    "lane %d slice %d..%d lost after "
                                    "retry", lane, lo, hi)
                                self.metrics.inc(
                                    "pipeline.deliver.deliver_errors")
                    finally:
                        plan._finish_part()
                else:  # barrier
                    _k, plan = item
                    plan._barrier_left -= 1
                    if plan._barrier_left == 0:
                        try:
                            await self._run_slow(plan)
                        finally:
                            plan._barrier_evt.set()
                            plan._finish_part()
                    else:
                        # waiting out another lane's slow tail is not
                        # THIS lane's work: recording it would read as
                        # uniform slowness and mask real per-lane
                        # hashing skew in the deliver_lane{i}
                        # histograms
                        worked = False
                        await plan._barrier_evt.wait()
            finally:
                # gauge accounting must survive cancellation anywhere
                # in the item's processing (mid-slice, barrier wait) or
                # lane_depth overreports a stuck-deep lane forever
                self._lane_items[lane] -= 1
            if tele is not None and worked:
                now = time.perf_counter()
                tele.observe_stage(f"deliver_lane{lane}", now - t0)
                rec = getattr(tele, "recorder", None)
                if rec is not None:
                    # item is ("slice", plan, lo, hi) or ("barrier",
                    # plan): either way the plan rides at [1] and
                    # carries its window's trace
                    tr = getattr(item[1], "trace", 0)
                    if tr:
                        rec.record(tr, f"lane{lane}", t0, now,
                                   track=f"lane{lane}")

    def _surrender(self, item) -> None:
        """Account a popped-but-unprocessed queue item when its worker
        dies: the plan part is finished so drains can complete (the
        worker's finally owns the lane-depth gauge decrement). A
        surrendered slice loses its deliveries (counted as
        deliver_errors; finalize then books the no-subscriber drops);
        a surrendered barrier passes this lane through, and the LAST
        lane's surrender runs the slow closures synchronously (they
        are plain callables) so their deliveries survive."""
        if item[0] == "slice":
            self.metrics.inc("pipeline.deliver.deliver_errors")
            item[1]._finish_part()
        elif item[0] == "barrier":
            plan = item[1]
            plan._barrier_left -= 1
            if plan._barrier_left == 0:
                for idx, fn in plan.slow_items:
                    try:
                        plan.counts[idx] = fn()
                    except Exception:  # noqa: BLE001 — death path
                        self.metrics.inc("pipeline.deliver.slow_errors")
                if plan._barrier_evt is not None:
                    plan._barrier_evt.set()
                plan._finish_part()

    async def _run_slice(self, plan: DeliveryPlan, lane: int,
                         lo: int, hi: int) -> None:
        """Deliver one lane's slice, coalescing same-session runs, with
        a cooperative yield between chunks so a huge fan-out cannot
        monopolize the loop (other lanes and the producer keep running;
        later plans queue behind this one per-lane, so order holds).

        Fault containment is PER CHUNK (ISSUE 6): a raising chunk is
        retried once, and only that chunk — retrying the whole slice
        would re-deliver (and double-count) the chunks that already
        succeeded. Counts apply only on a chunk's successful return, so
        a retried chunk is at-least-once for its subscribers but never
        double-counted toward the publisher."""
        sids = plan.s_sid
        sup = self.sup
        pos = lo
        while pos < hi:
            nxt = min(hi, pos + self._chunk)
            # never split a same-session run across chunks: the
            # coalesced drain and its all-or-none accept are per run
            while nxt < hi and sids[nxt] == sids[nxt - 1]:
                nxt += 1
            if sup is None:
                self._deliver_rows(plan, pos, nxt)
            else:
                try:
                    self._deliver_rows(plan, pos, nxt)
                except Exception as e:  # noqa: BLE001 — contained
                    sup.note_fault("lane_deliver", e)
                    try:
                        self._deliver_rows(plan, pos, nxt)
                    except Exception:  # noqa: BLE001
                        log.exception("lane %d chunk %d..%d lost "
                                      "after retry", lane, pos, nxt)
                        self.metrics.inc(
                            "pipeline.deliver.deliver_errors")
            pos = nxt
            if pos < hi:
                await asyncio.sleep(0)

    def _deliver_rows(self, plan: DeliveryPlan, lo: int, hi: int) -> None:
        broker = self.broker
        registry = broker._subscribers
        meta = broker._sub_meta
        hooks = self.hooks
        delivered_cbs = hooks.lookup("message.delivered") \
            if hooks is not None else ()
        msgs = plan.msgs
        filters = plan.filters
        sids, opts = plan.s_sid, plan.s_opt
        fids, midx = plan.s_fid, plan.s_midx
        delivered = 0
        drains = 0
        # one DeliveryView per (message, packed subopts), shared across
        # the fan-out: at fan-out F this builds 1 view instead of F. The
        # share is safe by the copy-on-write contract — every mutation
        # path on the view (set_header/set_flag/update_expiry/copy)
        # materializes private state, and delivered messages are
        # read-only by protocol (Subscriber docstring in pubsub.py).
        vcache: dict[int, DeliveryView] = {}
        delivered_midx: list[int] = []
        i = lo
        while i < hi:
            sid = sids[i]
            j = i + 1
            while j < hi and sids[j] == sid:
                j += 1
            sub = registry.get(sid)
            if sub is None:
                i = j
                continue
            items = []
            for k in range(i, j):
                vk = (midx[k] << 6) | (opts[k] & 0x3F)
                view = vcache.get(vk)
                if view is None:
                    view = vcache[vk] = DeliveryView(
                        msgs[midx[k]], OPT_TABLE[opts[k] & 0x3F])
                items.append((filters[fids[k]], view))
            batch_fn = getattr(sub, "deliver_batch", None) \
                if j - i > 1 else None
            # Deliberate divergence from the inline loop: a raising
            # subscriber/hook here is contained to ITS deliveries
            # (logged + counted) instead of failing the whole batch's
            # publish futures — one bad session must not poison every
            # publisher sharing the window. deliver_errors/slow_errors
            # make the containment observable.
            if batch_fn is not None:
                # coalesced drain: one session accept + one socket
                # write for the whole run (all-or-none by contract)
                try:
                    got = batch_fn(items)
                except Exception:  # noqa: BLE001 — one bad subscriber
                    log.exception("deliver_batch failed sid=%s", sid)
                    self.metrics.inc("pipeline.deliver.deliver_errors")
                    got = 0
                drains += 1
                if got:
                    delivered_midx.extend(midx[i:j])
                    delivered += len(items)
                    if delivered_cbs:
                        for _f, v in items:
                            hooks.run("message.delivered",
                                      (meta.get(sid), v))
            else:
                drains += j - i
                for k, (f, view) in zip(range(i, j), items):
                    try:
                        ok = sub.deliver(f, view)
                    except Exception:  # noqa: BLE001
                        log.exception("deliver failed sid=%s", sid)
                        self.metrics.inc(
                            "pipeline.deliver.deliver_errors")
                        ok = False
                    if ok:
                        delivered_midx.append(midx[k])
                        delivered += 1
                        if delivered_cbs:
                            hooks.run("message.delivered",
                                      (meta.get(sid), view))
            i = j
        if delivered_midx:
            np.add.at(plan.counts, delivered_midx, 1)
        # per-slice (not per-row) bookkeeping: the batching win the
        # coalesce.ratio histogram quantifies
        metrics = self.metrics
        if delivered:
            metrics.inc("messages.delivered", delivered)
            if plan.routed_device:
                metrics.inc("messages.routed.device", delivered)
        n_rows = hi - lo
        metrics.inc("pipeline.deliver.deliveries", n_rows)
        metrics.inc("pipeline.deliver.drains", drains)
        if n_rows:
            metrics.hist("pipeline.deliver.coalesce.ratio",
                         lo=1.0 / 256, n_buckets=9,
                         unit="ratio").observe(1.0 - drains / n_rows)

    async def _run_slow(self, plan: DeliveryPlan) -> None:
        """The ordering-safe serialized tail: slow-path messages in
        batch order, all lanes held at the barrier."""
        for n, (idx, fn) in enumerate(plan.slow_items):
            try:
                plan.counts[idx] = fn()
            except Exception:  # noqa: BLE001 — a failing hook/deliver
                log.exception("slow-path consume failed")  # != lost lane
                self.metrics.inc("pipeline.deliver.slow_errors")
            if n % 64 == 63:
                await asyncio.sleep(0)
