"""Token-bucket rate limiting + force-shutdown overload policy.

Parity: apps/emqx/src/emqx_limiter.erl (conn/pub rate + quota buckets via
esockd_limiter, emqx_limiter.erl:62-87) and the force_shutdown policy
checked on the connection loop (emqx_connection.erl check_oom :463,
emqx_gc/emqx_oom). A depleted bucket answers with the pause needed until
refill — the `{active,N}`-off backpressure analog: the connection task
sleeps instead of reading more from the socket.
"""

from __future__ import annotations

import time
from typing import Optional


class TokenBucket:
    """rate tokens/sec, burst capacity.

    Two consumption modes:
    - `take(n)` always charges (balance may go negative — debt) and
      returns the pause (s) needed to repay it. Right for ingress
      batches whose size exceeds the capacity: the work already
      happened, so it must be charged or the limit is systematically
      exceeded.
    - `try_take(n)` charges only when affordable and returns bool.
      Right for quota checks where denied work is not performed.
    """

    def __init__(self, rate: float, burst: Optional[float] = None):
        self.rate = float(rate)
        self.capacity = float(burst if burst is not None else rate)
        self.tokens = self.capacity
        self._t = time.monotonic()

    def _refill(self, now: float) -> None:
        self.tokens = min(self.capacity,
                          self.tokens + (now - self._t) * self.rate)
        self._t = now

    def take(self, n: float = 1.0, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        self._refill(now)
        self.tokens -= n
        if self.tokens >= 0:
            return 0.0
        return -self.tokens / self.rate

    def try_take(self, n: float = 1.0,
                 now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        self._refill(now)
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def debt(self, now: Optional[float] = None) -> float:
        """Outstanding debt in tokens (0 when the balance is positive):
        how far `take()` has charged past capacity, refill applied.
        The ISSUE-14 overload governor ranks connections by this for
        the top-offender disconnect (force_shutdown parity) — the
        connection with the deepest unrepaid ingress debt is the one
        whose flood the limiter is already fighting."""
        now = time.monotonic() if now is None else now
        self._refill(now)
        return max(0.0, -self.tokens)

    # kept for compatibility with try_take semantics
    def consume(self, n: float = 1.0,
                now: Optional[float] = None) -> float:
        """try_take as a pause: 0.0 if granted, else seconds until n
        tokens accumulate (tokens NOT taken on failure)."""
        now = time.monotonic() if now is None else now
        self._refill(now)
        if self.tokens >= n:
            self.tokens -= n
            return 0.0
        return (n - self.tokens) / self.rate


class ConnectionLimiter:
    """Per-connection ingress limits: packets/sec and bytes/sec.

    Config (zone `rate_limit`): conn_messages_in "100/s"-style pairs in
    the reference schema; here plain numbers {msgs_rate, bytes_rate}.
    """

    def __init__(self, msgs_rate: Optional[float] = None,
                 bytes_rate: Optional[float] = None):
        self.msgs = TokenBucket(msgs_rate) if msgs_rate else None
        self.bytes = TokenBucket(bytes_rate) if bytes_rate else None

    def check(self, n_msgs: int, n_bytes: int) -> float:
        """Charge the already-done work; returns pause seconds (0 =
        proceed). Debt carries over so oversized batches still average
        out to the configured rate."""
        pause = 0.0
        if self.msgs is not None and n_msgs:
            pause = max(pause, self.msgs.take(n_msgs))
        if self.bytes is not None and n_bytes:
            pause = max(pause, self.bytes.take(n_bytes))
        return pause

    def debt(self) -> float:
        """Deepest per-bucket debt in seconds-to-repay units (tokens /
        rate) so msgs- and bytes-bucket debts compare on one scale.
        0.0 when no bucket is configured or none is in debt."""
        worst = 0.0
        for bucket in (self.msgs, self.bytes):
            if bucket is not None and bucket.rate > 0:
                worst = max(worst, bucket.debt() / bucket.rate)
        return worst


class QuotaLimiter:
    """Publish-quota buckets (conn_messages_routing in the reference):
    overall messages a client may publish per time unit."""

    def __init__(self, rate: Optional[float] = None,
                 burst: Optional[float] = None):
        self.bucket = TokenBucket(rate, burst) if rate else None

    def check_publish(self) -> bool:
        if self.bucket is None:
            return True
        return self.bucket.try_take(1.0)


class ForceShutdownPolicy:
    """Kill a connection whose session buffers blow past limits
    (force_shutdown zone config: max_mqueue_len / max_heap_size analog)."""

    def __init__(self, max_mqueue_len: int = 0, max_awaiting_rel: int = 0):
        self.max_mqueue_len = max_mqueue_len
        self.max_awaiting_rel = max_awaiting_rel

    def violated(self, session) -> Optional[str]:
        if session is None:
            return None
        if self.max_mqueue_len and len(session.mqueue) > self.max_mqueue_len:
            return "mqueue_overflow"
        if (self.max_awaiting_rel
                and len(session.awaiting_rel) > self.max_awaiting_rel):
            return "awaiting_rel_overflow"
        return None
