"""Snapshot-keyed host-side LRU of device match rows.

The wildcard match stage (ops.match / ops.shapes) is a pure function of
an immutable table snapshot and the encoded topic: for one `_Built`
snapshot, the same topic always produces the same (matches row, count,
overflow) triple. Real MQTT publish traffic is heavily skewed to a small
hot topic set (arXiv:1811.07088 §5, arXiv:2603.21600), so paying the
NFA/shape-hash cost once per (snapshot, topic) instead of once per
message removes most of the match work from the device route path.

This cache holds those triples host-side, keyed by a 128-bit hash of the
encoded level words + `is_dollar` (two independent 64-bit folds over the
interned ids — same collision posture as ops/shapes.py's 2x32-bit path
hashes: a wrong row needs a 128-bit collision inside one snapshot's live
key set, ~2^-128 per pair). Rows are numpy: matches [Mw] int32, count
int32, overflow bool, where Mw is the snapshot's match width (shape
capacity for the shapes backend, match_cap for the trie NFA).

Row layout note (ISSUE 3): rows populated from a COMPACTED readback
(device_engine.materialize's CSR branch) are hole-compacted — the valid
filter ids as a prefix, -1 beyond — while a dense readback preserves the
shape-slot hole positions of the shapes backend. The two layouts are
interchangeable by the hole-insensitivity contract (ops/compact.py):
fan-out/shared expansion treat -1 as a zero-length segment and consume
skips it, the valid ids keep their match ORDER either way, and `count`
equals the true match count for both. Deliveries and cursor threading
are therefore bit-identical regardless of which readback populated a
row (oracle-tested in tests/test_compact_readback.py).

Consistency invariant (why per-snapshot keying suffices): mutations
never edit the device tables in place — subscription churn marks
filters/slots dirty and those serve host-side against the PINNED
snapshot until the next rebuild (broker/device_engine.py's
dirty/delta scheme), so the match output for a given snapshot id never
changes during that snapshot's lifetime. `attach()` at snapshot swap
(DeviceRouteEngine._apply_build) is therefore the ONLY invalidation
point needed: rows can never be stale within a snapshot, and the id
check on every get/put batch makes cross-snapshot serving structurally
impossible (a reader thread racing a swap inserts into /reads from
nothing).

Thread model: looked up on the event loop (prepare), populated from the
materialize/read executor threads, invalidated on the loop at swap — one
plain lock around the OrderedDict; every operation is a small dict walk,
orders of magnitude below the batch work it fronts.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

DEFAULT_CAPACITY = 8192


class MatchCache:
    """LRU of per-(snapshot, topic) match rows with hit/miss accounting.

    `metrics` is a broker.metrics.Metrics (or None): hit/miss/evict/
    invalidation counters land there as `match_cache.*`, which is how the
    Prometheus/StatsD/$SYS/mgmt exporters and the telemetry snapshot see
    the cache with zero coupling to this module.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY, metrics=None):
        self.capacity = max(1, int(capacity))
        self.metrics = metrics
        self._lock = threading.Lock()
        self._rows: OrderedDict = OrderedDict()   # key -> (m, c, o[, ...])
        self.snapshot_id: Optional[int] = None
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        # delta-overlay coherence (ISSUE 4): bumped on every overlay
        # filter insert/delete. Rows under the overlay carry the delta
        # match triple + the encoded topic (fields 3..8); an overlay
        # change drops exactly the cached topics the changed filter
        # matches (drop_where) and invalidates in-flight readbacks that
        # predate it (the put-side version check) — the surgical
        # replacement for the pre-overlay wholesale flush.
        self.delta_version = 0
        self.delta_invalidated = 0
        # drop_where's columnar view of the stored topic encodings,
        # memoized on a content generation: row content only changes on
        # insert/evict/invalidate (LRU touches reorder, not mutate), so
        # consecutive overlay changes — the churn regime, several per
        # batch window — reuse one stack instead of re-copying the
        # whole cache per subscription change
        self._content_gen = 0
        self._stack = None      # (gen, keys, encs, lens, dollars)

    def _inc(self, name: str, n: int) -> None:
        if self.metrics is not None and n:
            self.metrics.inc(f"match_cache.{name}", n)

    def attach(self, snapshot_id: Optional[int]) -> None:
        """Bind the cache to a new snapshot, dropping every row of the
        previous one (wholesale invalidation at swap — see the module
        docstring for why this is the only invalidation point)."""
        with self._lock:
            if self._rows:
                self.invalidations += 1
                self._inc("invalidations", 1)
                self._inc("invalidated_rows", len(self._rows))
                self._rows.clear()
                self._content_gen += 1
                self._stack = None
            self.snapshot_id = snapshot_id

    def get_many(self, snapshot_id, keys: list) -> list:
        """Row per key (None = miss), LRU-touching hits. A snapshot-id
        mismatch (reader raced a swap) misses everything. Does NOT count
        hit/miss accounting — lookups also run for windows that end up
        dispatching the plain program, and counting those would inflate
        the exported hit rate with reuse that never fed a dispatch; the
        planner calls count_lookups() only for engaged plans."""
        with self._lock:
            if snapshot_id != self.snapshot_id:
                return [None] * len(keys)
            rows = self._rows
            out = []
            for k in keys:
                row = rows.get(k)
                if row is not None:
                    rows.move_to_end(k)
                out.append(row)
            return out

    def count_lookups(self, hits: int, misses: int) -> None:
        """Account one ENGAGED window's lookup outcome (see get_many)."""
        with self._lock:
            self.hits += hits
            self.misses += misses
        self._inc("hits", hits)
        self._inc("misses", misses)

    def bump_delta_version(self) -> None:
        """An overlay filter was inserted/deleted: in-flight readbacks
        computed before this moment describe a stale overlay — put_many
        batches pinned to an older version are dropped whole."""
        with self._lock:
            self.delta_version += 1

    def drop_where(self, snapshot_id, pred) -> int:
        """Drop every cached row whose TOPIC satisfies `pred(encs
        [N, L], lens [N], dollars [N]) -> bool [N]` — the delta-aware
        invalidation: an overlay insert/delete calls this with the
        changed filter's host-mirror matcher (ops.delta.np_filter_match,
        vectorized over ALL cached topics in one call — a per-row
        Python predicate measured ~50x slower at 8k rows), so only the
        topics whose delta match set actually changed pay, instead of
        the wholesale flush. Rows without a stored topic encoding
        (pre-overlay 3-tuples) are conservatively dropped too. Returns
        the count."""
        import numpy as np
        dropped = []
        with self._lock:
            if snapshot_id != self.snapshot_id:
                return 0
            st = self._stack
            if st is None or st[0] != self._content_gen:
                keys, encs, lens, dols = [], [], [], []
                for k, row in self._rows.items():
                    if len(row) < 9:
                        dropped.append(k)
                    else:
                        keys.append(k)
                        encs.append(row[6])
                        lens.append(row[7])
                        dols.append(row[8])
                st = (self._content_gen, keys,
                      np.stack(encs) if keys else None,
                      np.asarray(lens), np.asarray(dols, bool))
                self._stack = st
            _gen, keys, encs, lens, dols = st
            if keys:
                mask = pred(encs, lens, dols)
                dropped.extend(k for k, m in zip(keys, mask) if m)
            for k in dropped:
                self._rows.pop(k, None)
            if dropped:
                self._content_gen += 1
                self._stack = None
            self.delta_invalidated += len(dropped)
        self._inc("delta_invalidated", len(dropped))
        return len(dropped)

    def put_many(self, snapshot_id, items: list, version=None) -> None:
        """Insert (key, row) pairs read back from a finished dispatch.
        Dropped whole when the snapshot moved on while the batch was in
        flight — those rows describe tables that no longer serve — or,
        under the delta overlay, when `version` (the delta version at
        the batch's plan time) is stale: the rows predate an overlay
        filter change and their delta triples may be wrong."""
        n_evict = 0
        with self._lock:
            if snapshot_id != self.snapshot_id:
                return
            if version is not None and version != self.delta_version:
                return
            rows = self._rows
            for k, row in items:
                rows[k] = row
                rows.move_to_end(k)
            while len(rows) > self.capacity:
                rows.popitem(last=False)
                n_evict += 1
            if items:
                self._content_gen += 1      # drop_where stack is stale
                self._stack = None
            # instance counters stay lock-guarded (two materialize
            # threads may finish concurrently); the Metrics incs below
            # follow the registry's own repo-wide threading model
            self.evictions += n_evict
        self._inc("inserts", len(items))
        self._inc("evictions", n_evict)

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def stats(self) -> dict:
        with self._lock:
            size = len(self._rows)
            hits, misses = self.hits, self.misses
        total = hits + misses
        return {
            "size": size,
            "capacity": self.capacity,
            "snapshot_id": self.snapshot_id,
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / total, 4) if total else 0.0,
            "evictions": self.evictions,
            "invalidations": self.invalidations,
            "delta_version": self.delta_version,
            "delta_invalidated": self.delta_invalidated,
        }
