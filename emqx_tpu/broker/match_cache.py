"""Snapshot-keyed host-side LRU of device match rows.

The wildcard match stage (ops.match / ops.shapes) is a pure function of
an immutable table snapshot and the encoded topic: for one `_Built`
snapshot, the same topic always produces the same (matches row, count,
overflow) triple. Real MQTT publish traffic is heavily skewed to a small
hot topic set (arXiv:1811.07088 §5, arXiv:2603.21600), so paying the
NFA/shape-hash cost once per (snapshot, topic) instead of once per
message removes most of the match work from the device route path.

This cache holds those triples host-side, keyed by a 128-bit hash of the
encoded level words + `is_dollar` (two independent 64-bit folds over the
interned ids — same collision posture as ops/shapes.py's 2x32-bit path
hashes: a wrong row needs a 128-bit collision inside one snapshot's live
key set, ~2^-128 per pair). Rows are numpy: matches [Mw] int32, count
int32, overflow bool, where Mw is the snapshot's match width (shape
capacity for the shapes backend, match_cap for the trie NFA).

Row layout note (ISSUE 3): rows populated from a COMPACTED readback
(device_engine.materialize's CSR branch) are hole-compacted — the valid
filter ids as a prefix, -1 beyond — while a dense readback preserves the
shape-slot hole positions of the shapes backend. The two layouts are
interchangeable by the hole-insensitivity contract (ops/compact.py):
fan-out/shared expansion treat -1 as a zero-length segment and consume
skips it, the valid ids keep their match ORDER either way, and `count`
equals the true match count for both. Deliveries and cursor threading
are therefore bit-identical regardless of which readback populated a
row (oracle-tested in tests/test_compact_readback.py).

Consistency invariant (why per-snapshot keying suffices): mutations
never edit the device tables in place — subscription churn marks
filters/slots dirty and those serve host-side against the PINNED
snapshot until the next rebuild (broker/device_engine.py's
dirty/delta scheme), so the match output for a given snapshot id never
changes during that snapshot's lifetime. `attach()` at snapshot swap
(DeviceRouteEngine._apply_build) is therefore the ONLY invalidation
point needed: rows can never be stale within a snapshot, and the id
check on every get/put batch makes cross-snapshot serving structurally
impossible (a reader thread racing a swap inserts into /reads from
nothing).

Thread model: looked up on the event loop (prepare), populated from the
materialize/read executor threads, invalidated on the loop at swap — one
plain lock around the OrderedDict; every operation is a small dict walk,
orders of magnitude below the batch work it fronts.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

DEFAULT_CAPACITY = 8192


class MatchCache:
    """LRU of per-(snapshot, topic) match rows with hit/miss accounting.

    `metrics` is a broker.metrics.Metrics (or None): hit/miss/evict/
    invalidation counters land there as `match_cache.*`, which is how the
    Prometheus/StatsD/$SYS/mgmt exporters and the telemetry snapshot see
    the cache with zero coupling to this module.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY, metrics=None):
        self.capacity = max(1, int(capacity))
        self.metrics = metrics
        self._lock = threading.Lock()
        self._rows: OrderedDict = OrderedDict()   # key -> (m, c, o)
        self.snapshot_id: Optional[int] = None
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0

    def _inc(self, name: str, n: int) -> None:
        if self.metrics is not None and n:
            self.metrics.inc(f"match_cache.{name}", n)

    def attach(self, snapshot_id: Optional[int]) -> None:
        """Bind the cache to a new snapshot, dropping every row of the
        previous one (wholesale invalidation at swap — see the module
        docstring for why this is the only invalidation point)."""
        with self._lock:
            if self._rows:
                self.invalidations += 1
                self._inc("invalidations", 1)
                self._inc("invalidated_rows", len(self._rows))
                self._rows.clear()
            self.snapshot_id = snapshot_id

    def get_many(self, snapshot_id, keys: list) -> list:
        """Row per key (None = miss), LRU-touching hits. A snapshot-id
        mismatch (reader raced a swap) misses everything. Does NOT count
        hit/miss accounting — lookups also run for windows that end up
        dispatching the plain program, and counting those would inflate
        the exported hit rate with reuse that never fed a dispatch; the
        planner calls count_lookups() only for engaged plans."""
        with self._lock:
            if snapshot_id != self.snapshot_id:
                return [None] * len(keys)
            rows = self._rows
            out = []
            for k in keys:
                row = rows.get(k)
                if row is not None:
                    rows.move_to_end(k)
                out.append(row)
            return out

    def count_lookups(self, hits: int, misses: int) -> None:
        """Account one ENGAGED window's lookup outcome (see get_many)."""
        with self._lock:
            self.hits += hits
            self.misses += misses
        self._inc("hits", hits)
        self._inc("misses", misses)

    def put_many(self, snapshot_id, items: list) -> None:
        """Insert (key, row) pairs read back from a finished dispatch.
        Dropped whole when the snapshot moved on while the batch was in
        flight — those rows describe tables that no longer serve."""
        n_evict = 0
        with self._lock:
            if snapshot_id != self.snapshot_id:
                return
            rows = self._rows
            for k, row in items:
                rows[k] = row
                rows.move_to_end(k)
            while len(rows) > self.capacity:
                rows.popitem(last=False)
                n_evict += 1
            # instance counters stay lock-guarded (two materialize
            # threads may finish concurrently); the Metrics incs below
            # follow the registry's own repo-wide threading model
            self.evictions += n_evict
        self._inc("inserts", len(items))
        self._inc("evictions", n_evict)

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def stats(self) -> dict:
        with self._lock:
            size = len(self._rows)
            hits, misses = self.hits, self.misses
        total = hits + misses
        return {
            "size": size,
            "capacity": self.capacity,
            "snapshot_id": self.snapshot_id,
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / total, 4) if total else 0.0,
            "evictions": self.evictions,
            "invalidations": self.invalidations,
        }
