"""Host broker runtime: the non-device half of the framework.

Mirrors the reference's core app layers (SURVEY.md §1 layers 0-7):
listeners → connections → channel FSM → session → pubsub engine, with the
wildcard match + fan-out hot path delegated to the device router
(emqx_tpu.models.router_engine) in micro-batches.
"""
