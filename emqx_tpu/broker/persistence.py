"""Checkpoint/resume: durable state snapshot + write-ahead op log.

Parity: SURVEY.md §5.4 — the reference has no whole-broker checkpoint;
durability is per-subsystem (retained/delayed in mnesia disc copies,
sessions via takeover, bridge egress via replayq). The TPU-era design makes
the device tables SOFT state rebuilt from a host-side durable log: snapshot
= the authoritative host structures (routes, retained, delayed, parked
sessions) serialized to disk; resume = load snapshot, replay the op log
written since, then recompile the device trie from the restored routes.

Log entries ride the replayq segment format (fsync'd, torn-tail safe).
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Optional

from emqx_tpu.broker.message import Message
from emqx_tpu.broker.session import Session
from emqx_tpu.utils.replayq import ReplayQ

log = logging.getLogger("emqx_tpu.persistence")

SNAPSHOT = "snapshot.json"
WAL_DIR = "wal"


def _enc(o):
    if isinstance(o, (bytes, bytearray)):
        import base64
        return {"$b": base64.b64encode(bytes(o)).decode()}
    raise TypeError(repr(o))


def _dec(v):
    if isinstance(v, dict) and "$b" in v:
        import base64
        return base64.b64decode(v["$b"])
    return v


def _dec_deep(o):
    if isinstance(o, dict):
        if "$b" in o and len(o) == 1:
            return _dec(o)
        return {k: _dec_deep(v) for k, v in o.items()}
    if isinstance(o, list):
        return [_dec_deep(v) for v in o]
    return o


class Persistence:
    """Attach to a Node: journals retained/delayed/route mutations and
    snapshots+restores the whole durable state."""

    def __init__(self, node, data_dir: str):
        self.node = node
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.wal = ReplayQ(os.path.join(data_dir, WAL_DIR))
        node.persistence = self

    # ---- write-ahead log ----
    def journal(self, op: str, **fields) -> None:
        fields["op"] = op
        self.wal.append(json.dumps(fields, default=_enc).encode())

    # ---- snapshot ----
    def save_snapshot(self) -> str:
        """Serialize durable state; truncates the WAL (entries are now
        reflected in the snapshot)."""
        node = self.node
        from emqx_tpu.apps.delayed import DelayedPublish
        from emqx_tpu.apps.retainer import Retainer
        snap: dict = {"version": 1, "ts": int(time.time() * 1000),
                      "node": node.name}
        snap["routes"] = {
            "exact": sorted(node.router.exact),
            "wildcards": sorted(node.router.wildcards)}
        retainer = node.get_app(Retainer)
        if retainer is not None:
            snap["retained"] = [
                {"msg": m.to_wire(), "expire_at": exp}
                for _t, m, exp in retainer.storage.items()]
        delayed = node.get_app(DelayedPublish)
        if delayed is not None:
            snap["delayed"] = [
                {"msg": m.to_wire(), "fire_at": at}
                for at, _seq, m in delayed.pending()]
        snap["sessions"] = {
            cid: s.to_wire() for cid, s in node.cm._detached.items()}
        path = os.path.join(self.data_dir, SNAPSHOT)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f, default=_enc)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # WAL reset: everything journaled so far is inside the snapshot
        items, ref = self.wal.pop(1 << 30)
        if ref is not None:
            self.wal.ack(ref)
        return path

    def load_snapshot(self) -> bool:
        """Restore state from disk; then replay WAL entries written after
        the snapshot. Returns False when no snapshot exists."""
        path = os.path.join(self.data_dir, SNAPSHOT)
        try:
            with open(path) as f:
                snap = _dec_deep(json.load(f))
        except FileNotFoundError:
            snap = None
        if snap is not None:
            self._apply_snapshot(snap)
        # WAL replay (ops since the snapshot)
        items, _ref = self.wal.pop(1 << 30)
        for raw in items:
            try:
                self._apply_wal(_dec_deep(json.loads(raw)))
            except Exception:  # noqa: BLE001 — one bad entry never blocks boot
                log.exception("WAL entry replay failed")
        # recompile the device tables from the restored route set
        if self.node.router.use_device and self.node.router.wildcards:
            self.node.router.rebuild()
        return snap is not None

    def _apply_snapshot(self, snap: dict) -> None:
        node = self.node
        from emqx_tpu.apps.delayed import DelayedPublish
        from emqx_tpu.apps.retainer import Retainer
        for t in snap.get("routes", {}).get("exact", []):
            node.router.add_route(t)
        for t in snap.get("routes", {}).get("wildcards", []):
            node.router.add_route(t)
        retainer = node.get_app(Retainer)
        if retainer is not None:
            for ent in snap.get("retained", []):
                msg = Message.from_wire(ent["msg"])
                retainer.storage.insert(msg.topic, msg,
                                        ent.get("expire_at"))
        delayed = node.get_app(DelayedPublish)
        if delayed is not None:
            now = int(time.time() * 1000)
            for ent in snap.get("delayed", []):
                msg = Message.from_wire(ent["msg"])
                delayed.restore(msg, max(ent["fire_at"], now + 1))
        for cid, wire in snap.get("sessions", {}).items():
            sess = Session.from_wire(wire)
            node.cm.park_session(cid, sess)

    def _apply_wal(self, entry: dict) -> None:
        node = self.node
        op = entry.get("op")
        from emqx_tpu.apps.retainer import Retainer
        if op == "retain":
            retainer = node.get_app(Retainer)
            if retainer is not None:
                msg = Message.from_wire(entry["msg"])
                retainer.storage.insert(msg.topic, msg,
                                        entry.get("expire_at"))
        elif op == "retain_del":
            retainer = node.get_app(Retainer)
            if retainer is not None:
                retainer.delete(entry["topic"])
        elif op == "route_add":
            node.router.add_route(entry["topic"])
        elif op == "route_del":
            node.router.delete_route(entry["topic"])
        else:
            log.warning("unknown WAL op %r", op)


def attach_retainer_journal(node) -> bool:
    """Hook the retainer so every retained set/delete is WAL-journaled
    (the mnesia disc_copies analog)."""
    from emqx_tpu.apps.retainer import Retainer
    retainer = node.get_app(Retainer)
    pers = getattr(node, "persistence", None)
    if retainer is None or pers is None:
        return False
    orig_insert, orig_delete = retainer._insert, retainer.delete

    def insert(msg):
        ok = orig_insert(msg)
        if ok:
            pers.journal("retain", msg=msg.to_wire(),
                         expire_at=retainer._expire_at(msg))
        return ok

    def delete(topic):
        ok = orig_delete(topic)
        if ok:
            pers.journal("retain_del", topic=topic)
        return ok

    retainer._insert = insert
    retainer.delete = delete
    return True
