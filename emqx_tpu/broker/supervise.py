"""Fault-domain supervision for the device route pipeline (ISSUE 6).

The Erlang reference's defining property is OTP supervision: every
subsystem runs under a supervisor that restarts, isolates and degrades
on failure (emqx_sup.erl's one_for_one trees) — that, not raw speed, is
what earns "10M connections on one cluster". Our five-stage async
pipeline (batcher → dispatch → materialize → delta-overlay rebuild →
delivery lanes, PRs 2–5) had *no* systematic failure layer: an
exception in any stage unwound ad hoc, a wedged readback froze the
consumer, and a dying stage lost its window's publishes. This module is
the supervision tree those stages plug into:

- **Deterministic fault injection** (`FaultInjector`): named injection
  points at every stage boundary — ``dispatch``, ``materialize``,
  ``cache_insert``, ``overlay_apply``, ``lane_deliver``,
  ``snapshot_swap``, ``mesh_exchange`` — armed via the
  ``EMQX_TPU_FAULTS`` spec so every failure mode is reproducible in CI
  (tools/chaos_bench.py drives the matrix). Spec grammar, comma-
  separated clauses::

      point:kind[:after=N][:count=M][:hang_s=S]

  ``kind`` ∈ {``exception``, ``resource`` (an OOM-like
  RESOURCE_EXHAUSTED), ``hang`` (sleeps ``hang_s``, default 30 — at the
  watchdogged executor-thread stages (dispatch/materialize/
  mesh_exchange) the consumer's deadline trips first; at the loop-side
  points a bounded hang blocks the loop for ``hang_s``, modeling a
  synchronous stall), ``corrupt`` (shape-corrupts the stage's
  output where meaningful — materialize readbacks; elsewhere it decays
  to ``exception``)}. ``after=N`` skips the first N traversals of the
  point (arm mid-stream), ``count=M`` fires at most M times (so probes
  eventually succeed and the ladder steps back up); ``count`` defaults
  to 1, ``after`` to 0.

- **Circuit breakers + the degradation ladder** (`CircuitBreaker`,
  `PipelineSupervisor`): each fault domain gets a breaker (closed →
  open after ``threshold`` consecutive faults → half-open probe *off
  the serving path*, mirroring the demand-warm pattern — a probe runs
  on an executor thread against engine-registered probe functions,
  never inline with a live window). Open breakers step the pipeline
  down the ladder per window:

      rung 0  device + cache + delta + compact   (everything on)
      rung 1  device-plain                        (reuse layers off:
              cache_insert / overlay_apply domain open)
      rung 2  host-trie                           (device off:
              dispatch / materialize domain open)

  and probe success steps back up. The ``lane_deliver`` breaker gates
  the ISSUE-5 delivery lanes (open → inline delivery), ``snapshot_swap``
  gates background rebuild attempts (open → serve the old snapshot +
  host deltas), ``mesh_exchange`` gates the sharded mesh path (open →
  host route). Knob: ``broker.supervise`` / ``EMQX_TPU_SUPERVISE``
  (config beats env beats default-on); ``=0`` restores the pre-ISSUE-6
  unwind behavior exactly — the A/B baseline.

- **Window-journal replay** (`journal_admit`/`journal_settle`): every
  window entering the pipeline is journaled at admit (topic keys +
  publisher future ids, the same journal discipline as the PR-4 churn
  journal) and settled when its counts resolve. A stage death
  mid-window — dispatch/materialize raising, a corrupt readback blowing
  up consume, a watchdog trip — re-routes the journaled window through
  the next ladder rung (the batcher's host path, which drains the
  lanes first) instead of failing its publishers: zero message loss
  for QoS≥1 and per-session order preserved. Replays are counted
  (``supervise.replays``); the journal depth is a live gauge.

- **Watchdogs**: the batcher's consumer bounds its dispatch/materialize
  awaits with ``deadline(stage)`` — derived from the PR-1 stage
  histograms' p99 (``clamp(mult·p99, floor, cap)``) — and trips the
  stage's breaker instead of wedging; lane drains/admits likewise
  detect stalls, restart dead lane workers (which then drain their
  queues in order), and trip the ``lane_deliver`` breaker.

Everything lands in the shared Metrics registry
(``supervise.faults[.point]``, ``supervise.trips``, ``supervise.probes``,
``supervise.replays``, ``supervise.stalls[.stage]``,
``supervise.restarts``, ``supervise.task_errors``,
``supervise.rung_changes``), so all four exporters carry the counters;
`PipelineTelemetry.snapshot()['supervise']` is the derived section with
the live breaker/rung/journal state.
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
import time
from typing import Callable, Optional

log = logging.getLogger("emqx.supervise")

# the named stage boundaries (one fault domain each). The two
# overload points (ISSUE 14) are traversed by the OverloadGovernor's
# poll, not a pipeline stage: a fired `signal_spike` clause forces the
# raw grade to critical for that poll, a fired `stuck_grade` clause
# blocks grade transitions (sustained blocking raises the
# overload_stuck alarm) — recommended kind `corrupt` (fires without
# raising; other kinds are caught by the governor and count the same).
# Their breakers exist but never open (no serving path notes faults
# against them); the ladder gates ignore them.
FAULT_POINTS = ("dispatch", "materialize", "cache_insert",
                "overlay_apply", "lane_deliver", "snapshot_swap",
                "mesh_exchange", "signal_spike", "stuck_grade")
FAULT_KINDS = ("exception", "resource", "hang", "corrupt")

# ladder rungs (PipelineSupervisor.rung())
RUNG_FULL = 0          # device + cache + delta + compact
RUNG_DEVICE_PLAIN = 1  # device, reuse layers off
RUNG_HOST = 2          # host trie


def resolve_supervise(configured=None) -> bool:
    """The one supervision-knob resolution: config beats
    EMQX_TPU_SUPERVISE beats default-on. ``=0`` restores the pre-ISSUE-6
    ad-hoc unwind behavior exactly (no injector, no breakers, no
    watchdogs, no journal) — the A/B baseline the chaos acceptance
    criteria compare."""
    if configured is not None:
        return bool(configured)
    return os.environ.get("EMQX_TPU_SUPERVISE", "1") \
        not in ("0", "false", "off")


class InjectedFault(RuntimeError):
    """A deterministic injected stage failure (kind=exception)."""


class InjectedResourceExhausted(InjectedFault):
    """OOM-like injected failure; the message carries the XLA status
    string so log-greppers and error classifiers treat it like a real
    device RESOURCE_EXHAUSTED."""

    def __init__(self, point: str):
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected at {point} "
            f"(out of memory simulation)")


class _Fault:
    """One armed fault clause: fires on traversals (after, after+count]
    of its injection point."""

    __slots__ = ("point", "kind", "after", "count", "hang_s", "hits",
                 "fired")

    def __init__(self, point: str, kind: str, after: int = 0,
                 count: int = 1, hang_s: float = 30.0):
        if point not in FAULT_POINTS:
            raise ValueError(f"unknown fault point {point!r} "
                             f"(know {FAULT_POINTS})")
        if kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(know {FAULT_KINDS})")
        self.point = point
        self.kind = kind
        self.after = int(after)
        self.count = int(count)
        self.hang_s = float(hang_s)
        self.hits = 0     # traversals of the point seen by this clause
        self.fired = 0    # times this clause actually fired


def parse_faults(spec: Optional[str]) -> list[_Fault]:
    """Parse an EMQX_TPU_FAULTS spec: comma-separated
    ``point:kind[:after=N][:count=M][:hang_s=S]`` clauses. Raises
    ValueError on malformed input — a typo'd chaos spec silently doing
    nothing would defeat the whole point of deterministic injection."""
    out: list[_Fault] = []
    if not spec:
        return out
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        parts = clause.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"fault clause {clause!r}: want point:kind[:k=v...]")
        kw: dict = {}
        for p in parts[2:]:
            if "=" not in p:
                raise ValueError(
                    f"fault clause {clause!r}: option {p!r} is not k=v")
            k, v = p.split("=", 1)
            if k not in ("after", "count", "hang_s"):
                raise ValueError(
                    f"fault clause {clause!r}: unknown option {k!r}")
            kw[k] = float(v) if k == "hang_s" else int(v)
        out.append(_Fault(parts[0], parts[1], **kw))
    return out


def resolve_faults(configured=None) -> list:
    """The one fault-spec resolution: an explicit clause list beats the
    ``EMQX_TPU_FAULTS`` env spec beats none. Deliberately has NO config
    key — fault injection is a per-process chaos knob (chaos_bench,
    tier-1 chaos cells), never cluster configuration; a malformed spec
    raises at parse so a typo'd chaos run fails loudly."""
    if configured is not None:
        return configured
    return parse_faults(os.environ.get("EMQX_TPU_FAULTS"))


class FaultInjector:
    """Deterministic injection-point registry. ``fire(point)`` is the
    stage-boundary check: raises (exception/resource), sleeps (hang) or
    returns ``"corrupt"`` for the caller to corrupt its own output.
    Thread-safe — dispatch/materialize traverse their points on
    executor threads."""

    def __init__(self, faults: Optional[list[_Fault]] = None):
        self.faults = resolve_faults(faults)
        self._lock = threading.Lock()

    def armed(self) -> bool:
        return bool(self.faults)

    def fire(self, point: str, corrupt_ok: bool = False) -> Optional[str]:
        """Traverse an injection point. Returns None (no fault due) or
        "corrupt" (only where the caller can corrupt its own output —
        ``corrupt_ok``; elsewhere a corrupt clause decays to
        ``exception``); raises InjectedFault/InjectedResourceExhausted
        or sleeps for the hang kind."""
        action = None
        with self._lock:
            for f in self.faults:
                if f.point != point:
                    continue
                f.hits += 1
                if f.hits > f.after and f.fired < f.count:
                    f.fired += 1
                    action = f
                    break
        if action is None:
            return None
        if action.kind == "hang":
            # analysis: ok(loop-affinity) — the hang IS the injected
            # fault: a chaos clause emulating a wedged stage/link must
            # block exactly where the real wedge would (loop-side
            # points included); never armed outside chaos runs
            time.sleep(action.hang_s)
            return None
        if action.kind == "resource":
            raise InjectedResourceExhausted(point)
        if action.kind == "corrupt" and corrupt_ok:
            return "corrupt"
        raise InjectedFault(f"injected fault at {point}")

    def state(self) -> list[dict]:
        with self._lock:
            return [{"point": f.point, "kind": f.kind, "after": f.after,
                     "count": f.count, "hits": f.hits, "fired": f.fired}
                    for f in self.faults]


class CircuitBreaker:
    """Per-stage breaker: closed → open after ``threshold`` consecutive
    faults → (cooldown) → half-open, where exactly one off-path probe
    decides close vs re-open with doubled cooldown. ``allow()`` answers
    the serving path's question — half-open still answers False, because
    the probe runs off the serving path (the demand-warm pattern: live
    traffic is never the guinea pig)."""

    __slots__ = ("stage", "threshold", "base_cooldown_s", "max_cooldown_s",
                 "state", "fails", "opened_at", "cooldown_s", "trips",
                 "_clock", "_lock")

    def __init__(self, stage: str, *, threshold: int = 3,
                 cooldown_s: float = 1.0, max_cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.stage = stage
        self.threshold = max(1, int(threshold))
        self.base_cooldown_s = cooldown_s
        self.max_cooldown_s = max_cooldown_s
        self.state = "closed"
        self.fails = 0          # consecutive faults while closed
        self.opened_at = 0.0
        self.cooldown_s = cooldown_s
        self.trips = 0          # closed→open transitions
        self._clock = clock
        # note_fault/note_ok run on executor threads (dispatch thread,
        # read pool) concurrently with poll/probes on the loop: the
        # read-modify-writes below must not lose increments. allow()
        # stays lock-free — a single attribute read is atomic and a
        # one-batch-stale answer is harmless (the gates re-check every
        # window).
        self._lock = threading.Lock()

    def allow(self) -> bool:
        return self.state == "closed"

    def record_ok(self) -> None:
        """A successful serving-path traversal (only meaningful while
        closed — the serving path never runs through an open/half-open
        stage, so this cannot mask a pending probe)."""
        with self._lock:
            if self.state == "closed":
                self.fails = 0

    def record_fault(self) -> bool:
        """One serving-path fault. Returns True when this fault OPENED
        the breaker (the rung-change edge the caller counts)."""
        with self._lock:
            if self.state != "closed":
                return False
            self.fails += 1
            if self.fails >= self.threshold:
                self.state = "open"
                self.opened_at = self._clock()
                self.cooldown_s = self.base_cooldown_s
                self.trips += 1
                return True
            return False

    def probe_due(self) -> bool:
        with self._lock:
            return self.state == "open" \
                and self._clock() >= self.opened_at + self.cooldown_s

    def begin_probe(self) -> None:
        with self._lock:
            self.state = "half_open"

    def probe_ok(self) -> None:
        with self._lock:
            self.state = "closed"
            self.fails = 0
            self.cooldown_s = self.base_cooldown_s

    def probe_fail(self) -> None:
        with self._lock:
            self.state = "open"
            self.opened_at = self._clock()
            self.cooldown_s = min(2 * self.cooldown_s,
                                  self.max_cooldown_s)

    def snapshot(self) -> dict:
        return {"state": self.state, "fails": self.fails,
                "trips": self.trips,
                "cooldown_s": round(self.cooldown_s, 3)}


# watchdog deadline shape: clamp(mult * p99, floor, cap). The floor
# absorbs cold histograms and scheduling jitter; the cap bounds how long
# a wedged stage can hold a pipeline slot even when the p99 history is
# already pathological.


def resolve_watchdog_floor_s(configured=None) -> float:
    """Watchdog deadline floor: an explicit supervisor kwarg beats
    ``EMQX_TPU_WATCHDOG_FLOOR_S`` beats 10s."""
    if configured is not None:
        return float(configured)
    return float(os.environ.get("EMQX_TPU_WATCHDOG_FLOOR_S", "10"))


def resolve_watchdog_cap_s(configured=None) -> float:
    """Watchdog deadline cap: an explicit supervisor kwarg beats
    ``EMQX_TPU_WATCHDOG_CAP_S`` beats 120s."""
    if configured is not None:
        return float(configured)
    return float(os.environ.get("EMQX_TPU_WATCHDOG_CAP_S", "120"))


def resolve_watchdog_mult(configured=None) -> float:
    """Watchdog p99 multiplier: an explicit supervisor kwarg beats
    ``EMQX_TPU_WATCHDOG_MULT`` beats 8."""
    if configured is not None:
        return float(configured)
    return float(os.environ.get("EMQX_TPU_WATCHDOG_MULT", "8"))


_WD_FLOOR_S = resolve_watchdog_floor_s()
_WD_CAP_S = resolve_watchdog_cap_s()
_WD_MULT = resolve_watchdog_mult()


def resolve_breaker_threshold(configured=None) -> int:
    """Consecutive faults before a stage breaker opens: config
    (``broker.supervise_threshold``, passed down by the node) beats
    ``EMQX_TPU_BREAKER_THRESHOLD`` beats 3."""
    if configured is not None:
        return int(configured)
    return int(os.environ.get("EMQX_TPU_BREAKER_THRESHOLD", "3"))


def resolve_breaker_cooldown_s(configured=None) -> float:
    """Half-open probe base cooldown: an explicit supervisor kwarg
    beats ``EMQX_TPU_BREAKER_COOLDOWN_S`` beats 1s (exponential up to
    the breaker's 30s max)."""
    if configured is not None:
        return float(configured)
    return float(os.environ.get("EMQX_TPU_BREAKER_COOLDOWN_S", "1.0"))

# process-wide count of guarded-task deaths, for contexts without a
# Metrics registry (and for tests asserting the guard fired at all)
_task_errors = 0
_task_errors_lock = threading.Lock()


def task_error_count() -> int:
    return _task_errors


def guard_task(task: "asyncio.Task", name: str, metrics=None,
               on_error: Optional[Callable[[BaseException], None]] = None
               ) -> "asyncio.Task":
    """Attach the one done-callback every pipeline task must carry: a
    non-cancelled exception is logged and counted
    (``supervise.task_errors``) instead of vanishing into the loop's
    never-retrieved-exception limbo — today a lane or consumer task can
    die silently between windows (ISSUE 6 satellite). ``on_error`` lets
    owners add recovery (e.g. restart a lane worker)."""
    def _done(t: "asyncio.Task") -> None:
        if t.cancelled():
            return
        exc = t.exception()     # marks the exception as retrieved
        if exc is None:
            return
        global _task_errors
        with _task_errors_lock:
            _task_errors += 1
        if metrics is not None:
            try:
                metrics.inc("supervise.task_errors")
            except Exception:  # noqa: BLE001 — accounting must not mask
                pass           # the original failure being logged below
        log.error("task %r died: %s: %s", name, type(exc).__name__, exc,
                  exc_info=exc)
        if on_error is not None:
            try:
                on_error(exc)
            except Exception:  # noqa: BLE001
                log.exception("task %r on_error recovery failed", name)

    task.add_done_callback(_done)
    return task


# strong refs for guarded fire-and-forget tasks: the loop keeps only
# weak refs, so an unheld in-flight task can be GC'd mid-run
_spawned: set = set()


def spawn(coro, name: str, metrics=None) -> Optional["asyncio.Task"]:
    """Fire-and-forget a coroutine UNDER the task guard: strong ref
    until done + logged/counted death. The replacement for bare
    ``asyncio.ensure_future(...)`` statements (which tools/
    check_task_hygiene.py flags). Returns None (coroutine closed) when
    no loop is running."""
    try:
        task = asyncio.get_running_loop().create_task(coro)
    except RuntimeError:
        coro.close()
        return None
    _spawned.add(task)
    task.add_done_callback(_spawned.discard)
    return guard_task(task, name, metrics)


class _JournalEntry:
    """One admitted window's manifest: a REFERENCE to the batcher's
    live (message, future) batch list — zero per-window allocation
    beyond this object on the hot admit path. The replay itself
    re-routes the batcher's own entry — this record is the accounting
    view: depth gauges, leak detection, and the debug surfaces
    (`topics`/`futs`) for a wedged window."""

    __slots__ = ("batch", "t0")

    def __init__(self, batch):
        self.batch = batch          # [(Message, Optional[Future])]
        self.t0 = time.monotonic()

    @property
    def topics(self):
        return tuple(m.topic for m, _f in self.batch)

    @property
    def futs(self):
        return tuple(f for _m, f in self.batch if f is not None)


class PipelineSupervisor:
    """The per-node supervision tree for the device route pipeline.

    Owns one breaker per fault domain, the fault injector, the window
    journal, and the watchdog deadlines. Components register probe
    functions (run on an executor thread, off the serving path) and
    consult the gates:

        allow_device()   rung < 2  — the batcher's device/host choice
        reuse_enabled()  rung == 0 — dedup/cache/delta/compact layers
        lanes_enabled()  the delivery-lane pool may take plans
        rebuild_enabled() background rebuilds may be attempted
        mesh_enabled()   the sharded mesh path may serve

    ``poll()`` runs on the batch cadence (like poll_rebuild): it
    launches due half-open probes in the background. All gates are
    plain attribute/dict reads — no locks on the serving path.
    """

    def __init__(self, metrics, *, telemetry=None,
                 injector: Optional[FaultInjector] = None,
                 threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 watchdog_floor_s: Optional[float] = None,
                 watchdog_cap_s: Optional[float] = None,
                 watchdog_mult: Optional[float] = None):
        self.metrics = metrics
        self.telemetry = telemetry
        self.injector = injector if injector is not None else \
            FaultInjector()
        threshold = resolve_breaker_threshold(threshold)
        cooldown_s = resolve_breaker_cooldown_s(cooldown_s)
        self.breakers: dict[str, CircuitBreaker] = {
            p: CircuitBreaker(p, threshold=threshold,
                              cooldown_s=cooldown_s)
            for p in FAULT_POINTS}
        self.wd_floor_s = _WD_FLOOR_S if watchdog_floor_s is None \
            else watchdog_floor_s
        self.wd_cap_s = _WD_CAP_S if watchdog_cap_s is None \
            else watchdog_cap_s
        self.wd_mult = _WD_MULT if watchdog_mult is None \
            else watchdog_mult
        # flight recorder (ISSUE 7; set by the node when tracing is
        # on): trips / rung changes / restarts land as node-scope
        # events on the causal timeline, so a post-mortem dump shows
        # WHEN the ladder moved relative to the windows around it
        self.recorder = None
        self._probe_fns: dict[str, Callable[[], None]] = {}
        self._probe_tasks: dict[str, "asyncio.Task"] = {}
        self._journal: dict[int, _JournalEntry] = {}
        self._journal_ids = iter(range(1, 1 << 62)).__next__
        self._journal_lock = threading.Lock()

    # ---- fault injection (stage boundaries call these) -------------------
    def fire(self, point: str, corrupt_ok: bool = False) -> Optional[str]:
        """Traverse an injection point (no-op unless a chaos spec armed
        it). Raises/sleeps/returns "corrupt" per the armed clause."""
        if not self.injector.armed():
            return None
        return self.injector.fire(point, corrupt_ok=corrupt_ok)

    # ---- fault accounting + breakers ------------------------------------
    def note_fault(self, point: str, exc: Optional[BaseException] = None
                   ) -> None:
        """One serving-path fault in a domain: count it, advance the
        breaker, and log the rung change when the breaker opens."""
        m = self.metrics
        m.inc("supervise.faults")
        m.inc(f"supervise.faults.{point}")
        br = self.breakers.get(point)
        if br is None:
            return
        before = self.rung()
        if br.record_fault():
            m.inc("supervise.trips")
            rung_moved = self.rung() != before
            if rung_moved:
                m.inc("supervise.rung_changes")
            if self.recorder is not None:
                # orthogonal-gate breakers (lane_deliver,
                # snapshot_swap) trip without moving the rung — the
                # timeline event must agree with the rung_changes
                # counter, so those record as "trip"
                self.recorder.event(
                    0, "rung_change" if rung_moved else "trip",
                    meta={"point": point, "rung": self.rung(),
                          "trip": True})
            log.warning(
                "breaker %s OPEN after %d consecutive fault(s)%s — "
                "pipeline now at rung %d", point, br.threshold,
                f" ({type(exc).__name__}: {exc})" if exc else "",
                self.rung())

    def note_ok(self, point: str) -> None:
        br = self.breakers.get(point)
        if br is not None:
            br.record_ok()

    def note_stall(self, stage: str) -> None:
        """A watchdog deadline expired waiting on `stage`: count the
        stall and advance the stage's breaker — tripping instead of
        wedging the consumer is the entire point."""
        self.metrics.inc("supervise.stalls")
        self.metrics.inc(f"supervise.stalls.{stage}")
        self.note_fault(stage)

    def note_restart(self, what: str) -> None:
        self.metrics.inc("supervise.restarts")
        self.metrics.inc(f"supervise.restarts.{what}")
        if self.recorder is not None:
            self.recorder.event(0, "restart", meta={"what": what})

    def note_replay(self) -> None:
        self.metrics.inc("supervise.replays")

    # ---- the degradation ladder -----------------------------------------
    def rung(self) -> int:
        b = self.breakers
        if not (b["dispatch"].allow() and b["materialize"].allow()):
            return RUNG_HOST
        if not (b["cache_insert"].allow() and b["overlay_apply"].allow()):
            return RUNG_DEVICE_PLAIN
        return RUNG_FULL

    def allow_device(self) -> bool:
        return self.rung() < RUNG_HOST

    def reuse_enabled(self) -> bool:
        return self.rung() == RUNG_FULL

    def lanes_enabled(self) -> bool:
        return self.breakers["lane_deliver"].allow()

    def rebuild_enabled(self) -> bool:
        return self.breakers["snapshot_swap"].allow()

    def mesh_enabled(self) -> bool:
        return self.breakers["mesh_exchange"].allow()

    # ---- half-open probes (off the serving path) ------------------------
    def register_probe(self, stage: str, fn: Callable[[], None]) -> None:
        """A stage's health probe: a sync callable run on an executor
        thread when the stage's breaker is due for half-open; raising
        means still broken. Every probe ALSO re-traverses the stage's
        injection point, so an exhausted chaos clause (count=M spent)
        lets the probe succeed and the ladder step back up — the
        deterministic recovery the chaos matrix asserts."""
        self._probe_fns[stage] = fn

    def poll(self) -> None:
        """Batch-cadence tick: launch due probes in the background.
        Cheap when nothing is open (one dict scan of closed breakers)."""
        for stage, br in self.breakers.items():
            t = self._probe_tasks.get(stage)
            if br.state == "half_open":
                dead = t is None or t.done()
                if not dead:
                    # a probe stranded on a torn-down loop never
                    # reaches done(): treat any probe not on the
                    # CURRENT loop as dead (this codebase runs several
                    # loops against one node — deliver.py's rebind)
                    try:
                        dead = t.get_loop() is not \
                            asyncio.get_running_loop()
                    except RuntimeError:
                        dead = False    # sync caller: can't judge
                if dead:
                    # the probe died without a verdict: a half-open
                    # breaker with no live probe would otherwise be
                    # stuck degraded FOREVER (probe_due requires
                    # "open") — re-open so the cooldown→probe cycle
                    # re-arms
                    self._probe_tasks.pop(stage, None)
                    br.probe_fail()
                    self.metrics.inc("supervise.probe_failures")
                continue
            if not br.probe_due():
                continue
            if t is not None and not t.done():
                continue
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                # no loop (sync callers): probe inline — still off the
                # serving path in the sense that no live window rides it
                br.begin_probe()
                self._run_probe_sync(stage, br)
                continue
            br.begin_probe()
            self._probe_tasks[stage] = guard_task(
                loop.create_task(self._probe_async(stage, br)),
                f"supervise-probe-{stage}", self.metrics)

    def _run_probe_sync(self, stage: str, br: CircuitBreaker) -> None:
        self.metrics.inc("supervise.probes")
        before = self.rung()
        try:
            self.fire(stage)
            fn = self._probe_fns.get(stage)
            if fn is not None:
                fn()
        except Exception as e:  # noqa: BLE001 — probe verdict, not a bug
            br.probe_fail()
            self.metrics.inc("supervise.probe_failures")
            log.info("probe %s failed (%s): breaker stays open "
                     "(cooldown %.1fs)", stage, type(e).__name__,
                     br.cooldown_s)
            return
        br.probe_ok()
        if self.rung() != before:
            self.metrics.inc("supervise.rung_changes")
            if self.recorder is not None:
                self.recorder.event(
                    0, "rung_change",
                    meta={"point": stage, "rung": self.rung(),
                          "trip": False})
        log.info("probe %s ok: breaker closed — pipeline back at "
                 "rung %d", stage, self.rung())

    async def _probe_async(self, stage: str, br: CircuitBreaker) -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            None, self._run_probe_sync, stage, br)

    # ---- watchdog deadlines ---------------------------------------------
    def deadline(self, stage: str) -> float:
        """Stall deadline for one stage await: clamp(mult * p99, floor,
        cap) off the PR-1 stage histogram — a stage may legitimately be
        slow (relay round trips), so the deadline adapts to measured
        behavior instead of hardcoding an SLA. The lane domain's time
        lands in the per-lane ``deliver_lane{i}`` histograms (there is
        no single ``lane_deliver`` stage), so its deadline tracks the
        SLOWEST lane's p99."""
        p99 = 0.0
        if self.telemetry is not None:
            hists = self.telemetry.metrics.histograms()
            if stage == "lane_deliver":
                names = [n for n in hists
                         if n.startswith("pipeline.stage.deliver_lane")]
            elif stage == "dispatch":
                # cache-planned windows record under dispatch_cached:
                # on a dedup-heavy workload the plain histogram can be
                # empty while cached dispatches run seconds — the
                # deadline must track whichever variant is serving
                names = ["pipeline.stage.dispatch.seconds",
                         "pipeline.stage.dispatch_cached.seconds"]
            else:
                names = [f"pipeline.stage.{stage}.seconds"]
            for n in names:
                h = hists.get(n)
                if h is not None and h.count:
                    p99 = max(p99, h.percentile(0.99))
        return min(self.wd_cap_s, max(self.wd_floor_s,
                                      self.wd_mult * p99))

    # ---- window journal (admit → settle / replay) -----------------------
    def journal_admit(self, batch) -> int:
        """Journal one window at pipeline admit: a reference to its
        (message, publisher-future) batch. The REPLAY itself re-routes
        the batcher's own entry through the next rung — this journal is
        the accounting that proves nothing was dropped on the floor:
        depth is the live in-flight gauge, and an entry still present
        after its futures settled is a leak. Returns the window id to
        settle with."""
        wid = self._journal_ids()
        entry = _JournalEntry(batch)
        with self._journal_lock:
            self._journal[wid] = entry
        return wid

    def journal_settle(self, wid: Optional[int]) -> None:
        if wid is None:
            return
        with self._journal_lock:
            self._journal.pop(wid, None)

    def journal_depth(self) -> int:
        return len(self._journal)

    # ---- telemetry ------------------------------------------------------
    def state(self) -> dict:
        """Live gauges for the telemetry snapshot's `supervise` section
        (counters ride the Metrics registry)."""
        return {
            "rung": self.rung(),
            "breakers": {s: b.snapshot()
                         for s, b in self.breakers.items()},
            "journal_depth": self.journal_depth(),
            "faults_armed": self.injector.state(),
            "watchdog": {"floor_s": self.wd_floor_s,
                         "cap_s": self.wd_cap_s,
                         "mult": self.wd_mult},
        }
