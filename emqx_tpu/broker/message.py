"""Canonical broker message + GUID generation.

Parity: reference `#message` record (apps/emqx/include/emqx.hrl:55-73),
`emqx_message.erl` constructors/flag ops, and `emqx_guid.erl` (timestamp +
node + sequence GUIDs, base62-renderable).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from emqx_tpu.mqtt import constants as C

_BASE62 = "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz"


def base62_encode(n: int) -> str:
    """Parity: emqx_base62:encode/1."""
    if n == 0:
        return "0"
    out = []
    while n:
        n, r = divmod(n, 62)
        out.append(_BASE62[r])
    return "".join(reversed(out))


def base62_decode(s: str) -> int:
    n = 0
    for ch in s:
        n = n * 62 + _BASE62.index(ch)
    return n


class GuidGen:
    """128-bit GUIDs: 64b microsecond timestamp | 48b node id | 16b sequence.

    Parity: emqx_guid.erl (ts+node+seq scheme); monotone within a node so
    message ids sort by arrival, which the device batching relies on for
    per-publisher ordering (SURVEY.md §7 hard part 5).
    """

    def __init__(self, node_id: Optional[int] = None):
        self._node = (node_id if node_id is not None else
                      (os.getpid() << 16) ^ (threading.get_ident() & 0xFFFF)
                      ) & 0xFFFFFFFFFFFF
        self._seq = itertools.count()
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            ts = time.time_ns() // 1000
            seq = next(self._seq) & 0xFFFF
        return (ts << 64) | (self._node << 16) | seq

    def next_batch(self, n: int) -> list:
        """Reserve n GUIDs in ONE locked pass — the columnar burst
        path's allocation (a per-message lock + clock read was the
        single largest row cost in the ingest profile). Same layout and
        monotonicity as n next() calls within one microsecond tick."""
        with self._lock:
            base = (time.time_ns() // 1000 << 64) | (self._node << 16)
            seq = self._seq
            return [base | (next(seq) & 0xFFFF) for _ in range(n)]


_GUID = GuidGen()


def now_ms() -> int:
    return time.time_ns() // 1_000_000


@dataclass
class Message:
    """Parity: #message{} — id, qos, from, flags, headers, topic, payload, ts
    (include/emqx.hrl:55-73)."""

    topic: str
    payload: bytes = b""
    qos: int = C.QOS_0
    from_: str = ""                       # publisher clientid ('from' field)
    flags: dict = field(default_factory=dict)     # retain / dup / sys
    headers: dict = field(default_factory=dict)   # username, peerhost, props,
                                                  # allow_publish, re-dispatch
    id: int = 0
    ts: int = 0                            # ms epoch
    extra: dict = field(default_factory=dict)

    # ingress stamp (ISSUE 13): perf_counter_ns at frame decode,
    # carried from the Publish packet / PublishBurst by the channel so
    # the latency observatory can record this message's ingress→routed
    # and ingress→delivered spans at batch settle. A plain class
    # attribute, not a dataclass field: every message answers 0 with no
    # per-instance cost and the dataclass __init__/eq/repr contract is
    # untouched; only socket-ingress messages ever carry a real stamp
    # (internal publishes — $SYS, bridges, rule republish — stay 0 and
    # are deliberately excluded from the e2e percentiles).
    ingress_ns = 0

    def __post_init__(self):
        if not self.id:
            self.id = _GUID.next()
        if not self.ts:
            self.ts = now_ms()

    # -- flag ops (emqx_message:get_flag/set_flag/clean_dup) --
    def get_flag(self, name: str, default: bool = False) -> bool:
        return bool(self.flags.get(name, default))

    def set_flag(self, name: str, val: bool = True) -> "Message":
        self.flags[name] = val
        return self

    @property
    def retain(self) -> bool:
        return self.get_flag("retain")

    @property
    def dup(self) -> bool:
        return self.get_flag("dup")

    @property
    def is_sys(self) -> bool:
        return self.get_flag("sys") or self.topic.startswith("$SYS/")

    def get_header(self, name: str, default: Any = None) -> Any:
        return self.headers.get(name, default)

    def set_header(self, name: str, val: Any) -> "Message":
        self.headers[name] = val
        return self

    # -- expiry (emqx_message:is_expired/1 via v5 Message-Expiry-Interval) --
    def expiry_interval(self) -> Optional[int]:
        props = self.headers.get("properties") or {}
        return props.get("message_expiry_interval")

    def is_expired(self) -> bool:
        exp = self.expiry_interval()
        if exp is None:
            return False
        return now_ms() > self.ts + exp * 1000

    def update_expiry(self) -> "Message":
        """Shrink remaining expiry before delivery (emqx_message:update_expiry)."""
        exp = self.expiry_interval()
        if exp is not None:
            remaining = max(1, exp - (now_ms() - self.ts) // 1000)
            props = dict(self.headers.get("properties") or {})
            props["message_expiry_interval"] = int(remaining)
            self.headers["properties"] = props
        return self

    def copy(self) -> "Message":
        return Message(topic=self.topic, payload=self.payload, qos=self.qos,
                       from_=self.from_, flags=dict(self.flags),
                       headers=dict(self.headers), id=self.id, ts=self.ts,
                       extra=dict(self.extra))

    def to_map(self) -> dict:
        """For the REST API / rule engine event columns."""
        return {
            "id": base62_encode(self.id), "topic": self.topic,
            "qos": self.qos, "from": self.from_,
            "payload": self.payload, "flags": dict(self.flags),
            "timestamp": self.ts, "retain": self.retain,
        }

    def to_wire(self) -> dict:
        """Full-fidelity encoding for cross-node forwarding (the gen_rpc
        #delivery{} payload). Non-serializable header values (live objects
        planted by local hooks) are dropped — they are node-local by nature."""
        def safe(v):
            return isinstance(v, (str, int, float, bool, bytes, type(None))) \
                or (isinstance(v, (list, tuple)) and all(safe(x) for x in v)) \
                or (isinstance(v, dict)
                    and all(isinstance(k, str) and safe(x)
                            for k, x in v.items()))
        return {"topic": self.topic, "payload": self.payload,
                "qos": self.qos, "from": self.from_,
                "flags": dict(self.flags),
                "headers": {k: v for k, v in self.headers.items()
                            if safe(v)},
                "msgid": self.id, "ts": self.ts}

    @staticmethod
    def from_wire(d: dict) -> "Message":
        return Message(topic=d["topic"], payload=d["payload"], qos=d["qos"],
                       from_=d["from"], flags=dict(d["flags"]),
                       headers=dict(d["headers"]), id=d["msgid"], ts=d["ts"])


def make(from_: str, qos: int, topic: str, payload: bytes,
         flags: Optional[dict] = None, headers: Optional[dict] = None) -> Message:
    """Parity: emqx_message:make/4."""
    return Message(topic=topic, payload=payload, qos=qos, from_=from_,
                   flags=dict(flags or {}), headers=dict(headers or {}))


def guid_batch(n: int) -> list:
    """n GUIDs from the process generator in one locked pass (the
    columnar ingress burst allocation)."""
    return _GUID.next_batch(n)
