"""Per-connection TCP send-queue congestion alarms.

Parity: apps/emqx/src/emqx_congestion.erl — alarm
`conn_congestion/<clientid>/<username>` is activated when the socket has
pending unsent bytes (send_pend > 0; here the asyncio transport write
buffer), re-armed on every congested observation, and deactivated only
after `min_alarm_sustain_duration` with no congestion (the WontClearIn
hysteresis so a flapping socket doesn't spam alarm churn).
"""

from __future__ import annotations

import time
from typing import Optional


class Congestion:
    REASON = "conn_congestion"

    def __init__(self, node, channel, writer, *,
                 enable_alarm: bool = False,
                 min_alarm_sustain_duration: float = 60.0):
        self.node = node
        self.channel = channel
        self.writer = writer
        self.enable = enable_alarm
        self.sustain = min_alarm_sustain_duration
        self._sent_at: Optional[float] = None    # last congested ts

    def _alarm_name(self) -> str:
        user = self.channel.clientinfo.get("username") or "unknown_user"
        return f"{self.REASON}/{self.channel.clientid}/{user}"

    def _details(self) -> dict:
        t = self.writer.transport
        return {"clientid": self.channel.clientid,
                "username": self.channel.clientinfo.get("username"),
                "peername": str(self.channel.conninfo.get("peername")),
                "conn_state": self.channel.conn_state,
                "send_pend": t.get_write_buffer_size()
                if t is not None else 0}

    def _congested(self) -> bool:
        t = self.writer.transport
        return t is not None and t.get_write_buffer_size() > 0

    def check(self) -> None:
        """One observation (called from the connection timer loop)."""
        if not self.enable:
            return
        if self._congested():
            self._sent_at = time.monotonic()
            # key on the global alarm table, not this object: another
            # connection's terminate sweep may have cleared our name
            if not self.node.alarms.is_active(self._alarm_name()):
                self.node.metrics.inc("connection.congested")
                self.node.alarms.activate(self._alarm_name(),
                                          self._details())
        elif self._sent_at is not None and \
                time.monotonic() - self._sent_at >= self.sustain:
            self.cancel()

    def cancel(self) -> None:
        """Deactivate if raised (also the connection-terminate sweep,
        emqx_congestion:cancel_alarms)."""
        if self._sent_at is not None:
            self._sent_at = None
            self.node.alarms.deactivate(self._alarm_name())
