"""End-to-end message latency SLO observatory (ISSUE 13).

The missing observability leg after time-per-stage (PR 1), window
causality (PR 7) and space/cost (PR 8): the latency a *message* actually
experiences from socket read to delivery write, decomposed by path —
the end-to-end percentile framing the IoT broker benchmarking study
(arXiv:2603.21600, PAPERS.md) compares brokers on, and the number the
north star's **p99 < 2ms PUBLISH→route** criterion is judged against.
The only tail number ever committed before this (BENCH_r02's 194ms sync
p99) is window-granularity and contaminated by relay HTTP dispatch
overhead; this module measures per message and starts the clock at
frame decode, before any relay is involved.

Mechanics:

- **Ingress stamp**: ``mqtt.frame.FrameParser`` stamps
  ``perf_counter_ns`` at frame decode — one clock read per read burst
  (the PR 11 columnar path stores it on the ``PublishBurst``, the
  per-packet fallback on each ``Publish`` packet, so the A/B ingress
  twins stay comparable) — and the channel carries it onto
  ``Message.ingress_ns``.
- **Two legs**: ``ingress→routed`` (frame decode → route result in
  hand; the SLO objective's leg) and ``ingress→delivered`` (frame
  decode → every delivery written, i.e. the PR 5 delivery plan
  settled). Both recorded per message at batch settle, keyed by
  ``(qos, path)`` where path ∈ {device, device_cached, host,
  host_fallback, replay} — a breaker-driven journal replay and a
  prepare-time device fallback each land in their OWN series, so a
  latency regression names its rung.
- **Fine histograms**: the sub-millisecond log2 ladder
  (``metrics.Histogram(substeps=4)``) — quarter-octave buckets from
  1µs, so a 2ms objective resolves to ~19% instead of the plain
  ladder's factor-of-2.
- **SLO engine**: configurable objective (``broker.slo_route_p99_ms``
  / ``EMQX_TPU_SLO_ROUTE_P99_MS``, default 2.0 — the ROADMAP
  criterion), rolling multi-window error-budget burn rates (1m/5m/30m;
  burn 1.0 = spending the 1% p99 budget exactly at the sustainable
  rate), and **breach exemplars**: a message exceeding the objective
  records a bounded exemplar carrying its window's PR 7 flight-
  recorder trace id, lands a ``slo_breach`` instant event on that
  trace, and fires a throttled ``latency.breach`` hook so the tracer
  logs the causal chain (queue wait vs dispatch vs materialize vs lane
  backpressure) for the exact slow message, not an aggregate.

Knobs: ``broker.latency_observatory`` / ``EMQX_TPU_LATENCY`` (config
beats env beats default-on; ``=0`` restores the pre-ISSUE-13 behavior
exactly — no observatory object, no ``latency`` snapshot section, REST
404) and ``broker.slo_route_p99_ms`` / ``EMQX_TPU_SLO_ROUTE_P99_MS``.

Exported four ways like every other section: ``latency`` in
`PipelineTelemetry.snapshot()` ($SYS ``pipeline/latency``), the
``pipeline.latency.*`` histogram families (Prometheus buckets, StatsD
timers ride the shared registry) and ``GET /api/v5/pipeline/latency``.
``tools/latency_report.py`` renders the same schema offline from a
bench JSON or checkpoint.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Optional

SCHEMA = "emqx_tpu.latency/v1"

# the per-message path attribution (batcher settle decides):
#   device         routed by a fused device window (plain/compact/delta)
#   device_cached  device window with the dedup/match-cache plan attached
#   host           host-routed by decision (probe, bypass, min-batch,
#                  trickle, or a node with no batcher at all)
#   host_fallback  a prepared device window that fell back to the host
#                  path WITHOUT a supervision replay (prepare_window
#                  declined mid-rebuild, fused follower of a dead lead,
#                  unsupervised dispatch failure)
#   replay         a journaled window re-routed through the host rung by
#                  the ISSUE 6 supervisor (breaker trip, watchdog stall,
#                  injected fault)
PATHS = ("device", "device_cached", "host", "host_fallback", "replay")
LEGS = ("routed", "delivered")

# latency histograms: 1µs floor, quarter-octave (substeps=4) ladder,
# 112 buckets -> ~1µs..220s. The plain 28-bucket octave ladder cannot
# resolve a 2ms objective (neighbouring bounds 1.024/2.048ms).
_LAT_LO, _LAT_BUCKETS, _LAT_SUBSTEPS = 1e-6, 112, 4

# SLO burn accounting: breach/total counts in 10s slots, ring bounded
# to the widest burn window (30m)
_SLOT_S = 10.0
_BURN_WINDOWS = (("1m", 6), ("5m", 30), ("30m", 180))
# the error budget at a p99 objective: 1% of messages may exceed it
_P99_BUDGET = 0.01

_EXEMPLAR_CAP = 16
_HOOK_MIN_INTERVAL_S = 1.0


def resolve_latency_observatory(configured=None) -> bool:
    """The one latency-observatory resolution (ISSUE 13): config
    (``broker.latency_observatory``) beats ``EMQX_TPU_LATENCY`` beats
    default-on. ``=0`` restores the pre-ISSUE-13 observable behavior —
    no observatory object anywhere, no ``latency`` snapshot section,
    REST ``/pipeline/latency`` 404, bit-identical delivery counts and
    per-publisher order (the A/B twin test pins all four). The frame-
    decode ingress stamp itself is NOT gated: messages always carry
    ``ingress_ns`` (one clock read per read burst + one attribute per
    PUBLISH — negligible against the parse cost) so the stamp path
    cannot drift untested between twins; the knob gates everything
    that READS the stamp."""
    if configured is not None:
        return bool(configured)
    return os.environ.get("EMQX_TPU_LATENCY", "1") \
        not in ("0", "false", "off")


def resolve_slo_route_p99_ms(configured=None) -> float:
    """The SLO objective: config (``broker.slo_route_p99_ms``) beats
    ``EMQX_TPU_SLO_ROUTE_P99_MS`` beats the built-in 2.0 (the ROADMAP
    **p99 < 2ms PUBLISH→route** criterion). Must be a positive number;
    anything else is a deployment error worth failing loudly on."""
    if configured is None:
        env = os.environ.get("EMQX_TPU_SLO_ROUTE_P99_MS")
        if env is None:
            return 2.0
        configured = env
    try:
        val = float(configured)
    except (TypeError, ValueError):
        raise ValueError(
            f"EMQX_TPU_SLO_ROUTE_P99_MS={configured!r} is not a number")
    if val <= 0:
        raise ValueError(
            f"EMQX_TPU_SLO_ROUTE_P99_MS must be > 0, got {val}")
    return val


class LatencyObservatory:
    """Per-node end-to-end latency recorder + SLO engine.

    Hot-path contract: ``record_routed`` / ``record_delivered`` run on
    the event loop only (batcher settle, host publish path) — one
    histogram observe plus, on the routed leg, one slot-counter bump;
    no locks, no allocation beyond the first observation of a new
    ``(leg, qos, path)`` series. Everything else (burn rates, the
    section document) is read-side."""

    def __init__(self, metrics, *, hooks=None, recorder=None,
                 objective_ms: Optional[float] = None):
        self.metrics = metrics
        self.hooks = hooks
        # the PR 7 flight recorder: breach exemplars land a
        # `slo_breach` instant event on the slow message's window trace
        # so the causal chain is one trace-id lookup away. None (trace
        # knob off) degrades to exemplars without trace linkage.
        self.recorder = recorder
        self.objective_ms = resolve_slo_route_p99_ms(objective_ms)
        self._objective_s = self.objective_ms / 1000.0
        self._hist: dict = {}      # (leg, qos, path) -> Histogram
        self._slots: deque = deque(maxlen=_BURN_WINDOWS[-1][1])
        self.samples = 0           # routed-leg observations
        self.breaches = 0
        self.exemplars: deque = deque(maxlen=_EXEMPLAR_CAP)
        self.hook_fires = 0
        self.hook_throttled = 0
        self._last_hook = 0.0
        # overload sampling clamp (ISSUE 14): >1 records 1-in-clamp
        # messages. Set/restored ONLY by the overload governor's
        # clamp_sampling shed action; burn rates stay unbiased under
        # the clamp because they are breach FRACTIONS (uniform
        # sampling preserves a ratio).
        self.clamp = 1
        self._clamp_tick = 0
        self._clamp_tick_d = 0
        self.clamped = 0

    # ---- recording (event loop) -----------------------------------------
    def _h(self, leg: str, qos: int, path: str):
        key = (leg, qos, path)
        h = self._hist.get(key)
        if h is None:
            # written as two explicit literals (not one f-string over
            # `leg`) so the doc-drift gate can resolve the documented
            # family templates against the source
            name = f"pipeline.latency.routed.q{qos}.{path}" \
                if leg == "routed" else \
                f"pipeline.latency.delivered.q{qos}.{path}"
            h = self.metrics.hist(name, lo=_LAT_LO,
                                  n_buckets=_LAT_BUCKETS,
                                  substeps=_LAT_SUBSTEPS)
            self._hist[key] = h
        return h

    def record_routed(self, msg, path: str, seconds: float,
                      trace: int = 0) -> None:
        """One message's ingress→routed latency (the SLO leg)."""
        if self.clamp > 1:
            self._clamp_tick += 1
            if self._clamp_tick % self.clamp:
                self.clamped += 1
                return
        self._h("routed", min(msg.qos, 2), path).observe(seconds)
        self.samples += 1
        sid = int(time.monotonic() / _SLOT_S)
        slots = self._slots
        if not slots or slots[-1][0] != sid:
            slots.append([sid, 0, 0])
        cur = slots[-1]
        cur[1] += 1
        if seconds > self._objective_s:
            cur[2] += 1
            self.breaches += 1
            self.metrics.inc("pipeline.latency.breaches")
            self._exemplar(msg, path, seconds, trace)

    def record_delivered(self, msg, path: str, seconds: float) -> None:
        """One message's ingress→delivered latency (route + the PR 5
        delivery-lane walk / inline delivery, settled)."""
        if self.clamp > 1:
            # the delivered leg keeps its OWN 1-in-N phase: deliveries
            # settle asynchronously (lane done-callbacks), so reusing
            # the routed tick would sample in window-sized clumps
            # decided by whichever routed call last moved it
            self._clamp_tick_d += 1
            if self._clamp_tick_d % self.clamp:
                return
        self._h("delivered", min(msg.qos, 2), path).observe(seconds)

    def _exemplar(self, msg, path: str, seconds: float,
                  trace: int) -> None:
        """Breach exemplar: the exact slow message, linked to its
        window's flight-recorder trace, with the hook throttled so a
        degraded pipeline (where EVERY message breaches) logs one
        causal chain per second instead of one per message."""
        ex = {"topic": msg.topic, "qos": msg.qos, "path": path,
              "latency_ms": round(seconds * 1000, 3),
              "trace_id": trace, "ts": round(time.time(), 3)}
        self.exemplars.append(ex)
        rec = self.recorder
        if rec is not None and trace:
            rec.event(trace, "slo_breach", track="latency",
                      meta={"latency_ms": ex["latency_ms"],
                            "path": path})
        hooks = self.hooks
        if hooks is not None:
            now = time.monotonic()
            if now - self._last_hook >= _HOOK_MIN_INTERVAL_S:
                self._last_hook = now
                self.hook_fires += 1
                hooks.run("latency.breach", (ex,))
            else:
                self.hook_throttled += 1

    def reset(self) -> None:
        """Zero every recorded distribution, slot and exemplar (the
        registry histogram objects are kept and zeroed in place, so
        exporters and cached references stay valid). Bench-phase
        tooling only — tools/overload_bench.py resets at the
        ramp→steady-state boundary so the graded p99 measures the
        governed steady state, not the untimed ramp."""
        for h in self._hist.values():
            h.counts = [0] * len(h.counts)
            h.count = 0
            h.sum = 0.0
        self._slots.clear()
        self.samples = 0
        self.breaches = 0
        self.exemplars.clear()
        # clamp/hook bookkeeping resets with the distributions: the
        # post-reset section's clamp.skipped must describe the
        # post-reset distribution, not the discarded ramp
        self._clamp_tick = 0
        self._clamp_tick_d = 0
        self.clamped = 0
        self.hook_fires = 0
        self.hook_throttled = 0

    # ---- read side -------------------------------------------------------
    def burn_rates(self) -> dict:
        """Rolling error-budget burn per window: (breach fraction) /
        (allowed fraction). 1.0 = breaching exactly 1% of messages —
        the budget a p99 objective grants; >1 over-burning (alert
        thresholds: the classic multi-window pairs, e.g. 1m>14 AND
        5m>14 for a page, 30m>1 for a ticket)."""
        slots = list(self._slots)
        now_sid = int(time.monotonic() / _SLOT_S)
        out = {}
        for label, n in _BURN_WINDOWS:
            tot = br = 0
            for sid, t, b in slots:
                if sid > now_sid - n:
                    tot += t
                    br += b
            out[label] = round((br / tot) / _P99_BUDGET, 3) if tot \
                else 0.0
        return out

    def _merged_percentile(self, leg: str, p: float):
        """Percentile across every (qos, path) series of one leg: the
        histograms share one bucket ladder, so summed counts walk the
        same bounds (the aggregate p99 the SLO verdict grades)."""
        hs = [h for (lg, _q, _pa), h in self._hist.items()
              if lg == leg and h.count]
        if not hs:
            return None
        bounds = hs[0].bounds
        counts = [0] * (len(bounds) + 1)
        total = 0
        for h in hs:
            total += h.count
            for i, c in enumerate(h.counts):
                counts[i] += c
        want = p * total
        acc = 0
        for b, c in zip(bounds, counts):
            acc += c
            if acc >= want:
                return b
        return 2 * bounds[-1]

    def section(self) -> dict:
        """The ``latency`` snapshot section — the one schema shared by
        telemetry.snapshot(), $SYS ``pipeline/latency``,
        ``GET /api/v5/pipeline/latency``, the bench phase rows and
        ``tools/latency_report.py``."""
        routed: dict = {}
        delivered: dict = {}
        for (leg, qos, path), h in sorted(self._hist.items()):
            if not h.count:
                continue
            row = {
                "count": h.count,
                "p50_ms": round(h.percentile(0.50) * 1000, 4),
                "p99_ms": round(h.percentile(0.99) * 1000, 4),
                "p999_ms": round(h.percentile(0.999) * 1000, 4),
            }
            (routed if leg == "routed" else
             delivered)[f"q{qos}.{path}"] = row
        p99 = self._merged_percentile("routed", 0.99)
        slo = {
            "objective_p99_ms": self.objective_ms,
            "samples": self.samples,
            "breaches": self.breaches,
            "burn": self.burn_rates(),
        }
        if p99 is None:
            slo["verdict"] = "no_data"
        else:
            slo["routed_p99_ms"] = round(p99 * 1000, 4)
            slo["verdict"] = "met" if p99 * 1000 <= self.objective_ms \
                else "breached"
        dp99 = self._merged_percentile("delivered", 0.99)
        if dp99 is not None:
            slo["delivered_p99_ms"] = round(dp99 * 1000, 4)
        out = {
            "schema": SCHEMA,
            "objective_p99_ms": self.objective_ms,
            "routed": routed,
            "delivered": delivered,
            "slo": slo,
        }
        if self.clamp > 1 or self.clamped:
            out["clamp"] = {"factor": self.clamp,
                            "skipped": self.clamped}
        if self.exemplars:
            out["exemplars"] = list(self.exemplars)
        if self.hook_fires or self.hook_throttled:
            out["breach_hook"] = {"fired": self.hook_fires,
                                  "throttled": self.hook_throttled}
        return out
