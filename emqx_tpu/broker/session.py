"""Per-client MQTT session state.

Parity: emqx_session.erl — subscriptions map, inflight window (QoS1/2 out),
mqueue (pending), packet-id allocation, QoS2 `awaiting_rel` (incoming),
retry, expiry, and takeover/resume/replay (emqx_session.erl:82-122).

The session is a plain object owned by its connection task (the reference
keeps it inside the connection process and moves it wholesale on takeover);
all methods are synchronous and non-blocking.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from emqx_tpu.broker.inflight import Inflight
from emqx_tpu.broker.message import Message, now_ms
from emqx_tpu.broker.mqueue import MQueue, MQueueOpts
from emqx_tpu.mqtt import constants as C


class SessionError(Exception):
    def __init__(self, rc: int, detail: str = ""):
        self.rc = rc
        super().__init__(f"session error rc=0x{rc:02x} {detail}")


@dataclass
class SessionConf:
    max_subscriptions: int = 0            # 0 = unlimited
    upgrade_qos: bool = False
    retry_interval: float = 30.0          # s; 0 disables retry
    max_awaiting_rel: int = 100
    await_rel_timeout: float = 300.0      # s
    session_expiry_interval: int = 0      # s (v5) / 0 clean
    max_inflight: int = 32
    mqueue: MQueueOpts = field(default_factory=MQueueOpts)


class Session:
    """Outbound phases: ('publish', msg) awaiting PUBACK/PUBREC,
    ('pubrel', ts) awaiting PUBCOMP."""

    def __init__(self, clientid: str, conf: Optional[SessionConf] = None):
        self.clientid = clientid
        self.conf = conf or SessionConf()
        self.subscriptions: dict[str, dict] = {}   # filter -> subopts
        self.inflight = Inflight(self.conf.max_inflight)
        self.mqueue = MQueue(self.conf.mqueue)
        self.awaiting_rel: dict[int, int] = {}     # incoming QoS2 pid -> ts ms
        self.next_pkt_id = 1
        self.created_at = now_ms()
        # counters (emqx_session:info/1)
        self.deliver_count = 0
        self.enqueue_count = 0
        # wired by the owning channel: callable(msg, reason) invoked when
        # the mqueue evicts a message (the reference's delivery.dropped
        # hook + delivery.dropped.queue_full metric)
        self.on_dropped: Optional[Callable[[Message, str], None]] = None

    def _mq_insert(self, m: Message) -> None:
        dropped = self.mqueue.insert(m)
        if dropped is not None and self.on_dropped is not None:
            self.on_dropped(dropped, "queue_full")

    # ---- packet id allocation (emqx_session:next_pkt_id) ----
    def alloc_packet_id(self) -> int:
        for _ in range(C.MAX_PACKET_ID):
            pid = self.next_pkt_id
            self.next_pkt_id = 1 if pid >= C.MAX_PACKET_ID else pid + 1
            if not self.inflight.contain(pid):
                return pid
        raise SessionError(C.RC_QUOTA_EXCEEDED, "no free packet id")

    # ---- subscriptions ----
    def subscribe(self, topic_filter: str, subopts: dict) -> None:
        if (self.conf.max_subscriptions and
                topic_filter not in self.subscriptions and
                len(self.subscriptions) >= self.conf.max_subscriptions):
            raise SessionError(C.RC_QUOTA_EXCEEDED, "max_subscriptions")
        self.subscriptions[topic_filter] = subopts

    def unsubscribe(self, topic_filter: str) -> dict:
        try:
            return self.subscriptions.pop(topic_filter)
        except KeyError:
            raise SessionError(C.RC_NO_SUBSCRIPTION_EXISTED, topic_filter)

    # ---- incoming QoS2 (publisher side) ----
    def publish_qos2(self, packet_id: int) -> None:
        """Track an incoming QoS2 PUBLISH until PUBREL
        (emqx_session:publish/3 awaiting_rel)."""
        if packet_id in self.awaiting_rel:
            raise SessionError(C.RC_PACKET_IDENTIFIER_IN_USE)
        if (self.conf.max_awaiting_rel and
                len(self.awaiting_rel) >= self.conf.max_awaiting_rel):
            raise SessionError(C.RC_RECEIVE_MAXIMUM_EXCEEDED,
                               "max_awaiting_rel")
        self.awaiting_rel[packet_id] = now_ms()

    def pubrel(self, packet_id: int) -> None:
        if self.awaiting_rel.pop(packet_id, None) is None:
            raise SessionError(C.RC_PACKET_IDENTIFIER_NOT_FOUND)

    def expire_awaiting_rel(self) -> int:
        """Drop timed-out QoS2 ids (emqx_session:expire/2)."""
        deadline = now_ms() - int(self.conf.await_rel_timeout * 1000)
        stale = [p for p, ts in self.awaiting_rel.items() if ts < deadline]
        for p in stale:
            del self.awaiting_rel[p]
        return len(stale)

    # ---- outbound delivery (emqx_session:deliver/2) ----
    def deliver(self, msgs: list[tuple[Message, dict]]
                ) -> list[tuple[Optional[int], Message]]:
        """Accept routed messages; returns [(packet_id|None, msg)] to send
        now. QoS0 → (None, msg); QoS1/2 → allocated id + inflight; window
        full → mqueue."""
        out = []
        for msg, subopts in msgs:
            m = self._enrich(msg, subopts)
            if m is None:
                continue
            if m.qos == C.QOS_0:
                self.deliver_count += 1
                out.append((None, m))
            elif self.inflight.is_full():
                self.enqueue_count += 1
                self._mq_insert(m)
            else:
                pid = self.alloc_packet_id()
                self.inflight.insert(pid, ("publish", m))
                self.deliver_count += 1
                out.append((pid, m))
        return out

    def _enrich(self, msg: Message, subopts: dict) -> Optional[Message]:
        """Apply subopts to the delivered copy (emqx_session:enrich_*):
        QoS cap or upgrade, nl (no-local), rap (retain-as-published),
        subscription identifier."""
        if subopts.get("nl") and msg.from_ == self.clientid:
            return None
        m = msg.copy()
        sub_qos = int(subopts.get("qos", 0))
        if self.conf.upgrade_qos:
            m.qos = max(m.qos, sub_qos)
        else:
            m.qos = min(m.qos, sub_qos)
        if not subopts.get("rap") and not m.get_flag("retained"):
            m.flags["retain"] = False
        sid = subopts.get("subid")
        if sid is not None:
            props = dict(m.headers.get("properties") or {})
            props["subscription_identifier"] = sid
            m.headers["properties"] = props
        return m

    def enqueue(self, msgs: list[tuple[Message, dict]]) -> None:
        """Buffer while disconnected (persistent session)."""
        for msg, subopts in msgs:
            m = self._enrich(msg, subopts)
            if m is not None:
                self.enqueue_count += 1
                self._mq_insert(m)

    # ---- acks (emqx_session:puback/pubrec/pubcomp) ----
    def puback(self, packet_id: int) -> Message:
        val = self.inflight.lookup(packet_id)
        if not val or val[0] != "publish":
            raise SessionError(C.RC_PACKET_IDENTIFIER_NOT_FOUND)
        self.inflight.delete(packet_id)
        return val[1]

    def pubrec(self, packet_id: int) -> Message:
        val = self.inflight.lookup(packet_id)
        if not val:
            raise SessionError(C.RC_PACKET_IDENTIFIER_NOT_FOUND)
        if val[0] == "pubrel":
            raise SessionError(C.RC_PACKET_IDENTIFIER_IN_USE)
        self.inflight.update(packet_id, ("pubrel", val[1]))
        return val[1]

    def pubcomp(self, packet_id: int) -> Message:
        val = self.inflight.lookup(packet_id)
        if not val or val[0] != "pubrel":
            raise SessionError(C.RC_PACKET_IDENTIFIER_NOT_FOUND)
        self.inflight.delete(packet_id)
        return val[1]

    def dequeue(self) -> list[tuple[int, Message]]:
        """Refill the inflight window from the mqueue after an ack
        (emqx_session:dequeue/1)."""
        out = []
        while not self.inflight.is_full():
            m = self.mqueue.out()
            if m is None:
                break
            if m.is_expired():
                continue
            if m.qos == C.QOS_0:
                out.append((0, m))
                continue
            pid = self.alloc_packet_id()
            self.inflight.insert(pid, ("publish", m))
            self.deliver_count += 1
            out.append((pid, m))
        return out

    # ---- retry (emqx_session:retry/1) ----
    def retry(self) -> list[tuple[int, str, Message]]:
        """Returns [(pid, phase, msg)] needing resend (dup PUBLISH or PUBREL)."""
        if not self.conf.retry_interval:
            return []
        now = time.monotonic()
        out = []
        for pid, entry in self.inflight.items():
            if now - entry.ts >= self.conf.retry_interval:
                phase, msg = entry.value
                if phase == "publish" and msg.is_expired():
                    self.inflight.delete(pid)
                    continue
                entry.ts = now
                out.append((pid, phase, msg))
        return out

    # ---- takeover / resume / replay (emqx_session.erl:82-85) ----
    def takeover(self) -> "Session":
        """The old connection hands the session object over wholesale."""
        return self

    def rebalance_inflight(self) -> None:
        """After the window shrinks on resume (client sent a smaller
        Receive Maximum), move the newest publish-phase entries back to the
        front of the mqueue so replay never exceeds the client's RM
        (MQTT-3.3.4-9). PUBREL-phase entries don't count toward RM."""
        if not self.inflight.max_size:
            return
        pubs = [(pid, e) for pid, e in self.inflight.items()
                if e.value[0] == "publish"]
        over = len(pubs) - self.inflight.max_size
        if over <= 0:
            return
        for pid, entry in reversed(pubs[-over:]):
            self.inflight.delete(pid)
            self.mqueue.insert_front(entry.value[1])

    def replay(self) -> list[tuple[int, str, Message]]:
        """On resume: re-send all inflight (dup) then drain mqueue
        (emqx_session:replay/1)."""
        self.rebalance_inflight()
        out = []
        for pid, entry in self.inflight.items():
            phase, msg = entry.value
            if phase == "publish":
                msg.set_flag("dup", True)
            entry.ts = time.monotonic()
            out.append((pid, phase, msg))
        for pid, m in self.dequeue():
            out.append((pid, "publish", m))
        return out

    def clear_expired(self) -> int:
        return self.mqueue.filter(lambda m: not m.is_expired())

    # ---- cross-node takeover serialization (the reference moves the live
    # session term over disterl, emqx_cm.erl:268-298; we move a wire map
    # over the rpc plane) ----
    def to_wire(self) -> dict:
        return {
            "clientid": self.clientid,
            "subscriptions": dict(self.subscriptions),
            "awaiting_rel": dict(self.awaiting_rel),
            "next_pkt_id": self.next_pkt_id,
            "created_at": self.created_at,
            "expiry_interval": self.conf.session_expiry_interval,
            # both phases hold the Message (pubrec keeps it for PUBCOMP)
            "inflight": [[pid, e.value[0], e.value[1].to_wire()]
                         for pid, e in self.inflight.items()],
            "mqueue": [m.to_wire() for m in self.mqueue.to_list()],
        }

    @staticmethod
    def from_wire(d: dict, conf: Optional[SessionConf] = None) -> "Session":
        s = Session(d["clientid"], conf)
        s.conf.session_expiry_interval = d.get(
            "expiry_interval", s.conf.session_expiry_interval)
        s.subscriptions = {str(k): dict(v)
                           for k, v in d["subscriptions"].items()}
        s.awaiting_rel = {int(k): int(v)
                          for k, v in d["awaiting_rel"].items()}
        s.next_pkt_id = d["next_pkt_id"]
        s.created_at = d["created_at"]
        for pid, phase, val in d["inflight"]:
            s.inflight.insert(int(pid), (phase, Message.from_wire(val)))
        for m in d["mqueue"]:
            s.mqueue.insert(Message.from_wire(m))
        return s

    def info(self) -> dict:
        return {
            "clientid": self.clientid,
            "subscriptions_cnt": len(self.subscriptions),
            "inflight_cnt": len(self.inflight),
            "inflight_max": self.inflight.max_size,
            "mqueue_len": len(self.mqueue),
            "mqueue_max": self.mqueue.max_len(),
            "mqueue_dropped": self.mqueue.dropped,
            "awaiting_rel_cnt": len(self.awaiting_rel),
            "awaiting_rel_max": self.conf.max_awaiting_rel,
            "next_pkt_id": self.next_pkt_id,
            "created_at": self.created_at,
        }
