"""MQTT-over-WebSocket listener (RFC 6455, subprotocol "mqtt").

Parity: emqx_ws_connection.erl + the cowboy websocket listener
(emqx_listeners.erl:132-138). The WS layer is a transparent byte transport:
binary frames carry MQTT wire data into the same Connection/Channel stack
as TCP (the reference likewise reuses emqx_channel under cowboy callbacks).

Hand-rolled RFC 6455 server side: HTTP upgrade handshake (Sec-WebSocket-
Accept), masked client frame decoding with fragmentation, ping/pong, close.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import logging
import struct
from typing import Optional

from emqx_tpu.broker.connection import Connection

log = logging.getLogger("emqx_tpu.ws")

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT, OP_TEXT, OP_BIN, OP_CLOSE, OP_PING, OP_PONG = \
    0x0, 0x1, 0x2, 0x8, 0x9, 0xA


def accept_key(key: str) -> str:
    return base64.b64encode(
        hashlib.sha1((key + _GUID).encode()).digest()).decode()


def encode_frame(opcode: int, payload: bytes, fin: bool = True) -> bytes:
    head = bytes([(0x80 if fin else 0) | opcode])
    n = len(payload)
    if n < 126:
        head += bytes([n])
    elif n < 65536:
        head += bytes([126]) + struct.pack(">H", n)
    else:
        head += bytes([127]) + struct.pack(">Q", n)
    return head + payload


DEFAULT_MAX_FRAME = 16 << 20


async def read_frame(reader: asyncio.StreamReader,
                     max_size: int = DEFAULT_MAX_FRAME
                     ) -> Optional[tuple[int, bool, bytes]]:
    """-> (opcode, fin, payload); None on EOF or oversized frame (the
    claimed 64-bit length is attacker-controlled — never buffer it blind)."""
    try:
        b0, b1 = await reader.readexactly(2)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    fin = bool(b0 & 0x80)
    opcode = b0 & 0x0F
    masked = bool(b1 & 0x80)
    n = b1 & 0x7F
    try:
        if n == 126:
            (n,) = struct.unpack(">H", await reader.readexactly(2))
        elif n == 127:
            (n,) = struct.unpack(">Q", await reader.readexactly(8))
        if n > max_size:
            return None
        mask = await reader.readexactly(4) if masked else b""
        payload = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    if masked:
        payload = bytes(c ^ mask[i & 3] for i, c in enumerate(payload))
    return opcode, fin, payload


class _WsWriter:
    """Writer adapter: Connection writes MQTT bytes; we wrap them into WS
    binary frames on the underlying TCP writer."""

    def __init__(self, writer: asyncio.StreamWriter):
        self._w = writer

    def write(self, data: bytes) -> None:
        self._w.write(encode_frame(OP_BIN, data))

    async def drain(self) -> None:
        await self._w.drain()

    def is_closing(self) -> bool:
        return self._w.is_closing()

    def close(self) -> None:
        if not self._w.is_closing():
            try:
                self._w.write(encode_frame(OP_CLOSE, b"\x03\xe8"))
            except (ConnectionError, OSError):
                pass
        self._w.close()

    async def wait_closed(self) -> None:
        try:
            await self._w.wait_closed()
        except (ConnectionError, OSError):
            pass

    def get_extra_info(self, name, default=None):
        return self._w.get_extra_info(name, default)


class WsListener:
    """Parity: the ws/wss listener entry of emqx_listeners (wss = the same
    RFC6455 server over a TLS transport, emqx_listeners.erl:132-138)."""

    protocol = "mqtt:ws"

    def __init__(self, node, *, bind: str = "0.0.0.0", port: int = 8083,
                 path: str = "/mqtt", zone: Optional[str] = None,
                 max_connections: int = 1024000,
                 ssl_opts: Optional[dict] = None):
        self.node = node
        self.bind = bind
        self.port = port
        self.path = path
        self.zone = zone
        self.ssl_opts = ssl_opts
        if ssl_opts:
            self.protocol = "mqtt:wss"
        self.max_connections = max_connections
        self.current_conns = 0
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set[asyncio.Task] = set()

    async def start(self) -> None:
        ssl_ctx = None
        if self.ssl_opts:
            from emqx_tpu.utils.tls import make_server_context
            ssl_ctx = make_server_context(self.ssl_opts)
        self._server = await asyncio.start_server(self._on_client,
                                                  self.bind, self.port,
                                                  ssl=ssl_ctx)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server:
            self._server.close()
        for t in list(self._conns):
            t.cancel()
        if self._conns:
            await asyncio.gather(*self._conns, return_exceptions=True)
        if self._server:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2)
            except asyncio.TimeoutError:
                pass

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        if self.current_conns >= self.max_connections:
            writer.close()       # same cap behavior as the TCP listener
            return
        task = asyncio.current_task()
        self._conns.add(task)
        self.current_conns += 1
        try:
            if not await self._handshake(reader, writer):
                writer.close()
                return
            await self._run_ws(reader, writer)
        except (ConnectionError, OSError):
            pass
        finally:
            self.current_conns -= 1
            self._conns.discard(task)
            writer.close()

    async def _handshake(self, reader, writer) -> bool:
        try:
            request = await asyncio.wait_for(
                reader.readuntil(b"\r\n\r\n"), 10)
        except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                asyncio.LimitOverrunError):
            return False
        lines = request.decode("latin1").split("\r\n")
        try:
            _method, path, _ver = lines[0].split()
        except ValueError:
            return False
        headers = {}
        for ln in lines[1:]:
            k, _, v = ln.partition(":")
            if k:
                headers[k.strip().lower()] = v.strip()
        key = headers.get("sec-websocket-key")
        protos = [p.strip() for p in
                  headers.get("sec-websocket-protocol", "").split(",")
                  if p.strip()]
        if (path.split("?")[0] != self.path or key is None
                or headers.get("upgrade", "").lower() != "websocket"):
            writer.write(b"HTTP/1.1 400 Bad Request\r\n"
                         b"content-length: 0\r\n\r\n")
            await writer.drain()
            return False
        resp = ["HTTP/1.1 101 Switching Protocols",
                "upgrade: websocket", "connection: Upgrade",
                f"sec-websocket-accept: {accept_key(key)}"]
        # the MQTT-over-WS subprotocol must be echoed ([MQTT-6.0.0-3])
        if "mqtt" in [p.lower() for p in protos]:
            resp.append("sec-websocket-protocol: mqtt")
        writer.write(("\r\n".join(resp) + "\r\n\r\n").encode())
        await writer.drain()
        return True

    async def _run_ws(self, reader, writer) -> None:
        # inner pipe: WS binary payloads -> Connection's StreamReader
        pipe = asyncio.StreamReader()
        ws_writer = _WsWriter(writer)
        conn = Connection(self.node, pipe, ws_writer, zone=self.zone)
        from emqx_tpu.broker.supervise import guard_task
        conn_task = guard_task(asyncio.ensure_future(conn.run()),
                               "ws-conn", self.node.metrics)
        fragments: list[bytes] = []
        frag_op = OP_BIN
        try:
            while not conn_task.done():
                frame = await read_frame(reader)
                if frame is None:
                    break
                opcode, fin, payload = frame
                if opcode == OP_PING:
                    writer.write(encode_frame(OP_PONG, payload))
                    continue
                if opcode == OP_CLOSE:
                    break
                if opcode in (OP_BIN, OP_TEXT, OP_CONT):
                    if opcode != OP_CONT and not fin:
                        fragments, frag_op = [payload], opcode
                        continue
                    if opcode == OP_CONT:
                        fragments.append(payload)
                        if sum(len(f) for f in fragments) \
                                > DEFAULT_MAX_FRAME:
                            break    # unbounded fragment stream
                        if not fin:
                            continue
                        payload = b"".join(fragments)
                        opcode = frag_op
                        fragments = []
                    if opcode == OP_BIN:
                        pipe.feed_data(payload)
        finally:
            pipe.feed_eof()
            try:
                await asyncio.wait_for(conn_task, 5)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                conn_task.cancel()
