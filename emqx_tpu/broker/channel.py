"""MQTT protocol state machine, transport-agnostic.

Parity: emqx_channel.erl — CONNECT pipeline (check → enrich → authenticate →
open session, :285-533), PUBLISH pipeline (quota → alias → authz → caps,
:539-628), SUBSCRIBE with per-filter authz (:427-460,660-691), QoS0/1/2
semantics, will message, keepalive accounting, takeover pendings (:746-790),
and MQTT5 extras (topic alias, assigned clientid, session expiry).

The channel is owned by one connection task; `handle_in(pkt)` returns and
the channel pushes outbound packets through the `send` callback. Broker
deliveries arrive via `deliver()` from the same event loop.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Optional

from emqx_tpu.broker.message import Message, guid_batch, make, now_ms
from emqx_tpu.broker.mqueue import MQueueOpts
from emqx_tpu.broker.session import Session, SessionConf, SessionError
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt import packet as P
from emqx_tpu.utils import topic as T

class ParkedSubscriber:
    """Deliver target for a detached persistent session: enqueue only
    (the reference's disconnected-state channel, emqx_channel handle_deliver
    while conn_state=disconnected)."""

    def __init__(self, session, node):
        self.session = session
        self.node = node

    def deliver(self, topic_filter: str, msg) -> bool:
        if msg.is_expired():
            self.node.metrics.inc("delivery.dropped")
            self.node.metrics.inc("delivery.dropped.expired")
            return True
        self.session.enqueue([(msg, msg.headers.get("subopts", {}))])
        return True

    def deliver_batch(self, items: list) -> int:
        """Coalesced same-session run (ISSUE-5 delivery lanes): one
        mqueue append pass for the whole run. All-or-none accept."""
        pairs = []
        for _f, msg in items:
            if msg.is_expired():
                self.node.metrics.inc("delivery.dropped")
                self.node.metrics.inc("delivery.dropped.expired")
                continue
            pairs.append((msg, msg.headers.get("subopts", {})))
        if pairs:
            self.session.enqueue(pairs)
        return len(items)


CONN_IDLE = "idle"
CONN_CONNECTING = "connecting"
CONN_CONNECTED = "connected"
CONN_TAKING_OVER = "taking_over"
CONN_DISCONNECTED = "disconnected"

_ASSIGNED_SEQ = iter(range(1, 1 << 62))


class ProtocolError(Exception):
    def __init__(self, rc: int, detail: str = ""):
        self.rc = rc
        super().__init__(f"protocol error rc=0x{rc:02x} {detail}")


def session_conf_from(mqtt: dict, expiry_interval: int) -> SessionConf:
    return SessionConf(
        max_subscriptions=mqtt.get("max_subscriptions", 0),
        upgrade_qos=mqtt.get("upgrade_qos", False),
        retry_interval=mqtt.get("retry_interval", 30),
        max_awaiting_rel=mqtt.get("max_awaiting_rel", 100),
        await_rel_timeout=mqtt.get("await_rel_timeout", 300),
        session_expiry_interval=expiry_interval,
        max_inflight=mqtt.get("max_inflight", 32),
        mqueue=MQueueOpts(
            max_len=mqtt.get("max_mqueue_len", 1000),
            store_qos0=mqtt.get("mqueue_store_qos0", True),
            priorities=mqtt.get("mqueue_priorities", {}),
            default_priority=mqtt.get("mqueue_default_priority", "lowest")))


class Channel:
    def __init__(self, node, conninfo: dict,
                 send: Callable[[list[P.Packet]], None],
                 close: Callable[[str], None]):
        self.node = node
        self.conninfo = conninfo        # peername, sockname, ws?, zone
        self.send = send
        self.close = close
        self.conn_state = CONN_IDLE
        self.zone = conninfo.get("zone")
        self.mqtt = node.config.mqtt(self.zone)
        from emqx_tpu.broker.limiter import QuotaLimiter
        self.quota = QuotaLimiter(
            (node.config.get_zone(self.zone, "rate_limit") or {})
            .get("quota_messages_routing") or None)

        self.proto_ver = C.MQTT_V4
        self.clientinfo: dict = {}
        self.clientid: str = ""
        self.session: Optional[Session] = None
        self.sid: Optional[int] = None  # broker subscriber id
        self.keepalive: int = 0
        self.will_msg: Optional[Message] = None
        self.alias_in: dict[int, str] = {}   # v5 inbound topic aliases
        self.alias_out: dict[str, int] = {}  # v5 outbound: topic -> alias
        self.alias_out_max = 0               # client's Topic-Alias-Maximum
        self.connected_at: int = 0
        self.disconnect_reason: Optional[str] = None
        self._aborted = False     # server-initiated DISCONNECT sent; no
                                  # further packets may go out (MQTT-3.14)
        self._pendings: list[Message] = []   # deliveries during takeover
        self.mountpoint: Optional[str] = None
        self._enh: Optional[dict] = None     # enhanced-auth exchange state
        self._enh_connack_props: Optional[dict] = None

    # ================= inbound dispatch =================
    async def handle_in(self, pkt: P.Packet) -> None:
        m = self.node.metrics
        name = type(pkt).__name__.lower()
        if isinstance(pkt, P.Connect):
            m.inc_recv("connect")
            await self._handle_connect(pkt)
        elif isinstance(pkt, P.Auth) and self._enh is not None:
            # mid-exchange AUTH is legal while still CONNECTING
            m.inc_recv("auth")
            await self._handle_auth(pkt)
        elif self.conn_state != CONN_CONNECTED:
            raise ProtocolError(C.RC_PROTOCOL_ERROR,
                                f"{name} before CONNECT")
        elif isinstance(pkt, P.Publish):
            m.inc_recv("publish")
            await self._handle_publish(pkt)
        elif isinstance(pkt, P.Puback):
            m.inc_recv("puback")
            self._handle_puback(pkt)
        elif isinstance(pkt, P.Pubrec):
            m.inc_recv("pubrec")
            self._handle_pubrec(pkt)
        elif isinstance(pkt, P.Pubrel):
            m.inc_recv("pubrel")
            self._handle_pubrel(pkt)
        elif isinstance(pkt, P.Pubcomp):
            m.inc_recv("pubcomp")
            self._handle_pubcomp(pkt)
        elif isinstance(pkt, P.Subscribe):
            m.inc_recv("subscribe")
            await self._handle_subscribe(pkt)
        elif isinstance(pkt, P.Unsubscribe):
            m.inc_recv("unsubscribe")
            self._handle_unsubscribe(pkt)
        elif isinstance(pkt, P.Pingreq):
            m.inc_recv("pingreq")
            self._send([P.Pingresp()])
        elif isinstance(pkt, P.Disconnect):
            m.inc_recv("disconnect")
            self._handle_disconnect(pkt)
        elif isinstance(pkt, P.Auth):
            m.inc_recv("auth")
            await self._handle_auth(pkt)
        else:
            raise ProtocolError(C.RC_PROTOCOL_ERROR, f"unexpected {name}")

    def _send(self, pkts: list[P.Packet]) -> None:
        if self._aborted:
            return
        for p in pkts:
            self.node.metrics.inc_sent(type(p).__name__.lower())
        self.send(pkts)

    # ================= CONNECT =================
    async def _handle_connect(self, pkt: P.Connect) -> None:
        if self.conn_state != CONN_IDLE:
            raise ProtocolError(C.RC_PROTOCOL_ERROR, "duplicate CONNECT")
        self.conn_state = CONN_CONNECTING
        self.proto_ver = pkt.proto_ver
        self.node.metrics.inc("client.connect")
        self.node.hooks.run("client.connect", (self._conninfo_map(pkt),))

        # --- check: protocol version / clientid (emqx_channel check_connect)
        if pkt.proto_ver not in (C.MQTT_V3, C.MQTT_V4, C.MQTT_V5):
            return self._connack_error(C.RC_UNSUPPORTED_PROTOCOL_VERSION)

        # --- overload admission gate (ISSUE 14 pause_connects action):
        #     at grade overload+ new CONNECTs are refused with the v5
        #     reason 0x97 (quota exceeded; the serializer downgrades
        #     for v3/v4 clients) — the emqx_olp/esockd overload analog.
        #     Existing sessions are untouched; recovery re-admits.
        gov = getattr(self.node, "overload_governor", None)
        if gov is not None and gov.connects_paused:
            gov.count_connect_rejected()
            return self._connack_error(C.RC_QUOTA_EXCEEDED)
        clientid = pkt.clientid
        if not clientid:
            if pkt.proto_ver < C.MQTT_V5 and not pkt.clean_start:
                return self._connack_error(C.RC_CLIENT_IDENTIFIER_NOT_VALID)
            clientid = f"emqx_tpu_{next(_ASSIGNED_SEQ)}_{now_ms()}"
            self._assigned_clientid = clientid
        else:
            self._assigned_clientid = None
        if len(clientid) > self.mqtt.get("max_clientid_len", 65535):
            return self._connack_error(C.RC_CLIENT_IDENTIFIER_NOT_VALID)

        props = pkt.properties or {}
        if pkt.proto_ver == C.MQTT_V5:
            expiry = props.get("session_expiry_interval", 0)
        else:
            expiry = (self.mqtt.get("session_expiry_interval", 7200)
                      if not pkt.clean_start else 0)

        if self.mqtt.get("use_username_as_clientid") and pkt.username:
            clientid = pkt.username
        # TLS peer-cert enrichment (emqx_channel peer_cert_as_username/
        # clientid zone opts; cert fields via utils.tls.cert_field)
        username = pkt.username
        peercert = self.conninfo.get("peercert")
        if peercert:
            from emqx_tpu.utils.tls import cert_field
            src = self.mqtt.get("peer_cert_as_username")
            if src:
                username = cert_field(peercert, src) or username
            src = self.mqtt.get("peer_cert_as_clientid")
            if src:
                clientid = cert_field(peercert, src) or clientid
        self.clientid = clientid
        self.clientinfo = {
            "clientid": clientid, "username": username,
            "peername": self.conninfo.get("peername"),
            "sockname": self.conninfo.get("sockname"),
            "proto_ver": pkt.proto_ver, "proto_name": pkt.proto_name,
            "clean_start": pkt.clean_start, "keepalive": pkt.keepalive,
            "zone": self.zone, "mountpoint": None,
            "is_bridge": getattr(pkt, "is_bridge", False),
            "connected_at": now_ms(),
            "conn_props": props,
        }

        # --- will capability caps (emqx_mqtt_caps check via emqx_channel
        #     check_connect: a will above the zone's QoS/retain caps refuses
        #     the CONNECT outright — MQTT-3.2.2-12 / MQTT-3.2.2-13)
        if pkt.will is not None:
            if pkt.will.qos > self.mqtt.get("max_qos_allowed", 2):
                return self._connack_error(C.RC_QOS_NOT_SUPPORTED)
            if pkt.will.retain and not self.mqtt.get("retain_available",
                                                     True):
                return self._connack_error(C.RC_RETAIN_NOT_SUPPORTED)

        # --- banned check (emqx_banned:check in emqx_channel:authenticate)
        banned = getattr(self.node, "banned", None)
        if banned is not None and banned.check(self.clientinfo):
            return self._connack_error(C.RC_BANNED)

        # --- enhanced authentication (MQTT5 AUTH exchange, emqx_channel
        #     enhanced_auth/authenticate: the authentication_method CONNECT
        #     property switches to a SASL-style challenge flow)
        auth_method = (props.get("authentication_method")
                       if pkt.proto_ver == C.MQTT_V5 else None)
        if auth_method is not None:
            enh = getattr(self.node, "enhanced_authn", {}).get(auth_method)
            if enh is None:
                return self._connack_error(C.RC_BAD_AUTHENTICATION_METHOD)
            data = props.get("authentication_data", b"")
            try:
                challenge, st = enh.begin_enhanced_auth(data)
            except Exception:  # noqa: BLE001 (ScramError and malformed)
                self.node.metrics.inc("packets.connack.auth_error")
                return self._connack_error(C.RC_NOT_AUTHORIZED)
            self._enh = {"method": auth_method, "auth": enh, "state": st,
                         "pkt": pkt, "expiry": expiry, "reauth": False}
            self._send([P.Auth(
                reason_code=C.RC_CONTINUE_AUTHENTICATION,
                properties={"authentication_method": auth_method,
                            "authentication_data": challenge})])
            return

        # --- authenticate (hooks chain; default allow)
        self.node.metrics.inc("client.authenticate")
        auth_result = await self.node.hooks.run_fold_async(
            "client.authenticate", (self.clientinfo,),
            {"ok": True, "password": pkt.password})
        if not (isinstance(auth_result, dict) and auth_result.get("ok")):
            self.node.metrics.inc("packets.connack.auth_error")
            rc = (auth_result or {}).get("rc", C.RC_NOT_AUTHORIZED) \
                if isinstance(auth_result, dict) else C.RC_NOT_AUTHORIZED
            return self._connack_error(rc)
        if isinstance(auth_result, dict):
            self.clientinfo.update(
                {k: v for k, v in auth_result.items()
                 if k in ("is_superuser", "mountpoint", "username", "acl")})
        self.mountpoint = self.clientinfo.get("mountpoint")
        if self.mountpoint:
            self.mountpoint = T.feed_var(
                "%c", self.clientid,
                T.feed_var("%u", self.clientinfo.get("username") or "",
                           self.mountpoint))
            self.clientinfo["mountpoint"] = self.mountpoint

        await self._continue_connect(pkt, expiry)

    async def _continue_connect(self, pkt: P.Connect, expiry: int) -> None:
        """CONNECT pipeline after authentication succeeded (the reference's
        process_connect half of emqx_channel handle_in CONNECT)."""
        clientid = self.clientid
        props = pkt.properties or {}
        from emqx_tpu.utils.logger import set_metadata_clientid
        set_metadata_clientid(clientid)
        # --- will message
        if pkt.will is not None:
            self.will_msg = make(
                clientid, pkt.will.qos, self._mount(pkt.will.topic),
                pkt.will.payload, flags={"retain": pkt.will.retain},
                headers={"username": pkt.username,
                         "properties": pkt.will.properties or {}})

        # --- keepalive (server may override, v5 server_keep_alive)
        server_ka = self.mqtt.get("server_keepalive", 0)
        self.keepalive = server_ka or pkt.keepalive

        # --- open session (clean-start discard / takeover)
        conf = session_conf_from(self.mqtt, expiry)
        if pkt.proto_ver == C.MQTT_V5:
            # MQTT-3.3.4-9: never exceed the client's Receive Maximum
            rm = props.get("receive_maximum")
            if rm:
                conf.max_inflight = min(conf.max_inflight, int(rm))
        session, present = await self.node.cm.open_session(
            pkt.clean_start, clientid, conf, self)
        session.inflight.max_size = conf.max_inflight
        session.on_dropped = self._delivery_dropped
        self.session = session
        if present:
            self.node.metrics.inc("session.resumed")
            self.node.hooks.run("session.resumed",
                                (self.clientinfo, session))
        else:
            self.node.metrics.inc("session.created")
            self.node.hooks.run("session.created",
                                (self.clientinfo, session))

        # --- register + connack
        self.node.cm.register_channel(clientid, self, self.info())
        parked_sid = getattr(session, "parked_sid", None)
        if parked_sid is not None:
            # re-attach to the parked session's live broker subscriptions
            self.sid = parked_sid
            session.parked_sid = None
            self.node.broker.swap_subscriber(self.sid, self)
        else:
            self.sid = self.node.broker.register(self, clientid)
            # resumed (takenover) sessions re-install routes under new sid
            for f, opts in list(session.subscriptions.items()):
                self.node.broker.subscribe(
                    self.sid, f,
                    {k: v for k, v in opts.items() if k != "share"})
        self.conn_state = CONN_CONNECTED
        self.connected_at = now_ms()
        self.node.metrics.inc("client.connected")
        self.node.hooks.run("client.connected", (self.clientinfo, self.info()))

        # --- outbound topic aliasing (emqx_channel packing_alias): the
        #     client's Topic-Alias-Maximum caps how many aliases WE may
        #     assign on deliveries to it
        self.alias_out_max = int(props.get("topic_alias_maximum", 0)) \
            if pkt.proto_ver == C.MQTT_V5 else 0

        ack_props = None
        if pkt.proto_ver == C.MQTT_V5:
            ack_props = {
                "session_expiry_interval": expiry,
                # the broker's own inbound window (zone max_inflight), NOT
                # the client-RM-capped outbound window
                "receive_maximum": self.mqtt.get("max_inflight", 32),
                "retain_available": int(self.mqtt.get("retain_available", True)),
                "maximum_packet_size": self.mqtt.get("max_packet_size"),
                "topic_alias_maximum": self.mqtt.get("max_topic_alias", 65535),
                "wildcard_subscription_available":
                    int(self.mqtt.get("wildcard_subscription", True)),
                "subscription_identifier_available": 1,
                "shared_subscription_available":
                    int(self.mqtt.get("shared_subscription", True)),
            }
            # MQTT-3.2.2-9: Maximum-QoS is only sent when the broker caps
            # below 2 (absence means the full range is supported)
            if self.mqtt.get("max_qos_allowed", 2) < 2:
                ack_props["maximum_qos"] = self.mqtt["max_qos_allowed"]
            if server_ka:
                ack_props["server_keep_alive"] = server_ka
            if self._assigned_clientid:
                ack_props["assigned_client_identifier"] = clientid
            if self._enh_connack_props:
                ack_props.update(self._enh_connack_props)
                self._enh_connack_props = None
        self.node.metrics.inc("client.connack")
        self.node.hooks.run("client.connack",
                            (self.clientinfo, C.RC_SUCCESS))
        self._send([P.Connack(session_present=present,
                              reason_code=C.RC_SUCCESS,
                              properties=ack_props)])
        # replay resumed session state
        if present:
            self._send_replay(session.replay())

    # ================= AUTH (MQTT5 enhanced authentication) =============
    async def _handle_auth(self, pkt: P.Auth) -> None:
        """Continue/complete a SASL exchange (emqx_channel handle_in AUTH:
        RC 0x18 continue, 0x19 re-authenticate from a connected client)."""
        props = pkt.properties or {}
        method = props.get("authentication_method")
        if pkt.reason_code == C.RC_RE_AUTHENTICATE and \
                self.conn_state == CONN_CONNECTED and self._enh is None:
            enh = getattr(self.node, "enhanced_authn", {}).get(method)
            if enh is None:
                return self._disconnect_now(C.RC_BAD_AUTHENTICATION_METHOD)
            try:
                challenge, st = enh.begin_enhanced_auth(
                    props.get("authentication_data", b""))
            except Exception:  # noqa: BLE001
                return self._disconnect_now(C.RC_NOT_AUTHORIZED)
            self._enh = {"method": method, "auth": enh, "state": st,
                         "pkt": None, "expiry": 0, "reauth": True}
            return self._send([P.Auth(
                reason_code=C.RC_CONTINUE_AUTHENTICATION,
                properties={"authentication_method": method,
                            "authentication_data": challenge})])
        if self._enh is None or \
                pkt.reason_code != C.RC_CONTINUE_AUTHENTICATION:
            raise ProtocolError(C.RC_PROTOCOL_ERROR, "unexpected AUTH")
        if method is not None and method != self._enh["method"]:
            raise ProtocolError(C.RC_BAD_AUTHENTICATION_METHOD,
                                "AUTH method changed mid-exchange")
        enh, st = self._enh["auth"], self._enh["state"]
        try:
            server_final, extra = enh.continue_enhanced_auth(
                props.get("authentication_data", b""), st)
        except Exception:  # noqa: BLE001 (ScramError: bad proof)
            self.node.metrics.inc("client.auth.failure")
            reauth = self._enh["reauth"]
            self._enh = None
            if reauth:
                return self._disconnect_now(C.RC_NOT_AUTHORIZED)
            return self._connack_error(C.RC_NOT_AUTHORIZED)
        self.node.metrics.inc("client.auth.success")
        state = self._enh
        self._enh = None
        auth_props = {"authentication_method": state["method"],
                      "authentication_data": server_final}
        if state["reauth"]:
            return self._send([P.Auth(reason_code=C.RC_SUCCESS,
                                      properties=auth_props)])
        self.clientinfo.update(
            {k: v for k, v in extra.items()
             if k in ("is_superuser", "username", "acl")})
        self._enh_connack_props = auth_props
        await self._continue_connect(state["pkt"], state["expiry"])

    def _connack_error(self, rc: int) -> None:
        self.node.metrics.inc("packets.connack.error")
        self.node.hooks.run("client.connack", (self.clientinfo, rc))
        # always the v5 code here; the serializer downgrades for v3 clients
        self._send([P.Connack(session_present=False, reason_code=rc)])
        self.close(f"connack_error_0x{rc:02x}")

    # ================= PUBLISH =================
    def _mount(self, topic: str) -> str:
        return T.prepend(self.mountpoint, topic)

    def _unmount(self, topic: str) -> str:
        if self.mountpoint and topic.startswith(self.mountpoint):
            return topic[len(self.mountpoint):]
        return topic

    async def _handle_publish(self, pkt: P.Publish) -> None:
        topic = pkt.topic
        # v5 topic alias resolution (emqx_channel packet_to_message)
        props = pkt.properties or {}
        alias = props.pop("topic_alias", None) if props else None
        # the publisher's alias is connection-scoped: it must never leak
        # into the routed message (a subscriber's alias space is its own —
        # the reference strips it in packet_to_message the same way)
        if self.proto_ver == C.MQTT_V5 and alias is not None:
            if not (0 < alias <= self.mqtt.get("max_topic_alias", 65535)):
                return self._disconnect_now(C.RC_TOPIC_ALIAS_INVALID)
            if topic:
                self.alias_in[alias] = topic
            else:
                topic = self.alias_in.get(alias)
                if topic is None:
                    return self._disconnect_now(C.RC_PROTOCOL_ERROR,
                                                "unknown topic alias")
        try:
            valid = bool(topic) and T.validate(topic, "name")
        except T.TopicError:
            valid = False       # wildcard/too-long/bad-level topic NAME
        if not valid:
            return self._puberr(pkt, C.RC_TOPIC_NAME_INVALID)
        if self.proto_ver == C.MQTT_V5 and props.get("response_topic") \
                and T.wildcard(props["response_topic"]):
            # MQTT-3.3.2-14: a Response Topic must not contain wildcards
            return self._disconnect_now(C.RC_PROTOCOL_ERROR,
                                        "wildcard response topic")
        if pkt.qos > self.mqtt.get("max_qos_allowed", 2):
            # MQTT-3.2.2-11: publishing above the broker's Maximum QoS is
            # a DISCONNECT-worthy offence, not a per-packet nack
            return self._disconnect_now(C.RC_QOS_NOT_SUPPORTED)
        if pkt.retain and not self.mqtt.get("retain_available", True):
            return self._puberr(pkt, C.RC_RETAIN_NOT_SUPPORTED)

        # quota (emqx_channel process_publish pipeline: check_quota first)
        if not self.quota.check_publish():
            self.node.metrics.inc("packets.publish.quota_exceeded")
            return self._puberr(pkt, C.RC_QUOTA_EXCEEDED)

        # authz (emqx_channel check_pub_authz)
        if not await self._authorize("publish", topic):
            self.node.metrics.inc("packets.publish.auth_error")
            if self._aborted:       # deny_action=disconnect: no PUBACK after
                return              # the DISCONNECT went out
            return self._puberr(pkt, C.RC_NOT_AUTHORIZED)

        msg = make(self.clientid, pkt.qos, self._mount(topic), pkt.payload,
                   flags={"retain": pkt.retain, "dup": pkt.dup},
                   headers={"username": self.clientinfo.get("username"),
                            "peername": self.conninfo.get("peername"),
                            "properties": props,
                            "proto_ver": self.proto_ver})
        if pkt.ingress_ns:
            # ingress stamp (ISSUE 13): frame-decode clock rides the
            # message so the latency observatory can attribute this
            # message's e2e spans at settle
            msg.ingress_ns = pkt.ingress_ns
        self.node.metrics.inc_msg_recv(pkt.qos)

        if pkt.qos == C.QOS_0:
            if not self.node.publish_nowait(msg):
                await self.node.publish_async(msg)
        elif pkt.qos == C.QOS_1:
            n = await self.node.publish_async(msg)
            rc = C.RC_SUCCESS if n else C.RC_NO_MATCHING_SUBSCRIBERS
            if self.proto_ver < C.MQTT_V5:
                rc = C.RC_SUCCESS
            self._send([P.Puback(packet_id=pkt.packet_id, reason_code=rc)])
        else:
            # QoS2: publish immediately, track the packet id in awaiting_rel
            # purely for duplicate suppression until PUBREL — the reference's
            # method (emqx_session:publish/3); avoids buffering payloads
            try:
                self.session.publish_qos2(pkt.packet_id)
                n = await self.node.publish_async(msg)
                rc = C.RC_SUCCESS if n or self.proto_ver < C.MQTT_V5 \
                    else C.RC_NO_MATCHING_SUBSCRIBERS
                self._send([P.Pubrec(packet_id=pkt.packet_id,
                                     reason_code=rc)])
            except SessionError as e:
                self.node.metrics.inc("packets.publish.dropped")
                self._send([P.Pubrec(packet_id=pkt.packet_id,
                                     reason_code=e.rc)])

    async def handle_publish_burst(self, burst) -> None:
        """Columnar-ingress PUBLISH hand-off (ISSUE 11): one call per
        PublishBurst replaces burst-many handle_in(Publish) calls.

        Every row runs the same check pipeline as _handle_publish —
        alias resolution, topic validation, response-topic/max-qos/
        retain caps, quota, authz, QoS dispatch — but the per-row work
        is amortized: topic-validation and authz verdicts are memoized
        per unique topic WITHIN the burst (the reference's
        emqx_authz_cache caches authz per connection the same way), the
        packet/message counters are incremented once per burst, and all
        surviving rows enter the batcher through ONE submit_burst call
        (QoS0 rows without per-message futures). Acks — and any
        deferred per-row error ack or DISCONNECT — go out strictly in
        row order after submission, once each QoS>=1 row's delivery
        count resolves through the batcher's normal journal/settle
        machinery. Per-publisher delivery order is the batcher FIFO =
        row order, so order and counts are bit-identical to the
        per-packet path (the A/B twin test pins this)."""
        if self.conn_state != CONN_CONNECTED:
            raise ProtocolError(C.RC_PROTOCOL_ERROR,
                                "publish before CONNECT")
        node = self.node
        m = node.metrics
        n = len(burst.topics)
        m.inc("packets.received", n)
        m.inc("packets.publish.received", n)
        v5 = self.proto_ver == C.MQTT_V5
        max_alias = self.mqtt.get("max_topic_alias", 65535)
        max_qos = self.mqtt.get("max_qos_allowed", 2)
        retain_ok = self.mqtt.get("retain_available", True)
        mount = self.mountpoint
        base_headers = {"username": self.clientinfo.get("username"),
                        "peername": self.conninfo.get("peername"),
                        "proto_ver": self.proto_ver}
        valid_memo: dict = {}
        auth_memo: dict = {}
        rows: list = []        # (Message, needs_count) for submit_burst
        seq: list = []         # ordered ack/disconnect plan
        qos_counts = [0, 0, 0]
        # one locked GUID pass + one clock read for the whole burst
        # (rows that fail a check burn an id — ids only need to be
        # unique and monotone, which a batch reservation preserves)
        ids = guid_batch(n)
        ts_ms = now_ms()
        clientid = self.clientid
        for j in range(n):
            if j and not j % 64:
                # the handle_in loop's pacing: a read can carry hundreds
                # of frames; yield so other tasks are not stalled
                await asyncio.sleep(0)
            topic = burst.topics[j]
            qos = burst.qos[j]
            props = burst.props[j]
            pid = burst.pids[j]
            retain = burst.retain[j]
            alias = props.pop("topic_alias", None) if props else None
            if v5 and alias is not None:
                if not (0 < alias <= max_alias):
                    seq.append(("disc", C.RC_TOPIC_ALIAS_INVALID, ""))
                    continue
                if topic:
                    self.alias_in[alias] = topic
                else:
                    topic = self.alias_in.get(alias)
                    if topic is None:
                        seq.append(("disc", C.RC_PROTOCOL_ERROR,
                                    "unknown topic alias"))
                        continue
            valid = valid_memo.get(topic)
            if valid is None:
                try:
                    valid = bool(topic) and T.validate(topic, "name")
                except T.TopicError:
                    valid = False
                valid_memo[topic] = valid
            if not valid:
                self._burst_puberr(seq, qos, pid, C.RC_TOPIC_NAME_INVALID)
                continue
            if v5 and props.get("response_topic") \
                    and T.wildcard(props["response_topic"]):
                seq.append(("disc", C.RC_PROTOCOL_ERROR,
                            "wildcard response topic"))
                continue
            if qos > max_qos:
                seq.append(("disc", C.RC_QOS_NOT_SUPPORTED, ""))
                continue
            if retain and not retain_ok:
                self._burst_puberr(seq, qos, pid,
                                   C.RC_RETAIN_NOT_SUPPORTED)
                continue
            if not self.quota.check_publish():
                m.inc("packets.publish.quota_exceeded")
                self._burst_puberr(seq, qos, pid, C.RC_QUOTA_EXCEEDED)
                continue
            ok = auth_memo.get(topic)
            if ok is None:
                ok = await self._authorize("publish", topic)
                auth_memo[topic] = ok
            if not ok:
                m.inc("packets.publish.auth_error")
                if not self._aborted:
                    self._burst_puberr(seq, qos, pid,
                                       C.RC_NOT_AUTHORIZED)
                continue
            if qos == C.QOS_2:
                try:
                    self.session.publish_qos2(pid)
                except SessionError as e:
                    m.inc("packets.publish.dropped")
                    seq.append(("err", P.Pubrec(packet_id=pid,
                                                reason_code=e.rc)))
                    continue
            # direct construction: the dataclass __init__/__post_init__
            # machinery is ~half the per-row cost at this point, and
            # every field is explicit here (ids/ts pre-reserved above)
            msg = Message.__new__(Message)
            msg.__dict__ = {
                "topic": T.prepend(mount, topic) if mount else topic,
                "payload": burst.payloads[j], "qos": qos,
                "from_": clientid,
                "flags": {"retain": retain, "dup": burst.dup[j]},
                "headers": dict(base_headers, properties=props),
                "id": ids[j], "ts": ts_ms, "extra": {},
                # ISSUE 13: the burst's one frame-decode clock read,
                # attributed per row (stamp-equivalent to the
                # per-packet path's pkt.ingress_ns carry)
                "ingress_ns": burst.ingress_ns,
            }
            qos_counts[qos] += 1
            rows.append((msg, qos > 0))
            if qos:
                seq.append(("ack", qos, pid, len(rows) - 1))
        for q in (0, 1, 2):
            if qos_counts[q]:
                m.inc("messages.received", qos_counts[q])
                m.inc(f"messages.qos{q}.received", qos_counts[q])
        futs: dict = {}
        if rows:
            pb = node.publish_batcher
            if pb is not None:
                futs = pb.submit_burst(rows)
            else:
                # no batcher wired: the host per-message path, awaited
                # in row order (exactly what publish_async would do)
                loop = asyncio.get_running_loop()
                for k, (msg, need) in enumerate(rows):
                    cnt = await node.broker.publish_async(msg)
                    if need:
                        f = loop.create_future()
                        f.set_result(cnt)
                        futs[k] = f
        # flush: acks/errors/disconnects strictly in row order (wire
        # order is the order of _send calls — awaits between them do
        # not reorder the transport buffer)
        for item in seq:
            tag = item[0]
            if tag == "disc":
                self._disconnect_now(item[1], item[2])
            elif tag == "err":
                self._send([item[1]])
            else:
                _tag, qos, pid, ridx = item
                cnt = await futs[ridx]
                rc = C.RC_SUCCESS if (cnt or not v5) \
                    else C.RC_NO_MATCHING_SUBSCRIBERS
                cls = P.Puback if qos == C.QOS_1 else P.Pubrec
                self._send([cls(packet_id=pid, reason_code=rc)])
        # backpressure stragglers (QoS0 rows the batcher bounded): a
        # full queue stalls this read loop, like a refused enqueue()
        # falling back to an awaited submit() does on the packet path
        for fut in futs.values():
            await fut

    def _burst_puberr(self, seq: list, qos: int, pid, rc: int) -> None:
        """_puberr over a columnar row: same metrics and packets, but
        the outbound ack (when one exists) is DEFERRED into the burst's
        ordered ack plan so error acks cannot overtake the success acks
        of earlier rows awaiting their delivery counts."""
        self.node.metrics.inc("packets.publish.error")
        if qos == C.QOS_0:
            if self.proto_ver == C.MQTT_V5 and rc in (
                    C.RC_TOPIC_NAME_INVALID,):
                seq.append(("disc", rc, ""))
            return
        if self.proto_ver < C.MQTT_V5 and rc == C.RC_NOT_AUTHORIZED:
            # v3: no way to signal; drop silently (emqx behavior)
            return
        cls = P.Puback if qos == C.QOS_1 else P.Pubrec
        code = rc if self.proto_ver == C.MQTT_V5 else C.RC_SUCCESS
        seq.append(("err", cls(packet_id=pid, reason_code=code)))

    def _puberr(self, pkt: P.Publish, rc: int) -> None:
        self.node.metrics.inc("packets.publish.error")
        if pkt.qos == C.QOS_0:
            if self.proto_ver == C.MQTT_V5 and rc in (
                    C.RC_TOPIC_NAME_INVALID,):
                self._disconnect_now(rc)
            return
        cls = P.Puback if pkt.qos == C.QOS_1 else P.Pubrec
        code = rc if self.proto_ver == C.MQTT_V5 else C.RC_SUCCESS
        if self.proto_ver < C.MQTT_V5 and rc == C.RC_NOT_AUTHORIZED:
            # v3: no way to signal; drop silently (emqx behavior)
            return
        self._send([cls(packet_id=pkt.packet_id, reason_code=code)])

    async def _authorize(self, action: str, topic: str) -> bool:
        if self.clientinfo.get("is_superuser"):
            return True
        self.node.metrics.inc("client.authorize")
        res = await self.node.hooks.run_fold_async(
            "client.authorize", (self.clientinfo, action, topic), "allow")
        allowed = res != "deny"
        self.node.metrics.inc(
            "authorization.allow" if allowed else "authorization.deny")
        if not allowed and self.node.config.get(
                "authz", "deny_action") == "disconnect":
            self._disconnect_now(C.RC_NOT_AUTHORIZED)
        return allowed

    # ================= acks =================
    def _handle_puback(self, pkt: P.Puback) -> None:
        try:
            msg = self.session.puback(pkt.packet_id)
            self.node.metrics.inc("messages.acked")
            self.node.hooks.run("message.acked", (self.clientinfo, msg))
            self._send_dequeued(self.session.dequeue())
        except SessionError:
            self.node.metrics.inc("packets.puback.missed")

    def _handle_pubrec(self, pkt: P.Pubrec) -> None:
        try:
            if pkt.reason_code >= 0x80:
                self.session.inflight.delete(pkt.packet_id)
                return
            self.session.pubrec(pkt.packet_id)
            self._send([P.Pubrel(packet_id=pkt.packet_id)])
        except SessionError as e:
            self.node.metrics.inc("packets.pubrec.missed")
            if e.rc == C.RC_PACKET_IDENTIFIER_NOT_FOUND:
                self._send([P.Pubrel(packet_id=pkt.packet_id,
                                     reason_code=C.RC_PACKET_IDENTIFIER_NOT_FOUND)])

    def _handle_pubrel(self, pkt: P.Pubrel) -> None:
        try:
            self.session.pubrel(pkt.packet_id)
            self._send([P.Pubcomp(packet_id=pkt.packet_id)])
        except SessionError:
            self.node.metrics.inc("packets.pubrel.missed")
            self._send([P.Pubcomp(packet_id=pkt.packet_id,
                                  reason_code=C.RC_PACKET_IDENTIFIER_NOT_FOUND)])

    def _handle_pubcomp(self, pkt: P.Pubcomp) -> None:
        try:
            msg = self.session.pubcomp(pkt.packet_id)
            self.node.metrics.inc("messages.acked")
            self.node.hooks.run("message.acked", (self.clientinfo, msg))
            self._send_dequeued(self.session.dequeue())
        except SessionError:
            self.node.metrics.inc("packets.pubcomp.missed")

    # ================= SUBSCRIBE / UNSUBSCRIBE =================
    async def _handle_subscribe(self, pkt: P.Subscribe) -> None:
        import dataclasses
        raw = [(tf, dataclasses.asdict(o) if dataclasses.is_dataclass(o)
                else dict(o)) for tf, o in pkt.filters]
        filters = self.node.hooks.run_fold(
            "client.subscribe", (self.clientinfo, pkt.properties or {}), raw)
        self.node.metrics.inc("client.subscribe")
        codes = []
        sub_props = pkt.properties or {}
        subid = sub_props.get("subscription_identifier")
        for tf, opts in filters:
            if self._aborted:     # deny_action=disconnect mid-SUBSCRIBE
                return
            code = await self._do_subscribe(tf, dict(opts), subid)
            codes.append(code)
        self._send([P.Suback(packet_id=pkt.packet_id, reason_codes=codes)])

    async def _do_subscribe(self, tf: str, opts: dict, subid) -> int:
        try:
            real, popts = T.parse(tf, opts)
            T.validate(real, "filter")   # raises TopicError when invalid
        except T.TopicError:
            return C.RC_TOPIC_FILTER_INVALID
        if T.levels(real) > self.mqtt.get("max_topic_levels", 128):
            return C.RC_TOPIC_FILTER_INVALID
        if T.wildcard(real) and not self.mqtt.get("wildcard_subscription", True):
            return C.RC_WILDCARD_SUBSCRIPTIONS_NOT_SUPPORTED
        if popts.get("share"):
            if not self.mqtt.get("shared_subscription", True):
                return C.RC_SHARED_SUBSCRIPTIONS_NOT_SUPPORTED
            if popts.get("nl"):
                return C.RC_PROTOCOL_ERROR  # v5: no-local on shared is error
        if not await self._authorize("subscribe", real):
            self.node.metrics.inc("packets.subscribe.auth_error")
            return C.RC_NOT_AUTHORIZED
        # NOT capped by max_qos_allowed: the reference grants the requested
        # QoS even under a lower broker cap (emqx_mqtt_protocol_v5_SUITE
        # t_connack_max_qos_allowed, MQTT-3.2.2-10) — the cap applies to
        # inbound PUBLISH packets, not to subscription grants
        qos = int(popts.get("qos", 0))
        popts["qos"] = qos
        if subid is not None:
            popts["subid"] = subid
        # mountpoint applies to the real filter, share prefix kept outside
        mounted_real = self._mount(real)
        group = popts.get("share")
        full = f"$share/{group}/{mounted_real}" if group else mounted_real
        is_new = full not in self.session.subscriptions
        try:
            self.session.subscribe(full, popts)
        except SessionError as e:
            return e.rc
        self.node.broker.subscribe(self.sid, full,
                                   {k: v for k, v in popts.items()
                                    if k != "share"})
        # is_new feeds the retainer's Retain-Handling decision (rh=1 sends
        # retained msgs only on a NEW subscription, MQTT5 [MQTT-3.3.1-10])
        self.node.hooks.run("session.subscribed",
                            (self.clientinfo, mounted_real,
                             dict(popts, is_new=is_new)))
        return qos  # granted QoS doubles as v5 success code 0..2

    def _handle_unsubscribe(self, pkt: P.Unsubscribe) -> None:
        self.node.metrics.inc("client.unsubscribe")
        filters = self.node.hooks.run_fold(
            "client.unsubscribe", (self.clientinfo, pkt.properties or {}),
            list(pkt.filters))
        codes = [self._do_unsubscribe(tf) for tf in filters]
        self._send([P.Unsuback(packet_id=pkt.packet_id, reason_codes=codes)])

    def _do_unsubscribe(self, tf: str) -> int:
        try:
            real, popts = T.parse(tf)
        except T.TopicError:
            return C.RC_TOPIC_FILTER_INVALID
        mounted_real = self._mount(real)
        group = popts.get("share")
        full = (f"$share/{group}/{mounted_real}" if group
                else mounted_real)
        self.node.broker.unsubscribe(self.sid, full)
        try:
            self.session.unsubscribe(full)
        except SessionError:
            return C.RC_NO_SUBSCRIPTION_EXISTED
        self.node.hooks.run("session.unsubscribed",
                            (self.clientinfo, mounted_real))
        return C.RC_SUCCESS

    # ---- management-initiated subscribe/unsubscribe (emqx_mgmt:subscribe
    # sends the request into the client's channel process) ----
    async def mgmt_subscribe(self, topic_filter: str, qos: int = 0) -> int:
        return await self._do_subscribe(topic_filter, {"qos": qos}, None)

    def mgmt_unsubscribe(self, topic_filter: str) -> bool:
        return self._do_unsubscribe(topic_filter) == C.RC_SUCCESS

    # ================= DISCONNECT =================
    def _handle_disconnect(self, pkt: P.Disconnect) -> None:
        props = pkt.properties or {}
        if self.proto_ver == C.MQTT_V5 and self.session is not None:
            new_exp = props.get("session_expiry_interval")
            if new_exp is not None:
                if (self.session.conf.session_expiry_interval == 0
                        and new_exp > 0):
                    return self._disconnect_now(C.RC_PROTOCOL_ERROR)
                self.session.conf.session_expiry_interval = new_exp
        if pkt.reason_code == C.RC_SUCCESS:
            self.will_msg = None        # normal disconnect drops the will
        self.disconnect_reason = "normal"
        self.close("disconnect")

    def _disconnect_now(self, rc: int, detail: str = "") -> None:
        if self._aborted:
            return
        if self.proto_ver == C.MQTT_V5:
            self._send([P.Disconnect(reason_code=rc)])
        self._aborted = True
        self.disconnect_reason = f"protocol_0x{rc:02x}"
        self.close(detail or f"disconnect_0x{rc:02x}")

    def _delivery_dropped(self, msg: Message, reason: str) -> None:
        """Session mqueue eviction (delivery.dropped hook,
        emqx_session dropping path)."""
        self.node.metrics.inc("delivery.dropped")
        self.node.metrics.inc(f"delivery.dropped.{reason}")
        self.node.hooks.run("delivery.dropped",
                            (self.clientinfo, msg, reason))

    # ================= delivery (broker → client) =================
    def deliver(self, topic_filter: str, msg: Message) -> bool:
        """Subscriber callback (the `{deliver,...}` message analog)."""
        if self.conn_state == CONN_TAKING_OVER:
            self._pendings.append(msg)
            return True
        if self.session is None:
            return False
        subopts = msg.headers.get("subopts", {})
        if (self.mqtt.get("ignore_loop_deliver")
                and msg.from_ == self.clientid):
            self.node.metrics.inc("delivery.dropped")
            self.node.metrics.inc("delivery.dropped.no_local")
            return True
        if msg.is_expired():
            self.node.metrics.inc("delivery.dropped")
            self.node.metrics.inc("delivery.dropped.expired")
            return True
        if self.conn_state != CONN_CONNECTED:
            self.session.enqueue([(msg, subopts)])
            return True
        out = self.session.deliver([(msg, subopts)])
        self._send_deliveries(out)
        return True

    def deliver_batch(self, items: list) -> int:
        """Coalesced delivery (ISSUE-5 lanes): a same-session run of
        routed messages accepted by ONE session.deliver pass and
        flushed in ONE socket write, instead of a per-message accept +
        drain — the per-delivery transport cost at high fan-out is the
        drain, not the enrich. All-or-none by contract (the lane
        attributes per-message counts uniformly): returns len(items)
        when the session accepted the run, 0 when there is no session."""
        if self.conn_state == CONN_TAKING_OVER:
            self._pendings.extend(m for _f, m in items)
            return len(items)
        if self.session is None:
            return 0
        metrics = self.node.metrics
        ignore_loop = self.mqtt.get("ignore_loop_deliver")
        pairs = []
        for _f, msg in items:
            if ignore_loop and msg.from_ == self.clientid:
                metrics.inc("delivery.dropped")
                metrics.inc("delivery.dropped.no_local")
                continue
            if msg.is_expired():
                metrics.inc("delivery.dropped")
                metrics.inc("delivery.dropped.expired")
                continue
            pairs.append((msg, msg.headers.get("subopts", {})))
        if pairs:
            if self.conn_state != CONN_CONNECTED:
                self.session.enqueue(pairs)
            else:
                self._send_deliveries(self.session.deliver(pairs))
        return len(items)

    def _send_deliveries(self, out: list) -> None:
        pkts = []
        for pid, m in out:
            m.update_expiry()
            pkts.append(self._to_publish(pid, m))
            self.node.metrics.inc_msg_sent(m.qos)
        if pkts:
            self._send(pkts)

    def _to_publish(self, pid: Optional[int], m: Message) -> P.Publish:
        props = dict(m.headers.get("properties") or {}) \
            if self.proto_ver == C.MQTT_V5 else None
        topic = self._unmount(m.topic)
        # outbound topic aliasing (emqx_channel packing_alias): within the
        # client's advertised Topic-Alias-Maximum, the first delivery of a
        # topic carries topic+alias, repeats carry the alias alone; topics
        # beyond the alias budget go un-aliased
        if self.alias_out_max and topic:
            alias = self.alias_out.get(topic)
            if alias is not None:
                props["topic_alias"] = alias
                topic = ""
            elif len(self.alias_out) < self.alias_out_max:
                alias = len(self.alias_out) + 1
                self.alias_out[topic] = alias
                props["topic_alias"] = alias
        return P.Publish(topic=topic, payload=m.payload,
                         qos=m.qos, retain=m.retain, dup=m.dup,
                         packet_id=pid or 0, properties=props)

    def _send_dequeued(self, items: list[tuple[int, Message]]) -> None:
        """Send mqueue refill: pid 0 entries are QoS0 (no ack expected)."""
        self._send_deliveries([(pid or None, m) for pid, m in items])

    def _send_replay(self, items: list) -> None:
        pkts = []
        for pid, phase, msg in items:
            if phase == "publish":
                pkts.append(self._to_publish(pid, msg))
                self.node.metrics.inc_msg_sent(msg.qos)
            else:
                pkts.append(P.Pubrel(packet_id=pid))
        if pkts:
            self._send(pkts)

    # ================= timers =================
    def retry_deliveries(self) -> None:
        if self.session and self.conn_state == CONN_CONNECTED:
            items = self.session.retry()
            for _pid, phase, m in items:
                if phase == "publish":
                    m.set_flag("dup", True)
            self._send_replay(items)
            self.session.expire_awaiting_rel()

    # ================= takeover / kick / terminate =================
    async def takeover_begin(self) -> Optional[Session]:
        if self.session is None:
            return None
        self.conn_state = CONN_TAKING_OVER
        return self.session.takeover()

    async def takeover_end(self) -> list:
        pendings = self._pendings
        self._pendings = []
        sess = self.session
        self.session = None     # ownership moved
        if self.proto_ver == C.MQTT_V5:
            # MQTT-3.1.4-3: tell the displaced connection why it's going
            # (the reference's ?RC_SESSION_TAKEN_OVER disconnect on kick,
            # asserted by emqx_mqtt_protocol_v5_SUITE t_connect_clean_start)
            self._send([P.Disconnect(
                reason_code=C.RC_SESSION_TAKEN_OVER)])
        self.node.metrics.inc("session.takenover")
        self.node.hooks.run("session.takenover", (self.clientinfo, sess))
        if self.sid is not None:
            self.node.broker.subscriber_down(self.sid)
            self.sid = None
        self.close("takenover")
        return pendings

    async def kick(self, reason: str) -> None:
        if self.proto_ver == C.MQTT_V5:
            rc = (C.RC_SESSION_TAKEN_OVER if reason == "discarded"
                  else C.RC_ADMINISTRATIVE_ACTION)
            self._send([P.Disconnect(reason_code=rc)])
        self.will_msg = None if reason == "discarded" else self.will_msg
        if reason == "discarded" and self.session is not None:
            self.node.metrics.inc("session.discarded")
            self.node.hooks.run("session.discarded",
                                (self.clientinfo, self.session))
            self.session = None
        self.close(reason)

    def terminate(self, reason: str) -> None:
        """Connection closed (emqx_channel:terminate) — publish will,
        park or drop the session, clean broker state."""
        sess = self.session
        park = (sess is not None and self.conn_state == CONN_CONNECTED
                and sess.conf.session_expiry_interval > 0
                and reason != "discarded")
        if self.sid is not None:
            if park:
                # keep routes alive: detached session keeps enqueueing
                sess.parked_sid = self.sid
                self.node.broker.swap_subscriber(
                    self.sid, ParkedSubscriber(sess, self.node))
                # don't pin this Channel via the bound-method callback:
                # rebind drop accounting to node-scoped state
                node, ci = self.node, {"clientid": self.clientid}
                def _parked_drop(m, r, node=node, ci=ci):
                    node.metrics.inc("delivery.dropped")
                    node.metrics.inc(f"delivery.dropped.{r}")
                    node.hooks.run("delivery.dropped", (ci, m, r))
                sess.on_dropped = _parked_drop
            else:
                self.node.broker.subscriber_down(self.sid)
            self.sid = None
        if self.conn_state in (CONN_CONNECTED, CONN_DISCONNECTED):
            self.node.cm.unregister_channel(self.clientid, self)
        if self.will_msg is not None and reason not in ("takenover",):
            # scheduled so exhook's async message.publish hooks still apply
            self.node.broker.publish_soon(self.will_msg)
            self.will_msg = None
        if sess is not None and self.conn_state == CONN_CONNECTED:
            if park:
                self.node.cm.park_session(self.clientid, sess)
            else:
                self.node.metrics.inc("session.terminated")
                self.node.hooks.run("session.terminated",
                                    (self.clientinfo, reason, sess))
        if self.conn_state == CONN_CONNECTED:
            self.node.metrics.inc("client.disconnected")
            self.node.hooks.run("client.disconnected",
                                (self.clientinfo, reason))
        self.conn_state = CONN_DISCONNECTED
        self.session = None

    # ================= info =================
    def _conninfo_map(self, pkt: P.Connect) -> dict:
        return {"clientid": pkt.clientid, "username": pkt.username,
                "proto_ver": pkt.proto_ver, "keepalive": pkt.keepalive,
                "clean_start": pkt.clean_start,
                "peername": self.conninfo.get("peername")}

    def info(self) -> dict:
        d = {
            "clientid": self.clientid,
            "username": self.clientinfo.get("username"),
            "peername": self.conninfo.get("peername"),
            "proto_ver": self.proto_ver,
            "keepalive": self.keepalive,
            "clean_start": self.clientinfo.get("clean_start", True),
            "conn_state": self.conn_state,
            "connected_at": self.connected_at,
            "zone": self.zone,
            "mountpoint": self.mountpoint,
        }
        if self.session is not None:
            d["session"] = self.session.info()
        return d
