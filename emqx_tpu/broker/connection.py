"""Per-client connection task + config-driven listeners.

Parity: emqx_connection.erl (per-client recvloop with {active,N}-style
read batching :318-345,404-516, keepalive + idle timeout, force-shutdown
policy) and emqx_listeners.erl (listener lifecycle :126-138). One asyncio
task per socket replaces the reference's per-connection BEAM process; the
read loop drains whatever bytes are available and feeds the streaming frame
parser, so a burst of packets is handled as one batch (the P10 batching
window).
"""

from __future__ import annotations

import asyncio
import logging
import os
import socket
import time
from typing import Optional

from emqx_tpu.broker.channel import Channel, ProtocolError
from emqx_tpu.broker.limiter import (ConnectionLimiter, ForceShutdownPolicy,
                                     TokenBucket)
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.frame import (FrameError, FrameParser, PublishBurst,
                                 serialize)

log = logging.getLogger("emqx_tpu.connection")

READ_CHUNK = 65536


def resolve_columnar_ingress(configured=None) -> bool:
    """The one columnar-ingress resolution (ISSUE 11): config
    (``broker.columnar_ingress``) beats ``EMQX_TPU_COLUMNAR_INGRESS``
    beats default-on. ``=0`` restores the per-packet ingress path
    EXACTLY — parser.feed, per-packet handle_in, one accept loop, no
    ``ingress`` telemetry section — the A/B baseline the twin test
    compares."""
    if configured is not None:
        return bool(configured)
    return os.environ.get("EMQX_TPU_COLUMNAR_INGRESS", "1") \
        not in ("0", "false", "off")


def resolve_ingress_lanes(configured=None) -> int:
    """Sharded-acceptor lane count: config (``broker.ingress_lanes``)
    beats ``EMQX_TPU_INGRESS_LANES`` beats the built-in min(4, cpus).
    1 = the single accept loop; the whole layer additionally rides the
    columnar_ingress knob (=0 forces 1 lane). Must be a positive
    integer — anything else is a deployment error worth failing loudly
    on."""
    if configured is not None:
        val = int(configured)
    else:
        env = os.environ.get("EMQX_TPU_INGRESS_LANES")
        if env is None:
            return min(4, os.cpu_count() or 1)
        try:
            val = int(env)
        except ValueError:
            raise ValueError(
                f"EMQX_TPU_INGRESS_LANES={env!r} is not an integer")
    if val < 1:
        raise ValueError(f"ingress_lanes must be >= 1, got {val}")
    return val


class Connection:
    def __init__(self, node, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, zone: Optional[str] = None):
        self.node = node
        self.reader = reader
        self.writer = writer
        peer = writer.get_extra_info("peername") or ("?", 0)
        sock = writer.get_extra_info("sockname") or ("?", 0)
        from emqx_tpu.utils.tls import peer_cert_info
        peercert = peer_cert_info(writer)
        self.parser = FrameParser(
            max_size=node.config.mqtt(zone).get("max_packet_size"),
            strict=node.config.mqtt(zone).get("strict_mode", False))
        # columnar ingress (ISSUE 11): resolved once per node; off means
        # this connection's read loop is byte-for-byte the per-packet
        # path (parser.feed + handle_in, no ingress counters)
        self._columnar = bool(getattr(node, "columnar_ingress", False))
        self.channel = Channel(
            node, {"peername": peer, "sockname": sock, "zone": zone,
                   "peercert": peercert},
            send=self._send_packets, close=self._request_close)
        self.last_rx = time.monotonic()
        self._closing: Optional[str] = None
        self._timer_task: Optional[asyncio.Task] = None
        rl = node.config.get_zone(zone, "rate_limit") or {}
        self.limiter = ConnectionLimiter(
            rl.get("conn_messages_in") or None,
            rl.get("conn_bytes_in") or None)
        fs = node.config.get_zone(zone, "force_shutdown") or {}
        self.force_shutdown = ForceShutdownPolicy(
            fs.get("max_mqueue_len", 0), fs.get("max_awaiting_rel", 0))
        from emqx_tpu.broker.congestion import Congestion
        cc = node.config.get_zone(zone, "conn_congestion") or {}
        self.congestion = Congestion(
            node, self.channel, writer,
            enable_alarm=cc.get("enable_alarm", False),
            min_alarm_sustain_duration=cc.get(
                "min_alarm_sustain_duration", 60))
        # overload governor (ISSUE 14): registered (weakly) so the
        # critical-grade top-offender shed can rank live connections by
        # limiter debt; shed_rows is the ingress-volume fallback score
        # when no rate limit is configured (decayed by the governor)
        self.shed_rows = 0.0
        gov = getattr(node, "overload_governor", None)
        if gov is not None:
            gov.register_conn(self)

    # ---- outbound ----
    def _send_packets(self, pkts: list[P.Packet]) -> None:
        if self.writer.is_closing():
            return
        data = b"".join(serialize(p, self.channel.proto_ver) for p in pkts)
        self.node.metrics.inc("bytes.sent", len(data))
        self.writer.write(data)

    def _request_close(self, reason: str) -> None:
        if self._closing is None:
            self._closing = reason
            if not self.writer.is_closing():
                self.writer.close()

    # overload top-offender volume floor (ISSUE 14): without a
    # configured rate limit, a connection only qualifies for the
    # critical-grade disconnect when its DECAYED recent row count
    # reads as a genuine flood — a subscriber's ack stream or a
    # moderate publisher must never rank
    _SHED_VOLUME_FLOOR = 1000.0

    # ---- overload shed (ISSUE 14: force_shutdown parity) ----
    def shed_score(self) -> float:
        """How much this connection is over-driving ingress: limiter
        debt (seconds-to-repay) when a rate limit is configured — the
        primary ranking, offset so ANY debt outranks plain volume —
        else the decayed recent-rows count, floored so only a genuine
        flooder qualifies. 0.0 = not a shed candidate."""
        debt = self.limiter.debt()
        if debt > 0:
            return 1e6 + debt
        if self.shed_rows >= self._SHED_VOLUME_FLOOR:
            return self.shed_rows
        return 0.0

    def overload_disconnect(self) -> None:
        """The governor's critical-grade disconnect: v5 clients get a
        DISCONNECT with reason 0x97 (quota exceeded), everyone gets the
        close — exactly the force_shutdown lifecycle, so the session
        parks or terminates per its expiry config."""
        self.node.metrics.inc("connection.force_shutdown")
        if self.channel.proto_ver == C.MQTT_V5 \
                and self.channel.conn_state == "connected":
            self._send_packets([P.Disconnect(
                reason_code=C.RC_QUOTA_EXCEEDED)])
        self._request_close("overload_shed")

    # ---- main loop (emqx_connection:recvloop) ----
    async def run(self) -> None:
        from emqx_tpu.utils.logger import set_metadata_peername
        peer = self.channel.conninfo.get("peername")
        if peer:
            set_metadata_peername(f"{peer[0]}:{peer[1]}")
        from emqx_tpu.broker.supervise import guard_task
        self._timer_task = guard_task(
            asyncio.ensure_future(self._timers()), "conn-timers",
            self.node.metrics)
        reason = "closed"
        try:
            idle_timeout = self.node.config.mqtt(
                self.channel.zone).get("idle_timeout", 15)
            while self._closing is None:
                timeout = (idle_timeout
                           if self.channel.conn_state == "idle" else None)
                try:
                    data = await asyncio.wait_for(
                        self.reader.read(READ_CHUNK), timeout)
                except asyncio.TimeoutError:
                    reason = "idle_timeout"
                    break
                if not data:
                    reason = "closed"
                    break
                self.last_rx = time.monotonic()
                m = self.node.metrics
                m.inc("bytes.received", len(data))
                columnar = self._columnar
                try:
                    if columnar:
                        # columnar ingress (ISSUE 11): PUBLISH runs
                        # decode as PublishBurst items, everything else
                        # (and small reads) stays per-packet, in order
                        items = self.parser.feed_columnar(data)
                    else:
                        items = self.parser.feed(data)
                except FrameError as e:
                    reason = f"frame_error:{e.code}"
                    self._frame_error_out(e)
                    break
                n_rows = 0
                n_pub = 0
                for it in items:
                    if type(it) is PublishBurst:
                        n_rows += len(it)
                        n_pub += len(it)
                    else:
                        n_rows += 1
                        if type(it) is P.Publish:
                            n_pub += 1
                if columnar and items:
                    m.inc("pipeline.ingress.bytes", len(data))
                n_done = 0
                for item in items:
                    if type(item) is PublishBurst:
                        m.inc("pipeline.ingress.bursts")
                        m.inc("pipeline.ingress.rows", len(item))
                        tele = self.node.pipeline_telemetry
                        if tele is not None:
                            tele.record_ingress_burst(len(item))
                        try:
                            await self.channel.handle_publish_burst(item)
                        except ProtocolError as e:
                            reason = f"protocol_error:0x{e.rc:02x}"
                            self._protocol_error_out(e)
                            break
                        n_done += len(item)
                        continue
                    if columnar:
                        m.inc("pipeline.ingress.fallback_frames")
                    try:
                        await self.channel.handle_in(item)
                    except ProtocolError as e:
                        reason = f"protocol_error:0x{e.rc:02x}"
                        self._protocol_error_out(e)
                        break
                    n_done += 1
                    if n_done % 64 == 0:
                        # one read can carry hundreds of frames; without
                        # a scheduling point the whole burst handles
                        # back-to-back and stalls every other task for
                        # tens of ms (handle_in's awaits don't yield
                        # unless they actually block)
                        await asyncio.sleep(0)
                if items:
                    # offender score counts PUBLISH rows ONLY: a
                    # subscriber's PUBACK stream (or SUBSCRIBE/PING
                    # chatter) must never rank it for the overload
                    # disconnect — only publish pressure does
                    self.shed_rows += n_pub
                    await self._drain()
                    # ingress rate limit: a depleted bucket pauses reading
                    # (the {active,N}-off backpressure, emqx_connection
                    # ensure_rate_limit)
                    pause = self.limiter.check(n_rows, len(data))
                    if pause > 0:
                        self.node.metrics.inc("connection.rate_limited")
                        await asyncio.sleep(pause)
            reason = self._closing or reason
        except (ConnectionResetError, BrokenPipeError):
            reason = "closed"
        except asyncio.CancelledError:
            reason = "shutdown"
        except Exception:
            log.exception("connection crashed")
            reason = "internal_error"
        finally:
            if self._timer_task:
                self._timer_task.cancel()
            self.congestion.cancel()
            self.channel.terminate(self._closing or reason)
            try:
                # graceful close first (flushes the DISCONNECT we may have
                # just written); a stuck peer that can never drain falls
                # into the timeout and gets hard-aborted
                if not self.writer.is_closing():
                    self.writer.close()
                await asyncio.wait_for(self.writer.wait_closed(), 5)
            except (asyncio.CancelledError, KeyboardInterrupt, SystemExit):
                try:
                    self.writer.transport.abort()
                except Exception:  # noqa: BLE001 — transport already gone
                    pass
                raise               # preserve the cancellation contract
            except Exception:       # TimeoutError, reset mid-flush, ...
                try:
                    self.writer.transport.abort()
                except Exception:  # noqa: BLE001 — transport already gone
                    pass

    def _frame_error_out(self, e: FrameError) -> None:
        if self.channel.proto_ver == C.MQTT_V5 and \
                self.channel.conn_state == "connected":
            self._send_packets([P.Disconnect(
                reason_code=C.RC_MALFORMED_PACKET)])

    def _protocol_error_out(self, e: ProtocolError) -> None:
        if self.channel.proto_ver == C.MQTT_V5 and \
                self.channel.conn_state == "connected":
            self._send_packets([P.Disconnect(reason_code=e.rc)])
        self._request_close(f"protocol_error_0x{e.rc:02x}")

    async def _drain(self) -> None:
        try:
            await self.writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            self._request_close("closed")

    # ---- keepalive + retry timers (emqx_channel timer table) ----
    async def _timers(self) -> None:
        backoff = self.node.config.mqtt(
            self.channel.zone).get("keepalive_backoff", 0.75)
        retry_iv = self.node.config.mqtt(
            self.channel.zone).get("retry_interval", 30)
        last_retry = time.monotonic()
        while True:
            await asyncio.sleep(1.0)
            now = time.monotonic()
            self.congestion.check()
            ka = self.channel.keepalive
            if (ka and self.channel.conn_state == "connected"
                    and now - self.last_rx > ka * 2 * backoff):
                if self.channel.proto_ver == C.MQTT_V5:
                    self._send_packets([P.Disconnect(
                        reason_code=C.RC_KEEP_ALIVE_TIMEOUT)])
                self._request_close("keepalive_timeout")
                return
            if retry_iv and now - last_retry >= retry_iv:
                last_retry = now
                self.channel.retry_deliveries()
            why = self.force_shutdown.violated(self.channel.session)
            if why is not None:
                self.node.metrics.inc("connection.force_shutdown")
                self._request_close(f"force_shutdown:{why}")
                return


class Listener:
    """One TCP/TLS listener (emqx_listeners:start_listener/3; ssl opts per
    emqx_listeners.erl:126-129 + emqx_schema ssl block via utils.tls)."""

    def __init__(self, node, *, bind: str = "0.0.0.0", port: int = 1883,
                 zone: Optional[str] = None, max_connections: int = 1024000,
                 name: str = "tcp:default", ssl_opts: Optional[dict] = None):
        self.node = node
        self.bind = bind
        self.port = port
        self.zone = zone
        self.name = name
        self.ssl_opts = ssl_opts
        if ssl_opts and name == "tcp:default":
            self.name = "ssl:default"
        self.max_connections = max_connections
        self._server: Optional[asyncio.AbstractServer] = None
        self._lane_servers: list[asyncio.AbstractServer] = []
        self.lane_conns: list[int] = []    # live conns per accept lane
        self._conns: set[asyncio.Task] = set()
        self.current_conns = 0
        rate = (node.config.get_zone(zone, "rate_limit") or {}) \
            .get("max_conn_rate", 0)
        self._accept_bucket = TokenBucket(rate) if rate else None

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        if self.current_conns >= self.max_connections:
            writer.close()
            return
        if self._accept_bucket is not None \
                and self._accept_bucket.consume() > 0:
            # accept-rate limit: drop the connection (esockd max_conn_rate)
            self.node.metrics.inc("connection.accept_limited")
            writer.close()
            return
        self.current_conns += 1
        conn = Connection(self.node, reader, writer, self.zone)
        task = asyncio.current_task()
        self._conns.add(task)
        try:
            await conn.run()
        finally:
            self.current_conns -= 1
            self._conns.discard(task)

    def _ingress_lanes(self) -> int:
        """Acceptor-lane count for this listener (ISSUE 11): N
        SO_REUSEPORT listening sockets on the same port, each with its
        own accept loop, so the kernel spreads incoming connections —
        the ingress mirror of PR 5's egress lanes. Engages only for
        plain IPv4 TCP with columnar ingress on; TLS/IPv6 keep the
        single accept loop."""
        if not getattr(self.node, "columnar_ingress", False):
            return 1
        if self.ssl_opts or ":" in self.bind \
                or not hasattr(socket, "SO_REUSEPORT"):
            return 1
        return getattr(self.node, "ingress_lanes", 1)

    async def start(self) -> None:
        ssl_ctx = None
        if self.ssl_opts:
            from emqx_tpu.utils.tls import make_server_context
            ssl_ctx = make_server_context(self.ssl_opts)
        lanes = self._ingress_lanes()
        if lanes > 1:
            port = self.port
            self.lane_conns = [0] * lanes
            for i in range(lanes):
                sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                try:
                    sock.setsockopt(socket.SOL_SOCKET,
                                    socket.SO_REUSEADDR, 1)
                    sock.setsockopt(socket.SOL_SOCKET,
                                    socket.SO_REUSEPORT, 1)
                    sock.bind((self.bind, port))
                except OSError:
                    sock.close()
                    if i == 0:
                        raise
                    break   # partial lane set still serves
                if port == 0:   # ephemeral port: later lanes join it
                    port = sock.getsockname()[1]
                srv = await asyncio.start_server(
                    self._lane_handler(i), sock=sock)
                self._lane_servers.append(srv)
            self.port = port
            self._server = self._lane_servers[0]
            log.info("listener %s started on %s:%d (%d ingress lanes)",
                     self.name, self.bind, self.port,
                     len(self._lane_servers))
            return
        self._server = await asyncio.start_server(
            self._on_client, self.bind, self.port, ssl=ssl_ctx)
        if self.port == 0:   # ephemeral port for tests
            self.port = self._server.sockets[0].getsockname()[1]
        log.info("listener %s started on %s:%d", self.name, self.bind,
                 self.port)

    def _lane_handler(self, lane: int):
        async def _on_lane_client(reader, writer):
            gov = getattr(self.node, "overload_governor", None)
            if lane > 0 and gov is not None and gov.connects_paused:
                # overload pause_connects (ISSUE 14): the extra
                # acceptor lanes stop taking connections — lane 0
                # keeps accepting so the CONNECT still gets its v5
                # 0x97 CONNACK (the channel-side half of this action)
                gov.count_accept_paused()
                writer.close()
                return
            self.node.metrics.inc(
                f"pipeline.ingress.lane{lane}.accepted")
            self.lane_conns[lane] += 1
            try:
                await self._on_client(reader, writer)
            finally:
                self.lane_conns[lane] -= 1
        return _on_lane_client

    async def stop(self) -> None:
        # stop accepting first so no connection slips in during the cancel
        # window; then cancel handlers (py3.12 wait_closed blocks until
        # every handler coroutine finishes, so cancel before waiting)
        servers = self._lane_servers or \
            ([self._server] if self._server else [])
        for srv in servers:
            srv.close()
        for t in list(self._conns):
            t.cancel()
        if self._conns:
            await asyncio.gather(*self._conns, return_exceptions=True)
        for srv in servers:
            try:
                await asyncio.wait_for(srv.wait_closed(), 2)
            except asyncio.TimeoutError:
                pass
        self._lane_servers = []
