"""Per-client connection task + config-driven listeners.

Parity: emqx_connection.erl (per-client recvloop with {active,N}-style
read batching :318-345,404-516, keepalive + idle timeout, force-shutdown
policy) and emqx_listeners.erl (listener lifecycle :126-138). One asyncio
task per socket replaces the reference's per-connection BEAM process; the
read loop drains whatever bytes are available and feeds the streaming frame
parser, so a burst of packets is handled as one batch (the P10 batching
window).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from emqx_tpu.broker.channel import Channel, ProtocolError
from emqx_tpu.broker.limiter import (ConnectionLimiter, ForceShutdownPolicy,
                                     TokenBucket)
from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.frame import FrameError, FrameParser, serialize

log = logging.getLogger("emqx_tpu.connection")

READ_CHUNK = 65536


class Connection:
    def __init__(self, node, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, zone: Optional[str] = None):
        self.node = node
        self.reader = reader
        self.writer = writer
        peer = writer.get_extra_info("peername") or ("?", 0)
        sock = writer.get_extra_info("sockname") or ("?", 0)
        from emqx_tpu.utils.tls import peer_cert_info
        peercert = peer_cert_info(writer)
        self.parser = FrameParser(
            max_size=node.config.mqtt(zone).get("max_packet_size"),
            strict=node.config.mqtt(zone).get("strict_mode", False))
        self.channel = Channel(
            node, {"peername": peer, "sockname": sock, "zone": zone,
                   "peercert": peercert},
            send=self._send_packets, close=self._request_close)
        self.last_rx = time.monotonic()
        self._closing: Optional[str] = None
        self._timer_task: Optional[asyncio.Task] = None
        rl = node.config.get_zone(zone, "rate_limit") or {}
        self.limiter = ConnectionLimiter(
            rl.get("conn_messages_in") or None,
            rl.get("conn_bytes_in") or None)
        fs = node.config.get_zone(zone, "force_shutdown") or {}
        self.force_shutdown = ForceShutdownPolicy(
            fs.get("max_mqueue_len", 0), fs.get("max_awaiting_rel", 0))
        from emqx_tpu.broker.congestion import Congestion
        cc = node.config.get_zone(zone, "conn_congestion") or {}
        self.congestion = Congestion(
            node, self.channel, writer,
            enable_alarm=cc.get("enable_alarm", False),
            min_alarm_sustain_duration=cc.get(
                "min_alarm_sustain_duration", 60))

    # ---- outbound ----
    def _send_packets(self, pkts: list[P.Packet]) -> None:
        if self.writer.is_closing():
            return
        data = b"".join(serialize(p, self.channel.proto_ver) for p in pkts)
        self.node.metrics.inc("bytes.sent", len(data))
        self.writer.write(data)

    def _request_close(self, reason: str) -> None:
        if self._closing is None:
            self._closing = reason
            if not self.writer.is_closing():
                self.writer.close()

    # ---- main loop (emqx_connection:recvloop) ----
    async def run(self) -> None:
        from emqx_tpu.utils.logger import set_metadata_peername
        peer = self.channel.conninfo.get("peername")
        if peer:
            set_metadata_peername(f"{peer[0]}:{peer[1]}")
        from emqx_tpu.broker.supervise import guard_task
        self._timer_task = guard_task(
            asyncio.ensure_future(self._timers()), "conn-timers",
            self.node.metrics)
        reason = "closed"
        try:
            idle_timeout = self.node.config.mqtt(
                self.channel.zone).get("idle_timeout", 15)
            while self._closing is None:
                timeout = (idle_timeout
                           if self.channel.conn_state == "idle" else None)
                try:
                    data = await asyncio.wait_for(
                        self.reader.read(READ_CHUNK), timeout)
                except asyncio.TimeoutError:
                    reason = "idle_timeout"
                    break
                if not data:
                    reason = "closed"
                    break
                self.last_rx = time.monotonic()
                self.node.metrics.inc("bytes.received", len(data))
                try:
                    pkts = self.parser.feed(data)
                except FrameError as e:
                    reason = f"frame_error:{e.code}"
                    self._frame_error_out(e)
                    break
                for i, pkt in enumerate(pkts):
                    try:
                        await self.channel.handle_in(pkt)
                    except ProtocolError as e:
                        reason = f"protocol_error:0x{e.rc:02x}"
                        self._protocol_error_out(e)
                        break
                    if i % 64 == 63:
                        # one read can carry hundreds of frames; without
                        # a scheduling point the whole burst handles
                        # back-to-back and stalls every other task for
                        # tens of ms (handle_in's awaits don't yield
                        # unless they actually block)
                        await asyncio.sleep(0)
                if pkts:
                    await self._drain()
                    # ingress rate limit: a depleted bucket pauses reading
                    # (the {active,N}-off backpressure, emqx_connection
                    # ensure_rate_limit)
                    pause = self.limiter.check(len(pkts), len(data))
                    if pause > 0:
                        self.node.metrics.inc("connection.rate_limited")
                        await asyncio.sleep(pause)
            reason = self._closing or reason
        except (ConnectionResetError, BrokenPipeError):
            reason = "closed"
        except asyncio.CancelledError:
            reason = "shutdown"
        except Exception:
            log.exception("connection crashed")
            reason = "internal_error"
        finally:
            if self._timer_task:
                self._timer_task.cancel()
            self.congestion.cancel()
            self.channel.terminate(self._closing or reason)
            try:
                # graceful close first (flushes the DISCONNECT we may have
                # just written); a stuck peer that can never drain falls
                # into the timeout and gets hard-aborted
                if not self.writer.is_closing():
                    self.writer.close()
                await asyncio.wait_for(self.writer.wait_closed(), 5)
            except (asyncio.CancelledError, KeyboardInterrupt, SystemExit):
                try:
                    self.writer.transport.abort()
                except Exception:  # noqa: BLE001 — transport already gone
                    pass
                raise               # preserve the cancellation contract
            except Exception:       # TimeoutError, reset mid-flush, ...
                try:
                    self.writer.transport.abort()
                except Exception:  # noqa: BLE001 — transport already gone
                    pass

    def _frame_error_out(self, e: FrameError) -> None:
        if self.channel.proto_ver == C.MQTT_V5 and \
                self.channel.conn_state == "connected":
            self._send_packets([P.Disconnect(
                reason_code=C.RC_MALFORMED_PACKET)])

    def _protocol_error_out(self, e: ProtocolError) -> None:
        if self.channel.proto_ver == C.MQTT_V5 and \
                self.channel.conn_state == "connected":
            self._send_packets([P.Disconnect(reason_code=e.rc)])
        self._request_close(f"protocol_error_0x{e.rc:02x}")

    async def _drain(self) -> None:
        try:
            await self.writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            self._request_close("closed")

    # ---- keepalive + retry timers (emqx_channel timer table) ----
    async def _timers(self) -> None:
        backoff = self.node.config.mqtt(
            self.channel.zone).get("keepalive_backoff", 0.75)
        retry_iv = self.node.config.mqtt(
            self.channel.zone).get("retry_interval", 30)
        last_retry = time.monotonic()
        while True:
            await asyncio.sleep(1.0)
            now = time.monotonic()
            self.congestion.check()
            ka = self.channel.keepalive
            if (ka and self.channel.conn_state == "connected"
                    and now - self.last_rx > ka * 2 * backoff):
                if self.channel.proto_ver == C.MQTT_V5:
                    self._send_packets([P.Disconnect(
                        reason_code=C.RC_KEEP_ALIVE_TIMEOUT)])
                self._request_close("keepalive_timeout")
                return
            if retry_iv and now - last_retry >= retry_iv:
                last_retry = now
                self.channel.retry_deliveries()
            why = self.force_shutdown.violated(self.channel.session)
            if why is not None:
                self.node.metrics.inc("connection.force_shutdown")
                self._request_close(f"force_shutdown:{why}")
                return


class Listener:
    """One TCP/TLS listener (emqx_listeners:start_listener/3; ssl opts per
    emqx_listeners.erl:126-129 + emqx_schema ssl block via utils.tls)."""

    def __init__(self, node, *, bind: str = "0.0.0.0", port: int = 1883,
                 zone: Optional[str] = None, max_connections: int = 1024000,
                 name: str = "tcp:default", ssl_opts: Optional[dict] = None):
        self.node = node
        self.bind = bind
        self.port = port
        self.zone = zone
        self.name = name
        self.ssl_opts = ssl_opts
        if ssl_opts and name == "tcp:default":
            self.name = "ssl:default"
        self.max_connections = max_connections
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set[asyncio.Task] = set()
        self.current_conns = 0
        rate = (node.config.get_zone(zone, "rate_limit") or {}) \
            .get("max_conn_rate", 0)
        self._accept_bucket = TokenBucket(rate) if rate else None

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        if self.current_conns >= self.max_connections:
            writer.close()
            return
        if self._accept_bucket is not None \
                and self._accept_bucket.consume() > 0:
            # accept-rate limit: drop the connection (esockd max_conn_rate)
            self.node.metrics.inc("connection.accept_limited")
            writer.close()
            return
        self.current_conns += 1
        conn = Connection(self.node, reader, writer, self.zone)
        task = asyncio.current_task()
        self._conns.add(task)
        try:
            await conn.run()
        finally:
            self.current_conns -= 1
            self._conns.discard(task)

    async def start(self) -> None:
        ssl_ctx = None
        if self.ssl_opts:
            from emqx_tpu.utils.tls import make_server_context
            ssl_ctx = make_server_context(self.ssl_opts)
        self._server = await asyncio.start_server(
            self._on_client, self.bind, self.port, ssl=ssl_ctx)
        if self.port == 0:   # ephemeral port for tests
            self.port = self._server.sockets[0].getsockname()[1]
        log.info("listener %s started on %s:%d", self.name, self.bind,
                 self.port)

    async def stop(self) -> None:
        # stop accepting first so no connection slips in during the cancel
        # window; then cancel handlers (py3.12 wait_closed blocks until
        # every handler coroutine finishes, so cancel before waiting)
        if self._server:
            self._server.close()
        for t in list(self._conns):
            t.cancel()
        if self._conns:
            await asyncio.gather(*self._conns, return_exceptions=True)
        if self._server:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2)
            except asyncio.TimeoutError:
                pass
