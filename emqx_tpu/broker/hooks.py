"""Global ordered-callback hook registry.

Parity: emqx_hooks.erl — priority-ordered callback chains behind every
extension point (`client.*`, `session.*`, `message.*` hookpoints), with
`run` (fire-and-forget chain, callback may `stop`) and `run_fold`
(accumulator threads through, callback may `{stop,Acc}`) semantics
(emqx_hooks.erl:161-196).

Callbacks return:
  None / "ok"            → continue with unchanged acc
  ("ok", acc)            → continue with new acc (run_fold)
  "stop"                 → stop the chain
  ("stop", acc)          → stop with new acc (run_fold)
"""

from __future__ import annotations

import bisect
import inspect
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

# Highest-priority built-in hooks (reference ?HP_* in emqx_hooks.hrl)
HP_AUTHN = 1000
HP_AUTHZ = 1000
HP_RETAINER = 0
HP_RULE_ENGINE = -10
HP_LOWEST = -1000

# The reference's 20 hookpoints (exhook.proto:27-69 / emqx_hooks usage sites)
HOOKPOINTS = [
    "client.connect", "client.connack", "client.connected",
    "client.disconnected", "client.authenticate", "client.authorize",
    "client.subscribe", "client.unsubscribe",
    "session.created", "session.subscribed", "session.unsubscribed",
    "session.resumed", "session.discarded", "session.takenover",
    "session.terminated",
    "message.publish", "message.delivered", "message.acked",
    "message.dropped",
    "alarm.activated", "alarm.deactivated",
    "delivery.dropped", "delivery.completed",
]


@dataclass(order=True)
class Callback:
    sort_key: tuple = field(init=False, repr=False)
    priority: int
    seq: int
    action: Callable = field(compare=False)
    filter: Optional[Callable] = field(compare=False, default=None)
    tag: Optional[str] = field(compare=False, default=None)

    def __post_init__(self):
        # higher priority first; FIFO within a priority (emqx_hooks.erl:74-83)
        self.sort_key = (-self.priority, self.seq)


class Hooks:
    """One registry instance per broker node (the reference's ETS table)."""

    def __init__(self):
        self._chains: dict[str, list[Callback]] = {}
        self._lock = threading.Lock()
        self._seq = 0

    def add(self, name: str, action: Callable, priority: int = 0,
            filter: Optional[Callable] = None,
            tag: Optional[str] = None) -> None:
        """Parity: emqx_hooks:add/2,3,4."""
        with self._lock:
            self._seq += 1
            cb = Callback(priority=priority, seq=self._seq, action=action,
                          filter=filter, tag=tag)
            chain = self._chains.setdefault(name, [])
            bisect.insort(chain, cb)

    def delete(self, name: str, action_or_tag: Any) -> None:
        """Parity: emqx_hooks:del/2 — by callable or by tag."""
        with self._lock:
            chain = self._chains.get(name, [])
            self._chains[name] = [
                cb for cb in chain
                if cb.action is not action_or_tag and cb.tag != action_or_tag]

    def lookup(self, name: str) -> list[Callback]:
        return list(self._chains.get(name, []))

    def run(self, name: str, args: tuple = ()) -> None:
        """Parity: emqx_hooks:run/2 — no accumulator, 'stop' halts chain."""
        for cb in self._chains.get(name, ()):
            if cb.filter and not cb.filter(*args):
                continue
            res = cb.action(*args)
            if res == "stop" or (isinstance(res, tuple) and res[:1] == ("stop",)):
                return

    @staticmethod
    def _fold_step(res: Any, acc: Any) -> tuple[bool, Any]:
        """Interpret one callback result → (stop?, new_acc).

        None/'ok' keep acc; 'stop' halts; ('ok'|'stop', acc) thread/halt
        with a new acc; any bare value becomes the new acc."""
        if res is None or res == "ok":
            return False, acc
        if res == "stop":
            return True, acc
        if isinstance(res, tuple) and len(res) == 2:
            verb, new_acc = res
            if verb == "ok":
                return False, new_acc
            if verb == "stop":
                return True, new_acc
        return False, res

    def run_fold(self, name: str, args: tuple, acc: Any) -> Any:
        """Parity: emqx_hooks:run_fold/3 — threads acc; ('stop',acc) halts.

        Async callbacks (exhook) are skipped here — they only take effect
        on the awaited paths (run_fold_async / Broker.publish_async)."""
        for cb in self._chains.get(name, ()):
            if cb.filter and not cb.filter(*args, acc):
                continue
            res = cb.action(*args, acc)
            if inspect.isawaitable(res):
                res.close()
                continue
            stop, acc = self._fold_step(res, acc)
            if stop:
                return acc
        return acc

    async def run_fold_async(self, name: str, args: tuple, acc: Any) -> Any:
        """run_fold that awaits coroutine callbacks (HTTP authn/authz,
        exhook gRPC — the reference blocks the channel process for these;
        here the connection task awaits without blocking the loop)."""
        for cb in self._chains.get(name, ()):
            if cb.filter and not cb.filter(*args, acc):
                continue
            res = cb.action(*args, acc)
            if inspect.isawaitable(res):
                res = await res
            stop, acc = self._fold_step(res, acc)
            if stop:
                return acc
        return acc
