"""Alarm lifecycle with history + hooks.

Parity: apps/emqx/src/emqx_alarm.erl — `activate(Name, Details)` /
`deactivate(Name)` maintain an activated table and a size-capped
deactivated history (emqx_alarm.erl:58-69); transitions run the
`alarm.activated` / `alarm.deactivated` hookpoints and are republished on
`$SYS/brokers/<node>/alarms/...` by the Sys app.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class Alarm:
    name: str
    details: dict = field(default_factory=dict)
    message: str = ""
    activate_at: float = field(default_factory=time.time)
    deactivate_at: Optional[float] = None

    def to_map(self) -> dict:
        return {"name": self.name, "details": self.details,
                "message": self.message,
                "activate_at": int(self.activate_at * 1000),
                "deactivate_at": (None if self.deactivate_at is None
                                  else int(self.deactivate_at * 1000))}


class AlarmManager:
    def __init__(self, hooks=None, size_limit: int = 1000,
                 validity_period: float = 24 * 3600.0):
        self.hooks = hooks
        self.size_limit = size_limit
        self.validity_period = validity_period
        self._activated: dict[str, Alarm] = {}
        self._history: list[Alarm] = []

    def activate(self, name: str, details: Optional[dict] = None,
                 message: str = "") -> bool:
        """Returns False if already active (emqx_alarm returns
        {error, already_existed})."""
        if name in self._activated:
            return False
        a = Alarm(name, dict(details or {}), message or name)
        self._activated[name] = a
        if self.hooks is not None:
            self.hooks.run("alarm.activated", (a.to_map(),))
        return True

    def deactivate(self, name: str) -> bool:
        a = self._activated.pop(name, None)
        if a is None:
            return False
        a.deactivate_at = time.time()
        self._history.append(a)
        while len(self._history) > self.size_limit:
            self._history.pop(0)
        if self.hooks is not None:
            self.hooks.run("alarm.deactivated", (a.to_map(),))
        return True

    def ensure(self, name: str, active: bool,
               details: Optional[dict] = None, message: str = "") -> None:
        """Edge-triggered helper for watermark monitors."""
        if active:
            self.activate(name, details, message)
        else:
            self.deactivate(name)

    def is_active(self, name: str) -> bool:
        return name in self._activated

    def get_alarms(self, which: str = "all") -> list[dict]:
        act = [a.to_map() for a in self._activated.values()]
        if which == "activated":
            return act
        hist = [a.to_map() for a in self._history]
        if which == "deactivated":
            return hist
        return act + hist

    def delete_all_deactivated(self) -> int:
        n = len(self._history)
        self._history.clear()
        return n

    def tick(self) -> None:
        """Expire deactivated history past validity_period."""
        cutoff = time.time() - self.validity_period
        self._history = [a for a in self._history
                         if (a.deactivate_at or 0) >= cutoff]
