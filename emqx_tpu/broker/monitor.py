"""OS/VM resource monitors feeding the alarm manager.

Parity: apps/emqx/src/emqx_os_mon.erl (sysmem/procmem high watermarks →
alarms, emqx_os_mon.erl:28-31), emqx_vm_mon.erl (process-count watermark)
and emqx_vm.erl introspection. Readings come from /proc (Linux) and the
`resource`/`os` modules — no psutil in this build.
"""

from __future__ import annotations

import os
import resource
from typing import Optional


def sys_memory() -> tuple[int, int]:
    """(used_bytes, total_bytes) from /proc/meminfo; (0, 0) if unreadable."""
    try:
        info = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, v = line.partition(":")
                info[k.strip()] = int(v.split()[0]) * 1024
        total = info.get("MemTotal", 0)
        avail = info.get("MemAvailable", info.get("MemFree", 0))
        return total - avail, total
    except OSError:
        return 0, 0


def proc_memory() -> int:
    """This process's RSS in bytes."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError):
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


def cpu_load() -> float:
    """1-minute loadavg normalized by core count (0..1-ish)."""
    try:
        return os.getloadavg()[0] / (os.cpu_count() or 1)
    except OSError:
        return 0.0


class OsMon:
    """Watermark checks run from Node housekeeping (`tick`)."""

    def __init__(self, alarms, conf: Optional[dict] = None):
        c = dict(conf or {})
        self.alarms = alarms
        self.sysmem_high = float(c.get("sysmem_high_watermark", 0.70))
        self.procmem_high = float(c.get("procmem_high_watermark", 0.05))
        self.cpu_high = float(c.get("cpu_high_watermark", 0.80))
        self.cpu_low = float(c.get("cpu_low_watermark", 0.60))

    def tick(self) -> None:
        used, total = sys_memory()
        if total:
            usage = used / total
            self.alarms.ensure(
                "high_system_memory_usage", usage > self.sysmem_high,
                {"usage": round(usage, 4),
                 "high_watermark": self.sysmem_high},
                f"system memory usage {usage:.1%}")
            pusage = proc_memory() / total
            self.alarms.ensure(
                "high_process_memory_usage", pusage > self.procmem_high,
                {"usage": round(pusage, 4),
                 "high_watermark": self.procmem_high},
                f"broker process memory usage {pusage:.1%}")
        load = cpu_load()
        if self.alarms.is_active("high_cpu_usage"):
            if load < self.cpu_low:
                self.alarms.deactivate("high_cpu_usage")
        elif load > self.cpu_high:
            self.alarms.activate("high_cpu_usage",
                                 {"usage": round(load, 4)},
                                 f"cpu load {load:.1%}")

    def info(self) -> dict:
        used, total = sys_memory()
        return {"sysmem_used": used, "sysmem_total": total,
                "procmem": proc_memory(), "cpu_load": cpu_load(),
                "pid": os.getpid()}
