"""Config store: schema-defaulted nested map with zone overrides.

Parity: emqx_config.erl (get/put with zone- and listener-scoped lookups,
emqx_config.erl:63-100) + the mqtt/zone portions of emqx_schema.erl. The
reference's HOCON files become plain dicts here (JSON/TOML-compatible);
`emqx_tpu.utils.hocon` provides a HOCON-lite loader for file parity.
"""

from __future__ import annotations

import copy
from typing import Any, Optional

# schema defaults — the reference's emqx_schema.erl zone/mqtt roots
DEFAULTS: dict = {
    "mqtt": {
        "max_packet_size": 1024 * 1024,
        "max_clientid_len": 65535,
        "max_topic_levels": 128,
        "max_qos_allowed": 2,
        "max_topic_alias": 65535,
        "retain_available": True,
        "wildcard_subscription": True,
        "shared_subscription": True,
        "ignore_loop_deliver": False,
        "strict_mode": False,
        "response_information": "",
        "server_keepalive": 0,           # 0 = accept client value
        "keepalive_backoff": 0.75,
        "max_subscriptions": 0,
        "upgrade_qos": False,
        "max_inflight": 32,
        "retry_interval": 30,
        "max_awaiting_rel": 100,
        "await_rel_timeout": 300,
        "session_expiry_interval": 7200,
        "max_mqueue_len": 1000,
        "mqueue_priorities": {},
        "mqueue_default_priority": "lowest",
        "mqueue_store_qos0": True,
        "use_username_as_clientid": False,
        "peer_cert_as_username": None,
        "idle_timeout": 15,
    },
    "broker": {
        "sys_msg_interval": 60,
        "sys_heartbeat_interval": 30,
        "shared_subscription_strategy": "round_robin",
        "shared_dispatch_ack_enabled": False,
        "route_batch_clean": True,
        "rebuild_threshold": 256,
        "device_min_batch": 4,
        "perf": {"trie_compaction": True},
    },
    "zones": {},                 # zone name -> {mqtt: {...}} overrides
    "listeners": {},             # name -> {type,bind,zone,...}
    "authn": {"enable": False, "chain": []},
    "authz": {"no_match": "allow", "deny_action": "ignore", "sources": []},
    "retainer": {
        "enable": True, "max_retained_messages": 0,
        "max_payload_size": 1024 * 1024, "msg_expiry_interval": 0,
        "msg_clear_interval": 0,
    },
    "delayed": {"enable": True, "max_delayed_messages": 0},
    "rewrite": [],               # [{action,source,re,dest}]
    "topic_metrics": [],         # topic filters to meter
    "event_message": {e: False for e in (
        "client_connected", "client_disconnected", "session_subscribed",
        "session_unsubscribed", "message_delivered", "message_acked",
        "message_dropped")},
    "flapping_detect": {
        "enable": False, "max_count": 15, "window_time": 60,
        "ban_time": 300,
    },
    "force_shutdown": {"max_mqueue_len": 10000, "max_awaiting_rel": 0},
    "rate_limit": {
        "max_conn_rate": 0,          # new connections/sec per listener
        "conn_messages_in": 0,       # packets/sec per connection
        "conn_bytes_in": 0,          # bytes/sec per connection
        "quota_messages_routing": 0,  # publishes/sec per connection
    },
    "alarm": {"size_limit": 1000, "validity_period": 86400},
    "sysmon": {"os": {"sysmem_high_watermark": 0.7,
                      "procmem_high_watermark": 0.05}},
    "rule_engine": {"rules": []},
    "cluster": {"name": "emqx_tpu", "discovery": "manual", "nodes": []},
    "rpc": {"mode": "async", "tcp_client_num": 4},
}


def deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = deep_merge(out[k], v)
        else:
            out[k] = v
    return out


class Config:
    def __init__(self, overrides: Optional[dict] = None):
        self._c = deep_merge(copy.deepcopy(DEFAULTS), overrides or {})

    def get(self, *path, default: Any = None) -> Any:
        """get('mqtt') or get('mqtt', 'max_inflight')."""
        cur: Any = self._c
        for p in path:
            if not isinstance(cur, dict) or p not in cur:
                return default if default is not None else (
                    {} if len(path) == 1 else None)
            cur = cur[p]
        return cur

    def put(self, path: "tuple | list", value: Any) -> None:
        cur = self._c
        for p in path[:-1]:
            cur = cur.setdefault(p, {})
        cur[path[-1]] = value

    def get_zone(self, zone: Optional[str], *path, default: Any = None) -> Any:
        """Zone-scoped lookup falling back to global (emqx_config:get_zone_conf)."""
        if zone:
            zconf = self._c.get("zones", {}).get(zone, {})
            cur: Any = zconf
            found = True
            for p in path:
                if not isinstance(cur, dict) or p not in cur:
                    found = False
                    break
                cur = cur[p]
            if found:
                return cur
        return self.get(*path, default=default)

    def mqtt(self, zone: Optional[str] = None) -> dict:
        base = self.get("mqtt")
        if zone:
            return deep_merge(base, self._c.get("zones", {})
                              .get(zone, {}).get("mqtt", {}))
        return base

    def to_dict(self) -> dict:
        return copy.deepcopy(self._c)
