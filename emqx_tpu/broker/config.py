"""Config store: schema-defaulted nested map with zone overrides.

Parity: emqx_config.erl (get/put with zone- and listener-scoped lookups,
emqx_config.erl:63-100) + the mqtt/zone portions of emqx_schema.erl. The
reference's HOCON files become plain dicts here (JSON/TOML-compatible);
`emqx_tpu.utils.hocon` provides a HOCON-lite loader for file parity.
"""

from __future__ import annotations

import copy
from typing import Any, Optional

# schema defaults — the reference's emqx_schema.erl zone/mqtt roots
DEFAULTS: dict = {
    "mqtt": {
        "max_packet_size": 1024 * 1024,
        "max_clientid_len": 65535,
        "max_topic_levels": 128,
        "max_qos_allowed": 2,
        "max_topic_alias": 65535,
        "retain_available": True,
        "wildcard_subscription": True,
        "shared_subscription": True,
        "ignore_loop_deliver": False,
        "strict_mode": False,
        "response_information": "",
        "server_keepalive": 0,           # 0 = accept client value
        "keepalive_backoff": 0.75,
        "max_subscriptions": 0,
        "upgrade_qos": False,
        "max_inflight": 32,
        "retry_interval": 30,
        "max_awaiting_rel": 100,
        "await_rel_timeout": 300,
        "session_expiry_interval": 7200,
        "max_mqueue_len": 1000,
        "mqueue_priorities": {},
        "mqueue_default_priority": "lowest",
        "mqueue_store_qos0": True,
        "use_username_as_clientid": False,
        "peer_cert_as_username": None,
        "idle_timeout": 15,
    },
    "broker": {
        "sys_msg_interval": 60,
        "sys_heartbeat_interval": 30,
        "shared_subscription_strategy": "round_robin",
        "shared_dispatch_ack_enabled": False,
        "route_batch_clean": True,
        # None = resolve via EMQX_TPU_REBUILD_THRESHOLD, then the
        # built-in 256 (device_engine.resolve_rebuild_threshold); an
        # explicit config value beats both. A baked-in number here
        # would silently shadow the env knob through the defaults merge.
        "rebuild_threshold": None,
        "device_min_batch": 4,
        # None = resolve via EMQX_TPU_DELIVER_LANES, then min(4, cpus)
        # (broker/deliver.resolve_deliver_lanes); 0 restores the inline
        # delivery loop exactly (the ISSUE-5 A/B baseline). A baked-in
        # number here would shadow the env knob through the merge.
        "deliver_lanes": None,
        # max outstanding delivery plans before the batcher's consumer
        # blocks (backpressure up through _inflight to submit/enqueue)
        "deliver_lane_depth": 8,
        # None = resolve via EMQX_TPU_SUPERVISE, then default-on
        # (broker/supervise.resolve_supervise); false restores the
        # pre-ISSUE-6 ad-hoc unwind behavior exactly (no breakers,
        # watchdogs, fault injection or window journal) — the chaos
        # A/B baseline. A baked-in bool here would shadow the env knob
        # through the defaults merge.
        "supervise": None,
        # consecutive faults before a stage's circuit breaker opens
        # (None = EMQX_TPU_BREAKER_THRESHOLD, then 3)
        "supervise_threshold": None,
        # None = resolve via EMQX_TPU_TRACE, then default-on
        # (broker/trace.resolve_trace); false restores the pre-ISSUE-7
        # behavior exactly (no flight recorder, no spans anywhere) —
        # the tracing A/B baseline. A baked-in bool here would shadow
        # the env knob through the defaults merge.
        "trace": None,
        # per-message span sampling 1-in-N (None = EMQX_TPU_TRACE_SAMPLE,
        # then 256; 0 disables message spans, window spans stay on)
        "trace_sample": None,
        # flight-recorder ring capacity, in spans
        "trace_ring": 4096,
        # None = resolve via EMQX_TPU_HBM_LEDGER, then default-on
        # (broker/hbm_ledger.resolve_hbm_ledger); false restores the
        # pre-ISSUE-8 untracked behavior exactly (no ledger object,
        # no `memory` telemetry section) — the A/B baseline. A
        # baked-in bool here would shadow the env knob through the
        # defaults merge.
        "hbm_ledger": None,
        # None = resolve via EMQX_TPU_COLUMNAR_INGRESS, then default-on
        # (broker/connection.resolve_columnar_ingress); false restores
        # the per-packet PUBLISH ingress path exactly — parser.feed,
        # per-packet handle_in, one accept loop, no `ingress` telemetry
        # section (the ISSUE-11 A/B baseline). A baked-in bool here
        # would shadow the env knob through the defaults merge.
        "columnar_ingress": None,
        # sharded SO_REUSEPORT acceptor lanes per TCP listener (None =
        # EMQX_TPU_INGRESS_LANES, then min(4, cpus); must be >= 1;
        # columnar_ingress=0 forces 1)
        "ingress_lanes": None,
        # None = resolve via EMQX_TPU_LATENCY, then default-on
        # (broker/latency.resolve_latency_observatory); false restores
        # the pre-ISSUE-13 observable behavior (no observatory object,
        # no `latency` snapshot section, REST /pipeline/latency 404,
        # bit-identical delivery counts/order) — the A/B baseline; the
        # frame-decode ingress stamp itself stays on (negligible, see
        # the resolver docstring). A baked-in bool here would shadow
        # the env knob through the defaults merge.
        "latency_observatory": None,
        # end-to-end SLO objective in ms for the ingress→routed p99
        # (None = EMQX_TPU_SLO_ROUTE_P99_MS, then 2.0 — the ROADMAP
        # p99 < 2ms PUBLISH→route criterion; must be > 0)
        "slo_route_p99_ms": None,
        # None = resolve via EMQX_TPU_OVERLOAD, then default-on
        # (broker/overload.resolve_overload); false restores the
        # pre-ISSUE-14 behavior exactly — no OverloadGovernor object,
        # no `overload` telemetry section, REST /pipeline/overload
        # 404, bit-identical delivery counts/order (the A/B baseline).
        # A baked-in bool here would shadow the env knob through the
        # defaults merge.
        "overload": None,
        # None = resolve via EMQX_TPU_EXCHANGE, then default-on
        # (parallel/serving.resolve_device_exchange); 0 restores the
        # host gather/merge mesh readback exactly — no exchange aux
        # tables, no exchange program, no pipeline.exchange.* traffic
        # (the ISSUE-15 A/B baseline, bit-identical delivery counts
        # and per-session order). A baked-in bool here would shadow
        # the env knob through the defaults merge.
        "device_exchange": None,
        # stale-pin sentinel threshold in windows (None =
        # EMQX_TPU_PIN_WARN_WINDOWS, then 64; must be > 0): a dispatch
        # handle pinning its snapshot longer than this fires the
        # pipeline.memory.pin_warnings counter + pipeline.pin_stale
        # hook + a stale_pin flight-recorder event
        "pin_warn_windows": None,
        "perf": {"trie_compaction": True},
    },
    "zones": {},                 # zone name -> {mqtt: {...}} overrides
    "listeners": {},             # name -> {type,bind,zone,...}
    "authn": {"enable": False, "chain": []},
    "authz": {"no_match": "allow", "deny_action": "ignore", "sources": []},
    "retainer": {
        "enable": True, "max_retained_messages": 0,
        "max_payload_size": 1024 * 1024, "msg_expiry_interval": 0,
        "msg_clear_interval": 0,
    },
    "delayed": {"enable": True, "max_delayed_messages": 0},
    "rewrite": [],               # [{action,source,re,dest}]
    "topic_metrics": [],         # topic filters to meter
    "event_message": {e: False for e in (
        "client_connected", "client_disconnected", "session_subscribed",
        "session_unsubscribed", "message_delivered", "message_acked",
        "message_dropped")},
    "flapping_detect": {
        "enable": False, "max_count": 15, "window_time": 60,
        "ban_time": 300,
    },
    "force_shutdown": {"max_mqueue_len": 10000, "max_awaiting_rel": 0},
    "conn_congestion": {"enable_alarm": False,
                        "min_alarm_sustain_duration": 60},
    "rate_limit": {
        "max_conn_rate": 0,          # new connections/sec per listener
        "conn_messages_in": 0,       # packets/sec per connection
        "conn_bytes_in": 0,          # bytes/sec per connection
        "quota_messages_routing": 0,  # publishes/sec per connection
    },
    "alarm": {"size_limit": 1000, "validity_period": 86400},
    "log": {"enable": False, "level": "warning", "formatter": "text"},
    "sysmon": {"os": {"sysmem_high_watermark": 0.7,
                      "procmem_high_watermark": 0.05}},
    "rule_engine": {"rules": []},
    "cluster": {"name": "emqx_tpu", "discovery": "manual", "nodes": []},
    "rpc": {"mode": "async", "tcp_client_num": 4},
}


def deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def check_schema(conf: dict, schema: Optional[dict] = None,
                 path: str = "") -> list[str]:
    """Type-check a loaded config against the DEFAULTS tree
    (the hocon_schema:check_plain analog). Duration strings ("30s") and
    size strings ("1MB") are coerced in place where the schema default is
    numeric; unknown keys are allowed (feature apps read their own
    sections). Returns a list of error strings."""
    from emqx_tpu.utils.hocon import parse_duration, parse_size
    schema = DEFAULTS if schema is None else schema
    errors: list[str] = []
    for key, val in list(conf.items()):
        here = f"{path}.{key}" if path else key
        if key not in schema:
            continue
        want = schema[key]
        if isinstance(want, dict) and path not in ("zones", "listeners"):
            if not isinstance(val, dict):
                errors.append(f"{here}: expected object, got "
                              f"{type(val).__name__}")
            elif here not in ("zones", "listeners", "mqueue_priorities"):
                errors.extend(check_schema(val, want, here))
            continue
        if isinstance(want, bool):
            if not isinstance(val, bool):
                errors.append(f"{here}: expected bool, got {val!r}")
            continue
        if isinstance(want, (int, float)) and not isinstance(want, bool):
            if isinstance(val, str):
                coerced = parse_duration(val)
                if coerced is None:
                    coerced = parse_size(val)
                if coerced is None:
                    errors.append(f"{here}: expected number, got {val!r}")
                else:
                    conf[key] = type(want)(coerced) \
                        if isinstance(want, int) and \
                        float(coerced).is_integer() else coerced
            elif isinstance(val, bool) or \
                    not isinstance(val, (int, float)):
                errors.append(f"{here}: expected number, got {val!r}")
            continue
        if isinstance(want, str) and val is not None and \
                not isinstance(val, str):
            errors.append(f"{here}: expected string, got {val!r}")
        if isinstance(want, list) and not isinstance(val, list):
            errors.append(f"{here}: expected array, got {val!r}")
    return errors


class Config:
    def __init__(self, overrides: Optional[dict] = None,
                 override_file: Optional[str] = None):
        self._c = deep_merge(copy.deepcopy(DEFAULTS), overrides or {})
        self.override_file = override_file
        self._overrides: dict = {}
        self._handlers: list[tuple[tuple, Any]] = []

    @classmethod
    def load_file(cls, path: str,
                  override_file: Optional[str] = None) -> "Config":
        """Boot from an etc/emqx.conf-style HOCON file, applying the
        persisted runtime-override file on top (emqx_config:init_load).
        Raises ValueError on schema type errors."""
        import os

        from emqx_tpu.utils import hocon
        conf = hocon.load(path)
        if override_file is None:
            override_file = os.path.join(
                os.path.dirname(path) or ".", "emqx_override.conf")
        persisted: dict = {}
        if os.path.exists(override_file):
            persisted = hocon.load(override_file)
            conf = deep_merge(conf, persisted)
        errors = check_schema(conf)
        if errors:
            raise ValueError("config schema errors: " + "; ".join(errors))
        out = cls(conf, override_file=override_file)
        # seed with what is already on disk so the next update() rewrite
        # does not discard overrides persisted by previous runs
        out._overrides = persisted
        return out

    # ---- runtime updates (emqx_config_handler) ----
    def register_handler(self, path: "tuple | list", handler) -> None:
        """handler(path, new_value, config) called before the update is
        applied for any update at or under `path`; raising vetoes it."""
        self._handlers.append((tuple(path), handler))

    def update(self, path: "tuple | list", value: Any,
               persist: bool = True) -> None:
        """Apply a runtime config update through registered handlers and
        persist it to the override file (emqx_config_handler:update +
        save_to_override_conf)."""
        path = tuple(path)
        for prefix, handler in self._handlers:
            if path[:len(prefix)] == prefix or prefix[:len(path)] == path:
                handler(path, value, self)
        self.put(path, value)
        cur = self._overrides
        for p in path[:-1]:
            cur = cur.setdefault(p, {})
        cur[path[-1]] = value
        if persist and self.override_file:
            from emqx_tpu.utils import hocon
            with open(self.override_file, "w", encoding="utf-8") as f:
                f.write(hocon.dumps(self._overrides))

    def get(self, *path, default: Any = None) -> Any:
        """get('mqtt') or get('mqtt', 'max_inflight')."""
        cur: Any = self._c
        for p in path:
            if not isinstance(cur, dict) or p not in cur:
                return default if default is not None else (
                    {} if len(path) == 1 else None)
            cur = cur[p]
        return cur

    def put(self, path: "tuple | list", value: Any) -> None:
        cur = self._c
        for p in path[:-1]:
            cur = cur.setdefault(p, {})
        cur[path[-1]] = value

    def get_zone(self, zone: Optional[str], *path, default: Any = None) -> Any:
        """Zone-scoped lookup falling back to global (emqx_config:get_zone_conf)."""
        if zone:
            zconf = self._c.get("zones", {}).get(zone, {})
            cur: Any = zconf
            found = True
            for p in path:
                if not isinstance(cur, dict) or p not in cur:
                    found = False
                    break
                cur = cur[p]
            if found:
                return cur
        return self.get(*path, default=default)

    def mqtt(self, zone: Optional[str] = None) -> dict:
        base = self.get("mqtt")
        if zone:
            return deep_merge(base, self._c.get("zones", {})
                              .get(zone, {}).get("mqtt", {}))
        return base

    def to_dict(self) -> dict:
        return copy.deepcopy(self._c)
