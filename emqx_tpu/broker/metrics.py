"""Broker metrics: named lock-free counters + periodic stats gauges.

Parity: emqx_metrics.erl (counters array behind persistent_term,
packets.* / messages.* / bytes.* / delivery.* names, :241-258) and
emqx_stats.erl (periodic gauge table fed by stats_funs).

Python ints under the GIL give the same practical property the reference
gets from `counters:add` — wait-free increments on the hot path.
"""

from __future__ import annotations

import itertools
import math
import threading
import time
from typing import Callable, Optional

# canonical metric names (emqx_metrics.erl defines ~90; same families here)
BYTES_METRICS = ["bytes.received", "bytes.sent"]
PACKET_METRICS = [
    "packets.received", "packets.sent",
    "packets.connect.received", "packets.connack.sent",
    "packets.connack.error", "packets.connack.auth_error",
    "packets.publish.received", "packets.publish.sent",
    "packets.publish.error", "packets.publish.auth_error",
    "packets.publish.dropped",
    "packets.puback.received", "packets.puback.sent",
    "packets.puback.missed",
    "packets.pubrec.received", "packets.pubrec.sent",
    "packets.pubrec.missed",
    "packets.pubrel.received", "packets.pubrel.sent",
    "packets.pubrel.missed",
    "packets.pubcomp.received", "packets.pubcomp.sent",
    "packets.pubcomp.missed",
    "packets.subscribe.received", "packets.suback.sent",
    "packets.subscribe.error", "packets.subscribe.auth_error",
    "packets.unsubscribe.received", "packets.unsuback.sent",
    "packets.unsubscribe.error",
    "packets.pingreq.received", "packets.pingresp.sent",
    "packets.disconnect.received", "packets.disconnect.sent",
    "packets.auth.received", "packets.auth.sent",
]
MESSAGE_METRICS = [
    "messages.received", "messages.sent",
    "messages.qos0.received", "messages.qos0.sent",
    "messages.qos1.received", "messages.qos1.sent",
    "messages.qos2.received", "messages.qos2.sent",
    "messages.publish", "messages.dropped",
    "messages.dropped.await_pubrel_timeout",
    "messages.dropped.no_subscribers",
    "messages.forward", "messages.delayed", "messages.delivered",
    "messages.acked", "messages.retained",
]
DELIVERY_METRICS = [
    "delivery.dropped", "delivery.dropped.no_local",
    "delivery.dropped.too_large", "delivery.dropped.qos0_msg",
    "delivery.dropped.queue_full", "delivery.dropped.expired",
]
CLIENT_METRICS = [
    "client.connect", "client.connack", "client.connected",
    "client.authenticate", "client.auth.anonymous", "client.authorize",
    "client.subscribe", "client.unsubscribe", "client.disconnected",
]
SESSION_METRICS = [
    "session.created", "session.resumed", "session.takenover",
    "session.discarded", "session.terminated",
]
AUTHZ_METRICS = ["authorization.allow", "authorization.deny",
                 "authorization.cache_hit"]
ALL_METRICS = (BYTES_METRICS + PACKET_METRICS + MESSAGE_METRICS +
               DELIVERY_METRICS + CLIENT_METRICS + SESSION_METRICS +
               AUTHZ_METRICS)


class Histogram:
    """Fixed log2-bucket histogram with wait-free increments.

    Bucket bounds are `lo * 2**(i/substeps)` for i in [0, n_buckets); an
    observation lands in the first bucket whose bound is >= the value
    (values <= lo — including 0 — land in bucket 0; values beyond the
    last bound land in the overflow bucket, visible only as the +Inf
    series). Increments are a frexp + two int adds under the GIL — the
    same practical wait-free property as the plain counters
    (emqx_metrics' counters:add analog; the bucket layout mirrors
    prometheus.erl's default log-scale histogram support).

    ``substeps`` (ISSUE 13 satellite) is the sub-millisecond fine mode:
    the default 1 keeps the classic one-bucket-per-octave ladder, while
    substeps=4 interleaves quarter-octave bounds (step 2^(1/4) ≈ 1.19x)
    so a µs-floored ladder can resolve a 2ms SLO objective — the plain
    ladder's neighbouring bounds sit at 1.024ms and 2.048ms, a factor-2
    ambiguity exactly where the north-star criterion lives. Percentiles
    then over-estimate by at most one sub-step instead of one octave.
    """

    __slots__ = ("name", "unit", "lo", "substeps", "bounds", "counts",
                 "sum", "count")

    def __init__(self, name: str, *, lo: float = 1e-6,
                 n_buckets: int = 28, unit: str = "seconds",
                 substeps: int = 1):
        self.name = name
        self.unit = unit
        self.lo = lo
        self.substeps = max(1, int(substeps))
        if self.substeps == 1:
            self.bounds = [lo * (1 << i) for i in range(n_buckets)]
        else:
            self.bounds = [lo * 2 ** (i / self.substeps)
                           for i in range(n_buckets)]
        self.counts = [0] * (n_buckets + 1)    # [-1] is overflow (+Inf)
        self.sum = 0.0
        self.count = 0

    def _index(self, v: float) -> int:
        if v <= self.lo:
            return 0
        if self.substeps == 1:
            m, e = math.frexp(v / self.lo)  # v/lo = m * 2^e, m in [0.5,1)
            i = e - 1 if m == 0.5 else e    # smallest i with v <= lo*2^i
            return min(i, len(self.bounds))  # beyond last bound: overflow
        # fine mode: log2 gives the neighbourhood, a bounded forward
        # probe settles exact-bound float edges (never more than a
        # couple of steps — the exactness of frexp without trusting
        # log2 rounding at bucket boundaries)
        i = max(0, int(self.substeps * math.log2(v / self.lo)) - 1)
        b = self.bounds
        n = len(b)
        if i > n:                           # far beyond the last bound
            return n
        while i < n and b[i] < v:
            i += 1
        return i                            # i == n -> overflow

    def observe(self, v: float) -> None:
        self.counts[self._index(v)] += 1
        self.sum += v
        self.count += 1

    def cumulative(self) -> list[tuple[float, int]]:
        """Prometheus-shaped (le, cumulative_count) pairs; the final
        entry is (+Inf, total count)."""
        out = []
        acc = 0
        for b, c in zip(self.bounds, self.counts):
            acc += c
            out.append((b, acc))
        out.append((math.inf, acc + self.counts[-1]))
        return out

    def percentile(self, p: float) -> float:
        """Upper bucket bound at quantile p (0..1) — an over-estimate by
        at most one bucket step (one octave at substeps=1, one
        quarter-octave ≈ 1.19x in the substeps=4 fine mode). Overflow
        observations clamp to twice the last finite bound (keeps
        snapshots JSON-finite)."""
        if self.count == 0:
            return 0.0
        want = p * self.count
        acc = 0
        for b, c in zip(self.bounds, self.counts):
            acc += c
            if acc >= want:
                return b
        return 2 * self.bounds[-1]

    def snapshot(self) -> dict:
        n = self.count
        return {
            "count": n,
            "sum": round(self.sum, 6),
            "mean": round(self.sum / n, 9) if n else 0.0,
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
        }


class Metrics:
    def __init__(self):
        self._c: dict[str, int] = {name: 0 for name in ALL_METRICS}
        self._h: dict[str, Histogram] = {}

    def hist(self, name: str, **kw) -> Histogram:
        """Get-or-create a named histogram (exported by every exporter
        alongside the counters)."""
        h = self._h.get(name)
        if h is None:
            h = self._h[name] = Histogram(name, **kw)
        return h

    def histograms(self) -> dict[str, Histogram]:
        return dict(self._h)

    def inc(self, name: str, n: int = 1) -> None:
        try:
            self._c[name] += n
        except KeyError:
            self._c[name] = n

    def val(self, name: str) -> int:
        return self._c.get(name, 0)

    def all(self) -> dict[str, int]:
        return dict(self._c)

    # packet-type helpers (emqx_metrics:inc_recv/inc_sent)
    def inc_recv(self, type_name: str, nbytes: int = 0) -> None:
        self.inc("packets.received")
        self.inc(f"packets.{type_name.lower()}.received")
        if nbytes:
            self.inc("bytes.received", nbytes)

    def inc_sent(self, type_name: str, nbytes: int = 0) -> None:
        self.inc("packets.sent")
        self.inc(f"packets.{type_name.lower()}.sent")
        if nbytes:
            self.inc("bytes.sent", nbytes)

    def inc_msg_recv(self, qos: int) -> None:
        self.inc("messages.received")
        self.inc(f"messages.qos{min(qos,2)}.received")

    def inc_msg_sent(self, qos: int) -> None:
        self.inc("messages.sent")
        self.inc(f"messages.qos{min(qos,2)}.sent")


class Stats:
    """Gauge table + registered stats functions sampled periodically
    (emqx_stats.erl; emqx_broker:stats_fun/0 emqx_broker.erl:403-412)."""

    GAUGES = [
        "connections.count", "connections.max",
        "live_connections.count", "live_connections.max",
        "sessions.count", "sessions.max",
        "topics.count", "topics.max",
        "subscribers.count", "subscribers.max",
        "subscriptions.count", "subscriptions.max",
        "subscriptions.shared.count", "subscriptions.shared.max",
        "retained.count", "retained.max",
        "delayed.count", "delayed.max",
    ]

    def __init__(self):
        self._g: dict[str, int] = {n: 0 for n in self.GAUGES}
        self._funs: list[Callable[["Stats"], None]] = []

    def setstat(self, name: str, val: int, max_name: Optional[str] = None) -> None:
        self._g[name] = val
        if max_name:
            self._g[max_name] = max(self._g.get(max_name, 0), val)

    def getstat(self, name: str) -> int:
        return self._g.get(name, 0)

    def register_stats_fun(self, fn: Callable[["Stats"], None]) -> None:
        self._funs.append(fn)

    def sample(self) -> dict[str, int]:
        for fn in list(self._funs):
            fn(self)
        return dict(self._g)
