"""Device-path pipeline telemetry: stage spans, occupancy, compiles.

The observability substrate for the batched PUBLISH pipeline (the
reference's layer-0 emqx_metrics/emqx_stats/emqx_tracer triplet, grown a
dimension: per-STAGE latency attribution instead of counters alone).
`PipelineTelemetry` owns log2-bucket histograms (broker.metrics.Histogram)
for every pipeline stage —

    enqueue      oldest-message wait in the submit queue before its batch
                 forms (broker/batcher._produce)
    batch_form   message.publish hook fold + live-filter per batch
    dispatch     the jitted route step, executor-thread wall time (on a
                 dispatch relay this is the HTTP round trip; match +
                 fan-out + shared picks all run inside it on device)
    dispatch_cached  same span for deduplicated / match-cache-backed
                 dispatches (route_*_cached) — the cached-vs-uncached
                 match latency split falls straight out of comparing the
                 two histograms
    materialize  device->host readbacks
    deliver      RouteResult consumption into session deliveries (with
                 the ISSUE-5 delivery lanes active this is the PLAN
                 construction span; the delivery walk itself lands in
                 the per-lane deliver_lane{i} histograms below)
    deliver_lane{i}  one delivery-lane item (slice or barrier) on lane i
    host_route   host-path match + route span for host-routed batches
    host_match   per-message host trie match latency (sampled 1-in-32 —
                 the host-side decomposition of dispatch's match stage)
    total        oldest-enqueue -> batch completion (the reservoir
                 lat_percentiles() draws from, now exportable)

— plus batch-occupancy histograms per device shape class (fill fraction
of the padded (W, Bp) program each dispatch actually used) and JIT
compile/recompile accounting fed by jax.monitoring: every jit-cache miss
(jaxpr trace) under an instrumented span counts as one compile event,
attributed to the (W, Bp) class that triggered it, with trace + lowering
+ backend-compile durations accumulated.

Everything lands in the node's Metrics registry, so the Prometheus,
StatsD and $SYS exporters pick the histograms up with zero coupling to
this module; `snapshot()` is the JSON schema shared by
`GET /api/v5/pipeline/stats`, bench.py's embedded telemetry and
`tools/profile_step.py --telemetry-out`.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional

from emqx_tpu.broker.metrics import Metrics

SCHEMA = "emqx_tpu.pipeline/v1"

STAGES = ("enqueue", "batch_form", "dispatch", "dispatch_cached",
          "materialize", "deliver", "host_route", "host_match", "total")

# stage histograms: 1µs floor, quarter-octave fine ladder (ISSUE 13
# satellite: the watchdog deadlines derive from these histograms' p99,
# and the plain octave ladder could not resolve the 2ms SLO objective
# — neighbouring bounds at 1.024/2.048ms). 112 quarter-octave buckets
# cover the same 1µs..~2e2s range the old 28-octave ladder did; the
# exported family names (pipeline.stage.*) are unchanged.
_STAGE_LO, _STAGE_BUCKETS, _STAGE_SUBSTEPS = 1e-6, 112, 4
# occupancy histograms: fill fraction 1/256 .. 1.0 in 9 log2 buckets
_OCC_LO, _OCC_BUCKETS = 1.0 / 256, 9

# ---- process-wide jax.monitoring listener --------------------------------
# ONE listener per process (jax.monitoring has no deregistration). A
# compile event is attributed to the instance whose compile_context() is
# active on the FIRING thread — jit traces/compiles run on the thread
# that called the jitted function, so the dispatch/warm spans in the
# engines scope attribution exactly; events outside any span are ignored
# (they belong to no pipeline).
_tls = threading.local()
_listener_installed = False
_install_lock = threading.Lock()

_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_COMPILE_EVENTS = (
    _TRACE_EVENT,
    "/jax/core/compile/jaxpr_to_mlir_module_duration",
    "/jax/core/compile/backend_compile_duration",
)


def _on_jax_event(name: str, dur: float, **_kw) -> None:
    if name not in _COMPILE_EVENTS:
        return
    # per-thread compile sequence: jit compiles run on the calling
    # thread, so this is the exact "did MY call compile?" signal the
    # ISSUE-8 cost registry confirms cache-size deltas against (a
    # cache grown by ANOTHER thread's concurrent compile must not be
    # attributed to this thread's class label)
    _tls.compile_seq = getattr(_tls, "compile_seq", 0) + 1
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return
    tele, shape = ctx
    tele._note_compile_event(shape, dur, is_trace=(name == _TRACE_EVENT))


def thread_compile_seq() -> "int | None":
    """Monotonic count of jax compile events observed on THIS thread,
    or None while no listener is installed (no confirmation signal
    available — callers fall back to cache-size-delta-only)."""
    if not _listener_installed:
        return None
    return getattr(_tls, "compile_seq", 0)


def _install_listener() -> bool:
    global _listener_installed
    with _install_lock:
        if _listener_installed:
            return True
        try:
            import jax.monitoring as M
            M.register_event_duration_secs_listener(_on_jax_event)
        except Exception:  # noqa: BLE001 — no jax / ancient jax: no-op
            return False
        _listener_installed = True
        return True


class PipelineTelemetry:
    """Per-node (or standalone) pipeline telemetry registry.

    Node wires one up as `node.pipeline_telemetry`; tools/profile_step
    builds a standalone one around its own Metrics. All hot-path entry
    points are plain histogram observes — no locks, no allocation beyond
    the first observation of a new occupancy class.
    """

    def __init__(self, metrics: Optional[Metrics] = None, *,
                 hooks=None, slow_batch_s: Optional[float] = None,
                 track_compiles: bool = True):
        self.metrics = metrics if metrics is not None else Metrics()
        self.hooks = hooks
        # live rebuild/overlay gauges provider (set by the device
        # engine): journal depth, overlay size etc. — point-in-time
        # values the counter registry can't carry. Best-effort: snapshot
        # must keep working on nodes without a device engine.
        self.rebuild_state_fn = None
        # live delivery-lane gauges provider (set by the node when the
        # ISSUE-5 DeliveryLanePool exists): lane depth, live plans
        self.deliver_state_fn = None
        # live supervision gauges provider (set by the node when the
        # ISSUE-6 PipelineSupervisor exists): breaker states, ladder
        # rung, window-journal depth, armed fault clauses
        self.supervise_state_fn = None
        # the window-causal flight recorder (ISSUE 7; set by the node
        # when broker.trace / EMQX_TPU_TRACE is on): snapshot() derives
        # the `trace` section — ring state + overlap/bubble analysis —
        # from it. None restores the pre-ISSUE-7 schema exactly.
        self.recorder = None
        # the HBM ledger (ISSUE 8; set by the node when
        # broker.hbm_ledger / EMQX_TPU_HBM_LEDGER is on): snapshot()
        # derives the `memory` section — per-category device bytes,
        # pin ages, backend memory_stats cross-check — from it. None
        # restores the pre-ISSUE-8 schema exactly.
        self.ledger = None
        # the overload governor's live gauges (ISSUE 14; set by the
        # node when broker.overload / EMQX_TPU_OVERLOAD is on):
        # snapshot() derives the `overload` section — grade, armed
        # shed actions, last signal readings, hysteresis counters —
        # from it. None restores the pre-ISSUE-14 schema exactly.
        self.overload_state_fn = None
        # the latency SLO observatory (ISSUE 13; set by the node when
        # broker.latency_observatory / EMQX_TPU_LATENCY is on):
        # snapshot() derives the `latency` section — per-(qos, path)
        # ingress→routed / ingress→delivered percentiles, SLO burn
        # rates, breach exemplars — from it. None restores the
        # pre-ISSUE-13 schema exactly.
        self.observatory = None
        # slow-batch watch: a total span beyond this fires the
        # `batch.slow` hook (apps/tracer writes the log line) and counts
        # pipeline.slow_batches. None disables.
        self.slow_batch_s = slow_batch_s
        self.started_at = time.time()
        self._compiles_lock = threading.Lock()
        self.compiles = 0            # jit-cache misses (trace events)
        self.compile_s = 0.0         # trace + lowering + backend time
        self.compiles_by_shape: dict[str, dict] = {}
        if track_compiles:
            _install_listener()
        for s in STAGES:
            self._stage_hist(s)

    # ---- stage spans -----------------------------------------------------
    def _stage_hist(self, stage: str):
        return self.metrics.hist(f"pipeline.stage.{stage}.seconds",
                                 lo=_STAGE_LO, n_buckets=_STAGE_BUCKETS,
                                 substeps=_STAGE_SUBSTEPS)

    def observe_stage(self, stage: str, seconds: float) -> None:
        self._stage_hist(stage).observe(seconds)

    @contextlib.contextmanager
    def stage(self, stage: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe_stage(stage, time.perf_counter() - t0)

    def record_total(self, seconds: float, **meta) -> None:
        """The end-of-batch span: feeds the `total` histogram and the
        slow-batch watch (threshold -> batch.slow hook + counter)."""
        self.observe_stage("total", seconds)
        if self.slow_batch_s is not None and seconds > self.slow_batch_s:
            self.metrics.inc("pipeline.slow_batches")
            if self.hooks is not None:
                self.hooks.run("batch.slow",
                               (dict(meta, duration_ms=round(
                                   seconds * 1000, 3)),))

    # ---- rebuild stages (ISSUE 4) ---------------------------------------
    # capture/build/warm/swap spans of the snapshot rebuild machinery
    # plus delta_apply (overlay refresh) — rebuilds used to be invisible
    # beyond a bare routing.device.rebuilds counter; these histograms
    # ride the registry so all four exporters carry them, and snapshot()
    # derives the `rebuild` section from them.
    REBUILD_STAGES = ("capture", "build", "warm", "swap", "delta_apply")

    def observe_rebuild(self, stage: str, seconds: float) -> None:
        self.metrics.hist(f"pipeline.rebuild.{stage}.seconds",
                          lo=_STAGE_LO, n_buckets=_STAGE_BUCKETS,
                          substeps=_STAGE_SUBSTEPS).observe(seconds)

    # ---- columnar ingress (ISSUE 11) ------------------------------------
    def record_ingress_burst(self, rows: int) -> None:
        """One columnar-decoded PublishBurst of `rows` PUBLISH frames:
        feeds the burst-size histogram (pipeline.ingress.burst). The
        companion counters — pipeline.ingress.bursts / rows /
        fallback_frames / bytes and the per-lane
        pipeline.ingress.lane{i}.accepted family — are incremented at
        the connection read loop; everything rides the shared registry,
        so all four exporters carry them with zero coupling here."""
        self.metrics.hist("pipeline.ingress.burst",
                          lo=1.0, n_buckets=16,
                          unit="rows").observe(rows)

    # ---- occupancy -------------------------------------------------------
    def record_occupancy(self, cls: str, fill: float) -> None:
        """Fill fraction of one dispatched batch within its padded shape
        class (`b{Bp}` for single batches, `w{Wp}` for fused-window
        width, `host` for host-routed batches vs max_batch)."""
        self.metrics.hist(f"pipeline.occupancy.{cls}",
                          lo=_OCC_LO, n_buckets=_OCC_BUCKETS,
                          unit="ratio").observe(fill)

    # ---- dedup / match-cache (device-path reuse layers) ------------------
    def record_dedup(self, lanes: int, unique: int) -> None:
        """One dispatch window's unique-topic compaction: `lanes` real
        (non-padding) message lanes collapsed onto `unique` distinct
        encoded topics. Feeds the dedup-ratio histogram (1 - Bu/B, the
        fraction of match work the window skipped) plus running lane /
        unique counters so exporters can derive the aggregate ratio."""
        self.metrics.inc("routing.dedup.lanes", lanes)
        self.metrics.inc("routing.dedup.unique", unique)
        if lanes:
            self.metrics.hist("pipeline.dedup.ratio",
                              lo=_OCC_LO, n_buckets=_OCC_BUCKETS,
                              unit="ratio").observe(1.0 - unique / lanes)

    # ---- routing decisions ----------------------------------------------
    def record_decision(self, path: str, n: int = 1) -> None:
        """Formed batches' device/host routing outcome
        (`device` | `host` — the finer-grained reasons keep their
        existing routing.device.* counters)."""
        self.metrics.inc(f"pipeline.batches.{path}", n)

    # ---- compile accounting ---------------------------------------------
    @contextlib.contextmanager
    def compile_context(self, shape: str):
        """Scope jit compile attribution to `shape` (e.g. "W8xB1024") on
        the current thread. Every jit-cache miss inside the span counts
        as one compile event for that shape."""
        prev = getattr(_tls, "ctx", None)
        _tls.ctx = (self, shape)
        try:
            yield
        finally:
            _tls.ctx = prev

    def _note_compile_event(self, shape: str, dur: float,
                            is_trace: bool) -> None:
        with self._compiles_lock:
            row = self.compiles_by_shape.setdefault(
                shape, {"count": 0, "total_s": 0.0})
            row["total_s"] += dur
            self.compile_s += dur
            if is_trace:
                row["count"] += 1
                self.compiles += 1
        if is_trace:
            self.metrics.inc("pipeline.jit.compiles")
        self.metrics.hist("pipeline.jit.compile.seconds",
                          lo=_STAGE_LO, n_buckets=_STAGE_BUCKETS,
                          substeps=_STAGE_SUBSTEPS).observe(dur)

    # ---- the `overload` section (ISSUE 14) ------------------------------
    def overload_section(self) -> dict:
        """The standalone `overload` document: shed/reject counters +
        the governor's live state. Shared by snapshot() and
        `GET /api/v5/pipeline/overload` — the endpoint is polled
        exactly when the broker is at capacity, so it must not pay
        the full-snapshot percentile walk per request."""
        overload: dict = {}
        for k in ("sheds", "grade_changes", "qos0_shed",
                  "connects_rejected", "accepts_paused",
                  "disconnects", "retained_deferred",
                  "stuck_polls", "rebreaches"):
            v = self.metrics.val(f"pipeline.overload.{k}")
            if v:
                overload[k] = v
        by_action = {k.rsplit(".", 1)[1]: v
                     for k, v in self.metrics.all().items()
                     if k.startswith("pipeline.overload.actions.")}
        if by_action:
            overload["actions_armed_counts"] = by_action
        if self.overload_state_fn is not None:
            try:
                overload["state"] = self.overload_state_fn()
            except Exception:  # noqa: BLE001 — telemetry never raises
                pass
        return overload

    # ---- snapshot (the shared schema) -----------------------------------
    def snapshot(self, full: bool = False) -> dict:
        """The one pipeline-telemetry JSON schema: served by
        GET /api/v5/pipeline/stats, embedded in bench.py's success and
        error JSON, dumped by tools/profile_step.py --telemetry-out and
        published (piecewise) on $SYS/brokers/<node>/pipeline/#.

        ``full=True`` emits EVERY section of the schema (rebuild /
        deliver / supervise / readback / match_cache / dedup / trace),
        empty when the layer has no traffic — consumers that diff
        snapshots across rounds (profile_step, offline tooling) get a
        stable shape instead of sections popping in and out."""
        stages = {}
        occupancy = {}
        prefix_s, prefix_o = "pipeline.stage.", "pipeline.occupancy."
        for name, h in self.metrics.histograms().items():
            if name.startswith(prefix_s):
                if not h.count:
                    continue
                snap = h.snapshot()
                stages[name[len(prefix_s):].removesuffix(".seconds")] = {
                    "count": snap["count"],
                    "sum_ms": round(snap["sum"] * 1000, 3),
                    "mean_ms": round(snap["mean"] * 1000, 4),
                    "p50_ms": round(snap["p50"] * 1000, 4),
                    "p95_ms": round(snap["p95"] * 1000, 4),
                    "p99_ms": round(snap["p99"] * 1000, 4),
                }
            elif name.startswith(prefix_o) and h.count:
                snap = h.snapshot()
                occupancy[name[len(prefix_o):]] = {
                    "count": snap["count"],
                    "mean_fill": round(snap["mean"], 4),
                    "p50_fill": round(min(1.0, snap["p50"]), 4),
                }
        with self._compiles_lock:
            by_shape = {k: {"count": v["count"],
                            "total_s": round(v["total_s"], 4)}
                        for k, v in self.compiles_by_shape.items()}
            compiles = {"count": self.compiles,
                        "total_s": round(self.compile_s, 4),
                        "by_shape": by_shape}
        decisions = {
            k.rsplit(".", 1)[1]: v
            for k, v in self.metrics.all().items()
            if k.startswith("pipeline.batches.")}
        for extra in ("routing.device.bypassed", "routing.device.cold_class",
                      "routing.device.cold_cached_class",
                      "routing.device.cold_compact_class",
                      "routing.device.cached_windows",
                      "routing.device.compact_overflow",
                      "routing.device.host_fallback",
                      "routing.device.dispatch_failed",
                      "pipeline.slow_batches"):
            v = self.metrics.val(extra)
            if v:
                decisions[extra] = v
        # device-match reuse layers: cross-batch cache + in-window dedup
        # (broker/device_engine.py; counters land in the shared Metrics
        # registry, so all four exporters already carry them — this
        # section is the derived view benches and the API embed)
        cache = {}
        for k in ("hits", "misses", "inserts", "evictions",
                  "invalidations", "invalidated_rows"):
            v = self.metrics.val(f"match_cache.{k}")
            if v:
                cache[k] = v
        lookups = cache.get("hits", 0) + cache.get("misses", 0)
        if lookups:
            cache["hit_rate"] = round(cache.get("hits", 0) / lookups, 4)
        dedup = {}
        lanes = self.metrics.val("routing.dedup.lanes")
        if lanes:
            uniq = self.metrics.val("routing.dedup.unique")
            dedup = {"lanes": lanes, "unique": uniq,
                     "ratio": round(1.0 - uniq / lanes, 4)}
        # device→host readback accounting (ISSUE 3): actual transferred
        # bytes per path. `reduction` compares the two paths' measured
        # per-window byte costs — the compaction win the acceptance
        # criteria grade, derived here once for every exporter/bench
        readback = {}
        for k in ("bytes.dense", "bytes.compact",
                  "windows.dense", "windows.compact"):
            v = self.metrics.val(f"pipeline.readback.{k}")
            if v:
                readback[k.replace(".", "_")] = v
        cw, dw = readback.get("windows_compact"), \
            readback.get("windows_dense")
        if cw:
            readback["bytes_per_window_compact"] = round(
                readback.get("bytes_compact", 0) / cw)
        if dw:
            readback["bytes_per_window_dense"] = round(
                readback.get("bytes_dense", 0) / dw)
        if cw and dw and readback["bytes_per_window_compact"]:
            readback["reduction"] = round(
                readback["bytes_per_window_dense"]
                / readback["bytes_per_window_compact"], 2)
        # device-to-device exchange stage (ISSUE 15): windows served
        # from exchanged per-dest plans vs the gather fallbacks (by
        # reason), ring rounds, interconnect bytes, and host-landed
        # bytes — `reduction` compares the exchange path's measured
        # per-window landed bytes against the gather path's, the win
        # the ISSUE-15 acceptance criterion grades, derived here once
        # for every exporter/bench. Absent without exchange traffic
        # (broker.device_exchange=0 leaves it exactly pre-ISSUE-15).
        exchange = {}
        for k in ("windows", "rounds", "bytes_exchanged",
                  "host_landed_bytes", "overflow", "cold_class",
                  "probe_bytes"):
            v = self.metrics.val(f"pipeline.exchange.{k}")
            if v:
                exchange[k] = v
        fb = {k.rsplit(".", 1)[1]: v
              for k, v in self.metrics.all().items()
              if k.startswith("pipeline.exchange.fallback.") and v}
        if fb:
            exchange["fallbacks"] = fb
        xw = exchange.get("windows")
        if xw:
            exchange["host_landed_per_window"] = round(
                exchange.get("host_landed_bytes", 0) / xw)
        # deliberately NO derived reduction ratio here: in a default-on
        # run the only gather windows are the exchange's own fallbacks
        # (overflow/unclean — systematically the largest windows), so a
        # same-snapshot ratio would inflate the win. The honest number
        # is the same-traffic A/B twin in tools/sharded_bench.py.
        # rebuild machinery (ISSUE 4): stage spans + counts + compaction
        # reasons + the engine's live gauges (journal depth, overlay
        # size) — the section that makes rebuilds visible beyond the
        # bare routing.device.rebuilds counter
        rebuild = {}
        rb_stages = {}
        prefix_r = "pipeline.rebuild."
        for name, h in self.metrics.histograms().items():
            if name.startswith(prefix_r) and h.count:
                snap = h.snapshot()
                rb_stages[name[len(prefix_r):]
                          .removesuffix(".seconds")] = {
                    "count": snap["count"],
                    "mean_ms": round(snap["mean"] * 1000, 4),
                    "p95_ms": round(snap["p95"] * 1000, 4),
                }
        if rb_stages:
            rebuild["stages"] = rb_stages
        for k in ("routing.device.rebuilds",
                  "routing.device.compactions",
                  "routing.device.rebuild_failed",
                  "routing.device.delta_applies",
                  "routing.device.host_delta",
                  "routing.device.cold_delta_class",
                  "routing.device.delta_compact_overflow",
                  "match_cache.delta_invalidated"):
            v = self.metrics.val(k)
            if v:
                rebuild[k.rsplit(".", 1)[1]] = v
        reasons = {k.rsplit(".", 1)[1]: v
                   for k, v in self.metrics.all().items()
                   if k.startswith("routing.device.compaction.")}
        if reasons:
            rebuild["compaction_reasons"] = reasons
        if self.rebuild_state_fn is not None:
            try:
                rebuild["state"] = self.rebuild_state_fn()
            except Exception:  # noqa: BLE001 — telemetry never raises
                pass
        # delivery-lane egress stage (ISSUE 5): coalesce/backpressure
        # counters + the pool's live gauges. `coalesce_ratio` is the
        # fraction of per-row session drains the coalescing removed
        # (rows vs actual deliver calls); lane depth rides the Stats
        # gauge table too (pipeline.deliver.lane_depth), so Prometheus/
        # StatsD/$SYS stats all carry the point-in-time value.
        deliver = {}
        for key in ("rows", "plans", "deliveries", "drains",
                    "backpressure_waits", "deliver_errors",
                    "slow_errors"):
            v = self.metrics.val(f"pipeline.deliver.{key}")
            if v:
                deliver[key] = v
        if deliver.get("deliveries"):
            deliver["coalesce_ratio"] = round(
                1.0 - deliver.get("drains", 0) / deliver["deliveries"],
                4)
        if self.deliver_state_fn is not None:
            try:
                deliver["state"] = self.deliver_state_fn()
            except Exception:  # noqa: BLE001 — telemetry never raises
                pass
        # fault-domain supervision (ISSUE 6): fault/trip/replay/stall
        # counters + the supervisor's live breaker/rung/journal state —
        # the section the chaos matrix and the OBSERVABILITY triage
        # order read first when a pipeline degrades
        supervise = {}
        for k in ("faults", "trips", "probes", "probe_failures",
                  "replays", "stalls", "restarts", "task_errors",
                  "rung_changes"):
            v = self.metrics.val(f"supervise.{k}")
            if v:
                supervise[k] = v
        by_point = {k.rsplit(".", 1)[1]: v
                    for k, v in self.metrics.all().items()
                    if k.startswith("supervise.faults.")}
        if by_point:
            supervise["faults_by_point"] = by_point
        by_stall = {k.rsplit(".", 1)[1]: v
                    for k, v in self.metrics.all().items()
                    if k.startswith("supervise.stalls.")}
        if by_stall:
            supervise["stalls_by_stage"] = by_stall
        if self.supervise_state_fn is not None:
            try:
                supervise["state"] = self.supervise_state_fn()
            except Exception:  # noqa: BLE001 — telemetry never raises
                pass
        # window-causal flight recorder (ISSUE 7): ring state + the
        # overlap/bubble analysis — the section bench rounds read for
        # the dispatch↔materialize overlap fraction and the top bubble
        # attributions per window
        trace = {}
        if self.recorder is not None:
            try:
                trace = self.recorder.snapshot_section()
            except Exception:  # noqa: BLE001 — telemetry never raises
                pass
        # columnar ingress (ISSUE 11): burst/row/fallback counters, the
        # burst-size histogram and per-acceptor-lane accept counts —
        # the section ingress_bench and the twin rows read. Derived
        # purely from traffic: with broker.columnar_ingress=0 nothing
        # increments, so the section is absent exactly as pre-ISSUE-11.
        ingress = {}
        for k in ("bursts", "rows", "fallback_frames", "bytes"):
            v = self.metrics.val(f"pipeline.ingress.{k}")
            if v:
                ingress[k] = v
        rows_c = ingress.get("rows", 0)
        fb = ingress.get("fallback_frames", 0)
        if rows_c or fb:
            ingress["columnar_ratio"] = round(rows_c / (rows_c + fb), 4)
        bh = self.metrics.histograms().get("pipeline.ingress.burst")
        if bh is not None and bh.count:
            snap = bh.snapshot()
            ingress["burst_rows"] = {
                "count": snap["count"],
                "mean": round(snap["mean"], 2),
                "p50": round(snap["p50"], 2),
                "p95": round(snap["p95"], 2),
            }
        lanes_acc = {k.split(".")[2]: v
                     for k, v in self.metrics.all().items()
                     if k.startswith("pipeline.ingress.lane") and v}
        if lanes_acc:
            ingress["lanes"] = lanes_acc
        # HBM ledger (ISSUE 8): per-category device bytes + peak
        # watermarks + pin ages + the backend memory_stats cross-check
        # — the section that makes "does it fit?" answerable before
        # ROADMAP items 1/3 size anything
        memory = {}
        if self.ledger is not None:
            try:
                memory = self.ledger.section()
            except Exception:  # noqa: BLE001 — telemetry never raises
                pass
        # overload governor (ISSUE 14): grade + armed shed actions +
        # signal readings (state_fn) and the pipeline.overload.*
        # shed/reject counters — the section the overload bench and
        # the $SYS alarm consumers read. Like `latency`, the section
        # exists ONLY when the governor does (knob-off twin: absent
        # even at full=True).
        overload = self.overload_section() \
            if self.overload_state_fn is not None else {}
        # latency SLO observatory (ISSUE 13): per-(qos, path)
        # ingress→routed / ingress→delivered percentiles + the SLO
        # burn/verdict + breach exemplars — the section bench phase
        # rows embed and tools/latency_report.py grades offline
        latency = {}
        if self.observatory is not None:
            try:
                latency = self.observatory.section()
            except Exception:  # noqa: BLE001 — telemetry never raises
                pass
        out = {
            "schema": SCHEMA,
            "stages": stages,
            "occupancy": occupancy,
            "compiles": compiles,
            "decisions": decisions,
        }
        if supervise or full:
            out["supervise"] = supervise
        if rebuild or full:
            out["rebuild"] = rebuild
        if deliver or full:
            out["deliver"] = deliver
        if cache or full:
            out["match_cache"] = cache
        if dedup or full:
            out["dedup"] = dedup
        if readback or full:
            out["readback"] = readback
        if exchange:
            # traffic-derived ONLY (never materialized at full=True):
            # broker.device_exchange=0 increments nothing, so the
            # section is absent exactly as pre-ISSUE-15 — the schema
            # half of the =0-restores-exactly twin contract
            out["exchange"] = exchange
        if trace or full:
            out["trace"] = trace
        if ingress or full:
            out["ingress"] = ingress
        if memory or full:
            out["memory"] = memory
        if self.overload_state_fn is not None and (overload or full):
            # knob-off leaves NO overload section even at full=True:
            # the A/B twin contract is "no governor object anywhere"
            out["overload"] = overload
        if self.observatory is not None and (latency or full):
            # knob-off leaves NO latency section even at full=True: the
            # A/B twin contract is "no observatory object anywhere" —
            # unlike trace/memory, whose sections full-materialize, the
            # latency schema simply does not exist without the knob
            out["latency"] = latency
        jc = _jit_cache_sizes()
        if jc:
            out["jit_cache"] = jc
        # jit-program cost registry (ISSUE 8): per-(program, class)
        # compile wall-time — and flops/bytes once an off-path consumer
        # (tools/profile_step.py --cost-out) has analyzed them — keyed
        # by the same labels as compiles.by_shape. Snapshot never
        # triggers the (re-lowering) analysis itself.
        pc = _program_costs()
        if pc is not None and (pc or full):
            out["program_costs"] = pc
        return out


def _jit_cache_sizes() -> dict:
    """Jit-cache entry counts of the route-step programs — the recompile
    accounting's ground truth (each entry is one compiled (shape,
    static-args) variant). Empty when jax / the models module isn't
    loaded yet, so snapshot() never forces a jax import."""
    import sys
    mod = sys.modules.get("emqx_tpu.models.router_engine")
    if mod is None:
        return {}
    try:
        return mod.compile_stats()
    except Exception:  # noqa: BLE001 — telemetry must never raise
        return {}


def _program_costs() -> "dict | None":
    """The ISSUE-8 jit-program cost registry (compile wall per class;
    flops/bytes where analyzed) — same import discipline as
    _jit_cache_sizes: snapshot() never forces a jax import and never
    pays the lazy cost analysis (analyze=False). None when the
    observatory knob is off (EMQX_TPU_HBM_LEDGER=0): the section must
    not exist at all, exactly pre-ISSUE-8."""
    import sys
    mod = sys.modules.get("emqx_tpu.models.router_engine")
    if mod is None:
        return {}
    try:
        if not mod.cost_registry_enabled():
            return None
        return mod.cost_stats()
    except Exception:  # noqa: BLE001 — telemetry must never raise
        return {}
