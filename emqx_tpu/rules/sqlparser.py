"""Rule-SQL parser.

Parity: emqx_rule_sqlparser.erl + the rulesql dep grammar. Supported:

  SELECT <field> [, <field>]* FROM "topic" [, "topic"]* [WHERE <cond>]
  FOREACH <expr> [AS <var>] [DO <field>,...] [INCASE <cond>]
      FROM "topic"[,...] [WHERE <cond>]

Fields: `*`, expressions with `AS` aliases (dotted aliases build nested
maps). Expressions: literals, dotted/indexed vars (`payload.data[1].x`,
1-based like nth/2), function calls, arithmetic (+ - * / div mod), string
comparison and `=`/`<>`/`!=`/`>=`/`<=`/`>`/`<`/`=~`, and/or/not,
CASE WHEN ... THEN ... [ELSE ...] END, parentheses.

AST is plain tuples so compiled rules are picklable/printable:
  ('lit', v) ('var', [seg|('idx', expr)...]) ('call', name, [args])
  ('bin', op, l, r) ('neg', e) ('not', e) ('and', l, r) ('or', l, r)
  ('case', [(when, then)...], else|None) ('*',)
"""

from __future__ import annotations

import re
from typing import Any, Optional

KEYWORDS = {"select", "from", "where", "foreach", "do", "incase", "as",
            "case", "when", "then", "else", "end", "and", "or", "not",
            "true", "false", "null", "div", "mod"}

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<num>\d+\.\d+|\d+)
  | (?P<str>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
  | (?P<name>[A-Za-z_$][A-Za-z0-9_$]*)
  | (?P<op><>|!=|>=|<=|=~|[=<>+\-*/%(),.\[\]])
""", re.VERBOSE)


class SqlError(Exception):
    pass


def _tokenize(sql: str) -> list[tuple[str, str]]:
    out = []
    i = 0
    while i < len(sql):
        m = _TOKEN_RE.match(sql, i)
        if not m:
            raise SqlError(f"bad token at: {sql[i:i+20]!r}")
        i = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        text = m.group()
        if kind == "name" and text.lower() in KEYWORDS:
            out.append(("kw", text.lower()))
        else:
            out.append((kind, text))
    out.append(("eof", ""))
    return out


class _Parser:
    def __init__(self, sql: str):
        self.toks = _tokenize(sql)
        self.pos = 0

    def peek(self):
        return self.toks[self.pos]

    def next(self):
        t = self.toks[self.pos]
        self.pos += 1
        return t

    def accept(self, kind: str, text: Optional[str] = None) -> bool:
        k, v = self.peek()
        if k == kind and (text is None or v == text):
            self.pos += 1
            return True
        return False

    def expect(self, kind: str, text: Optional[str] = None) -> str:
        k, v = self.next()
        if k != kind or (text is not None and v != text):
            raise SqlError(f"expected {text or kind}, got {v!r}")
        return v

    # ---- statement ----
    def parse(self) -> dict:
        k, v = self.peek()
        if k == "kw" and v == "select":
            return self._select()
        if k == "kw" and v == "foreach":
            return self._foreach()
        raise SqlError("statement must start with SELECT or FOREACH")

    def _select(self) -> dict:
        self.expect("kw", "select")
        fields = self._fields()
        topics = self._from()
        cond = self._where()
        self.expect("eof")
        return {"type": "select", "fields": fields, "from": topics,
                "where": cond}

    def _foreach(self) -> dict:
        self.expect("kw", "foreach")
        expr = self._expr()
        alias = None
        if self.accept("kw", "as"):
            alias = self.expect("name")
        do_fields = None
        if self.accept("kw", "do"):
            do_fields = self._fields()
        incase = None
        if self.accept("kw", "incase"):
            incase = self._expr()
        topics = self._from()
        cond = self._where()
        self.expect("eof")
        return {"type": "foreach", "foreach": expr, "alias": alias,
                "do": do_fields, "incase": incase, "from": topics,
                "where": cond}

    def _fields(self) -> list[tuple[Any, Optional[list[str]]]]:
        fields = [self._field()]
        while self.accept("op", ","):
            fields.append(self._field())
        return fields

    def _field(self):
        if self.accept("op", "*"):
            return (("*",), None)
        expr = self._expr()
        alias = None
        if self.accept("kw", "as"):
            alias = [self.expect("name")]
            while self.accept("op", "."):
                alias.append(self.expect("name"))
        return (expr, alias)

    def _from(self) -> list[str]:
        self.expect("kw", "from")
        topics = [self._topic()]
        while self.accept("op", ","):
            topics.append(self._topic())
        return topics

    def _topic(self) -> str:
        k, v = self.next()
        if k != "str":
            raise SqlError(f"FROM expects a quoted topic, got {v!r}")
        return _unquote(v)

    def _where(self):
        if self.accept("kw", "where"):
            return self._expr()
        return None

    # ---- expressions (precedence climbing) ----
    def _expr(self):
        return self._or()

    def _or(self):
        left = self._and()
        while self.accept("kw", "or"):
            left = ("or", left, self._and())
        return left

    def _and(self):
        left = self._not()
        while self.accept("kw", "and"):
            left = ("and", left, self._not())
        return left

    def _not(self):
        if self.accept("kw", "not"):
            return ("not", self._not())
        return self._cmp()

    def _cmp(self):
        left = self._add()
        k, v = self.peek()
        if k == "op" and v in ("=", "<>", "!=", ">", "<", ">=", "<=", "=~"):
            self.next()
            return ("bin", v, left, self._add())
        return left

    def _add(self):
        left = self._mul()
        while True:
            k, v = self.peek()
            if k == "op" and v in ("+", "-"):
                self.next()
                left = ("bin", v, left, self._mul())
            else:
                return left

    def _mul(self):
        left = self._unary()
        while True:
            k, v = self.peek()
            if (k == "op" and v in ("*", "/", "%")) or \
                    (k == "kw" and v in ("div", "mod")):
                self.next()
                left = ("bin", v, left, self._unary())
            else:
                return left

    def _unary(self):
        if self.accept("op", "-"):
            return ("neg", self._unary())
        return self._primary()

    def _primary(self):
        k, v = self.peek()
        if k == "num":
            self.next()
            return ("lit", float(v) if "." in v else int(v))
        if k == "str":
            self.next()
            return ("lit", _unquote(v))
        if k == "kw":
            if v in ("true", "false"):
                self.next()
                return ("lit", v == "true")
            if v == "null":
                self.next()
                return ("lit", None)
            if v == "case":
                return self._case()
            raise SqlError(f"unexpected keyword {v!r}")
        if k == "op" and v == "(":
            self.next()
            e = self._expr()
            self.expect("op", ")")
            return e
        if k == "name":
            self.next()
            if self.accept("op", "("):
                args = []
                if not self.accept("op", ")"):
                    args.append(self._expr())
                    while self.accept("op", ","):
                        args.append(self._expr())
                    self.expect("op", ")")
                return ("call", v.lower(), args)
            return ("var", self._path(v))
        raise SqlError(f"unexpected token {v!r}")

    def _case(self):
        self.expect("kw", "case")
        whens = []
        while self.accept("kw", "when"):
            cond = self._expr()
            self.expect("kw", "then")
            whens.append((cond, self._expr()))
        if not whens:
            raise SqlError("CASE needs at least one WHEN")
        els = self._expr() if self.accept("kw", "else") else None
        self.expect("kw", "end")
        return ("case", whens, els)

    def _path(self, head: str) -> list:
        segs: list = [head]
        while True:
            if self.accept("op", "."):
                segs.append(self.expect("name"))
            elif self.accept("op", "["):
                segs.append(("idx", self._expr()))
                self.expect("op", "]")
            else:
                return segs


def _unquote(s: str) -> str:
    body = s[1:-1]
    return re.sub(r"\\(.)", r"\1", body)


def parse_sql(sql: str) -> dict:
    """Parse one rule-SQL statement into its AST dict."""
    return _Parser(sql).parse()
