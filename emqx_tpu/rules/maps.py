"""Nested map/array access for rule SQL columns.

Parity: emqx_rule_maps.erl — nested_get/nested_put over dotted paths with
1-based array indexing (`a.b[1].c`). Paths are lists whose segments are
either string keys or ('idx', i) entries (i already evaluated, 1-based;
negative counts from the end like the reference's `[-1]`).
"""

from __future__ import annotations

import json
import re
from typing import Any

_PATH_RE = re.compile(r"([^.\[\]]+)|\[(-?\d+)\]")


def parse_path(path: str) -> list:
    """'a.b[1].c' -> ['a', 'b', ('idx', 1), 'c']."""
    out: list = []
    for m in _PATH_RE.finditer(path):
        if m.group(1) is not None:
            out.append(m.group(1))
        else:
            out.append(("idx", int(m.group(2))))
    return out


def _idx(seg) -> Any:
    return seg[1] if isinstance(seg, tuple) and seg[0] == "idx" else None


def nested_get(obj: Any, path: list, default: Any = None) -> Any:
    cur = obj
    for seg in path:
        i = _idx(seg)
        if i is not None:
            if not isinstance(cur, list):
                return default
            j = i - 1 if i > 0 else i        # 1-based; negatives from end
            if -len(cur) <= j < len(cur):
                cur = cur[j]
            else:
                return default
        else:
            if isinstance(cur, (str, bytes)):
                # lazy JSON decode on nested access (the runtime's
                # may_decode_payload behavior for the payload column)
                try:
                    cur = json.loads(cur)
                except (ValueError, TypeError):
                    return default
            if isinstance(cur, dict):
                if seg in cur:
                    cur = cur[seg]
                else:
                    return default
            else:
                return default
    return cur


def nested_put(obj: Any, path: list, value: Any) -> Any:
    if not path:
        return value
    seg, rest = path[0], path[1:]
    i = _idx(seg)
    if i is not None:
        lst = list(obj) if isinstance(obj, list) else []
        j = i - 1 if i > 0 else len(lst) + i
        while len(lst) <= j:
            lst.append(None)
        lst[j] = nested_put(lst[j], rest, value)
        return lst
    m = dict(obj) if isinstance(obj, dict) else {}
    m[seg] = nested_put(m.get(seg), rest, value)
    return m
