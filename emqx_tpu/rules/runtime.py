"""Rule runtime: evaluate a parsed SQL statement against event columns.

Parity: emqx_rule_runtime.erl — apply_rule pipeline: (FOREACH | SELECT)
columns -> WHERE filter -> per-output action invocation. Column references
resolve against the event map first, then against already-selected output
(so `SELECT payload.x as x, x + 1 as y` works, like select_and_transform's
fold). The special var `item` (or the FOREACH alias) binds the current array
element inside DO/INCASE.
"""

from __future__ import annotations

from typing import Any, Optional

from emqx_tpu.rules import funcs
from emqx_tpu.rules.maps import nested_get, nested_put


class EvalError(Exception):
    pass


def _resolve_var(path: list, scopes: list[dict]) -> Any:
    head = path[0]
    for scope in scopes:
        if isinstance(scope, dict) and head in scope:
            return nested_get(scope[head], path[1:]) if path[1:] \
                else scope[head]
    return None


def _eval_path(path: list, scopes: list[dict]) -> list:
    """Evaluate ('idx', expr) segments to concrete ('idx', int)."""
    out = []
    for seg in path:
        if isinstance(seg, tuple) and seg[0] == "idx":
            out.append(("idx", int(eval_expr(seg[1], scopes))))
        else:
            out.append(seg)
    return out


def eval_expr(ast: Any, scopes: list[dict]) -> Any:
    tag = ast[0]
    if tag == "lit":
        return ast[1]
    if tag == "var":
        return _resolve_var(_eval_path(ast[1], scopes), scopes)
    if tag == "call":
        name = ast[1]
        if not ast[2] and name in funcs.COLUMN_FUNCS:
            # zero-arg column accessors: qos(), topic(), clientid(), ...
            col = funcs.COLUMN_FUNCS[name]
            if name == "flags":
                return _resolve_var([col], scopes) or {}
            return _resolve_var([col], scopes)
        if name == "flag" and len(ast[2]) == 1:
            fl = _resolve_var(["flags"], scopes) or {}
            return bool(fl.get(funcs._s(eval_expr(ast[2][0], scopes))))
        return funcs.call(name, [eval_expr(a, scopes) for a in ast[2]])
    if tag == "neg":
        return -eval_expr(ast[1], scopes)
    if tag == "not":
        return not _truthy(eval_expr(ast[1], scopes))
    if tag == "and":
        return _truthy(eval_expr(ast[1], scopes)) and \
            _truthy(eval_expr(ast[2], scopes))
    if tag == "or":
        return _truthy(eval_expr(ast[1], scopes)) or \
            _truthy(eval_expr(ast[2], scopes))
    if tag == "bin":
        return _binop(ast[1], eval_expr(ast[2], scopes),
                      eval_expr(ast[3], scopes))
    if tag == "case":
        for cond, then in ast[1]:
            if _truthy(eval_expr(cond, scopes)):
                return eval_expr(then, scopes)
        return eval_expr(ast[2], scopes) if ast[2] is not None else None
    raise EvalError(f"bad ast node {tag!r}")


def _truthy(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    if v in ("true", "false"):
        return v == "true"
    raise EvalError(f"non-boolean in condition: {v!r}")


def _cmp_norm(v):
    return v


def _binop(op: str, a: Any, b: Any) -> Any:
    if op == "=":
        return _loose_eq(a, b)
    if op in ("<>", "!="):
        return not _loose_eq(a, b)
    if op == "=~":
        import re
        return bool(re.search(funcs._s(b), funcs._s(a)))
    if op in (">", "<", ">=", "<="):
        if isinstance(a, str) and isinstance(b, str):
            pass
        else:
            a, b = funcs._num(a), funcs._num(b)
        return {"<": a < b, ">": a > b, ">=": a >= b, "<=": a <= b}[op]
    if op == "%":
        op = "mod"
    return funcs.call(op, [a, b])


def _loose_eq(a: Any, b: Any) -> bool:
    if type(a) is type(b):
        return a == b
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a == b
    # string/number coercion ('1' = 1) like rulesql's compare
    try:
        return funcs._num(a) == funcs._num(b)
    except (TypeError, ValueError):
        return funcs._s(a) == funcs._s(b)


def select_fields(fields: list, scopes: list[dict]) -> dict:
    out: dict = {}
    # selected columns become visible to later fields and WHERE
    eval_scopes = [out] + scopes
    for expr, alias in fields:
        if expr == ("*",):
            for scope in reversed(scopes):
                out.update(scope)
            continue
        val = eval_expr(expr, eval_scopes)
        if alias:
            tmp = nested_put(out, list(alias), val)
            out.clear()
            out.update(tmp)
        else:
            key = _default_alias(expr)
            out[key] = val
    return out


def _default_alias(expr) -> str:
    if expr[0] == "var":
        last = expr[1][-1]
        return expr[1][0] if isinstance(last, tuple) else str(last)
    if expr[0] == "call":
        return expr[1]
    return "value"


def apply_sql(ast: dict, event: dict) -> list[dict]:
    """Run one statement against one event's columns.

    Returns the list of output column maps (0 or 1 for SELECT; one per
    array element for FOREACH). Empty list = WHERE/INCASE filtered out."""
    scopes = [event]
    where = ast.get("where")
    if ast["type"] == "select":
        out = select_fields(ast["fields"], scopes)
        if where is not None and not _truthy(eval_expr(where,
                                                       [out] + scopes)):
            return []
        return [out]

    # FOREACH
    if where is not None and not _truthy(eval_expr(where, scopes)):
        return []
    seq = eval_expr(ast["foreach"], scopes)
    if isinstance(seq, (str, bytes)):
        import json
        try:
            seq = json.loads(seq)
        except ValueError:
            return []
    if not isinstance(seq, list):
        return []
    alias = ast.get("alias") or "item"
    outs = []
    for elem in seq:
        item_scope = {alias: elem, "item": elem}
        sc = [item_scope] + scopes
        if ast.get("incase") is not None and \
                not _truthy(eval_expr(ast["incase"], sc)):
            continue
        if ast.get("do"):
            outs.append(select_fields(ast["do"], sc))
        else:
            outs.append(elem if isinstance(elem, dict) else {"item": elem})
    return outs


def apply_rule(rule, event: dict) -> list[dict]:
    """Convenience: rule has a compiled `.ast`."""
    return apply_sql(rule.ast, event)
