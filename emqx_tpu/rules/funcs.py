"""Rule-SQL function library.

Parity: emqx_rule_funcs.erl exports (arithmetic/math/bits/type/string/map/
array/hash/codec/date/kv groups). Functions operate on decoded column
values: str for binaries, int/float for numbers, dict for maps, list for
arrays, None for null/undefined. Missing args and type errors surface as
exceptions — the runtime counts them per rule ('failed.exception', as the
reference's metrics do).
"""

from __future__ import annotations

import base64
import hashlib
import json
import math
import re
import time
from datetime import datetime, timezone
from typing import Any

# global kv store (emqx_rule_funcs kv_store_* — an ets table there)
_KV: dict[str, Any] = {}


def _num(x):
    if isinstance(x, bool):
        raise TypeError("boolean is not a number")
    if isinstance(x, (int, float)):
        return x
    if isinstance(x, str):
        return float(x) if "." in x else int(x)
    raise TypeError(f"not a number: {x!r}")


def _s(x) -> str:
    if isinstance(x, bytes):
        return x.decode("utf-8", "replace")
    if isinstance(x, bool):
        return "true" if x else "false"
    if isinstance(x, (dict, list)):
        return json.dumps(x, separators=(",", ":"))
    if x is None:
        return "undefined"
    return str(x)


def _b(x) -> bytes:
    if isinstance(x, bytes):
        return x
    return _s(x).encode()


# ---- arithmetic (str + str concatenates, mirroring '+'/2) ----
def f_add(a, b):
    if isinstance(a, (str, bytes)) and isinstance(b, (str, bytes)):
        return _s(a) + _s(b)
    return _num(a) + _num(b)


def f_sub(a, b):
    return _num(a) - _num(b)


def f_mul(a, b):
    return _num(a) * _num(b)


def f_div(a, b):
    return _num(a) / _num(b)


def f_intdiv(a, b):
    return int(_num(a)) // int(_num(b))


def f_mod(a, b):
    return int(_num(a)) % int(_num(b))


def f_eq(a, b):
    return a == b


# ---- date helpers ----
_UNITS = {"second": 1, "millisecond": 10**3, "microsecond": 10**6,
          "nanosecond": 10**9}


def _now_ts(unit: str = "second") -> int:
    return time.time_ns() * _UNITS[unit] // 10**9


def _ts_to_rfc3339(ts: int, unit: str = "second") -> str:
    secs = ts / _UNITS[unit]
    dt = datetime.fromtimestamp(secs, tz=timezone.utc)
    if unit == "second":
        return dt.strftime("%Y-%m-%dT%H:%M:%S+00:00")
    return dt.isoformat().replace("+00:00", "") + "+00:00" \
        if dt.tzinfo else dt.isoformat()


def _rfc3339_to_ts(s: str, unit: str = "second") -> int:
    dt = datetime.fromisoformat(_s(s).replace("Z", "+00:00"))
    return int(dt.timestamp() * _UNITS[unit])


def f_subbits(bits, *args):
    """subbits(Bytes, Len) | (Bytes, Start, Len) |
    (Bytes, Start, Len, Type, Signedness, Endianness); Start is 1-based."""
    data = _b(bits)
    val = int.from_bytes(data, "big")
    total = len(data) * 8
    if len(args) == 1:
        start, length = 1, int(args[0])
        ty, signed, endian = "integer", "unsigned", "big"
    elif len(args) == 2:
        start, length = int(args[0]), int(args[1])
        ty, signed, endian = "integer", "unsigned", "big"
    else:
        start, length = int(args[0]), int(args[1])
        ty, signed, endian = (_s(args[2]), _s(args[3]), _s(args[4]))
    if start < 1 or start - 1 + length > total:
        return None
    shift = total - (start - 1) - length
    chunk = (val >> shift) & ((1 << length) - 1)
    if ty == "float":
        import struct
        nbytes = length // 8
        fmt = {4: "f", 8: "d"}[nbytes]
        bo = ">" if endian == "big" else "<"
        # chunk IS the wire bytes read big-endian; endianness applies only
        # to how those wire bytes are interpreted
        return struct.unpack(bo + fmt, chunk.to_bytes(nbytes, "big"))[0]
    if endian == "little":
        nbytes = (length + 7) // 8
        chunk = int.from_bytes(chunk.to_bytes(nbytes, "big"), "little")
    if signed == "signed" and chunk >= (1 << (length - 1)):
        chunk -= 1 << length
    return chunk


def _pad(s, length, side="trailing", char=" "):
    s, length, char = _s(s), int(length), _s(char)
    fill = char * max(0, length - len(s))
    # multi-char fills are truncated to exactly reach length (string:pad)
    fill = fill[:max(0, length - len(s))]
    if side == "leading":
        return fill + s
    if side == "both":
        half = (length - len(s))
        left = (char * length)[:half // 2]
        right = (char * length)[:half - half // 2]
        return left + s + right
    return s + fill


def _split(s, sep=None, where=None):
    s = _s(s)
    if sep is None:
        return [t for t in s.split() if t]
    sep = _s(sep)
    if where == "leading":
        parts = s.split(sep, 1)
        return parts if len(parts) > 1 else [s]
    if where == "trailing":
        parts = s.rsplit(sep, 1)
        return parts if len(parts) > 1 else [s]
    return [t for t in s.split(sep) if t != ""]


def _nested_get_path(path_str, m, default=None):
    # arg order per map_get(Key, Map[, Default])
    from emqx_tpu.rules.maps import nested_get, parse_path
    return nested_get(m, parse_path(_s(path_str)), default)


def _nested_put_path(path_str, val, m):
    from emqx_tpu.rules.maps import nested_put, parse_path
    return nested_put(dict(m if isinstance(m, dict) else {}),
                      parse_path(_s(path_str)), val)


def _sprintf(fmt, *args):
    """sprintf_s with Erlang io_lib ~s/~p/~w/~b controls."""
    out, i, ai = [], 0, 0
    fmt = _s(fmt)
    while i < len(fmt):
        c = fmt[i]
        if c == "~" and i + 1 < len(fmt):
            ctl = fmt[i + 1]
            if ctl in "spwb":
                out.append(_s(args[ai]) if ctl in "sb"
                           else json.dumps(args[ai], default=repr))
                ai += 1
                i += 2
                continue
            if ctl == "n":
                out.append("\n")
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


FUNCS: dict[str, Any] = {
    # arithmetic
    "+": f_add, "-": f_sub, "*": f_mul, "/": f_div,
    "div": f_intdiv, "mod": f_mod, "eq": f_eq,
    # math
    "abs": lambda x: abs(_num(x)),
    "acos": lambda x: math.acos(_num(x)),
    "acosh": lambda x: math.acosh(_num(x)),
    "asin": lambda x: math.asin(_num(x)),
    "asinh": lambda x: math.asinh(_num(x)),
    "atan": lambda x: math.atan(_num(x)),
    "atanh": lambda x: math.atanh(_num(x)),
    "ceil": lambda x: math.ceil(_num(x)),
    "cos": lambda x: math.cos(_num(x)),
    "cosh": lambda x: math.cosh(_num(x)),
    "exp": lambda x: math.exp(_num(x)),
    "floor": lambda x: math.floor(_num(x)),
    "fmod": lambda x, y: math.fmod(_num(x), _num(y)),
    "log": lambda x: math.log(_num(x)),
    "log10": lambda x: math.log10(_num(x)),
    "log2": lambda x: math.log2(_num(x)),
    "power": lambda x, y: math.pow(_num(x), _num(y)),
    "round": lambda x: round(_num(x)),
    "sin": lambda x: math.sin(_num(x)),
    "sinh": lambda x: math.sinh(_num(x)),
    "sqrt": lambda x: math.sqrt(_num(x)),
    "tan": lambda x: math.tan(_num(x)),
    "tanh": lambda x: math.tanh(_num(x)),
    # bits
    "bitnot": lambda x: ~int(_num(x)),
    "bitand": lambda a, b: int(_num(a)) & int(_num(b)),
    "bitor": lambda a, b: int(_num(a)) | int(_num(b)),
    "bitxor": lambda a, b: int(_num(a)) ^ int(_num(b)),
    "bitsl": lambda a, n: int(_num(a)) << int(_num(n)),
    "bitsr": lambda a, n: int(_num(a)) >> int(_num(n)),
    "bitsize": lambda b: len(_b(b)) * 8,
    "byteside": lambda b: len(_b(b)),
    "bytesize": lambda b: len(_b(b)),
    "subbits": f_subbits,
    # type conversion
    "str": _s,
    "str_utf8": _s,
    "bool": lambda x: {"true": True, "false": False, True: True,
                       False: False, 1: True, 0: False}[
                           x if isinstance(x, (bool, int)) else _s(x)],
    "int": lambda x: int(float(x)) if isinstance(x, str) and "." in x
        else (1 if x is True else 0 if x is False else int(x)),
    "float": lambda x: float(_num(x)),
    "map": lambda x: x if isinstance(x, dict) else json.loads(_s(x)),
    "bin2hexstr": lambda b: _b(b).hex().upper(),
    "hexstr2bin": lambda s: bytes.fromhex(_s(s)),
    # type validation
    "is_null": lambda x: x is None,
    "is_not_null": lambda x: x is not None,
    "is_str": lambda x: isinstance(x, str),
    "is_bool": lambda x: isinstance(x, bool),
    "is_int": lambda x: isinstance(x, int) and not isinstance(x, bool),
    "is_float": lambda x: isinstance(x, float),
    "is_num": lambda x: isinstance(x, (int, float))
        and not isinstance(x, bool),
    "is_map": lambda x: isinstance(x, dict),
    "is_array": lambda x: isinstance(x, list),
    # strings
    "lower": lambda s: _s(s).lower(),
    "upper": lambda s: _s(s).upper(),
    "trim": lambda s: _s(s).strip(),
    "ltrim": lambda s: _s(s).lstrip(),
    "rtrim": lambda s: _s(s).rstrip(),
    "reverse": lambda s: _s(s)[::-1],
    "strlen": lambda s: len(_s(s)),
    "substr": lambda s, start, length=None: (
        _s(s)[int(start):] if length is None
        else _s(s)[int(start):int(start) + int(length)]),
    "split": _split,
    "tokens": lambda s, seps, opt=None: (
        [t for t in re.split("|".join(re.escape(c) for c in _s(seps)),
                             _s(s).replace("\n", "" if opt == "nocrlf"
                                           else "\n")
                             .replace("\r", "" if opt == "nocrlf" else "\r"))
         if t]),
    "concat": lambda a, b: _s(a) + _s(b),
    "sprintf_s": _sprintf,
    "pad": _pad,
    "replace": lambda s, p, r, where=None: (
        _s(s).replace(_s(p), _s(r)) if where in (None, "all")
        else _s(s).replace(_s(p), _s(r), 1) if where == "leading"
        else _s(r).join(_s(s).rsplit(_s(p), 1))),
    "regex_match": lambda s, rx: bool(re.search(_s(rx), _s(s))),
    "regex_replace": lambda s, rx, r: re.sub(_s(rx), _s(r), _s(s)),
    "ascii": lambda c: ord(_s(c)[0]),
    "find": lambda s, sub, where=None: (
        (lambda st, sb: st[st.rfind(sb):] if where == "trailing"
         and sb in st else st[st.find(sb):] if sb in st else "")(
             _s(s), _s(sub))),
    # maps
    "map_new": lambda: {},
    "map_get": _nested_get_path,
    "map_put": _nested_put_path,
    "mget": _nested_get_path,
    "mput": _nested_put_path,
    # arrays (nth is 1-based like lists:nth)
    "nth": lambda n, lst: lst[int(n) - 1] if 0 < int(n) <= len(lst)
        else None,
    "length": lambda lst: len(lst),
    "sublist": lambda *a: (a[1][:int(a[0])] if len(a) == 2
                           else a[2][int(a[0]) - 1:int(a[0]) - 1 + int(a[1])]),
    "first": lambda lst: lst[0] if lst else None,
    "last": lambda lst: lst[-1] if lst else None,
    "contains": lambda x, lst: x in lst,
    # hashes (hex strings like emqx_misc:bin_to_hexstr)
    "md5": lambda x: hashlib.md5(_b(x)).hexdigest(),
    "sha": lambda x: hashlib.sha1(_b(x)).hexdigest(),
    "sha256": lambda x: hashlib.sha256(_b(x)).hexdigest(),
    # encode/decode
    "base64_encode": lambda x: base64.b64encode(_b(x)).decode(),
    "base64_decode": lambda x: base64.b64decode(_b(x)),
    "json_encode": lambda x: json.dumps(x, default=_s,
                                        separators=(",", ":")),
    "json_decode": lambda x: json.loads(_s(x)),
    "term_encode": lambda x: base64.b64encode(
        json.dumps(x, default=_s).encode()).decode(),
    "term_decode": lambda x: json.loads(base64.b64decode(_b(x))),
    # dates
    "now_rfc3339": lambda unit="second": _ts_to_rfc3339(_now_ts(_s(unit)),
                                                        _s(unit)),
    "unix_ts_to_rfc3339": lambda ts, unit="second":
        _ts_to_rfc3339(int(ts), _s(unit)),
    "rfc3339_to_unix_ts": lambda s, unit="second":
        _rfc3339_to_ts(s, _s(unit)),
    "now_timestamp": lambda unit="second": _now_ts(_s(unit)),
    "timezone_to_second": lambda tz: _tz_seconds(tz),
    # kv / "proc dict" (rule-engine-global kv table)
    "proc_dict_get": lambda k: _KV.get(_s(k)),
    "proc_dict_put": lambda k, v: _KV.__setitem__(_s(k), v),
    "proc_dict_del": lambda k: _KV.pop(_s(k), None) and None,
    "kv_store_get": lambda k, d=None: _KV.get(_s(k), d),
    "kv_store_put": lambda k, v: (_KV.__setitem__(_s(k), v), v)[1],
    "kv_store_del": lambda k: _KV.pop(_s(k), None) and None,
    "null": lambda: None,
    # topic-filter membership (emqx_rule_funcs contains_topic/2,3 +
    # contains_topic_match/2,3): first arg is a topic-filter array —
    # either plain strings or {"topic": ..., "qos": ...} maps
    "contains_topic": lambda fs, t, qos=None:
        _find_topic_filter(fs, t, False, qos),
    "contains_topic_match": lambda fs, t, qos=None:
        _find_topic_filter(fs, t, True, qos),
}

# message-column accessor functions (emqx_rule_funcs qos/1, topic/1,
# payload/1, clientid/1, username/1, clientip/peerhost/1, msgid/1,
# flags/1, flag/2): zero-arg in SQL — the runtime resolves them from the
# event columns in scope (see rules/runtime.py eval_expr 'call')
COLUMN_FUNCS: dict[str, str] = {
    "clientid": "clientid", "username": "username", "topic": "topic",
    "payload": "payload", "qos": "qos", "clientip": "peerhost",
    "peerhost": "peerhost", "msgid": "id", "flags": "flags",
}


def _find_topic_filter(filters, topic, wildcard: bool, qos=None) -> bool:
    from emqx_tpu.utils import topic as T
    t = _s(topic)
    for f in filters or []:
        if isinstance(f, dict):
            filt, fqos = _s(f.get("topic")), f.get("qos")
        else:
            filt, fqos = _s(f), None
        hit = T.match(t, filt) if wildcard else filt == t
        if hit and (qos is None or fqos == qos):
            return True
    return False


def _tz_seconds(tz) -> int:
    s = _s(tz)
    if s in ("Z", "z", "local"):
        return 0
    sign = -1 if s[0] == "-" else 1
    hh, _, mm = s.lstrip("+-").partition(":")
    return sign * (int(hh) * 3600 + int(mm or 0))


def call(name: str, args: list) -> Any:
    fn = FUNCS.get(name)
    if fn is None:
        raise NameError(f"unknown sql function {name!r}")
    return fn(*args)
