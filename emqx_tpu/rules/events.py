"""Event column construction from broker hookpoints.

Parity: emqx_rule_events.erl — each hookpoint builds a flat column map
(eventmsg_publish :139-153, eventmsg_connected :155-188, etc.), FROM topics
`$events/<name>` map to hookpoints (event_name/1 :561-569), and any other
FROM topic is a filter over 'message.publish'. with_basic_columns adds
event/timestamp/node.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from emqx_tpu.broker.message import Message, base62_encode

EVENT_TOPICS = {
    "$events/client_connected": "client.connected",
    "$events/client_disconnected": "client.disconnected",
    "$events/session_subscribed": "session.subscribed",
    "$events/session_unsubscribed": "session.unsubscribed",
    "$events/message_delivered": "message.delivered",
    "$events/message_acked": "message.acked",
    "$events/message_dropped": "message.dropped",
}


def event_name(topic: str) -> str:
    """FROM-topic -> hookpoint; non-$events topics select message.publish
    (emqx_rule_events:event_name/1)."""
    for prefix, name in EVENT_TOPICS.items():
        if topic.startswith(prefix):
            return name
    return "message.publish"


def _basic(event: str, columns: dict) -> dict:
    columns["event"] = event.replace(".", "_")
    columns["timestamp"] = int(time.time() * 1000)
    columns.setdefault("node", "emqx@127.0.0.1")
    return columns


def _payload_col(p: bytes) -> Any:
    try:
        return p.decode("utf-8")
    except UnicodeDecodeError:
        return p


def columns_publish(msg: Message) -> dict:
    """eventmsg_publish columns (emqx_rule_events.erl:139-153)."""
    return _basic("message.publish", {
        "id": base62_encode(msg.id),
        "clientid": msg.from_,
        "username": msg.get_header("username"),
        "payload": _payload_col(msg.payload),
        "peerhost": msg.get_header("peerhost"),
        "topic": msg.topic,
        "qos": msg.qos,
        "flags": dict(msg.flags),
        "pub_props": dict(msg.get_header("properties") or {}),
        "publish_received_at": msg.ts,
    })


def columns_connected(clientinfo: dict, conninfo: dict) -> dict:
    return _basic("client.connected", {
        "clientid": clientinfo.get("clientid"),
        "username": clientinfo.get("username"),
        "mountpoint": clientinfo.get("mountpoint"),
        "peername": _ntoa(conninfo.get("peername")
                          or clientinfo.get("peername")),
        "sockname": _ntoa(conninfo.get("sockname")),
        "proto_name": conninfo.get("proto_name", "MQTT"),
        "proto_ver": conninfo.get("proto_ver"),
        "keepalive": conninfo.get("keepalive"),
        "clean_start": conninfo.get("clean_start", True),
        "receive_maximum": conninfo.get("receive_maximum"),
        "expiry_interval": conninfo.get("expiry_interval", 0),
        "is_bridge": clientinfo.get("is_bridge", False),
        "conn_props": dict(conninfo.get("conn_props") or {}),
        "connected_at": conninfo.get("connected_at"),
    })


def columns_disconnected(clientinfo: dict, reason: Any) -> dict:
    return _basic("client.disconnected", {
        "reason": str(reason),
        "clientid": clientinfo.get("clientid"),
        "username": clientinfo.get("username"),
        "peername": _ntoa(clientinfo.get("peername")),
        "sockname": _ntoa(clientinfo.get("sockname")),
        "disconn_props": {},
        "disconnected_at": int(time.time() * 1000),
    })


def columns_sub_unsub(event: str, clientinfo: dict, topic: str,
                      subopts: Optional[dict] = None) -> dict:
    prop_key = ("sub_props" if event == "session.subscribed"
                else "unsub_props")
    return _basic(event, {
        "clientid": clientinfo.get("clientid"),
        "username": clientinfo.get("username"),
        "peerhost": clientinfo.get("peerhost"),
        prop_key: {},
        "topic": topic,
        "qos": (subopts or {}).get("qos", 0),
    })


def columns_delivered(clientid: Any, msg: Message) -> dict:
    cols = columns_publish(msg)
    cols.update({
        "event": "message_delivered",
        "from_clientid": msg.from_,
        "from_username": msg.get_header("username"),
        "clientid": clientid if isinstance(clientid, str)
        else (clientid or {}).get("clientid") if isinstance(clientid, dict)
        else clientid,
    })
    return cols


def columns_acked(clientinfo: Any, msg: Message) -> dict:
    cols = columns_delivered(clientinfo, msg)
    cols["event"] = "message_acked"
    cols["puback_props"] = {}
    return cols


def columns_dropped(msg: Message, reason: str) -> dict:
    cols = columns_publish(msg)
    cols["event"] = "message_dropped"
    cols["reason"] = reason
    return cols


def _ntoa(addr: Any) -> Optional[str]:
    if addr is None:
        return None
    if isinstance(addr, tuple):
        return f"{addr[0]}:{addr[1]}"
    return str(addr)
