"""Rule engine: SQL-on-events stream processing.

Parity: apps/emqx_rule_engine (emqx_rule_sqlparser.erl via dep rulesql,
emqx_rule_events.erl, emqx_rule_funcs.erl, emqx_rule_runtime.erl,
emqx_rule_registry.erl, emqx_rule_metrics.erl). SQL statements select and
transform event columns, filter with WHERE, optionally explode arrays with
FOREACH/DO/INCASE, and feed actions (republish, inspect, bridges).
"""

from emqx_tpu.rules.registry import Rule, RuleEngine
from emqx_tpu.rules.runtime import apply_rule
from emqx_tpu.rules.sqlparser import SqlError, parse_sql

__all__ = ["Rule", "RuleEngine", "apply_rule", "parse_sql", "SqlError"]
