"""Rule registry + engine: CRUD, hook wiring, per-event dispatch.

Parity: emqx_rule_registry.erl (rule table) + emqx_rule_engine.erl
(create_rule) + the hook bridging in emqx_rule_events.erl:47-51 (one hook
per event present in any enabled rule's FROM clause). message.publish rules
additionally topic-filter on their FROM patterns before running SQL
(emqx_rule_runtime:apply_rules per-rule topic match).
"""

from __future__ import annotations

import logging
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from emqx_tpu.rules import events as EV
from emqx_tpu.rules.actions import run_action
from emqx_tpu.rules.metrics import RuleMetrics
from emqx_tpu.rules.runtime import apply_sql
from emqx_tpu.rules.sqlparser import parse_sql
from emqx_tpu.utils import topic as T

log = logging.getLogger("emqx_tpu.rules")

HOOK_TAG = "rule_engine"


@dataclass
class Rule:
    id: str
    sql: str
    ast: dict
    actions: list[dict]                  # [{"name":..., "params": {...}}]
    enabled: bool = True
    description: str = ""
    created_at: int = 0
    metrics: RuleMetrics = field(default_factory=RuleMetrics)

    @property
    def events(self) -> list[str]:
        return sorted({EV.event_name(t) for t in self.ast["from"]})

    def publish_filters(self) -> list[str]:
        """Non-$events FROM topics (message.publish topic filters)."""
        return [t for t in self.ast["from"]
                if EV.event_name(t) == "message.publish"]

    def to_map(self) -> dict:
        return {"id": self.id, "sql": self.sql, "enabled": self.enabled,
                "description": self.description,
                "created_at": self.created_at,
                "actions": [dict(a) for a in self.actions],
                "for": self.ast["from"],
                "metrics": self.metrics.to_map()}


class RuleEngine:
    def __init__(self, node):
        self.node = node
        self.rules: dict[str, Rule] = {}
        # event -> set of rule ids (emqx_rule_registry's rules_for)
        self._by_event: dict[str, set[str]] = {}
        self._hooked: set[str] = set()

    # ---- lifecycle ----
    def load(self) -> "RuleEngine":
        self.node.rule_engine = self
        return self

    def unload(self) -> None:
        for event in list(self._hooked):
            self._unhook(event)
        self.rules.clear()
        self._by_event.clear()
        if getattr(self.node, "rule_engine", None) is self:
            self.node.rule_engine = None

    # ---- CRUD (emqx_rule_engine:create_rule) ----
    def create_rule(self, sql: str, actions: list[dict],
                    rule_id: Optional[str] = None, enabled: bool = True,
                    description: str = "") -> Rule:
        ast = parse_sql(sql)
        rid = rule_id or f"rule:{uuid.uuid4().hex[:8]}"
        if rid in self.rules:
            raise ValueError(f"rule {rid} already exists")
        rule = Rule(id=rid, sql=sql, ast=ast, actions=list(actions),
                    enabled=enabled, description=description,
                    created_at=int(time.time() * 1000))
        self.rules[rid] = rule
        for event in rule.events:
            self._by_event.setdefault(event, set()).add(rid)
            if enabled:
                self._hook(event)
        return rule

    def delete_rule(self, rule_id: str) -> bool:
        rule = self.rules.pop(rule_id, None)
        if rule is None:
            return False
        for event, ids in list(self._by_event.items()):
            ids.discard(rule_id)
            if not ids:
                del self._by_event[event]
                self._unhook(event)
        return True

    def enable_rule(self, rule_id: str, enabled: bool) -> None:
        self.rules[rule_id].enabled = enabled
        if enabled:
            for event in self.rules[rule_id].events:
                self._hook(event)

    def get_rule(self, rule_id: str) -> Optional[Rule]:
        return self.rules.get(rule_id)

    def list_rules(self) -> list[Rule]:
        return sorted(self.rules.values(), key=lambda r: r.id)

    def tick_metrics(self) -> None:
        for r in self.rules.values():
            r.metrics.tick()

    # ---- hook wiring ----
    def _hook(self, event: str) -> None:
        if event in self._hooked:
            return
        self._hooked.add(event)
        handler = {
            "message.publish": self._on_publish,
            "client.connected": self._on_connected,
            "client.disconnected": self._on_disconnected,
            "session.subscribed": self._on_subscribed,
            "session.unsubscribed": self._on_unsubscribed,
            "message.delivered": self._on_delivered,
            "message.acked": self._on_acked,
            "message.dropped": self._on_dropped,
        }[event]
        self.node.hooks.add(event, handler, tag=HOOK_TAG, priority=-99)

    def _unhook(self, event: str) -> None:
        if event in self._hooked:
            self._hooked.discard(event)
            self.node.hooks.delete(event, HOOK_TAG)

    # ---- dispatch ----
    def _apply(self, event: str, columns: dict,
               publish_topic: Optional[str] = None) -> None:
        for rid in sorted(self._by_event.get(event, ())):
            rule = self.rules.get(rid)
            if rule is None or not rule.enabled:
                continue
            if publish_topic is not None:
                pats = rule.publish_filters()
                if pats and not any(T.match(publish_topic, p)
                                    for p in pats):
                    continue
            self._apply_one(rule, columns)

    def _apply_one(self, rule: Rule, columns: dict) -> None:
        m = rule.metrics
        m.inc("sql.matched")
        try:
            outs = apply_sql(rule.ast, columns)
        except Exception:  # noqa: BLE001 — SQL eval errors are per-rule stats
            m.inc("sql.failed")
            m.inc("sql.failed.exception")
            log.debug("rule %s sql failed", rule.id, exc_info=True)
            return
        if not outs:
            m.inc("sql.failed")
            m.inc("sql.failed.no_result")
            return
        m.inc("sql.passed")
        envs = {"rule_id": rule.id, "event": columns.get("event"),
                "__republished": columns.get("__republished", False)}
        for out in outs:
            for action in rule.actions:
                try:
                    run_action(self.node, action["name"],
                               action.get("params", {}), out, envs)
                    m.inc("actions.success")
                except Exception:  # noqa: BLE001
                    m.inc("actions.error")
                    log.debug("rule %s action %s failed", rule.id,
                              action["name"], exc_info=True)

    # ---- hook handlers (arg shapes per this broker's hookpoints) ----
    def _on_publish(self, msg):
        if msg.topic.startswith("$SYS/"):
            return
        cols = EV.columns_publish(msg)
        cols["__republished"] = bool(msg.get_header("__republished"))
        self._apply("message.publish", cols, publish_topic=msg.topic)

    def _on_connected(self, clientinfo, info):
        self._apply("client.connected",
                    EV.columns_connected(clientinfo, info or {}))

    def _on_disconnected(self, clientinfo, reason):
        self._apply("client.disconnected",
                    EV.columns_disconnected(clientinfo, reason))

    def _on_subscribed(self, clientinfo, topic, subopts):
        self._apply("session.subscribed",
                    EV.columns_sub_unsub("session.subscribed", clientinfo,
                                         topic, subopts))

    def _on_unsubscribed(self, clientinfo, topic):
        self._apply("session.unsubscribed",
                    EV.columns_sub_unsub("session.unsubscribed",
                                         clientinfo, topic))

    def _on_delivered(self, clientid, msg):
        self._apply("message.delivered", EV.columns_delivered(clientid, msg))

    def _on_acked(self, clientinfo, msg):
        cid = clientinfo.get("clientid") if isinstance(clientinfo, dict) \
            else clientinfo
        self._apply("message.acked", EV.columns_acked(cid, msg))

    def _on_dropped(self, msg, reason):
        self._apply("message.dropped", EV.columns_dropped(msg, reason))

    # ---- sql test (emqx_rule_sqltester) ----
    def test_sql(self, sql: str, context: dict) -> list[dict]:
        """Dry-run a SQL statement against a sample event context."""
        ast = parse_sql(sql)
        event = dict(context)
        event.setdefault("event", "message_publish")
        return apply_sql(ast, event)
