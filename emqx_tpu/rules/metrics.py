"""Per-rule metrics: matched / passed / failed counters + rolling speed.

Parity: emqx_rule_metrics.erl — per-rule counters (sql.matched, sql.passed,
sql.failed, sql.failed.exception, sql.failed.no_result, actions.success,
actions.error) and a speed gauge (current / max / last5m) computed by a
periodic tick over the matched counter.
"""

from __future__ import annotations

import time


class RuleMetrics:
    TICK_S = 1.0

    def __init__(self):
        self.counters: dict[str, int] = {}
        self._last_matched = 0
        self._last_tick = time.monotonic()
        self.speed = 0.0
        self.speed_max = 0.0
        self._window: list[float] = []   # last-5m samples

    def inc(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def val(self, name: str) -> int:
        return self.counters.get(name, 0)

    def tick(self) -> None:
        now = time.monotonic()
        dt = now - self._last_tick
        if dt <= 0:
            return
        matched = self.val("sql.matched")
        self.speed = (matched - self._last_matched) / dt
        self.speed_max = max(self.speed_max, self.speed)
        self._window.append(self.speed)
        if len(self._window) > 300:
            self._window.pop(0)
        self._last_matched = matched
        self._last_tick = now

    @property
    def speed_last5m(self) -> float:
        return sum(self._window) / len(self._window) if self._window else 0.0

    def to_map(self) -> dict:
        return {**self.counters,
                "speed": {"current": round(self.speed, 2),
                          "max": round(self.speed_max, 2),
                          "last5m": round(self.speed_last5m, 2)}}
