"""Built-in rule actions.

Parity: emqx_rule_actions.erl — inspect (console trace), republish
(template topic/payload/qos re-publish with loop protection), do_nothing;
data-to-bridge actions resolve through the resources layer (emqx_tpu.
resources) by resource id. Templates use ${var.path} placeholders like
emqx_rule_utils:preproc_tmpl.
"""

from __future__ import annotations

import json
import logging
import re
from typing import Any, Callable

from emqx_tpu.broker.message import make
from emqx_tpu.rules.maps import nested_get, parse_path

log = logging.getLogger("emqx_tpu.rules.actions")

_TMPL_RE = re.compile(r"\$\{([^}]+)\}")


def render_template(tmpl: str, columns: dict) -> str:
    """'${payload.x}' substitution (emqx_rule_utils:proc_tmpl)."""
    def sub(m):
        val = nested_get(columns, parse_path(m.group(1)))
        if val is None:
            return "undefined"
        if isinstance(val, (dict, list)):
            return json.dumps(val, separators=(",", ":"))
        if isinstance(val, bytes):
            return val.decode("utf-8", "replace")
        if isinstance(val, bool):
            return "true" if val else "false"
        return str(val)
    return _TMPL_RE.sub(sub, tmpl)


class ActionError(Exception):
    pass


def act_inspect(node, params: dict, columns: dict, envs: dict) -> None:
    log.info("[inspect] selected=%s envs=%s params=%s",
             columns, envs.get("event"), params)


def act_do_nothing(node, params: dict, columns: dict, envs: dict) -> None:
    return None


def act_republish(node, params: dict, columns: dict, envs: dict) -> None:
    """Re-publish with ${}-templated topic/payload/qos; republishing a
    message that itself came from a republish is refused to stop loops
    (emqx_rule_actions republish checks the republish-by flag)."""
    if envs.get("__republished"):
        log.warning("republish loop stopped for rule %s", envs.get("rule_id"))
        raise ActionError("republish loop detected")   # -> actions.error
    topic = render_template(params.get("target_topic", "repub/${topic}"),
                            columns)
    payload = render_template(params.get("payload_tmpl", "${payload}"),
                              columns)
    qos_t = params.get("target_qos", 0)
    if isinstance(qos_t, str):
        qos_t = int(render_template(qos_t, columns) or 0)
    qos = columns.get("qos", 0) if qos_t == -1 else qos_t
    msg = make(str(columns.get("clientid") or "rule_engine"), int(qos),
               topic, payload.encode(),
               headers={"republish_by": envs.get("rule_id")})
    msg.set_header("__republished", True)
    node.broker.publish_soon(msg)


BUILTIN_ACTIONS: dict[str, Callable] = {
    "inspect": act_inspect,
    "do_nothing": act_do_nothing,
    "republish": act_republish,
}


def run_action(node, name: str, params: dict, columns: dict,
               envs: dict) -> Any:
    fn = BUILTIN_ACTIONS.get(name)
    if fn is None:
        # resource-backed actions (data_to_*) dispatch via the resources app
        resources = getattr(node, "resources", None)
        if resources is not None and resources.has_action(name):
            return resources.run_action(name, params, columns, envs)
        raise ActionError(f"unknown action {name!r}")
    return fn(node, params, columns, envs)
