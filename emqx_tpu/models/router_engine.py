"""The flagship device program: one fused PUBLISH route step.

This is the TPU replacement for the reference broker's per-message hot path
(emqx_broker:publish/1 → emqx_router:match_routes → emqx_trie:match →
dispatch fold, emqx_broker.erl:199-308): for a whole micro-batch of publishes
it runs, in one jitted program,

  1. wildcard NFA match over the compiled trie        (ops.match)
  2. normal-subscriber fan-out segment-gather         (ops.fanout)
  3. shared-subscription member selection + cursors   (ops.shared)

State model: `RouterTables` is immutable (rebuilt/double-buffered by the host
router on subscription churn); `cursors` is the only mutable device state and
is threaded functionally through each step.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from emqx_tpu.ops.delta import (DeltaPlanes, DeltaTables, delta_expand,
                                delta_match)
from emqx_tpu.ops.fanout import FanoutResult, SubTable, fanout_normal, shared_slots
from emqx_tpu.ops.match import MatchResult, match_batch, merge_match_results
from emqx_tpu.ops.shapes import ShapeTables, shape_match
from emqx_tpu.ops.shared import SharedPickResult, pick_members
from emqx_tpu.ops.trie import TrieTables


class RouterTables(NamedTuple):
    """Device routing state for the trie-NFA backend (general shapes)."""
    trie: TrieTables
    subs: SubTable


class ShapeRouterTables(NamedTuple):
    """Device routing state for the shape-hash backend (the fast path)."""
    shapes: ShapeTables
    subs: SubTable


class RouteResult(NamedTuple):
    matches: jax.Array        # [B, M] matched filter ids
    match_counts: jax.Array   # [B]
    rows: jax.Array           # [B, D] normal delivery session rows
    opts: jax.Array           # [B, D] packed subopts
    fan_counts: jax.Array     # [B]
    shared_sids: jax.Array    # [B, K] matched shared-slot ids (-1 pad)
    shared_rows: jax.Array    # [B, K] shared picks (session rows)
    shared_opts: jax.Array    # [B, K]
    overflow: jax.Array       # [B] any capacity overflow → host fallback
    new_cursors: jax.Array    # [G]
    occur: jax.Array          # [G] shared-slot occurrences this batch


class ExchangeAux(NamedTuple):
    """Per-shard static companions the exchange stage (ISSUE 15) needs
    on device, stacked on the 'route' axis next to RouterTables. Built
    once per snapshot from the same capture as the shard tables (the
    host `_ShardBuilt` index), slice-updated by the per-shard churn
    path exactly like the tables."""
    seg_len: jax.Array   # [R, F_cap] int32: fan-out segment length per fid
    fid_slow: jax.Array  # [R, F_cap] bool: rich subopts / snapshot slots
    fid_off: jax.Array   # [R] int32: global-fid base per shard


class ExchangeResult(NamedTuple):
    """Output of the device-to-device exchange stage: each (dp, dest)
    device's final delivery plan — ONLY the rows whose sessions it owns
    (sid % R == dest), received from every source shard around the
    'route' ring. Rows are (msg, sid, gfid | packed_opt << 24) int32
    triples in (source shard asc, msg asc, row asc) order — the exact
    per-session interleaving the host gather/merge path produces."""
    plan: jax.Array      # [dp, R_dst, E, 3] int32, -1 pad
    plan_cnt: jax.Array  # [dp, R_dst] int32 (clamped to E)
    src_cnt: jax.Array   # [dp, R_dst, R_src] int32 segment boundaries
    ok: jax.Array        # [dp, R] int32 bitmask: 1=msgs clean, 2=caps fit


def post_match(subs: SubTable, mr: MatchResult, cursors: jax.Array,
               msg_hash: jax.Array, strategy: jax.Array, *,
               fanout_cap: int, slot_cap: int) -> RouteResult:
    """Fan-out + shared-sub selection on a MatchResult (backend-agnostic)."""
    fr: FanoutResult = fanout_normal(subs, mr.matches, fanout_cap=fanout_cap)
    sids, slot_oflow = shared_slots(subs, mr.matches, slot_cap=slot_cap)
    sp: SharedPickResult = pick_members(subs, cursors, sids, strategy,
                                        msg_hash)
    overflow = mr.overflow | fr.overflow | slot_oflow
    return RouteResult(
        matches=mr.matches, match_counts=mr.counts,
        rows=fr.rows, opts=fr.opts, fan_counts=fr.counts,
        shared_sids=sids, shared_rows=sp.rows, shared_opts=sp.opts,
        overflow=overflow, new_cursors=sp.new_cursors, occur=sp.occur)


@functools.partial(
    jax.jit,
    static_argnames=("frontier_cap", "match_cap", "fanout_cap", "slot_cap"))
def route_step(tables: RouterTables, cursors: jax.Array, topics: jax.Array,
               lens: jax.Array, is_dollar: jax.Array, msg_hash: jax.Array,
               strategy: jax.Array, *, frontier_cap: int = 16,
               match_cap: int = 64, fanout_cap: int = 128,
               slot_cap: int = 16) -> RouteResult:
    """Trie-NFA route step: match + fan-out + shared picks (general shapes)."""
    mr = match_batch(tables.trie, topics, lens, is_dollar,
                     frontier_cap=frontier_cap, match_cap=match_cap)
    return post_match(tables.subs, mr, cursors, msg_hash, strategy,
                      fanout_cap=fanout_cap, slot_cap=slot_cap)


@functools.partial(jax.jit, static_argnames=("fanout_cap", "slot_cap"))
def route_step_shapes(tables: ShapeRouterTables, cursors: jax.Array,
                      topics: jax.Array, lens: jax.Array,
                      is_dollar: jax.Array, msg_hash: jax.Array,
                      strategy: jax.Array, *, fanout_cap: int = 128,
                      slot_cap: int = 16) -> RouteResult:
    """Shape-hash route step: one bucket gather per (topic, shape)."""
    mr = shape_match(tables.shapes, topics, lens, is_dollar)
    return post_match(tables.subs, mr, cursors, msg_hash, strategy,
                      fanout_cap=fanout_cap, slot_cap=slot_cap)


@functools.partial(
    jax.jit,
    static_argnames=("frontier_cap", "match_cap", "fanout_cap", "slot_cap"))
def route_step_cached(tables: RouterTables, cursors: jax.Array,
                      miss_topics: jax.Array, miss_lens: jax.Array,
                      miss_dollar: jax.Array, base_matches: jax.Array,
                      base_counts: jax.Array, base_overflow: jax.Array,
                      miss_pos: jax.Array, inv: jax.Array,
                      msg_hash: jax.Array, strategy: jax.Array, *,
                      frontier_cap: int = 16, match_cap: int = 64,
                      fanout_cap: int = 128,
                      slot_cap: int = 16) -> RouteResult:
    """Trie-NFA route step over a DEDUPLICATED batch with cached rows.

    The match stage runs only on the [Bm] compacted miss lanes
    (Bm quantized to the standard batch-class ladder); cache-hit unique
    topics ride in as host-filled base_* rows ([U] per-unique-topic).
    `inv` [B] scatters the merged unique MatchResult back to full batch
    width before the cursor-dependent post stage, so fan-out, shared
    picks and cursor threading are bit-identical to the un-deduplicated
    `route_step` on the same batch (oracle-tested)."""
    mr = match_batch(tables.trie, miss_topics, miss_lens, miss_dollar,
                     frontier_cap=frontier_cap, match_cap=match_cap)
    um = merge_match_results(base_matches, base_counts, base_overflow,
                             mr, miss_pos)
    full = MatchResult(matches=um.matches[inv], counts=um.counts[inv],
                       overflow=um.overflow[inv])
    return post_match(tables.subs, full, cursors, msg_hash, strategy,
                      fanout_cap=fanout_cap, slot_cap=slot_cap)


@functools.partial(jax.jit, static_argnames=("fanout_cap", "slot_cap"))
def route_window_cached(tables: ShapeRouterTables, cursors: jax.Array,
                        miss_topics: jax.Array, miss_lens: jax.Array,
                        miss_dollar: jax.Array, base_matches: jax.Array,
                        base_counts: jax.Array, base_overflow: jax.Array,
                        miss_pos: jax.Array, inv: jax.Array,
                        msg_hash: jax.Array, strategy: jax.Array, *,
                        fanout_cap: int = 128,
                        slot_cap: int = 16) -> RouteResult:
    """Shape-hash window step over a DEDUPLICATED window with cached rows.

    One dispatch routes W sub-batches while the shape-hash match runs
    ONCE over the [Bm] compacted miss lanes (every other lane of the
    [W, B] window is either a duplicate of a miss lane, a cache hit
    served from base_* rows, or padding collapsed onto the shared
    sentinel row). `inv` [W, B] gathers the merged unique rows back to
    full window width per scan step; cursors thread through the scan
    exactly as W sequential `route_step_shapes` calls, so the stacked
    RouteResult is bit-identical to `route_window_full` on the same
    window (oracle-tested)."""
    mr = shape_match(tables.shapes, miss_topics, miss_lens, miss_dollar)
    um = merge_match_results(base_matches, base_counts, base_overflow,
                             mr, miss_pos)

    def step(cur, xs):
        inv_k, mh_k = xs
        full = MatchResult(matches=um.matches[inv_k],
                           counts=um.counts[inv_k],
                           overflow=um.overflow[inv_k])
        r = post_match(tables.subs, full, cur, mh_k, strategy,
                       fanout_cap=fanout_cap, slot_cap=slot_cap)
        return r.new_cursors, r

    _, stacked = jax.lax.scan(step, cursors, (inv, msg_hash))
    return stacked


class CompactRouteResult(NamedTuple):
    """A route result with its fused CSR readback (ops.compact).

    `res` carries the FULL window-stacked dense planes — they are
    intermediates of the same program, so returning them costs nothing;
    the host reads them back only when `compact.row_overflow` fires
    (payload class too small for this window) — the dense fallback needs
    no re-dispatch. Every per-topic plane in `res` is window-shaped
    ([W, ...]) for ALL variants, including the single-batch trie steps
    (W = 1), so the consume path is uniform."""
    res: RouteResult
    compact: "CompactPlanes"  # noqa: F821 — imported lazily below


def _with_compact(r: RouteResult, payload_cap: int,
                  match_holes: bool) -> CompactRouteResult:
    """match_holes=True for the shape-hash backend (matches carry
    interior holes at unmatched shape slots), False for the trie NFA
    (emissions are densely packed already — the hole-closing stage
    compiles away). The engine's window variants are shapes-only and
    the step variants trie-only, so each hardcodes its flag."""
    from emqx_tpu.ops.compact import compact_result
    cp = compact_result(r.matches, r.rows, r.opts, r.fan_counts,
                        r.shared_sids, r.shared_rows, r.shared_opts,
                        payload_cap=payload_cap, match_holes=match_holes)
    return CompactRouteResult(res=r, compact=cp)


def _stack1(r: RouteResult) -> RouteResult:
    """Lift a single-batch RouteResult to window form (W = 1)."""
    return RouteResult(*[x[None] for x in r])


@functools.partial(
    jax.jit,
    static_argnames=("frontier_cap", "match_cap", "fanout_cap",
                     "slot_cap", "payload_cap"))
def route_step_compact(tables: RouterTables, cursors: jax.Array,
                       topics: jax.Array, lens: jax.Array,
                       is_dollar: jax.Array, msg_hash: jax.Array,
                       strategy: jax.Array, *, frontier_cap: int = 16,
                       match_cap: int = 64, fanout_cap: int = 128,
                       slot_cap: int = 16,
                       payload_cap: int = 4096) -> CompactRouteResult:
    """Trie-NFA route step with the fused CSR readback (window-shaped)."""
    r = route_step(tables, cursors, topics, lens, is_dollar, msg_hash,
                   strategy, frontier_cap=frontier_cap,
                   match_cap=match_cap, fanout_cap=fanout_cap,
                   slot_cap=slot_cap)
    return _with_compact(_stack1(r), payload_cap, match_holes=False)


@functools.partial(
    jax.jit,
    static_argnames=("frontier_cap", "match_cap", "fanout_cap",
                     "slot_cap", "payload_cap"))
def route_step_cached_compact(tables: RouterTables, cursors: jax.Array,
                              miss_topics: jax.Array,
                              miss_lens: jax.Array,
                              miss_dollar: jax.Array,
                              base_matches: jax.Array,
                              base_counts: jax.Array,
                              base_overflow: jax.Array,
                              miss_pos: jax.Array, inv: jax.Array,
                              msg_hash: jax.Array, strategy: jax.Array,
                              *, frontier_cap: int = 16,
                              match_cap: int = 64, fanout_cap: int = 128,
                              slot_cap: int = 16,
                              payload_cap: int = 4096
                              ) -> CompactRouteResult:
    """Deduplicated trie step + fused CSR readback (window-shaped)."""
    r = route_step_cached(tables, cursors, miss_topics, miss_lens,
                          miss_dollar, base_matches, base_counts,
                          base_overflow, miss_pos, inv, msg_hash,
                          strategy, frontier_cap=frontier_cap,
                          match_cap=match_cap, fanout_cap=fanout_cap,
                          slot_cap=slot_cap)
    return _with_compact(_stack1(r), payload_cap, match_holes=False)


@functools.partial(jax.jit,
                   static_argnames=("fanout_cap", "slot_cap",
                                    "payload_cap"))
def route_window_full_compact(tables: ShapeRouterTables,
                              cursors: jax.Array, topics: jax.Array,
                              lens: jax.Array, is_dollar: jax.Array,
                              msg_hash: jax.Array, strategy: jax.Array,
                              *, fanout_cap: int = 128,
                              slot_cap: int = 16,
                              payload_cap: int = 4096
                              ) -> CompactRouteResult:
    """route_window_full + fused CSR readback in the same dispatch."""
    r = route_window_full(tables, cursors, topics, lens, is_dollar,
                          msg_hash, strategy, fanout_cap=fanout_cap,
                          slot_cap=slot_cap)
    return _with_compact(r, payload_cap, match_holes=True)


@functools.partial(jax.jit,
                   static_argnames=("fanout_cap", "slot_cap",
                                    "payload_cap"))
def route_window_cached_compact(tables: ShapeRouterTables,
                                cursors: jax.Array,
                                miss_topics: jax.Array,
                                miss_lens: jax.Array,
                                miss_dollar: jax.Array,
                                base_matches: jax.Array,
                                base_counts: jax.Array,
                                base_overflow: jax.Array,
                                miss_pos: jax.Array, inv: jax.Array,
                                msg_hash: jax.Array,
                                strategy: jax.Array, *,
                                fanout_cap: int = 128,
                                slot_cap: int = 16,
                                payload_cap: int = 4096
                                ) -> CompactRouteResult:
    """route_window_cached + fused CSR readback in the same dispatch."""
    r = route_window_cached(tables, cursors, miss_topics, miss_lens,
                            miss_dollar, base_matches, base_counts,
                            base_overflow, miss_pos, inv, msg_hash,
                            strategy, fanout_cap=fanout_cap,
                            slot_cap=slot_cap)
    return _with_compact(r, payload_cap, match_holes=True)


class DeltaRouteResult(NamedTuple):
    """A route result with its fused delta-overlay planes (ops.delta).

    `res` is the main-snapshot RouteResult, window-shaped [W, ...] for
    every variant (single-batch trie steps lift to W = 1 like the
    compact twins); `dp` carries the overlay's match + fan-out planes,
    each [W, B, ...]. The two fid spaces are disjoint by construction:
    `res.matches` are built-snapshot fids, `dp.fids` are the engine's
    delta fids — the host consume walks both, so a filter subscribed
    one window ago delivers from THIS dispatch instead of host-routing
    (the churn hole ISSUE 4 closes)."""
    res: RouteResult
    dp: DeltaPlanes           # every field [W, B, ...]


class CompactDeltaRouteResult(NamedTuple):
    """DeltaRouteResult + fused CSR readbacks for BOTH plane families.

    `compact` is the main planes' CSR (ops.compact); `d_compact` the
    overlay planes' CSR, reusing the same op with an empty shared
    family (cs == 0 in every row) so `csr_slices` decodes both with one
    code path. The dense planes stay in `dres` as free same-program
    outputs — either CSR overflowing its payload class falls back to
    the corresponding dense planes with no re-dispatch."""
    dres: DeltaRouteResult
    compact: "CompactPlanes"      # noqa: F821 — imported lazily
    d_compact: "CompactPlanes"    # noqa: F821


def _window_delta(delta: DeltaTables, topics: jax.Array, lens: jax.Array,
                  is_dollar: jax.Array, *, dmatch_cap: int,
                  dfan_cap: int) -> DeltaPlanes:
    """Overlay planes for a full [W, B] window: the linear matcher is
    cursor-independent, so it runs ONCE over the flattened lanes instead
    of per scan step."""
    W, B = topics.shape[:2]
    mr = delta_match(delta, topics.reshape(W * B, -1),
                     lens.reshape(W * B), is_dollar.reshape(W * B),
                     match_cap=dmatch_cap)
    dp = delta_expand(delta, mr, fanout_cap=dfan_cap)
    return DeltaPlanes(*[x.reshape((W, B) + x.shape[1:]) for x in dp])


def _cached_delta(delta: DeltaTables, miss_topics, miss_lens, miss_dollar,
                  base_dm, base_dc, base_do, miss_pos, inv, *,
                  dmatch_cap: int, dfan_cap: int) -> DeltaPlanes:
    """Overlay planes for a DEDUPLICATED dispatch: the linear matcher
    runs only on the [Bm] miss lanes; cache-hit unique topics ride in as
    host-filled base rows (overlay ROW indices + counts + MATCH-level
    overflow) merged with the same scatter as the main match
    (ops.match.merge_match_results), then fan-out expands the merged
    unique rows against the CURRENT overlay CSR — so cached rows carry
    no membership state and a subscriber change can never stale them —
    and `inv` gathers back to full width."""
    mr = delta_match(delta, miss_topics, miss_lens, miss_dollar,
                     match_cap=dmatch_cap)
    um = merge_match_results(base_dm, base_dc, base_do, mr, miss_pos)
    dp_u = delta_expand(delta, um, fanout_cap=dfan_cap)
    return DeltaPlanes(*[x[inv] for x in dp_u])


def _stack1_dp(dp: DeltaPlanes) -> DeltaPlanes:
    return DeltaPlanes(*[x[None] for x in dp])


@functools.partial(
    jax.jit,
    static_argnames=("frontier_cap", "match_cap", "fanout_cap",
                     "slot_cap", "delta_match_cap", "delta_fanout_cap"))
def route_step_delta(tables: RouterTables, delta: DeltaTables,
                     cursors: jax.Array, topics: jax.Array,
                     lens: jax.Array, is_dollar: jax.Array,
                     msg_hash: jax.Array, strategy: jax.Array, *,
                     frontier_cap: int = 16, match_cap: int = 64,
                     fanout_cap: int = 128, slot_cap: int = 16,
                     delta_match_cap: int = 16,
                     delta_fanout_cap: int = 64) -> DeltaRouteResult:
    """Trie-NFA route step + delta overlay in one dispatch (W = 1)."""
    r = route_step(tables, cursors, topics, lens, is_dollar, msg_hash,
                   strategy, frontier_cap=frontier_cap,
                   match_cap=match_cap, fanout_cap=fanout_cap,
                   slot_cap=slot_cap)
    dp = delta_expand(delta, delta_match(delta, topics, lens, is_dollar,
                                         match_cap=delta_match_cap),
                      fanout_cap=delta_fanout_cap)
    return DeltaRouteResult(res=_stack1(r), dp=_stack1_dp(dp))


@functools.partial(
    jax.jit,
    static_argnames=("fanout_cap", "slot_cap", "delta_match_cap",
                     "delta_fanout_cap"))
def route_window_delta(tables: ShapeRouterTables, delta: DeltaTables,
                       cursors: jax.Array, topics: jax.Array,
                       lens: jax.Array, is_dollar: jax.Array,
                       msg_hash: jax.Array, strategy: jax.Array, *,
                       fanout_cap: int = 128, slot_cap: int = 16,
                       delta_match_cap: int = 16,
                       delta_fanout_cap: int = 64) -> DeltaRouteResult:
    """route_window_full + delta overlay fused in the same dispatch."""
    r = route_window_full(tables, cursors, topics, lens, is_dollar,
                          msg_hash, strategy, fanout_cap=fanout_cap,
                          slot_cap=slot_cap)
    dp = _window_delta(delta, topics, lens, is_dollar,
                       dmatch_cap=delta_match_cap,
                       dfan_cap=delta_fanout_cap)
    return DeltaRouteResult(res=r, dp=dp)


@functools.partial(
    jax.jit,
    static_argnames=("frontier_cap", "match_cap", "fanout_cap",
                     "slot_cap", "delta_match_cap", "delta_fanout_cap"))
def route_step_delta_cached(tables: RouterTables, delta: DeltaTables,
                            cursors: jax.Array, miss_topics: jax.Array,
                            miss_lens: jax.Array, miss_dollar: jax.Array,
                            base_matches: jax.Array,
                            base_counts: jax.Array,
                            base_overflow: jax.Array,
                            base_dm: jax.Array, base_dc: jax.Array,
                            base_do: jax.Array, miss_pos: jax.Array,
                            inv: jax.Array, msg_hash: jax.Array,
                            strategy: jax.Array, *,
                            frontier_cap: int = 16, match_cap: int = 64,
                            fanout_cap: int = 128, slot_cap: int = 16,
                            delta_match_cap: int = 16,
                            delta_fanout_cap: int = 64
                            ) -> DeltaRouteResult:
    """Deduplicated trie step + delta overlay (cached base rows carry
    BOTH fid spaces; see _cached_delta for the merge contract)."""
    r = route_step_cached(tables, cursors, miss_topics, miss_lens,
                          miss_dollar, base_matches, base_counts,
                          base_overflow, miss_pos, inv, msg_hash,
                          strategy, frontier_cap=frontier_cap,
                          match_cap=match_cap, fanout_cap=fanout_cap,
                          slot_cap=slot_cap)
    dp = _cached_delta(delta, miss_topics, miss_lens, miss_dollar,
                       base_dm, base_dc, base_do, miss_pos, inv,
                       dmatch_cap=delta_match_cap,
                       dfan_cap=delta_fanout_cap)
    return DeltaRouteResult(res=_stack1(r), dp=_stack1_dp(dp))


@functools.partial(
    jax.jit,
    static_argnames=("fanout_cap", "slot_cap", "delta_match_cap",
                     "delta_fanout_cap"))
def route_window_delta_cached(tables: ShapeRouterTables,
                              delta: DeltaTables, cursors: jax.Array,
                              miss_topics: jax.Array,
                              miss_lens: jax.Array,
                              miss_dollar: jax.Array,
                              base_matches: jax.Array,
                              base_counts: jax.Array,
                              base_overflow: jax.Array,
                              base_dm: jax.Array, base_dc: jax.Array,
                              base_do: jax.Array, miss_pos: jax.Array,
                              inv: jax.Array, msg_hash: jax.Array,
                              strategy: jax.Array, *,
                              fanout_cap: int = 128, slot_cap: int = 16,
                              delta_match_cap: int = 16,
                              delta_fanout_cap: int = 64
                              ) -> DeltaRouteResult:
    """route_window_cached + delta overlay fused in the same dispatch."""
    r = route_window_cached(tables, cursors, miss_topics, miss_lens,
                            miss_dollar, base_matches, base_counts,
                            base_overflow, miss_pos, inv, msg_hash,
                            strategy, fanout_cap=fanout_cap,
                            slot_cap=slot_cap)
    dp = _cached_delta(delta, miss_topics, miss_lens, miss_dollar,
                       base_dm, base_dc, base_do, miss_pos, inv,
                       dmatch_cap=delta_match_cap,
                       dfan_cap=delta_fanout_cap)
    return DeltaRouteResult(res=r, dp=dp)


def _with_delta_compact(dres: DeltaRouteResult, payload_cap: int,
                        d_payload_cap: int,
                        match_holes: bool) -> CompactDeltaRouteResult:
    """Fuse both CSR compactions onto a delta route result. The delta
    family reuses ops.compact.compact_result with a width-1 all-empty
    shared family (cs == 0), so offsets/counts3/payload decode with the
    same csr_slices as the main planes; delta matches are always
    prefix-compacted (match_holes=False compiles the hole stage away)."""
    from emqx_tpu.ops.compact import compact_result
    r, dp = dres.res, dres.dp
    cp = compact_result(r.matches, r.rows, r.opts, r.fan_counts,
                        r.shared_sids, r.shared_rows, r.shared_opts,
                        payload_cap=payload_cap, match_holes=match_holes)
    W, B = dp.fids.shape[:2]
    no_slot = jnp.full((W, B, 1), -1, jnp.int32)
    zero32 = jnp.zeros((W, B, 1), jnp.int32)
    zero8 = jnp.zeros((W, B, 1), jnp.int8)
    dcp = compact_result(dp.fids, dp.rows, dp.opts, dp.fan_counts,
                         no_slot, zero32, zero8,
                         payload_cap=d_payload_cap, match_holes=False)
    return CompactDeltaRouteResult(dres=dres, compact=cp, d_compact=dcp)


@functools.partial(
    jax.jit,
    static_argnames=("frontier_cap", "match_cap", "fanout_cap",
                     "slot_cap", "delta_match_cap", "delta_fanout_cap",
                     "payload_cap", "d_payload_cap"))
def route_step_delta_compact(tables, delta, cursors, topics, lens,
                             is_dollar, msg_hash, strategy, *,
                             frontier_cap: int = 16, match_cap: int = 64,
                             fanout_cap: int = 128, slot_cap: int = 16,
                             delta_match_cap: int = 16,
                             delta_fanout_cap: int = 64,
                             payload_cap: int = 4096,
                             d_payload_cap: int = 1024
                             ) -> CompactDeltaRouteResult:
    """route_step_delta + fused CSR readbacks (both plane families)."""
    dres = route_step_delta(tables, delta, cursors, topics, lens,
                            is_dollar, msg_hash, strategy,
                            frontier_cap=frontier_cap,
                            match_cap=match_cap, fanout_cap=fanout_cap,
                            slot_cap=slot_cap,
                            delta_match_cap=delta_match_cap,
                            delta_fanout_cap=delta_fanout_cap)
    return _with_delta_compact(dres, payload_cap, d_payload_cap,
                               match_holes=False)


@functools.partial(
    jax.jit,
    static_argnames=("fanout_cap", "slot_cap", "delta_match_cap",
                     "delta_fanout_cap", "payload_cap", "d_payload_cap"))
def route_window_delta_compact(tables, delta, cursors, topics, lens,
                               is_dollar, msg_hash, strategy, *,
                               fanout_cap: int = 128, slot_cap: int = 16,
                               delta_match_cap: int = 16,
                               delta_fanout_cap: int = 64,
                               payload_cap: int = 4096,
                               d_payload_cap: int = 1024
                               ) -> CompactDeltaRouteResult:
    """route_window_delta + fused CSR readbacks (both plane families)."""
    dres = route_window_delta(tables, delta, cursors, topics, lens,
                              is_dollar, msg_hash, strategy,
                              fanout_cap=fanout_cap, slot_cap=slot_cap,
                              delta_match_cap=delta_match_cap,
                              delta_fanout_cap=delta_fanout_cap)
    return _with_delta_compact(dres, payload_cap, d_payload_cap,
                               match_holes=True)


@functools.partial(
    jax.jit,
    static_argnames=("frontier_cap", "match_cap", "fanout_cap",
                     "slot_cap", "delta_match_cap", "delta_fanout_cap",
                     "payload_cap", "d_payload_cap"))
def route_step_delta_cached_compact(tables, delta, cursors, miss_topics,
                                    miss_lens, miss_dollar, base_matches,
                                    base_counts, base_overflow, base_dm,
                                    base_dc, base_do, miss_pos, inv,
                                    msg_hash, strategy, *,
                                    frontier_cap: int = 16,
                                    match_cap: int = 64,
                                    fanout_cap: int = 128,
                                    slot_cap: int = 16,
                                    delta_match_cap: int = 16,
                                    delta_fanout_cap: int = 64,
                                    payload_cap: int = 4096,
                                    d_payload_cap: int = 1024
                                    ) -> CompactDeltaRouteResult:
    """Deduplicated trie step + overlay + both CSR readbacks."""
    dres = route_step_delta_cached(
        tables, delta, cursors, miss_topics, miss_lens, miss_dollar,
        base_matches, base_counts, base_overflow, base_dm, base_dc,
        base_do, miss_pos, inv, msg_hash, strategy,
        frontier_cap=frontier_cap, match_cap=match_cap,
        fanout_cap=fanout_cap, slot_cap=slot_cap,
        delta_match_cap=delta_match_cap,
        delta_fanout_cap=delta_fanout_cap)
    return _with_delta_compact(dres, payload_cap, d_payload_cap,
                               match_holes=False)


@functools.partial(
    jax.jit,
    static_argnames=("fanout_cap", "slot_cap", "delta_match_cap",
                     "delta_fanout_cap", "payload_cap", "d_payload_cap"))
def route_window_delta_cached_compact(tables, delta, cursors,
                                      miss_topics, miss_lens,
                                      miss_dollar, base_matches,
                                      base_counts, base_overflow,
                                      base_dm, base_dc, base_do,
                                      miss_pos, inv, msg_hash, strategy,
                                      *, fanout_cap: int = 128,
                                      slot_cap: int = 16,
                                      delta_match_cap: int = 16,
                                      delta_fanout_cap: int = 64,
                                      payload_cap: int = 4096,
                                      d_payload_cap: int = 1024
                                      ) -> CompactDeltaRouteResult:
    """Deduplicated window step + overlay + both CSR readbacks."""
    dres = route_window_delta_cached(
        tables, delta, cursors, miss_topics, miss_lens, miss_dollar,
        base_matches, base_counts, base_overflow, base_dm, base_dc,
        base_do, miss_pos, inv, msg_hash, strategy,
        fanout_cap=fanout_cap, slot_cap=slot_cap,
        delta_match_cap=delta_match_cap,
        delta_fanout_cap=delta_fanout_cap)
    return _with_delta_compact(dres, payload_cap, d_payload_cap,
                               match_holes=True)


def route_digest(r: RouteResult) -> jax.Array:
    """Scalar int32 reduction over EVERY RouteResult output plane.

    Benchmarks close a dispatch window with one scalar readback; summing
    every plane here (not a subset) stops XLA dead-code-eliminating any
    stage of the step out of the measurement. One definition shared by the
    fused window, bench.py's single-step path, and the oracle test, so the
    two measurements can never silently diverge."""
    return (r.matches.sum(dtype=jnp.int32)
            + r.rows.sum(dtype=jnp.int32)
            + r.opts.sum(dtype=jnp.int32)
            + r.fan_counts.sum(dtype=jnp.int32)
            + r.shared_sids.sum(dtype=jnp.int32)
            + r.shared_rows.sum(dtype=jnp.int32)
            + r.shared_opts.sum(dtype=jnp.int32)
            + r.match_counts.sum(dtype=jnp.int32)
            + r.overflow.sum(dtype=jnp.int32)
            + r.occur.sum(dtype=jnp.int32))


@functools.partial(jax.jit, static_argnames=("fanout_cap", "slot_cap"))
def route_window_shapes(tables: ShapeRouterTables, cursors: jax.Array,
                        topics: jax.Array, lens: jax.Array,
                        is_dollar: jax.Array, msg_hash: jax.Array,
                        strategy: jax.Array, *, fanout_cap: int = 128,
                        slot_cap: int = 16):
    """W fused route steps in ONE dispatch: scan over a [W, B, ...] window.

    Per-dispatch overhead (HTTP relay round trip, or runtime launch cost on
    co-located hardware) is paid once for W batches instead of W times —
    the round-2 bench showed the per-call floor (match-only 14.1ms vs the
    match fold's own rate) is a visible slice of the 65ms batch. Cursors
    thread through the scan exactly as through W sequential calls
    (bit-identical; oracle-tested), so round-robin fairness holds across
    the whole window.

    Returns (new_cursors, digest [W] int32) — route_digest per step forces
    the full routing computation while keeping the device→host readback
    scalar-sized.
    """
    def step(cur, batch):
        t, l, d, h = batch
        r = route_step_shapes(tables, cur, t, l, d, h, strategy,
                              fanout_cap=fanout_cap, slot_cap=slot_cap)
        return r.new_cursors, route_digest(r)

    new_cursors, digests = jax.lax.scan(
        step, cursors, (topics, lens, is_dollar, msg_hash))
    return new_cursors, digests


@functools.partial(jax.jit, static_argnames=("fanout_cap", "slot_cap"))
def route_window_full(tables: ShapeRouterTables, cursors: jax.Array,
                      topics: jax.Array, lens: jax.Array,
                      is_dollar: jax.Array, msg_hash: jax.Array,
                      strategy: jax.Array, *, fanout_cap: int = 128,
                      slot_cap: int = 16) -> RouteResult:
    """W fused route steps in ONE dispatch, returning the FULL stacked
    RouteResult (every field [W, ...]) — the serving path's window
    variant (route_window_shapes returns digests only, for benches).
    Cursors thread through the scan exactly as W sequential calls, so
    `new_cursors`/`occur` in row k reflect state after sub-batch k."""
    def step(cur, batch):
        t, l, d, h = batch
        r = route_step_shapes(tables, cur, t, l, d, h, strategy,
                              fanout_cap=fanout_cap, slot_cap=slot_cap)
        return r.new_cursors, r

    _, stacked = jax.lax.scan(
        step, cursors, (topics, lens, is_dollar, msg_hash))
    return stacked


def compile_stats() -> dict[str, int]:
    """Jit-cache entry counts per route-step program. Each entry is one
    compiled (shape, dtype, static-args) variant, so a growing number
    under steady traffic means the serving path is re-tracing — the
    recompile signal pipeline telemetry surfaces via
    `GET /api/v5/pipeline/stats` and the bench telemetry snapshot.
    The per-class flop/byte/compile-time decomposition of the same
    programs lives in `cost_stats()` (the ISSUE-8 cost registry)."""
    out = {}
    for fn in (route_step, route_step_shapes, route_window_shapes,
               route_window_full, route_step_cached, route_window_cached,
               route_step_compact, route_step_cached_compact,
               route_window_full_compact, route_window_cached_compact,
               route_step_delta, route_window_delta,
               route_step_delta_cached, route_window_delta_cached,
               route_step_delta_compact, route_window_delta_compact,
               route_step_delta_cached_compact,
               route_window_delta_cached_compact):
        try:
            out[fn.__name__] = fn._cache_size()
        except Exception:  # noqa: BLE001 — cache introspection is best-effort
            pass
    # the ISSUE-9 donating twins compile in their own caches but are
    # the same programs — fold their entry counts into the plain names
    # so the exported stats stay one name space at any dispatch depth
    for name, n in donating_compile_stats().items():
        out[name] = out.get(name, 0) + n
    # the ISSUE-15 exchange programs live in parallel.sharded (one per
    # segment-capacity class); fold them in without forcing the import
    import sys
    sh = sys.modules.get("emqx_tpu.parallel.sharded")
    if sh is not None:
        try:
            out.update(sh.exchange_compile_stats())
        except Exception:  # noqa: BLE001 — introspection is best-effort
            pass
    return out


# ---- jit-program cost registry (ISSUE 8) --------------------------------
# Every fused route program records, per compiled (W, B[, Bm][, dC][, P])
# class, its compile wall-time and — on demand — the lowered program's
# cost_analysis() (flops, bytes accessed). This is the per-program cost
# table the ROADMAP-item-2 stage-graph builder needs as its oracle, and
# the compiled-program leg of the ISSUE-8 device-resource observatory
# (the HBM ledger meters data; this meters programs).
#
# Mechanics: each public program is wrapped so a call that GREW the
# jit cache (a fresh compile) registers one row keyed by the active
# telemetry compile-context label (the same "warm W8xB1024" /
# "dispatch W1xB256" key space as snapshot()["compiles"]["by_shape"]),
# with the args saved as ShapeDtypeStructs — no device data retained.
# The flop/byte analysis itself is LAZY: `cost_stats(analyze=True)`
# re-lowers from the saved avals (tracing only, no backend compile, no
# jit-cache growth) the first time each row is queried, so the serving
# path never pays for it; re-traces run outside any compile_context,
# so telemetry's recompile counters are not inflated. Calls made while
# tracing (a program fused inside another) bypass the bookkeeping
# entirely — the outer program owns the compile.

_COSTS: dict[str, dict[str, dict]] = {}
_costs_lock = threading.Lock()
_cost_programs: dict[str, object] = {}

# The registry rides the observatory knob: EMQX_TPU_HBM_LEDGER=0 must
# restore pre-ISSUE-8 behavior EXACTLY, and the route programs are
# bound at import time, so this leg resolves the env half of the knob
# once here (the per-node `broker.hbm_ledger` config gates the per-node
# ledger; this registry is process-wide like the programs themselves).
# Off means: programs stay unwrapped, zero per-call introspection, no
# `program_costs` section in snapshots.
from emqx_tpu.broker.hbm_ledger import resolve_hbm_ledger as _resolve_hbm

COST_REGISTRY_ON = _resolve_hbm(None)


def cost_registry_enabled() -> bool:
    """Whether the route programs are wrapped with compile detection —
    telemetry gates the `program_costs` snapshot section on this."""
    return COST_REGISTRY_ON


def _thread_compile_seq():
    """Telemetry's per-thread compile-event counter (None when no
    jax.monitoring listener is installed — no confirmation signal)."""
    try:
        from emqx_tpu.broker import telemetry as _T
        return _T.thread_compile_seq()
    except Exception:  # noqa: BLE001 — confirmation is best-effort
        return None


def _active_cost_label() -> "str | None":
    """The thread's telemetry compile-context label, if any — keeps the
    registry keyed the same way as the recompile counters."""
    try:
        from emqx_tpu.broker import telemetry as _T
        ctx = getattr(_T._tls, "ctx", None)
        if ctx is not None:
            return ctx[1]
    except Exception:  # noqa: BLE001 — labeling is best-effort
        pass
    return None


def _avals_of(args, kwargs):
    """(args, kwargs) with array leaves replaced by ShapeDtypeStructs
    (statics pass through) — enough to re-lower, nothing pinned."""
    def one(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
        return x
    return jax.tree.map(one, (args, dict(kwargs)))


def record_program_cost(program: str, label: str, *,
                        compile_ms: float = 0.0, flops=None,
                        bytes_accessed=None, avals=None) -> None:
    """Register/extend one (program, class) cost row. The wrapped route
    programs call this on compile detection; external harnesses
    (tools/profile_step.py) use it to put their own kernels in the same
    table."""
    with _costs_lock:
        row = _COSTS.setdefault(program, {}).setdefault(
            label, {"compiles": 0, "compile_ms": 0.0})
        row["compiles"] += 1
        row["compile_ms"] = round(row["compile_ms"] + compile_ms, 3)
        if flops is not None:
            row["flops"] = flops
        if bytes_accessed is not None:
            row["bytes_accessed"] = bytes_accessed
        if avals is not None:
            row["_avals"] = avals


def _analyze_lowered(lowered) -> tuple:
    """(flops, bytes_accessed) out of a Lowered's cost_analysis(), or
    (None, None) where the backend provides none."""
    try:
        ca = lowered.cost_analysis()
    except Exception:  # noqa: BLE001 — analysis availability varies
        return None, None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None, None
    flops = ca.get("flops")
    ba = ca.get("bytes accessed")
    return (float(flops) if flops is not None else None,
            float(ba) if ba is not None else None)


def cost_stats(analyze: bool = False) -> dict:
    """The per-program cost table: {program: {class_label: {compiles,
    compile_ms[, flops, bytes_accessed]}}}. `analyze=True` fills any
    missing flop/byte rows by re-lowering from the saved avals —
    tracing cost only, meant for off-path consumers (profile_step
    --cost-out, tools) — and drops the avals afterwards. The default
    is cheap and is what snapshot()["program_costs"] embeds."""
    if analyze:
        with _costs_lock:
            todo = [(prog, label, row["_avals"])
                    for prog, rows in _COSTS.items()
                    for label, row in rows.items()
                    if "_avals" in row and "flops" not in row]
        for prog, label, avals in todo:
            fn = _cost_programs.get(prog)
            if fn is None:
                continue
            a, kw = avals
            try:
                flops, ba = _analyze_lowered(fn.lower(*a, **kw))
            except Exception:  # noqa: BLE001 — a stale aval set (deleted
                continue       # program variant) must not break the table
            with _costs_lock:
                row = _COSTS.get(prog, {}).get(label)
                if row is not None:
                    if flops is not None:
                        row["flops"] = flops
                    if ba is not None:
                        row["bytes_accessed"] = ba
                    row.pop("_avals", None)
    with _costs_lock:
        return {prog: {label: {k: v for k, v in row.items()
                               if not k.startswith("_")}
                       for label, row in rows.items()}
                for prog, rows in _COSTS.items()}


def reset_cost_stats() -> None:
    """Drop every registered row (test isolation)."""
    with _costs_lock:
        _COSTS.clear()


def _with_cost_registry(fn):
    """Wrap one jitted program with compile detection (see the registry
    comment above). Transparent to every existing caller: __name__,
    _cache_size and lower() delegate to the wrapped jit function.
    Identity when the observatory knob is off (EMQX_TPU_HBM_LEDGER=0):
    the program flows through unwrapped, exactly pre-ISSUE-8."""
    if not COST_REGISTRY_ON:
        return fn
    name = fn.__name__

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        before = -1
        try:
            # only the introspection sits in the try — fn itself runs
            # outside it, so a raising program is never mistaken for
            # an introspection gap and re-invoked
            if jax.core.trace_state_clean():
                before = fn._cache_size()
        except Exception:  # noqa: BLE001 — introspection gap: passthrough
            before = -1
        if before < 0:
            # fused inside another program's trace (the outer program
            # owns this compile), or introspection unavailable
            return fn(*args, **kwargs)
        seq0 = _thread_compile_seq()
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        try:
            if fn._cache_size() > before \
                    and not (seq0 is not None
                             and _thread_compile_seq() == seq0):
                # the seq check: jit compiles run on the calling
                # thread, so a cache grown with NO compile event on
                # this thread was another thread's concurrent compile
                # of this program — its row, not ours to record under
                # this class label
                label = _active_cost_label()
                if label is None:
                    shapes = [tuple(x.shape) for x in
                              jax.tree.leaves(args)
                              if hasattr(x, "shape") and
                              getattr(x, "ndim", 0) >= 2][:1]
                    label = f"adhoc {shapes[0] if shapes else '()'}"
                record_program_cost(
                    name, label,
                    compile_ms=(time.perf_counter() - t0) * 1000.0,
                    avals=_avals_of(args, kwargs))
        except Exception:  # noqa: BLE001 — cost accounting is best-effort
            pass
        return out

    wrapped._fun = fn
    wrapped._cache_size = fn._cache_size
    wrapped.lower = fn.lower
    _cost_programs[name] = fn
    return wrapped


# rebind the public programs through the registry wrapper — callers
# (device_engine, serving, benches, tests) see the same names with
# identical call/introspection surfaces
route_step = _with_cost_registry(route_step)
route_step_shapes = _with_cost_registry(route_step_shapes)
route_window_shapes = _with_cost_registry(route_window_shapes)
route_window_full = _with_cost_registry(route_window_full)
route_step_cached = _with_cost_registry(route_step_cached)
route_window_cached = _with_cost_registry(route_window_cached)
route_step_compact = _with_cost_registry(route_step_compact)
route_step_cached_compact = _with_cost_registry(route_step_cached_compact)
route_window_full_compact = _with_cost_registry(route_window_full_compact)
route_window_cached_compact = \
    _with_cost_registry(route_window_cached_compact)
route_step_delta = _with_cost_registry(route_step_delta)
route_window_delta = _with_cost_registry(route_window_delta)
route_step_delta_cached = _with_cost_registry(route_step_delta_cached)
route_window_delta_cached = _with_cost_registry(route_window_delta_cached)
route_step_delta_compact = _with_cost_registry(route_step_delta_compact)
route_window_delta_compact = \
    _with_cost_registry(route_window_delta_compact)
route_step_delta_cached_compact = \
    _with_cost_registry(route_step_delta_cached_compact)
route_window_delta_cached_compact = \
    _with_cost_registry(route_window_delta_cached_compact)


# ---- donating serving twins (ISSUE 9) -----------------------------------
# At dispatch_depth >= 2 the serving dispatch threads its cursors through
# the fused programs with the cursors slot DONATED (input-output aliasing:
# the ping-pong cursor buffers reuse HBM instead of allocating one fresh
# [G] array per window). Donation invalidates the caller's input buffer,
# so these twins are used ONLY where the call site immediately re-adopts
# the output under the snapshot identity guard (DeviceRouteEngine.
# _dispatch_inner) and by the warm passes that feed them THROWAWAY
# device_put buffers — never by tests/benches that reuse a cursors array
# across calls (those keep the non-donating originals above). Each twin
# shares the plain program's name in the cost registry (same program,
# donated cursor slot) and its jit cache is counted into compile_stats
# under the plain name. Stage-graph safe: donation is an annotation on
# the public entry points, not a change to any stage composition —
# ROADMAP item 2's builder can emit the same annotation per fused
# program.
#
# Measured cache-key caveat this design encodes: numpy inputs and
# device arrays do NOT share a jit-cache entry, while device_put arrays
# and jit outputs DO — so every warm/probe call through a twin must pass
# a fresh device_put zeros cursors (the engine's _warm_cursors), or the
# first serving dispatch would re-trace in-path.

_DONATE_STATICS = {
    "route_step": ("frontier_cap", "match_cap", "fanout_cap",
                   "slot_cap"),
    "route_step_shapes": ("fanout_cap", "slot_cap"),
    "route_window_full": ("fanout_cap", "slot_cap"),
    "route_step_cached": ("frontier_cap", "match_cap", "fanout_cap",
                          "slot_cap"),
    "route_window_cached": ("fanout_cap", "slot_cap"),
    "route_step_compact": ("frontier_cap", "match_cap", "fanout_cap",
                           "slot_cap", "payload_cap"),
    "route_step_cached_compact": ("frontier_cap", "match_cap",
                                  "fanout_cap", "slot_cap",
                                  "payload_cap"),
    "route_window_full_compact": ("fanout_cap", "slot_cap",
                                  "payload_cap"),
    "route_window_cached_compact": ("fanout_cap", "slot_cap",
                                    "payload_cap"),
    "route_step_delta": ("frontier_cap", "match_cap", "fanout_cap",
                         "slot_cap", "delta_match_cap",
                         "delta_fanout_cap"),
    "route_window_delta": ("fanout_cap", "slot_cap", "delta_match_cap",
                           "delta_fanout_cap"),
    "route_step_delta_cached": ("frontier_cap", "match_cap",
                                "fanout_cap", "slot_cap",
                                "delta_match_cap", "delta_fanout_cap"),
    "route_window_delta_cached": ("fanout_cap", "slot_cap",
                                  "delta_match_cap",
                                  "delta_fanout_cap"),
    "route_step_delta_compact": ("frontier_cap", "match_cap",
                                 "fanout_cap", "slot_cap",
                                 "delta_match_cap", "delta_fanout_cap",
                                 "payload_cap", "d_payload_cap"),
    "route_window_delta_compact": ("fanout_cap", "slot_cap",
                                   "delta_match_cap",
                                   "delta_fanout_cap", "payload_cap",
                                   "d_payload_cap"),
    "route_step_delta_cached_compact": ("frontier_cap", "match_cap",
                                        "fanout_cap", "slot_cap",
                                        "delta_match_cap",
                                        "delta_fanout_cap",
                                        "payload_cap", "d_payload_cap"),
    "route_window_delta_cached_compact": ("fanout_cap", "slot_cap",
                                          "delta_match_cap",
                                          "delta_fanout_cap",
                                          "payload_cap",
                                          "d_payload_cap"),
}

_donating_cache: dict[str, object] = {}
_donating_lock = threading.Lock()


def donating(fn):
    """The cursor-donating serving twin of a fused route program
    (lazy, one jit per program for the process lifetime). `fn` is one
    of the public programs above (cost-registry wrapped or not).
    Locked: the dispatch executor and the background build/warm
    threads both resolve twins through DeviceRouteEngine._rt — an
    unlocked check-then-act could build rival twins and discard the
    one whose jit cache the warm pass just populated (an in-path
    recompile on the next serving dispatch)."""
    name = fn.__name__
    tw = _donating_cache.get(name)
    if tw is None:
        with _donating_lock:
            tw = _donating_cache.get(name)
            if tw is None:
                import warnings
                # backends without donation support warn per lowering;
                # the fallback (a fresh output buffer per window) is
                # exactly the pre-donation behavior, so the warning is
                # noise there
                warnings.filterwarnings(
                    "ignore",
                    message="Some donated buffers were not usable")
                raw = getattr(fn, "_fun", fn).__wrapped__
                tw = _with_cost_registry(jax.jit(
                    raw, static_argnames=_DONATE_STATICS[name],
                    donate_argnames=("cursors",)))
                _donating_cache[name] = tw
    return tw


def donating_compile_stats() -> dict[str, int]:
    """Jit-cache entry counts of the instantiated donating twins,
    keyed by the PLAIN program names (compile_stats merges them in —
    one exported name space whatever depth the node serves at)."""
    out = {}
    for name, fn in _donating_cache.items():
        try:
            out[name] = fn._cache_size()
        except Exception:  # noqa: BLE001 — introspection is best-effort
            pass
    return out


def empty_router_tables(filter_cap: int = 16) -> RouterTables:
    """A valid all-empty RouterTables (useful before first build)."""
    from emqx_tpu.ops.fanout import build_subtable
    from emqx_tpu.ops.trie import build_tables
    trie = build_tables(np.zeros((0, 1), np.int32), np.zeros(0, np.int64))
    subs = build_subtable(filter_cap, {}, {}, {})
    return RouterTables(trie=trie, subs=subs)
