"""The flagship device program: one fused PUBLISH route step.

This is the TPU replacement for the reference broker's per-message hot path
(emqx_broker:publish/1 → emqx_router:match_routes → emqx_trie:match →
dispatch fold, emqx_broker.erl:199-308): for a whole micro-batch of publishes
it runs, in one jitted program,

  1. wildcard NFA match over the compiled trie        (ops.match)
  2. normal-subscriber fan-out segment-gather         (ops.fanout)
  3. shared-subscription member selection + cursors   (ops.shared)

State model: `RouterTables` is immutable (rebuilt/double-buffered by the host
router on subscription churn); `cursors` is the only mutable device state and
is threaded functionally through each step.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from emqx_tpu.ops.fanout import FanoutResult, SubTable, fanout_normal, shared_slots
from emqx_tpu.ops.match import MatchResult, match_batch, merge_match_results
from emqx_tpu.ops.shapes import ShapeTables, shape_match
from emqx_tpu.ops.shared import SharedPickResult, pick_members
from emqx_tpu.ops.trie import TrieTables


class RouterTables(NamedTuple):
    """Device routing state for the trie-NFA backend (general shapes)."""
    trie: TrieTables
    subs: SubTable


class ShapeRouterTables(NamedTuple):
    """Device routing state for the shape-hash backend (the fast path)."""
    shapes: ShapeTables
    subs: SubTable


class RouteResult(NamedTuple):
    matches: jax.Array        # [B, M] matched filter ids
    match_counts: jax.Array   # [B]
    rows: jax.Array           # [B, D] normal delivery session rows
    opts: jax.Array           # [B, D] packed subopts
    fan_counts: jax.Array     # [B]
    shared_sids: jax.Array    # [B, K] matched shared-slot ids (-1 pad)
    shared_rows: jax.Array    # [B, K] shared picks (session rows)
    shared_opts: jax.Array    # [B, K]
    overflow: jax.Array       # [B] any capacity overflow → host fallback
    new_cursors: jax.Array    # [G]
    occur: jax.Array          # [G] shared-slot occurrences this batch


def post_match(subs: SubTable, mr: MatchResult, cursors: jax.Array,
               msg_hash: jax.Array, strategy: jax.Array, *,
               fanout_cap: int, slot_cap: int) -> RouteResult:
    """Fan-out + shared-sub selection on a MatchResult (backend-agnostic)."""
    fr: FanoutResult = fanout_normal(subs, mr.matches, fanout_cap=fanout_cap)
    sids, slot_oflow = shared_slots(subs, mr.matches, slot_cap=slot_cap)
    sp: SharedPickResult = pick_members(subs, cursors, sids, strategy,
                                        msg_hash)
    overflow = mr.overflow | fr.overflow | slot_oflow
    return RouteResult(
        matches=mr.matches, match_counts=mr.counts,
        rows=fr.rows, opts=fr.opts, fan_counts=fr.counts,
        shared_sids=sids, shared_rows=sp.rows, shared_opts=sp.opts,
        overflow=overflow, new_cursors=sp.new_cursors, occur=sp.occur)


@functools.partial(
    jax.jit,
    static_argnames=("frontier_cap", "match_cap", "fanout_cap", "slot_cap"))
def route_step(tables: RouterTables, cursors: jax.Array, topics: jax.Array,
               lens: jax.Array, is_dollar: jax.Array, msg_hash: jax.Array,
               strategy: jax.Array, *, frontier_cap: int = 16,
               match_cap: int = 64, fanout_cap: int = 128,
               slot_cap: int = 16) -> RouteResult:
    """Trie-NFA route step: match + fan-out + shared picks (general shapes)."""
    mr = match_batch(tables.trie, topics, lens, is_dollar,
                     frontier_cap=frontier_cap, match_cap=match_cap)
    return post_match(tables.subs, mr, cursors, msg_hash, strategy,
                      fanout_cap=fanout_cap, slot_cap=slot_cap)


@functools.partial(jax.jit, static_argnames=("fanout_cap", "slot_cap"))
def route_step_shapes(tables: ShapeRouterTables, cursors: jax.Array,
                      topics: jax.Array, lens: jax.Array,
                      is_dollar: jax.Array, msg_hash: jax.Array,
                      strategy: jax.Array, *, fanout_cap: int = 128,
                      slot_cap: int = 16) -> RouteResult:
    """Shape-hash route step: one bucket gather per (topic, shape)."""
    mr = shape_match(tables.shapes, topics, lens, is_dollar)
    return post_match(tables.subs, mr, cursors, msg_hash, strategy,
                      fanout_cap=fanout_cap, slot_cap=slot_cap)


@functools.partial(
    jax.jit,
    static_argnames=("frontier_cap", "match_cap", "fanout_cap", "slot_cap"))
def route_step_cached(tables: RouterTables, cursors: jax.Array,
                      miss_topics: jax.Array, miss_lens: jax.Array,
                      miss_dollar: jax.Array, base_matches: jax.Array,
                      base_counts: jax.Array, base_overflow: jax.Array,
                      miss_pos: jax.Array, inv: jax.Array,
                      msg_hash: jax.Array, strategy: jax.Array, *,
                      frontier_cap: int = 16, match_cap: int = 64,
                      fanout_cap: int = 128,
                      slot_cap: int = 16) -> RouteResult:
    """Trie-NFA route step over a DEDUPLICATED batch with cached rows.

    The match stage runs only on the [Bm] compacted miss lanes
    (Bm quantized to the standard batch-class ladder); cache-hit unique
    topics ride in as host-filled base_* rows ([U] per-unique-topic).
    `inv` [B] scatters the merged unique MatchResult back to full batch
    width before the cursor-dependent post stage, so fan-out, shared
    picks and cursor threading are bit-identical to the un-deduplicated
    `route_step` on the same batch (oracle-tested)."""
    mr = match_batch(tables.trie, miss_topics, miss_lens, miss_dollar,
                     frontier_cap=frontier_cap, match_cap=match_cap)
    um = merge_match_results(base_matches, base_counts, base_overflow,
                             mr, miss_pos)
    full = MatchResult(matches=um.matches[inv], counts=um.counts[inv],
                       overflow=um.overflow[inv])
    return post_match(tables.subs, full, cursors, msg_hash, strategy,
                      fanout_cap=fanout_cap, slot_cap=slot_cap)


@functools.partial(jax.jit, static_argnames=("fanout_cap", "slot_cap"))
def route_window_cached(tables: ShapeRouterTables, cursors: jax.Array,
                        miss_topics: jax.Array, miss_lens: jax.Array,
                        miss_dollar: jax.Array, base_matches: jax.Array,
                        base_counts: jax.Array, base_overflow: jax.Array,
                        miss_pos: jax.Array, inv: jax.Array,
                        msg_hash: jax.Array, strategy: jax.Array, *,
                        fanout_cap: int = 128,
                        slot_cap: int = 16) -> RouteResult:
    """Shape-hash window step over a DEDUPLICATED window with cached rows.

    One dispatch routes W sub-batches while the shape-hash match runs
    ONCE over the [Bm] compacted miss lanes (every other lane of the
    [W, B] window is either a duplicate of a miss lane, a cache hit
    served from base_* rows, or padding collapsed onto the shared
    sentinel row). `inv` [W, B] gathers the merged unique rows back to
    full window width per scan step; cursors thread through the scan
    exactly as W sequential `route_step_shapes` calls, so the stacked
    RouteResult is bit-identical to `route_window_full` on the same
    window (oracle-tested)."""
    mr = shape_match(tables.shapes, miss_topics, miss_lens, miss_dollar)
    um = merge_match_results(base_matches, base_counts, base_overflow,
                             mr, miss_pos)

    def step(cur, xs):
        inv_k, mh_k = xs
        full = MatchResult(matches=um.matches[inv_k],
                           counts=um.counts[inv_k],
                           overflow=um.overflow[inv_k])
        r = post_match(tables.subs, full, cur, mh_k, strategy,
                       fanout_cap=fanout_cap, slot_cap=slot_cap)
        return r.new_cursors, r

    _, stacked = jax.lax.scan(step, cursors, (inv, msg_hash))
    return stacked


class CompactRouteResult(NamedTuple):
    """A route result with its fused CSR readback (ops.compact).

    `res` carries the FULL window-stacked dense planes — they are
    intermediates of the same program, so returning them costs nothing;
    the host reads them back only when `compact.row_overflow` fires
    (payload class too small for this window) — the dense fallback needs
    no re-dispatch. Every per-topic plane in `res` is window-shaped
    ([W, ...]) for ALL variants, including the single-batch trie steps
    (W = 1), so the consume path is uniform."""
    res: RouteResult
    compact: "CompactPlanes"  # noqa: F821 — imported lazily below


def _with_compact(r: RouteResult, payload_cap: int,
                  match_holes: bool) -> CompactRouteResult:
    """match_holes=True for the shape-hash backend (matches carry
    interior holes at unmatched shape slots), False for the trie NFA
    (emissions are densely packed already — the hole-closing stage
    compiles away). The engine's window variants are shapes-only and
    the step variants trie-only, so each hardcodes its flag."""
    from emqx_tpu.ops.compact import compact_result
    cp = compact_result(r.matches, r.rows, r.opts, r.fan_counts,
                        r.shared_sids, r.shared_rows, r.shared_opts,
                        payload_cap=payload_cap, match_holes=match_holes)
    return CompactRouteResult(res=r, compact=cp)


def _stack1(r: RouteResult) -> RouteResult:
    """Lift a single-batch RouteResult to window form (W = 1)."""
    return RouteResult(*[x[None] for x in r])


@functools.partial(
    jax.jit,
    static_argnames=("frontier_cap", "match_cap", "fanout_cap",
                     "slot_cap", "payload_cap"))
def route_step_compact(tables: RouterTables, cursors: jax.Array,
                       topics: jax.Array, lens: jax.Array,
                       is_dollar: jax.Array, msg_hash: jax.Array,
                       strategy: jax.Array, *, frontier_cap: int = 16,
                       match_cap: int = 64, fanout_cap: int = 128,
                       slot_cap: int = 16,
                       payload_cap: int = 4096) -> CompactRouteResult:
    """Trie-NFA route step with the fused CSR readback (window-shaped)."""
    r = route_step(tables, cursors, topics, lens, is_dollar, msg_hash,
                   strategy, frontier_cap=frontier_cap,
                   match_cap=match_cap, fanout_cap=fanout_cap,
                   slot_cap=slot_cap)
    return _with_compact(_stack1(r), payload_cap, match_holes=False)


@functools.partial(
    jax.jit,
    static_argnames=("frontier_cap", "match_cap", "fanout_cap",
                     "slot_cap", "payload_cap"))
def route_step_cached_compact(tables: RouterTables, cursors: jax.Array,
                              miss_topics: jax.Array,
                              miss_lens: jax.Array,
                              miss_dollar: jax.Array,
                              base_matches: jax.Array,
                              base_counts: jax.Array,
                              base_overflow: jax.Array,
                              miss_pos: jax.Array, inv: jax.Array,
                              msg_hash: jax.Array, strategy: jax.Array,
                              *, frontier_cap: int = 16,
                              match_cap: int = 64, fanout_cap: int = 128,
                              slot_cap: int = 16,
                              payload_cap: int = 4096
                              ) -> CompactRouteResult:
    """Deduplicated trie step + fused CSR readback (window-shaped)."""
    r = route_step_cached(tables, cursors, miss_topics, miss_lens,
                          miss_dollar, base_matches, base_counts,
                          base_overflow, miss_pos, inv, msg_hash,
                          strategy, frontier_cap=frontier_cap,
                          match_cap=match_cap, fanout_cap=fanout_cap,
                          slot_cap=slot_cap)
    return _with_compact(_stack1(r), payload_cap, match_holes=False)


@functools.partial(jax.jit,
                   static_argnames=("fanout_cap", "slot_cap",
                                    "payload_cap"))
def route_window_full_compact(tables: ShapeRouterTables,
                              cursors: jax.Array, topics: jax.Array,
                              lens: jax.Array, is_dollar: jax.Array,
                              msg_hash: jax.Array, strategy: jax.Array,
                              *, fanout_cap: int = 128,
                              slot_cap: int = 16,
                              payload_cap: int = 4096
                              ) -> CompactRouteResult:
    """route_window_full + fused CSR readback in the same dispatch."""
    r = route_window_full(tables, cursors, topics, lens, is_dollar,
                          msg_hash, strategy, fanout_cap=fanout_cap,
                          slot_cap=slot_cap)
    return _with_compact(r, payload_cap, match_holes=True)


@functools.partial(jax.jit,
                   static_argnames=("fanout_cap", "slot_cap",
                                    "payload_cap"))
def route_window_cached_compact(tables: ShapeRouterTables,
                                cursors: jax.Array,
                                miss_topics: jax.Array,
                                miss_lens: jax.Array,
                                miss_dollar: jax.Array,
                                base_matches: jax.Array,
                                base_counts: jax.Array,
                                base_overflow: jax.Array,
                                miss_pos: jax.Array, inv: jax.Array,
                                msg_hash: jax.Array,
                                strategy: jax.Array, *,
                                fanout_cap: int = 128,
                                slot_cap: int = 16,
                                payload_cap: int = 4096
                                ) -> CompactRouteResult:
    """route_window_cached + fused CSR readback in the same dispatch."""
    r = route_window_cached(tables, cursors, miss_topics, miss_lens,
                            miss_dollar, base_matches, base_counts,
                            base_overflow, miss_pos, inv, msg_hash,
                            strategy, fanout_cap=fanout_cap,
                            slot_cap=slot_cap)
    return _with_compact(r, payload_cap, match_holes=True)


def route_digest(r: RouteResult) -> jax.Array:
    """Scalar int32 reduction over EVERY RouteResult output plane.

    Benchmarks close a dispatch window with one scalar readback; summing
    every plane here (not a subset) stops XLA dead-code-eliminating any
    stage of the step out of the measurement. One definition shared by the
    fused window, bench.py's single-step path, and the oracle test, so the
    two measurements can never silently diverge."""
    return (r.matches.sum(dtype=jnp.int32)
            + r.rows.sum(dtype=jnp.int32)
            + r.opts.sum(dtype=jnp.int32)
            + r.fan_counts.sum(dtype=jnp.int32)
            + r.shared_sids.sum(dtype=jnp.int32)
            + r.shared_rows.sum(dtype=jnp.int32)
            + r.shared_opts.sum(dtype=jnp.int32)
            + r.match_counts.sum(dtype=jnp.int32)
            + r.overflow.sum(dtype=jnp.int32)
            + r.occur.sum(dtype=jnp.int32))


@functools.partial(jax.jit, static_argnames=("fanout_cap", "slot_cap"))
def route_window_shapes(tables: ShapeRouterTables, cursors: jax.Array,
                        topics: jax.Array, lens: jax.Array,
                        is_dollar: jax.Array, msg_hash: jax.Array,
                        strategy: jax.Array, *, fanout_cap: int = 128,
                        slot_cap: int = 16):
    """W fused route steps in ONE dispatch: scan over a [W, B, ...] window.

    Per-dispatch overhead (HTTP relay round trip, or runtime launch cost on
    co-located hardware) is paid once for W batches instead of W times —
    the round-2 bench showed the per-call floor (match-only 14.1ms vs the
    match fold's own rate) is a visible slice of the 65ms batch. Cursors
    thread through the scan exactly as through W sequential calls
    (bit-identical; oracle-tested), so round-robin fairness holds across
    the whole window.

    Returns (new_cursors, digest [W] int32) — route_digest per step forces
    the full routing computation while keeping the device→host readback
    scalar-sized.
    """
    def step(cur, batch):
        t, l, d, h = batch
        r = route_step_shapes(tables, cur, t, l, d, h, strategy,
                              fanout_cap=fanout_cap, slot_cap=slot_cap)
        return r.new_cursors, route_digest(r)

    new_cursors, digests = jax.lax.scan(
        step, cursors, (topics, lens, is_dollar, msg_hash))
    return new_cursors, digests


@functools.partial(jax.jit, static_argnames=("fanout_cap", "slot_cap"))
def route_window_full(tables: ShapeRouterTables, cursors: jax.Array,
                      topics: jax.Array, lens: jax.Array,
                      is_dollar: jax.Array, msg_hash: jax.Array,
                      strategy: jax.Array, *, fanout_cap: int = 128,
                      slot_cap: int = 16) -> RouteResult:
    """W fused route steps in ONE dispatch, returning the FULL stacked
    RouteResult (every field [W, ...]) — the serving path's window
    variant (route_window_shapes returns digests only, for benches).
    Cursors thread through the scan exactly as W sequential calls, so
    `new_cursors`/`occur` in row k reflect state after sub-batch k."""
    def step(cur, batch):
        t, l, d, h = batch
        r = route_step_shapes(tables, cur, t, l, d, h, strategy,
                              fanout_cap=fanout_cap, slot_cap=slot_cap)
        return r.new_cursors, r

    _, stacked = jax.lax.scan(
        step, cursors, (topics, lens, is_dollar, msg_hash))
    return stacked


def compile_stats() -> dict[str, int]:
    """Jit-cache entry counts per route-step program. Each entry is one
    compiled (shape, dtype, static-args) variant, so a growing number
    under steady traffic means the serving path is re-tracing — the
    recompile signal pipeline telemetry surfaces via
    `GET /api/v5/pipeline/stats` and the bench telemetry snapshot."""
    out = {}
    for fn in (route_step, route_step_shapes, route_window_shapes,
               route_window_full, route_step_cached, route_window_cached,
               route_step_compact, route_step_cached_compact,
               route_window_full_compact, route_window_cached_compact):
        try:
            out[fn.__name__] = fn._cache_size()
        except Exception:  # noqa: BLE001 — cache introspection is best-effort
            pass
    return out


def empty_router_tables(filter_cap: int = 16) -> RouterTables:
    """A valid all-empty RouterTables (useful before first build)."""
    from emqx_tpu.ops.fanout import build_subtable
    from emqx_tpu.ops.trie import build_tables
    trie = build_tables(np.zeros((0, 1), np.int32), np.zeros(0, np.int64))
    subs = build_subtable(filter_cap, {}, {}, {})
    return RouterTables(trie=trie, subs=subs)
