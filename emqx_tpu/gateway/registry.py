"""Gateway registry: load/unload/list gateway instances.

Parity: emqx_gateway_registry.erl + emqx_gateway.erl — named gateway types
register a loader; instances are started with a config and tracked for the
mgmt surface (`GET /gateway`, `gateway` CLI).
"""

from __future__ import annotations

from typing import Any, Callable, Optional


class GatewayRegistry:
    def __init__(self, node):
        self.node = node
        self._types: dict[str, Callable] = {}
        self._instances: dict[str, Any] = {}
        node.gateway_registry = self

    def register_type(self, name: str, loader: Callable) -> None:
        """loader(node, conf) -> gateway instance with async start/stop."""
        self._types[name] = loader

    async def load(self, name: str, conf: Optional[dict] = None) -> Any:
        if name in self._instances:
            raise ValueError(f"gateway {name} already loaded")
        loader = self._types.get(name)
        if loader is None:
            raise ValueError(f"unknown gateway type {name}")
        gw = loader(self.node, conf or {})
        await gw.start()
        self._instances[name] = gw
        return gw

    async def unload(self, name: str) -> bool:
        gw = self._instances.pop(name, None)
        if gw is None:
            return False
        await gw.stop()
        return True

    def lookup(self, name: str) -> Optional[Any]:
        return self._instances.get(name)

    def list(self) -> list[dict]:
        return [{"name": n, "status": "running",
                 **(gw.info() if hasattr(gw, "info") else {})}
                for n, gw in sorted(self._instances.items())]

    @staticmethod
    def with_builtins(node) -> "GatewayRegistry":
        reg = GatewayRegistry(node)
        from emqx_tpu.gateway.coap import CoapGateway
        from emqx_tpu.gateway.lwm2m import Lwm2mGateway
        from emqx_tpu.gateway.mqttsn import MqttSnGateway
        from emqx_tpu.gateway.stomp import StompGateway
        reg.register_type("stomp", lambda n, c: StompGateway(n, c))
        reg.register_type("mqttsn", lambda n, c: MqttSnGateway(n, c))
        reg.register_type("coap", lambda n, c: CoapGateway(n, c))
        reg.register_type("lwm2m", lambda n, c: Lwm2mGateway(n, c))
        try:
            from emqx_tpu.gateway.exproto import ExprotoGateway
            reg.register_type("exproto", lambda n, c: ExprotoGateway(n, c))
        except ImportError:
            pass   # grpc not available in this image profile
        return reg
