"""Gateways: non-MQTT protocol front-ends over the core pubsub engine.

Parity: apps/emqx_gateway — the behaviours (bhvrs/emqx_gateway_channel.erl,
emqx_gateway_frame.erl, emqx_gateway_conn.erl), the insulation context
(emqx_gateway_ctx.erl) brokering authn + pubsub into the core, the registry
(emqx_gateway_registry.erl), and the gateways themselves: STOMP (src/stomp),
MQTT-SN (src/mqttsn), CoAP (src/coap), LwM2M (src/lwm2m), exproto
(src/exproto, gRPC).
"""

from emqx_tpu.gateway.ctx import GatewayCtx
from emqx_tpu.gateway.registry import GatewayRegistry

__all__ = ["GatewayCtx", "GatewayRegistry"]
