"""MQTT-SN 1.2 gateway (UDP).

Parity: apps/emqx_gateway/src/mqttsn — message codec (emqx_sn_frame.erl),
gateway FSM (emqx_sn_gateway.erl): CONNECT/CONNACK, topic REGISTER/REGACK
with per-client alias registry, PUBLISH with normal/predefined/short topic
ids and QoS 0/1/2 plus QoS -1 (publish without connection), SUBSCRIBE with
wildcard names (topic id assigned on first matching REGISTER-less deliver),
sleeping clients (DISCONNECT with duration buffers messages, PINGREQ
drains), SEARCHGW/GWINFO and periodic ADVERTISE.
"""

from __future__ import annotations

import asyncio
import struct
import time
import uuid
from typing import Optional

from emqx_tpu.gateway.ctx import GatewayCtx
from emqx_tpu.utils import topic as T

# message types (MQTT-SN spec 5.2.1)
ADVERTISE = 0x00
SEARCHGW = 0x01
GWINFO = 0x02
CONNECT = 0x04
CONNACK = 0x05
WILLTOPICREQ = 0x06
WILLTOPIC = 0x07
WILLMSGREQ = 0x08
WILLMSG = 0x09
REGISTER = 0x0A
REGACK = 0x0B
PUBLISH = 0x0C
PUBACK = 0x0D
PUBCOMP = 0x0E
PUBREC = 0x0F
PUBREL = 0x10
SUBSCRIBE = 0x12
SUBACK = 0x13
UNSUBSCRIBE = 0x14
UNSUBACK = 0x15
PINGREQ = 0x16
PINGRESP = 0x17
DISCONNECT = 0x18

# flags
FLAG_DUP = 0x80
FLAG_QOS = 0x60
FLAG_RETAIN = 0x10
FLAG_WILL = 0x08
FLAG_CLEAN = 0x04
FLAG_TOPIC_TYPE = 0x03
TOPIC_NORMAL = 0
TOPIC_PREDEF = 1
TOPIC_SHORT = 2

RC_ACCEPTED = 0
RC_CONGESTION = 1
RC_INVALID_TOPIC_ID = 2
RC_NOT_SUPPORTED = 3


def qos_of(flags: int) -> int:
    q = (flags & FLAG_QOS) >> 5
    return -1 if q == 3 else q


def encode(msg_type: int, body: bytes) -> bytes:
    n = len(body) + 2
    if n + 2 > 255:
        return b"\x01" + struct.pack(">HB", n + 2, msg_type) + body
    return struct.pack(">BB", n, msg_type) + body


def decode(dgram: bytes) -> tuple[int, bytes]:
    if dgram[0] == 0x01:
        (_n,) = struct.unpack(">H", dgram[1:3])
        return dgram[3], dgram[4:]
    return dgram[1], dgram[2:]


class SnClient:
    """Per-peer state (the reference's per-socket emqx_sn_gateway FSM)."""

    def __init__(self, gw: "MqttSnGateway", addr):
        self.gw = gw
        self.addr = addr
        self.clientid = ""
        self.clientinfo: dict = {}
        self.state = "idle"            # idle|connected|asleep
        self.sid: Optional[int] = None
        # alias registries (both directions)
        self.topic_by_id: dict[int, str] = {}
        self.id_by_topic: dict[str, int] = {}
        self._next_topic_id = 1
        self._next_msg_id = 1
        self.buffered: list = []       # msgs while asleep
        self.awaiting_rel: dict[int, object] = {}   # QoS2 in (msgid -> msg)
        self.last_seen = time.monotonic()
        self.keepalive = 0
        self.will = None               # (topic, payload, qos, retain)

    def alloc_topic_id(self, topic: str) -> int:
        if topic in self.id_by_topic:
            return self.id_by_topic[topic]
        tid = self._next_topic_id
        self._next_topic_id += 1
        self.id_by_topic[topic] = tid
        self.topic_by_id[tid] = topic
        return tid

    def next_msg_id(self) -> int:
        mid = self._next_msg_id
        self._next_msg_id = 1 if mid >= 0xFFFF else mid + 1
        return mid

    # ---- broker subscriber protocol ----
    def deliver(self, topic_filter: str, msg) -> bool:
        if self.state == "asleep":
            self.buffered.append(msg)
            return True
        self._send_publish(msg)
        return True

    def _send_publish(self, msg) -> None:
        topic = msg.topic
        if len(topic) == 2 and not T.wildcard(topic):
            flags_tt, tid_bytes = TOPIC_SHORT, topic.encode()
        elif topic in self.gw.predefined_ids:
            flags_tt = TOPIC_PREDEF
            tid_bytes = struct.pack(">H", self.gw.predefined_ids[topic])
        else:
            tid = self.id_by_topic.get(topic)
            if tid is None:
                tid = self.alloc_topic_id(topic)
                # REGISTER the alias before first use (spec 6.10)
                self.gw.send(self.addr, REGISTER, struct.pack(
                    ">HH", tid, self.next_msg_id()) + topic.encode())
            flags_tt, tid_bytes = TOPIC_NORMAL, struct.pack(">H", tid)
        qos = min(msg.qos, 1)          # QoS2 out simplified to 1 (dev->gw acks)
        flags = (qos << 5) | flags_tt | (FLAG_RETAIN if msg.retain else 0)
        mid = self.next_msg_id() if qos else 0
        self.gw.send(self.addr, PUBLISH,
                     bytes([flags]) + tid_bytes +
                     struct.pack(">H", mid) + msg.payload)


class MqttSnGateway(asyncio.DatagramProtocol):
    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        self.conf = conf or {}
        self.ctx = GatewayCtx(node, "mqttsn")
        self.bind = self.conf.get("bind", "127.0.0.1")
        self.port = self.conf.get("port", 1884)
        self.gw_id = self.conf.get("gateway_id", 1)
        # predefined topics: {topic_id: topic_name} from config
        self.predefined: dict[int, str] = {
            int(k): v for k, v in
            (self.conf.get("predefined") or {}).items()}
        self.predefined_ids = {v: k for k, v in self.predefined.items()}
        self.clients: dict[tuple, SnClient] = {}
        self.by_clientid: dict[str, SnClient] = {}
        self.transport = None

    # ---- lifecycle ----
    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self.transport, _ = await loop.create_datagram_endpoint(
            lambda: self, local_addr=(self.bind, self.port))
        if self.port == 0:
            self.port = self.transport.get_extra_info("sockname")[1]
        self._sweeper = loop.create_task(self._sweep_loop())

    async def _sweep_loop(self) -> None:
        while True:
            await asyncio.sleep(1.0)
            self.sweep()

    def sweep(self) -> None:
        """Keepalive expiry: a silent connected client loses its session
        and its will fires — the abnormal-loss case wills exist for."""
        now = time.monotonic()
        for c in list(self.by_clientid.values()):
            if (c.state == "connected" and c.keepalive
                    and now - c.last_seen > c.keepalive * 1.5):
                self._publish_will(c)
                self._drop(c)

    async def stop(self) -> None:
        if getattr(self, "_sweeper", None):
            self._sweeper.cancel()
        for c in list(self.clients.values()):
            self._drop(c)
        if self.transport:
            self.transport.close()

    def info(self) -> dict:
        return {"listener": f"udp:{self.bind}:{self.port}",
                "current_connections": len(self.by_clientid)}

    def send(self, addr, msg_type: int, body: bytes = b"") -> None:
        if self.transport:
            self.transport.sendto(encode(msg_type, body), addr)

    # ---- datagram entry ----
    def datagram_received(self, data: bytes, addr) -> None:
        try:
            msg_type, body = decode(data)
        except (IndexError, struct.error):
            return
        from emqx_tpu.broker.supervise import spawn
        spawn(self._handle(addr, msg_type, body), "mqttsn-handle")

    async def _handle(self, addr, msg_type: int, body: bytes) -> None:
        client = self.clients.get(addr)
        if client is not None:
            client.last_seen = time.monotonic()
        try:
            if msg_type == SEARCHGW:
                self.send(addr, GWINFO, bytes([self.gw_id]))
            elif msg_type == CONNECT:
                await self._on_connect(addr, body)
            elif msg_type == PUBLISH:
                await self._on_publish(addr, client, body)
            elif msg_type == REGISTER:
                self._on_register(addr, client, body)
            elif msg_type == REGACK:
                pass
            elif msg_type == SUBSCRIBE:
                await self._on_subscribe(addr, client, body)
            elif msg_type == UNSUBSCRIBE:
                self._on_unsubscribe(addr, client, body)
            elif msg_type == PINGREQ:
                self._on_pingreq(addr, client, body)
            elif msg_type == DISCONNECT:
                self._on_disconnect(addr, client, body)
            elif msg_type == PUBACK:
                pass
            elif msg_type == PUBREL and client:
                (mid,) = struct.unpack(">H", body[:2])
                msg = client.awaiting_rel.pop(mid, None)
                if msg is not None:
                    self.ctx.publish_msg(msg)
                self.send(addr, PUBCOMP, struct.pack(">H", mid))
            elif msg_type == WILLTOPIC and client:
                self._on_willtopic(addr, client, body)
            elif msg_type == WILLMSG and client:
                self._on_willmsg(addr, client, body)
        except (IndexError, struct.error):
            pass   # malformed datagram: dropped like the reference's parser

    # ---- handlers ----
    async def _on_connect(self, addr, body: bytes) -> None:
        flags, _proto, duration = body[0], body[1], \
            struct.unpack(">H", body[2:4])[0]
        clientid = body[4:].decode("utf-8", "replace") \
            or f"sn-{uuid.uuid4().hex[:10]}"
        client = SnClient(self, addr)
        client.clientid = clientid
        client.keepalive = duration
        client.clientinfo = {"clientid": f"mqttsn:{clientid}",
                             "username": None, "protocol": "mqtt-sn",
                             "peername": addr}
        if not await self.ctx.authenticate(client.clientinfo):
            self.send(addr, CONNACK, bytes([RC_NOT_SUPPORTED]))
            return
        old = self.by_clientid.get(clientid) or self.clients.get(addr)
        if old is not None:
            # duplicate/retransmitted CONNECT (same or new address): the old
            # registration must go or its sid double-delivers
            self._drop(old)
        self.clients[addr] = client
        self.by_clientid[clientid] = client
        client.state = "connected"
        client.sid = self.ctx.register_subscriber(client, clientid)
        self.ctx.register_channel(clientid, client, {"proto": "mqtt-sn"})
        if flags & FLAG_WILL:
            # 3-step will setup (spec 6.3): ask for topic then message
            self.send(addr, WILLTOPICREQ)
        else:
            self.send(addr, CONNACK, bytes([RC_ACCEPTED]))
        self.node.hooks.run("client.connected",
                            (client.clientinfo, {"proto_name": "MQTT-SN"}))

    def _on_willtopic(self, addr, client: SnClient, body: bytes) -> None:
        flags = body[0] if body else 0
        client.will = {"topic": body[1:].decode("utf-8", "replace"),
                       "qos": max(0, qos_of(flags)),
                       "retain": bool(flags & FLAG_RETAIN)}
        self.send(addr, WILLMSGREQ)

    def _on_willmsg(self, addr, client: SnClient, body: bytes) -> None:
        if isinstance(client.will, dict):
            client.will["payload"] = body
        self.send(addr, CONNACK, bytes([RC_ACCEPTED]))

    def _resolve_topic(self, client: Optional[SnClient], tt: int,
                       tid_bytes: bytes) -> Optional[str]:
        if tt == TOPIC_SHORT:
            return tid_bytes.decode("utf-8", "replace")
        (tid,) = struct.unpack(">H", tid_bytes)
        if tt == TOPIC_PREDEF:
            return self.predefined.get(tid)
        if client is None:
            return None
        return client.topic_by_id.get(tid)

    async def _on_publish(self, addr, client: Optional[SnClient],
                          body: bytes) -> None:
        flags = body[0]
        tt = flags & FLAG_TOPIC_TYPE
        tid_bytes, (mid,) = body[1:3], struct.unpack(">H", body[3:5])
        payload = body[5:]
        qos = qos_of(flags)
        if qos == -1:
            # QoS -1: publish with no connection, predefined/short ids only
            topic = self._resolve_topic(None, tt, tid_bytes)
            if topic:
                self.ctx.publish("sn-anonymous", topic, payload, qos=0)
            return
        if client is None or client.state == "idle":
            return
        topic = self._resolve_topic(client, tt, tid_bytes)
        if topic is None:
            self.send(addr, PUBACK,
                      tid_bytes + struct.pack(">H", mid) +
                      bytes([RC_INVALID_TOPIC_ID]))
            return
        if not await self.ctx.authorize(client.clientinfo, "publish",
                                        topic):
            self.send(addr, PUBACK, tid_bytes + struct.pack(">H", mid) +
                      bytes([RC_NOT_SUPPORTED]))
            return
        retain = bool(flags & FLAG_RETAIN)
        if qos == 2:
            from emqx_tpu.broker.message import make
            client.awaiting_rel[mid] = make(
                f"mqttsn:{client.clientid}", 2, topic, payload,
                flags={"retain": retain})
            self.send(addr, PUBREC, struct.pack(">H", mid))
            return
        self.ctx.publish(client.clientid, topic, payload, qos=qos,
                         retain=retain)
        if qos == 1:
            self.send(addr, PUBACK, tid_bytes + struct.pack(">H", mid) +
                      bytes([RC_ACCEPTED]))

    def _on_register(self, addr, client: Optional[SnClient],
                     body: bytes) -> None:
        if client is None:
            return
        _tid, mid = struct.unpack(">HH", body[:4])
        topic = body[4:].decode("utf-8", "replace")
        tid = client.alloc_topic_id(topic)
        self.send(addr, REGACK,
                  struct.pack(">HH", tid, mid) + bytes([RC_ACCEPTED]))

    async def _on_subscribe(self, addr, client: Optional[SnClient],
                            body: bytes) -> None:
        if client is None:
            return
        flags = body[0]
        (mid,) = struct.unpack(">H", body[1:3])
        tt = flags & FLAG_TOPIC_TYPE
        qos = max(0, qos_of(flags))
        tid = 0
        if tt == TOPIC_NORMAL:
            topic = body[3:].decode("utf-8", "replace")
            if not T.wildcard(topic):
                tid = client.alloc_topic_id(topic)
        else:
            topic = self._resolve_topic(client, tt, body[3:5])
            if tt == TOPIC_PREDEF:
                tid = struct.unpack(">H", body[3:5])[0]
        if topic is None or not await self.ctx.authorize(
                client.clientinfo, "subscribe", topic):
            self.send(addr, SUBACK, bytes([flags]) +
                      struct.pack(">HH", 0, mid) +
                      bytes([RC_INVALID_TOPIC_ID]))
            return
        self.ctx.subscribe(client.sid, topic, {"qos": qos})
        self.send(addr, SUBACK, bytes([qos << 5]) +
                  struct.pack(">HH", tid, mid) + bytes([RC_ACCEPTED]))

    def _on_unsubscribe(self, addr, client: Optional[SnClient],
                        body: bytes) -> None:
        if client is None:
            return
        flags = body[0]
        (mid,) = struct.unpack(">H", body[1:3])
        tt = flags & FLAG_TOPIC_TYPE
        topic = body[3:].decode("utf-8", "replace") if tt == TOPIC_NORMAL \
            else self._resolve_topic(client, tt, body[3:5])
        if topic:
            self.ctx.unsubscribe(client.sid, topic)
        self.send(addr, UNSUBACK, struct.pack(">H", mid))

    def _on_pingreq(self, addr, client: Optional[SnClient],
                    body: bytes) -> None:
        if body:   # sleeping client wakes to collect buffered messages
            cid = body.decode("utf-8", "replace")
            client = self.by_clientid.get(cid)
            if client is not None:
                client.addr = addr
                self.clients[addr] = client
                buffered, client.buffered = client.buffered, []
                for m in buffered:
                    client._send_publish(m)
        self.send(addr, PINGRESP)

    def _on_disconnect(self, addr, client: Optional[SnClient],
                       body: bytes) -> None:
        if client is None:
            self.send(addr, DISCONNECT)
            return
        if len(body) >= 2:
            # sleep with duration: keep session + subscriptions, buffer
            client.state = "asleep"
            self.send(addr, DISCONNECT)
            return
        # clean disconnect: the will is NOT published (wills fire only on
        # abnormal loss — keepalive expiry in sweep())
        self._drop(client)
        self.send(addr, DISCONNECT)

    def _publish_will(self, client: SnClient) -> None:
        w = client.will
        if isinstance(w, dict) and "payload" in w and w.get("topic"):
            self.ctx.publish(client.clientid, w["topic"], w["payload"],
                             qos=w.get("qos", 0),
                             retain=w.get("retain", False))

    def _drop(self, client: SnClient) -> None:
        if client.sid is not None:
            self.ctx.unregister_subscriber(client.sid)
            client.sid = None
        self.ctx.unregister_channel(client.clientid, client)
        self.clients.pop(client.addr, None)
        if self.by_clientid.get(client.clientid) is client:
            del self.by_clientid[client.clientid]
        if client.state != "idle":
            client.state = "idle"
            self.node.hooks.run("client.disconnected",
                                (client.clientinfo, "disconnect"))
