"""LwM2M 1.0 gateway: CoAP registration interface + MQTT command bridge.

Parity: apps/emqx_gateway/src/lwm2m — registration resource
(emqx_lwm2m_coap_resource.erl: POST/PUT/DELETE /rd), protocol bridge
(emqx_lwm2m_protocol.erl: mountpoint `lwm2m/%e/`, downlink commands from
`dn/#`, uplink events to `up/resp` / `up/notify`, command JSON with
reqID/msgType/data), command translation (emqx_lwm2m_cmd_handler.erl:
read->GET, write->PUT, execute->POST, discover->GET(link), observe->GET+
Observe), and the OMA-TLV codec (emqx_lwm2m_tlv.erl).
"""

from __future__ import annotations

import asyncio
import json
import struct
import time
from typing import Any, Optional

from emqx_tpu.gateway import coap as C
from emqx_tpu.gateway.ctx import GatewayCtx

CF_LINK = 40
CF_TEXT = 0
CF_OPAQUE = 42
CF_TLV = 11542
CF_JSON = 11543

# ---- OMA-TLV (emqx_lwm2m_tlv.erl) ----
T_OBJECT_INSTANCE = 0
T_RESOURCE_INSTANCE = 1
T_MULTIPLE_RESOURCE = 2
T_RESOURCE = 3

_KIND = {T_OBJECT_INSTANCE: "obj_inst", T_RESOURCE_INSTANCE: "res_inst",
         T_MULTIPLE_RESOURCE: "multi_res", T_RESOURCE: "resource"}
_KIND_R = {v: k for k, v in _KIND.items()}


def tlv_decode(data: bytes) -> list[dict]:
    out = []
    i = 0
    while i < len(data):
        t = data[i]
        kind = (t >> 6) & 3
        id_len = 2 if t & 0x20 else 1
        len_size = (t >> 3) & 3
        i += 1
        ident = int.from_bytes(data[i:i + id_len], "big")
        i += id_len
        if len_size == 0:
            length = t & 0x07
        else:
            length = int.from_bytes(data[i:i + len_size], "big")
            i += len_size
        value = data[i:i + length]
        i += length
        entry: dict[str, Any] = {"kind": _KIND[kind], "id": ident}
        if kind in (T_OBJECT_INSTANCE, T_MULTIPLE_RESOURCE):
            entry["value"] = tlv_decode(value)
        else:
            entry["value"] = value
        out.append(entry)
    return out


def tlv_encode(entries: list[dict]) -> bytes:
    out = bytearray()
    for e in entries:
        kind = _KIND_R[e["kind"]]
        value = e["value"]
        if isinstance(value, list):
            value = tlv_encode(value)
        elif isinstance(value, str):
            value = value.encode()
        elif isinstance(value, int):
            n = max(1, (value.bit_length() + 7) // 8)
            value = value.to_bytes(n, "big", signed=value < 0)
        ident = e["id"]
        t = kind << 6
        idb = struct.pack(">H", ident) if ident > 255 else bytes([ident])
        if ident > 255:
            t |= 0x20
        n = len(value)
        if n < 8:
            t |= n
            lenb = b""
        elif n < 256:
            t |= 0x08
            lenb = bytes([n])
        elif n < 65536:
            t |= 0x10
            lenb = struct.pack(">H", n)
        else:
            t |= 0x18
            lenb = n.to_bytes(3, "big")
        out += bytes([t]) + idb + lenb + value
    return bytes(out)


def _decode_content(cf: int, payload: bytes) -> Any:
    if cf == CF_TLV:
        return _tlv_jsonable(tlv_decode(payload))
    if cf in (CF_TEXT, CF_LINK):
        return payload.decode("utf-8", "replace")
    if cf == CF_JSON:
        try:
            return json.loads(payload)
        except ValueError:
            return payload.decode("utf-8", "replace")
    import base64
    return base64.b64encode(payload).decode()


def _tlv_jsonable(entries: list[dict]) -> list[dict]:
    out = []
    for e in entries:
        v = e["value"]
        if isinstance(v, list):
            v = _tlv_jsonable(v)
        elif isinstance(v, bytes):
            try:
                v = v.decode("utf-8")
            except UnicodeDecodeError:
                import base64
                v = base64.b64encode(v).decode()
        out.append({"kind": e["kind"], "id": e["id"], "value": v})
    return out


class Lwm2mSession:
    """One registered endpoint (emqx_lwm2m_protocol state)."""

    def __init__(self, gw: "Lwm2mGateway", ep: str, addr,
                 lifetime: int, objects: str):
        self.gw = gw
        self.ep = ep
        self.addr = addr
        self.lifetime = lifetime
        self.objects = objects
        self.location = f"{abs(hash(ep)) % 100000}"
        self.sid: Optional[int] = None
        self.last_update = time.monotonic()
        self.pending: dict[bytes, dict] = {}   # coap token -> command ctx
        self.observe_tokens: dict[str, bytes] = {}   # path -> token

    def mount(self, suffix: str) -> str:
        return f"lwm2m/{self.ep}/{suffix}"

    # ---- broker subscriber protocol: downlink commands arrive here ----
    def deliver(self, topic_filter: str, msg) -> bool:
        try:
            cmd = json.loads(msg.payload)
        except ValueError:
            return False
        from emqx_tpu.broker.supervise import spawn
        spawn(self.gw.send_command(self, cmd), "lwm2m-send-command")
        return True


class Lwm2mGateway(asyncio.DatagramProtocol):
    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        self.conf = conf or {}
        self.ctx = GatewayCtx(node, "lwm2m")
        self.bind = self.conf.get("bind", "127.0.0.1")
        self.port = self.conf.get("port", 5783)
        self.lifetime_max = self.conf.get("lifetime_max", 86400)
        self.transport = None
        self._mid = 0
        self._token_seq = 0
        self.sessions: dict[str, Lwm2mSession] = {}      # ep -> session
        self.by_location: dict[str, Lwm2mSession] = {}
        self.by_addr: dict[tuple, Lwm2mSession] = {}
        # OMA object registry (emqx_lwm2m_xml_object_db analog): core
        # objects compiled in, custom objects from DDF XML when configured
        from emqx_tpu.gateway.lwm2m_objects import ObjectRegistry
        self.objects = ObjectRegistry.core()
        xml_dir = self.conf.get("xml_dir")
        if xml_dir:
            self.objects.load_xml_dir(xml_dir)

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self.transport, _ = await loop.create_datagram_endpoint(
            lambda: self, local_addr=(self.bind, self.port))
        if self.port == 0:
            self.port = self.transport.get_extra_info("sockname")[1]

    async def stop(self) -> None:
        for s in list(self.sessions.values()):
            self._deregister(s)
        if self.transport:
            self.transport.close()

    def info(self) -> dict:
        return {"listener": f"udp:{self.bind}:{self.port}",
                "endpoints": len(self.sessions)}

    def _next_mid(self) -> int:
        self._mid = (self._mid + 1) & 0xFFFF
        return self._mid

    def _next_token(self) -> bytes:
        self._token_seq += 1
        return struct.pack(">I", self._token_seq)

    def _send(self, addr, msg: C.CoapMessage) -> None:
        if self.transport:
            self.transport.sendto(C.encode(msg), addr)

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            msg = C.decode(data)
        except C.CoapError:
            return
        from emqx_tpu.broker.supervise import spawn
        spawn(self._handle(addr, msg), "lwm2m-handle")

    async def _handle(self, addr, msg: C.CoapMessage) -> None:
        cls = msg.code >> 5
        if cls == 0 and msg.code != 0:          # request from device
            await self._handle_request(addr, msg)
        elif cls in (2, 4, 5):                  # response to a command
            self._handle_response(addr, msg)

    # ---- registration interface (POST/PUT/DELETE /rd) ----
    async def _handle_request(self, addr, req: C.CoapMessage) -> None:
        path = req.uri_path
        if not path or path[0] != "rd":
            self._reply(addr, req, C.NOT_FOUND)
            return
        q = req.uri_query
        if req.code == C.POST and len(path) == 1:
            await self._register(addr, req, q)
        elif req.code == C.POST and len(path) == 2 or \
                req.code == C.PUT and len(path) == 2:
            s = self.by_location.get(path[1])
            if s is None:
                self._reply(addr, req, C.NOT_FOUND)
                return
            s.addr = addr
            self.by_addr[addr] = s
            s.last_update = time.monotonic()
            if "lt" in q:
                s.lifetime = int(q["lt"])
            self._uplink(s, "update", {"lifetime": s.lifetime,
                                       "objectList": s.objects})
            self._reply(addr, req, C.CHANGED)
        elif req.code == C.DELETE and len(path) == 2:
            s = self.by_location.get(path[1])
            if s is not None:
                self._uplink(s, "deregister", {})
                self._deregister(s)
            self._reply(addr, req, C.DELETED)
        else:
            self._reply(addr, req, C.METHOD_NOT_ALLOWED)

    async def _register(self, addr, req: C.CoapMessage, q: dict) -> None:
        ep = q.get("ep")
        if not ep:
            self._reply(addr, req, C.BAD_REQUEST)
            return
        clientinfo = {"clientid": f"lwm2m:{ep}", "username": None,
                      "protocol": "lwm2m", "peername": addr}
        if not await self.ctx.authenticate(clientinfo):
            self._reply(addr, req, C.UNAUTHORIZED)
            return
        old = self.sessions.get(ep)
        if old is not None:
            self._deregister(old)
        lifetime = min(int(q.get("lt", 86400)), self.lifetime_max)
        s = Lwm2mSession(self, ep, addr, lifetime,
                         req.payload.decode("utf-8", "replace"))
        self.sessions[ep] = s
        self.by_location[s.location] = s
        self.by_addr[addr] = s
        s.sid = self.ctx.register_subscriber(s, ep)
        self.ctx.subscribe(s.sid, s.mount("dn/#"), {"qos": 0})
        self.ctx.register_channel(ep, s, {"proto": "lwm2m",
                                          "lifetime": lifetime})
        self._uplink(s, "register", {
            "lt": lifetime, "lwm2m": q.get("lwm2m", "1.0"),
            "objectList": [o.strip().strip("<>")
                           for o in s.objects.split(",") if o.strip()]})
        self.node.hooks.run("client.connected",
                            (clientinfo, {"proto_name": "LwM2M"}))
        self._reply(addr, req, C.CREATED, options=[
            (C.OPT_LOCATION_PATH, b"rd"),
            (C.OPT_LOCATION_PATH, s.location.encode())])

    def _reply(self, addr, req: C.CoapMessage, rcode: int,
               options: Optional[list] = None,
               payload: bytes = b"") -> None:
        self._send(addr, C.CoapMessage(
            type=C.ACK if req.type == C.CON else C.NON, code=rcode,
            message_id=req.message_id, token=req.token,
            options=options or [], payload=payload))

    def _deregister(self, s: Lwm2mSession) -> None:
        if s.sid is not None:
            self.ctx.unregister_subscriber(s.sid)
            s.sid = None
        self.ctx.unregister_channel(s.ep, s)
        self.sessions.pop(s.ep, None)
        self.by_location.pop(s.location, None)
        self.by_addr.pop(s.addr, None)

    # ---- uplink publishing ----
    def _uplink(self, s: Lwm2mSession, msg_type: str, data: dict,
                req_id: Optional[int] = None) -> None:
        payload = {"msgType": msg_type, "data": data}
        if req_id is not None:
            payload["reqID"] = req_id
        suffix = "up/notify" if msg_type == "notify" else "up/resp"
        self.ctx.publish(s.ep, s.mount(suffix),
                         json.dumps(payload).encode(), qos=0)

    # ---- downlink commands (emqx_lwm2m_cmd_handler) ----
    async def send_command(self, s: Lwm2mSession, cmd: dict) -> None:
        msg_type = cmd.get("msgType")
        data = cmd.get("data") or {}
        path = data.get("path", "")
        try:
            # name paths ("/Device/0/Manufacturer") resolve through the
            # object registry; numeric paths pass through
            path = self.objects.resolve_path(path)
        except KeyError as e:
            self._uplink(s, msg_type or "unknown",
                         {"reqPath": str(data.get("path", "")),
                          "code": "4.04", "codeMsg": str(e)},
                         cmd.get("reqID"))
            return
        segs = [p for p in str(path).split("/") if p != ""]
        opts = [(C.OPT_URI_PATH, seg.encode()) for seg in segs]
        token = self._next_token()
        if msg_type == "read":
            code = C.GET
        elif msg_type == "discover":
            code = C.GET
            opts.append((C.OPT_CONTENT_FORMAT,
                         _cf_bytes(CF_LINK)))
        elif msg_type == "write":
            code = C.PUT
        elif msg_type == "execute":
            code = C.POST
        elif msg_type == "observe":
            code = C.GET
            opts.append((C.OPT_OBSERVE, b""))
            s.observe_tokens[path] = token
        elif msg_type == "cancel-observe":
            code = C.GET
            opts.append((C.OPT_OBSERVE, b"\x01"))
        else:
            self._uplink(s, msg_type or "unknown",
                         {"reqPath": path, "code": "4.00",
                          "codeMsg": "bad msgType"}, cmd.get("reqID"))
            return
        payload = b""
        if msg_type == "write":
            value = data.get("value", "")
            if isinstance(value, list):
                payload = tlv_encode(value)
                opts.append((C.OPT_CONTENT_FORMAT, _cf_bytes(CF_TLV)))
            else:
                payload = str(value).encode()
                opts.append((C.OPT_CONTENT_FORMAT, _cf_bytes(CF_TEXT)))
        elif msg_type == "execute":
            payload = str(data.get("args", "")).encode()
        s.pending[token] = {"cmd": cmd, "path": path}
        self._send(s.addr, C.CoapMessage(
            type=C.CON, code=code, message_id=self._next_mid(),
            token=token, options=opts, payload=payload))

    def _handle_response(self, addr, msg: C.CoapMessage) -> None:
        s = self.by_addr.get(addr)
        if s is None:
            return
        token = bytes(msg.token)
        cf_raw = msg.opt(C.OPT_CONTENT_FORMAT)
        cf = int.from_bytes(cf_raw, "big") if cf_raw else CF_TEXT
        obs = msg.opt(C.OPT_OBSERVE)
        ctxt = s.pending.get(token)
        code_str = f"{msg.code >> 5}.{msg.code & 0x1F:02d}"
        if obs is not None and ctxt is None:
            # notification on an observed path
            path = next((p for p, t in s.observe_tokens.items()
                         if t == token), "")
            self._uplink(s, "notify", {
                "reqPath": path, "code": code_str,
                "seqNum": int.from_bytes(obs, "big") if obs else 0,
                "content": _decode_content(cf, msg.payload)})
            return
        if ctxt is None:
            return
        if ctxt["cmd"].get("msgType") != "observe":
            s.pending.pop(token, None)
        data = {
            "reqPath": ctxt["path"], "code": code_str,
            "codeMsg": _code_msg(msg.code),
            "content": _decode_content(cf, msg.payload)}
        name = self.objects.path_name(ctxt["path"])
        if name:
            data["reqPathName"] = name   # resolved via the object registry
        self._uplink(s, ctxt["cmd"].get("msgType", "resp"), data,
                     ctxt["cmd"].get("reqID"))


def _cf_bytes(cf: int) -> bytes:
    return bytes([cf]) if cf < 256 else struct.pack(">H", cf)


def _code_msg(code: int) -> str:
    return {C.CONTENT: "content", C.CHANGED: "changed",
            C.CREATED: "created", C.DELETED: "deleted",
            C.BAD_REQUEST: "bad_request", C.UNAUTHORIZED: "unauthorized",
            C.NOT_FOUND: "not_found",
            C.METHOD_NOT_ALLOWED: "method_not_allowed"}.get(code, "unknown")
