"""exproto gateway: any external program implements a protocol over gRPC.

Parity: apps/emqx_gateway/src/exproto — the broker hosts a TCP/UDP listener
plus the `ConnectionAdapter` gRPC service (Send/Close/Authenticate/
StartTimer/Publish/Subscribe/Unsubscribe), and streams socket/message events
to the external `ConnectionHandler` service
(protos/exproto.proto:23-60). Messages are wire-compatible with the
reference's proto (emqx.exproto.v1 package).

grpc_tools isn't in this image, so service bindings are built directly on
grpc generic handlers + multi-callables over the protoc-generated messages.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Optional

import grpc

from emqx_tpu.gateway.ctx import GatewayCtx
from emqx_tpu.gateway.protos import exproto_pb2 as pb

log = logging.getLogger("emqx_tpu.gateway.exproto")

_PKG = "/emqx.exproto.v1"

SUCCESS = 0
CONN_NOT_ALIVE = 2
PARAMS_MISSED = 3
PERMISSION_DENY = 5


class ExprotoConn:
    """One accepted socket; `conn` id is the handle the external program
    uses in every adapter call."""

    def __init__(self, gw: "ExprotoGateway", reader, writer):
        self.gw = gw
        self.conn = uuid.uuid4().hex
        self.reader, self.writer = reader, writer
        self.clientid = ""
        self.clientinfo: dict = {}
        self.authenticated = False
        self.sid: Optional[int] = None
        self.keepalive_timer: Optional[asyncio.TimerHandle] = None
        self.closed = False

    def deliver(self, topic_filter: str, msg) -> bool:
        self.gw.handler.received_messages(self.conn, [msg])
        return True

    async def run(self) -> None:
        peer = self.writer.get_extra_info("peername") or ("0.0.0.0", 0)
        sock = self.writer.get_extra_info("sockname") or ("0.0.0.0", 0)
        self.gw.handler.socket_created(self.conn, peer, sock)
        try:
            while True:
                data = await self.reader.read(4096)
                if not data:
                    break
                self.gw.handler.received_bytes(self.conn, data)
        except (ConnectionError, OSError):
            pass
        finally:
            self.close("closed")

    def close(self, reason: str) -> None:
        if self.closed:
            return
        self.closed = True
        if self.sid is not None:
            self.gw.ctx.unregister_subscriber(self.sid)
            self.sid = None
        if self.clientid:
            self.gw.ctx.unregister_channel(self.clientid, self)
        self.gw.conns.pop(self.conn, None)
        self.gw.handler.socket_closed(self.conn, reason)
        try:
            self.writer.close()
        except Exception:  # noqa: BLE001
            pass


class _HandlerClient:
    """Streaming client to the external ConnectionHandler service. Each
    hookpoint is one long-lived client-stream (the reference keeps one
    stream per hookpoint per gRPC channel, emqx_exproto_gcli)."""

    def __init__(self, address: str):
        self.channel = grpc.insecure_channel(address)
        self._queues: dict[str, asyncio.Queue] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._tasks: list = []

    def start(self, loop) -> None:
        self._loop = loop
        for name, req_cls in [
                ("OnSocketCreated", pb.SocketCreatedRequest),
                ("OnSocketClosed", pb.SocketClosedRequest),
                ("OnReceivedBytes", pb.ReceivedBytesRequest),
                ("OnTimerTimeout", pb.TimerTimeoutRequest),
                ("OnReceivedMessages", pb.ReceivedMessagesRequest)]:
            q: asyncio.Queue = asyncio.Queue()
            self._queues[name] = q
            self._tasks.append(loop.create_task(
                self._pump(name, req_cls, q)))

    async def _pump(self, name: str, req_cls, q: asyncio.Queue) -> None:
        call = self.channel.stream_unary(
            f"{_PKG}.ConnectionHandler/{name}",
            request_serializer=req_cls.SerializeToString,
            response_deserializer=pb.EmptySuccess.FromString)
        loop = asyncio.get_running_loop()

        def gen():
            while True:
                fut = asyncio.run_coroutine_threadsafe(q.get(), loop)
                item = fut.result()
                if item is None:
                    return
                yield item

        try:
            await loop.run_in_executor(None, call, gen())
        except grpc.RpcError as e:
            log.warning("handler stream %s ended: %s", name, e)

    def _put(self, name: str, msg) -> None:
        q = self._queues.get(name)
        if q is not None:
            q.put_nowait(msg)

    def socket_created(self, conn: str, peer, sock) -> None:
        self._put("OnSocketCreated", pb.SocketCreatedRequest(
            conn=conn, conninfo=pb.ConnInfo(
                socktype=pb.TCP,
                peername=pb.Address(host=str(peer[0]), port=int(peer[1])),
                sockname=pb.Address(host=str(sock[0]),
                                    port=int(sock[1])))))

    def socket_closed(self, conn: str, reason: str) -> None:
        self._put("OnSocketClosed",
                  pb.SocketClosedRequest(conn=conn, reason=reason))

    def received_bytes(self, conn: str, data: bytes) -> None:
        self._put("OnReceivedBytes",
                  pb.ReceivedBytesRequest(conn=conn, bytes=data))

    def timer_timeout(self, conn: str) -> None:
        self._put("OnTimerTimeout",
                  pb.TimerTimeoutRequest(conn=conn, type=pb.KEEPALIVE))

    def received_messages(self, conn: str, msgs: list) -> None:
        self._put("OnReceivedMessages", pb.ReceivedMessagesRequest(
            conn=conn, messages=[pb.Message(
                id=str(m.id), qos=m.qos, topic=m.topic,
                payload=m.payload, timestamp=m.ts,
                **{"from": m.from_}) for m in msgs]))

    def stop(self) -> None:
        for q in self._queues.values():
            q.put_nowait(None)
        for t in self._tasks:
            t.cancel()
        self.channel.close()


class ExprotoGateway:
    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        self.conf = conf or {}
        self.ctx = GatewayCtx(node, "exproto")
        self.bind = self.conf.get("bind", "127.0.0.1")
        self.port = self.conf.get("port", 7993)
        self.adapter_port = self.conf.get("adapter_port", 9100)
        self.handler_address = self.conf.get("handler_address",
                                             "127.0.0.1:9001")
        self.conns: dict[str, ExprotoConn] = {}
        self.handler = _HandlerClient(self.handler_address)
        self._server: Optional[asyncio.AbstractServer] = None
        self._grpc_server = None
        self._loop = None

    # ---- lifecycle ----
    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self.handler.start(self._loop)
        self._grpc_server = grpc.server(
            __import__("concurrent.futures", fromlist=["x"])
            .ThreadPoolExecutor(max_workers=4))
        self._grpc_server.add_generic_rpc_handlers(
            (self._adapter_handler(),))
        self.adapter_port = self._grpc_server.add_insecure_port(
            f"{self.bind}:{self.adapter_port}")
        self._grpc_server.start()
        self._server = await asyncio.start_server(self._accept, self.bind,
                                                  self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]

    async def _accept(self, reader, writer) -> None:
        conn = ExprotoConn(self, reader, writer)
        self.conns[conn.conn] = conn
        await conn.run()

    async def stop(self) -> None:
        for c in list(self.conns.values()):
            c.close("shutdown")
        if self._server:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2)
            except asyncio.TimeoutError:
                pass
        if self._grpc_server:
            self._grpc_server.stop(grace=0.2)
        self.handler.stop()

    def info(self) -> dict:
        return {"listener": f"tcp:{self.bind}:{self.port}",
                "adapter": f"grpc:{self.bind}:{self.adapter_port}",
                "current_connections": len(self.conns)}

    # ---- ConnectionAdapter service (threadpool grpc -> loop calls) ----
    def _adapter_handler(self):
        gw = self

        def unary(fn, req_cls):
            def handler(request, _context):
                fut = asyncio.run_coroutine_threadsafe(
                    fn(request), gw._loop)
                try:
                    return fut.result(timeout=10)
                except Exception as e:  # noqa: BLE001
                    log.exception("adapter call failed")
                    return pb.CodeResponse(code=pb.UNKNOWN,
                                           message=str(e))
            return grpc.unary_unary_rpc_method_handler(
                handler, request_deserializer=req_cls.FromString,
                response_serializer=pb.CodeResponse.SerializeToString)

        handlers = {
            "Send": unary(self._h_send, pb.SendBytesRequest),
            "Close": unary(self._h_close, pb.CloseSocketRequest),
            "Authenticate": unary(self._h_auth, pb.AuthenticateRequest),
            "StartTimer": unary(self._h_timer, pb.TimerRequest),
            "Publish": unary(self._h_publish, pb.PublishRequest),
            "Subscribe": unary(self._h_subscribe, pb.SubscribeRequest),
            "Unsubscribe": unary(self._h_unsubscribe,
                                 pb.UnsubscribeRequest),
        }
        return grpc.method_handlers_generic_handler(
            "emqx.exproto.v1.ConnectionAdapter", handlers)

    def _conn(self, conn_id: str) -> Optional[ExprotoConn]:
        return self.conns.get(conn_id)

    async def _h_send(self, req) -> pb.CodeResponse:
        c = self._conn(req.conn)
        if c is None:
            return pb.CodeResponse(code=CONN_NOT_ALIVE)
        c.writer.write(req.bytes)
        await c.writer.drain()
        return pb.CodeResponse(code=SUCCESS)

    async def _h_close(self, req) -> pb.CodeResponse:
        c = self._conn(req.conn)
        if c is None:
            return pb.CodeResponse(code=CONN_NOT_ALIVE)
        c.close("closed_by_handler")
        return pb.CodeResponse(code=SUCCESS)

    async def _h_auth(self, req) -> pb.CodeResponse:
        c = self._conn(req.conn)
        if c is None:
            return pb.CodeResponse(code=CONN_NOT_ALIVE)
        ci = req.clientinfo
        if not ci.clientid:
            return pb.CodeResponse(code=PARAMS_MISSED,
                                   message="clientid required")
        c.clientinfo = {"clientid": f"exproto:{ci.clientid}",
                        "username": ci.username or None,
                        "protocol": ci.proto_name or "exproto",
                        "peername": c.writer.get_extra_info("peername")}
        if not await self.ctx.authenticate(c.clientinfo, req.password):
            return pb.CodeResponse(code=PERMISSION_DENY)
        c.clientid = ci.clientid
        c.authenticated = True
        c.sid = self.ctx.register_subscriber(c, c.clientid)
        self.ctx.register_channel(c.clientid, c,
                                  {"proto": ci.proto_name})
        self.node.hooks.run("client.connected",
                            (c.clientinfo,
                             {"proto_name": ci.proto_name}))
        return pb.CodeResponse(code=SUCCESS)

    async def _h_timer(self, req) -> pb.CodeResponse:
        c = self._conn(req.conn)
        if c is None:
            return pb.CodeResponse(code=CONN_NOT_ALIVE)
        if c.keepalive_timer:
            c.keepalive_timer.cancel()
        if req.interval > 0:
            c.keepalive_timer = self._loop.call_later(
                req.interval, self.handler.timer_timeout, c.conn)
        return pb.CodeResponse(code=SUCCESS)

    async def _h_publish(self, req) -> pb.CodeResponse:
        c = self._conn(req.conn)
        if c is None or not c.authenticated:
            return pb.CodeResponse(code=CONN_NOT_ALIVE)
        if not await self.ctx.authorize(c.clientinfo, "publish",
                                        req.topic):
            return pb.CodeResponse(code=PERMISSION_DENY)
        self.ctx.publish(c.clientid, req.topic, req.payload,
                         qos=min(req.qos, 2))
        return pb.CodeResponse(code=SUCCESS)

    async def _h_subscribe(self, req) -> pb.CodeResponse:
        c = self._conn(req.conn)
        if c is None or not c.authenticated:
            return pb.CodeResponse(code=CONN_NOT_ALIVE)
        if not await self.ctx.authorize(c.clientinfo, "subscribe",
                                        req.topic):
            return pb.CodeResponse(code=PERMISSION_DENY)
        self.ctx.subscribe(c.sid, req.topic, {"qos": min(req.qos, 2)})
        return pb.CodeResponse(code=SUCCESS)

    async def _h_unsubscribe(self, req) -> pb.CodeResponse:
        c = self._conn(req.conn)
        if c is None or not c.authenticated:
            return pb.CodeResponse(code=CONN_NOT_ALIVE)
        self.ctx.unsubscribe(c.sid, req.topic)
        return pb.CodeResponse(code=SUCCESS)
