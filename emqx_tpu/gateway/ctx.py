"""Gateway context: the insulation layer between a gateway and the core.

Parity: emqx_gateway_ctx.erl — authenticate, open_session (per-gateway CM
namespace `<gw>:<clientid>`), publish/subscribe into the core broker,
metrics. Gateway channels never touch broker internals directly; everything
goes through this object (so the core can evolve independently of the 5
protocol implementations).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from emqx_tpu.broker.message import Message, make
from emqx_tpu.broker.session import Session, SessionConf


class GatewayCtx:
    def __init__(self, node, gwname: str):
        self.node = node
        self.gwname = gwname

    def _cid(self, clientid: str) -> str:
        return f"{self.gwname}:{clientid}"

    # ---- authn (delegates to the core chain like emqx_gateway_ctx:authenticate)
    async def authenticate(self, clientinfo: dict,
                           password: Optional[str] = None) -> bool:
        if self.node.banned.check(clientinfo):
            return False
        self.node.metrics.inc("client.authenticate")
        if isinstance(password, str):
            password = password.encode()   # authn chain expects wire bytes
        res = await self.node.hooks.run_fold_async(
            "client.authenticate", (clientinfo,),
            {"ok": True, "password": password})
        return isinstance(res, dict) and bool(res.get("ok"))

    async def authorize(self, clientinfo: dict, action: str,
                        topic: str) -> bool:
        res = await self.node.hooks.run_fold_async(
            "client.authorize", (clientinfo, action, topic), "allow")
        return res != "deny"

    # ---- session / registry ----
    async def open_session(self, clean_start: bool, clientid: str,
                           channel: Any,
                           conf: Optional[SessionConf] = None
                           ) -> tuple[Session, bool]:
        sess, present = await self.node.cm.open_session(
            clean_start, self._cid(clientid), conf or SessionConf(),
            channel)
        return sess, present

    def register_channel(self, clientid: str, channel: Any,
                         info: Optional[dict] = None) -> None:
        self.node.cm.register_channel(self._cid(clientid), channel,
                                      dict(info or {},
                                           gateway=self.gwname))

    def unregister_channel(self, clientid: str,
                           channel: Any = None) -> None:
        self.node.cm.unregister_channel(self._cid(clientid), channel)

    def lookup_channel(self, clientid: str) -> Optional[Any]:
        return self.node.cm.lookup_channel(self._cid(clientid))

    # ---- pubsub ----
    def register_subscriber(self, subscriber, clientid: str) -> int:
        return self.node.broker.register(subscriber, self._cid(clientid))

    def unregister_subscriber(self, sid: int) -> None:
        self.node.broker.subscriber_down(sid)

    def subscribe(self, sid: int, topic_filter: str,
                  subopts: Optional[dict] = None) -> None:
        self.node.broker.subscribe(sid, topic_filter, subopts or {"qos": 0})

    def unsubscribe(self, sid: int, topic_filter: str) -> bool:
        return self.node.broker.unsubscribe(sid, topic_filter)

    def publish(self, clientid: str, topic: str, payload: bytes,
                qos: int = 0, retain: bool = False,
                headers: Optional[dict] = None) -> int:
        msg = make(self._cid(clientid), qos, topic, payload,
                   flags={"retain": retain}, headers=headers or {})
        # scheduled (not inline): async extension hooks must see gateway
        # publishes too; gateway callers don't consume the delivery count
        self.node.broker.publish_soon(msg)
        return 1

    def publish_msg(self, msg: Message) -> int:
        self.node.broker.publish_soon(msg)
        return 1

    def metrics_inc(self, name: str, n: int = 1) -> None:
        self.node.metrics.inc(f"gateway.{self.gwname}.{name}", n)
