"""STOMP 1.0/1.1/1.2 gateway.

Parity: apps/emqx_gateway/src/stomp — frame codec (emqx_stomp_frame.erl),
protocol FSM (emqx_stomp_channel.erl): CONNECT/STOMP auth + CONNECTED,
SEND -> publish (with transactions via BEGIN/COMMIT/ABORT), SUBSCRIBE with
per-subscription ids -> MESSAGE deliveries, receipts, heart-beats,
ERROR + close on protocol violations.

Destination = MQTT topic (the reference maps 1:1 and allows MQTT wildcard
destinations on subscribe).
"""

from __future__ import annotations

import asyncio
import time
import uuid
from typing import Optional

from emqx_tpu.gateway.ctx import GatewayCtx

MAX_FRAME = 1 << 20


class StompError(Exception):
    pass


class Frame:
    def __init__(self, command: str, headers: Optional[dict] = None,
                 body: bytes = b""):
        self.command = command
        self.headers = dict(headers or {})
        self.body = body

    def encode(self) -> bytes:
        out = [self.command.encode()]
        for k, v in self.headers.items():
            out.append(f"{_esc(k)}:{_esc(str(v))}".encode())
        if self.body and "content-length" not in self.headers:
            out.append(f"content-length:{len(self.body)}".encode())
        return b"\n".join(out) + b"\n\n" + self.body + b"\x00"


def _esc(v: str) -> str:
    return (v.replace("\\", "\\\\").replace("\n", "\\n")
            .replace(":", "\\c").replace("\r", "\\r"))


def _unesc(v: str) -> str:
    out, i = [], 0
    while i < len(v):
        if v[i] == "\\" and i + 1 < len(v):
            out.append({"n": "\n", "c": ":", "\\": "\\", "r": "\r"}
                       .get(v[i + 1], v[i + 1]))
            i += 2
        else:
            out.append(v[i])
            i += 1
    return "".join(out)


class FrameParser:
    """Incremental parser over a byte buffer (emqx_stomp_frame streaming)."""

    def __init__(self):
        self.buf = b""

    def feed(self, data: bytes) -> list[Frame]:
        self.buf += data
        if len(self.buf) > MAX_FRAME:
            raise StompError("frame too large")
        out = []
        while True:
            f = self._try_parse()
            if f is None:
                return out
            if f is not False:     # False = heart-beat newline
                out.append(f)

    def _try_parse(self):
        # leading EOLs between frames are heart-beats
        while self.buf[:1] in (b"\n", b"\r"):
            self.buf = self.buf[1:]
        if not self.buf:
            return None
        # take whichever header terminator appears FIRST: a CRLF frame whose
        # body contains "\n\n" must not be cut at the body (STOMP 1.2 EOLs)
        idx_lf = self.buf.find(b"\n\n")
        idx_crlf = self.buf.find(b"\r\n\r\n")
        if idx_crlf >= 0 and (idx_lf < 0 or idx_crlf <= idx_lf - 1):
            head_end, sep = idx_crlf, 4
        elif idx_lf >= 0:
            head_end, sep = idx_lf, 2
        else:
            return None
        head = self.buf[:head_end].decode("utf-8", "replace")
        lines = head.replace("\r\n", "\n").split("\n")
        command = lines[0].strip()
        headers: dict[str, str] = {}
        for ln in lines[1:]:
            k, _, v = ln.partition(":")
            k = _unesc(k)
            if k and k not in headers:      # first wins (spec)
                headers[k] = _unesc(v)
        body_start = head_end + sep
        if "content-length" in headers:
            try:
                n = int(headers["content-length"])
            except ValueError:
                raise StompError("bad content-length")
            if len(self.buf) < body_start + n + 1:
                return None
            body = self.buf[body_start:body_start + n]
            if self.buf[body_start + n:body_start + n + 1] != b"\x00":
                raise StompError("missing frame NUL")
            self.buf = self.buf[body_start + n + 1:]
        else:
            nul = self.buf.find(b"\x00", body_start)
            if nul < 0:
                return None
            body = self.buf[body_start:nul]
            self.buf = self.buf[nul + 1:]
        return Frame(command, headers, body)


class StompChannel:
    """One client connection (emqx_stomp_channel.erl)."""

    def __init__(self, gw: "StompGateway", reader, writer):
        self.gw = gw
        self.ctx = gw.ctx
        self.reader, self.writer = reader, writer
        self.parser = FrameParser()
        self.connected = False
        self.clientid = ""
        self.clientinfo: dict = {}
        self.sid: Optional[int] = None
        # stomp sub id -> (topic, ack_mode); topic -> sub id
        self.subs: dict[str, tuple[str, str]] = {}
        self.topic_to_sub: dict[str, str] = {}
        self.transactions: dict[str, list[Frame]] = {}
        self.heartbeat = (0, 0)
        self._last_recv = time.monotonic()

    # ---- broker subscriber protocol ----
    def deliver(self, topic_filter: str, msg) -> bool:
        subid = self.topic_to_sub.get(topic_filter, "0")
        self._send(Frame("MESSAGE", {
            "subscription": subid,
            "message-id": uuid.uuid4().hex[:16],
            "destination": msg.topic,
            "content-type": "text/plain",
        }, msg.payload))
        return True

    def _send(self, frame: Frame) -> None:
        try:
            self.writer.write(frame.encode())
        except (ConnectionError, OSError):
            pass

    def _error(self, message: str, detail: str = "",
               receipt: Optional[str] = None) -> None:
        h = {"message": message}
        if receipt:
            h["receipt-id"] = receipt
        self._send(Frame("ERROR", h, detail.encode()))

    def _receipt(self, frame: Frame) -> None:
        rid = frame.headers.get("receipt")
        if rid:
            self._send(Frame("RECEIPT", {"receipt-id": rid}))

    async def run(self) -> None:
        try:
            while True:
                data = await self.reader.read(4096)
                if not data:
                    break
                self._last_recv = time.monotonic()
                for frame in self.parser.feed(data):
                    await self.handle(frame)
                await self.writer.drain()
        except (StompError, ConnectionError,
                asyncio.IncompleteReadError) as e:
            if isinstance(e, StompError):
                self._error("protocol error", str(e))
        finally:
            self.terminate()

    def terminate(self) -> None:
        if self.sid is not None:
            self.ctx.unregister_subscriber(self.sid)
            self.sid = None
        if self.connected:
            self.ctx.unregister_channel(self.clientid, self)
            self.connected = False
            self.gw.node.hooks.run("client.disconnected",
                                   (self.clientinfo, "closed"))
        try:
            self.writer.close()
        except Exception:  # noqa: BLE001
            pass

    async def handle(self, frame: Frame) -> None:
        cmd = frame.command
        if not self.connected and cmd not in ("CONNECT", "STOMP"):
            self._error("not connected", f"got {cmd} before CONNECT")
            return
        handler = {
            "CONNECT": self._on_connect, "STOMP": self._on_connect,
            "SEND": self._on_send, "SUBSCRIBE": self._on_subscribe,
            "UNSUBSCRIBE": self._on_unsubscribe,
            "BEGIN": self._on_begin, "COMMIT": self._on_commit,
            "ABORT": self._on_abort, "ACK": self._on_ack,
            "NACK": self._on_ack, "DISCONNECT": self._on_disconnect,
        }.get(cmd)
        if handler is None:
            self._error("unknown command", cmd)
            return
        await handler(frame)

    async def _on_connect(self, frame: Frame) -> None:
        if self.connected:
            self._error("already connected", "")
            return
        h = frame.headers
        login = h.get("login", "")
        self.clientid = h.get("client-id") or f"stomp-{uuid.uuid4().hex[:12]}"
        self.clientinfo = {"clientid": f"stomp:{self.clientid}",
                           "username": login, "proto_name": "STOMP",
                           "protocol": "stomp",
                           "peername": self.writer.get_extra_info("peername")}
        if not await self.ctx.authenticate(self.clientinfo,
                                           h.get("passcode")):
            self._error("login failed", "authentication refused")
            self.terminate()
            return
        cx, _, cy = h.get("heart-beat", "0,0").partition(",")
        try:
            self.heartbeat = (int(cx or 0), int(cy or 0))
        except ValueError:
            self._error("bad heart-beat", h.get("heart-beat", ""))
            return
        self.connected = True
        self.sid = self.ctx.register_subscriber(self, self.clientid)
        self.ctx.register_channel(self.clientid, self,
                                  {"username": login, "proto": "stomp"})
        self.gw.node.hooks.run("client.connected",
                               (self.clientinfo, {"proto_name": "STOMP"}))
        self._send(Frame("CONNECTED", {
            "version": _negotiate(h.get("accept-version", "1.0")),
            "heart-beat": f"{self.heartbeat[1]},{self.heartbeat[0]}",
            "server": "emqx-tpu-stomp",
            "session": self.clientid,
        }))

    async def _on_send(self, frame: Frame) -> None:
        dest = frame.headers.get("destination")
        if not dest:
            self._error("missing destination", "")
            return
        tx = frame.headers.get("transaction")
        if tx is not None:
            if tx not in self.transactions:
                self._error("transaction not begun", tx)
                return
            self.transactions[tx].append(frame)
            self._receipt(frame)
            return
        await self._do_send(frame)
        self._receipt(frame)

    async def _do_send(self, frame: Frame) -> None:
        dest = frame.headers["destination"]
        if not await self.ctx.authorize(self.clientinfo, "publish", dest):
            self._error("not authorized", dest)
            return
        qos = int(frame.headers.get("qos", 0))
        self.ctx.publish(self.clientid, dest, frame.body, qos=qos)
        self.ctx.metrics_inc("messages.received")

    async def _on_subscribe(self, frame: Frame) -> None:
        dest = frame.headers.get("destination")
        subid = frame.headers.get("id", "0")
        if not dest:
            self._error("missing destination", "")
            return
        if not await self.ctx.authorize(self.clientinfo, "subscribe", dest):
            self._error("not authorized", dest)
            return
        ack = frame.headers.get("ack", "auto")
        self.subs[subid] = (dest, ack)
        self.topic_to_sub[dest] = subid
        self.ctx.subscribe(self.sid, dest, {"qos": 1})
        self._receipt(frame)

    async def _on_unsubscribe(self, frame: Frame) -> None:
        subid = frame.headers.get("id")
        ent = self.subs.pop(subid, None)
        if ent:
            self.topic_to_sub.pop(ent[0], None)
            self.ctx.unsubscribe(self.sid, ent[0])
        self._receipt(frame)

    async def _on_begin(self, frame: Frame) -> None:
        tx = frame.headers.get("transaction")
        if tx in self.transactions:
            self._error("transaction already begun", tx or "")
            return
        self.transactions[tx] = []
        self._receipt(frame)

    async def _on_commit(self, frame: Frame) -> None:
        tx = frame.headers.get("transaction")
        frames = self.transactions.pop(tx, None)
        if frames is None:
            self._error("transaction not begun", tx or "")
            return
        for f in frames:
            await self._do_send(f)
        self._receipt(frame)

    async def _on_abort(self, frame: Frame) -> None:
        tx = frame.headers.get("transaction")
        if self.transactions.pop(tx, None) is None:
            self._error("transaction not begun", tx or "")
            return
        self._receipt(frame)

    async def _on_ack(self, frame: Frame) -> None:
        self._receipt(frame)   # client-mode acks are accepted (no redelivery)

    async def _on_disconnect(self, frame: Frame) -> None:
        self._receipt(frame)
        await self.writer.drain()
        self.terminate()


def _negotiate(accept: str) -> str:
    versions = {v.strip() for v in accept.split(",")}
    for v in ("1.2", "1.1", "1.0"):
        if v in versions:
            return v
    return "1.0"


class StompGateway:
    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        self.conf = conf or {}
        self.ctx = GatewayCtx(node, "stomp")
        self.bind = self.conf.get("bind", "127.0.0.1")
        self.port = self.conf.get("port", 61613)
        self._server: Optional[asyncio.AbstractServer] = None
        self._channels: set[StompChannel] = set()

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._accept, self.bind,
                                                  self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]

    async def _accept(self, reader, writer) -> None:
        ch = StompChannel(self, reader, writer)
        self._channels.add(ch)
        try:
            await ch.run()
        finally:
            self._channels.discard(ch)

    async def stop(self) -> None:
        for ch in list(self._channels):
            ch.terminate()
        if self._server:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2)
            except asyncio.TimeoutError:
                pass

    def info(self) -> dict:
        return {"listener": f"tcp:{self.bind}:{self.port}",
                "current_connections": len(self._channels)}
