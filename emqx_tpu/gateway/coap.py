"""CoAP gateway (RFC 7252 over UDP) with MQTT pub/sub semantics.

Parity: apps/emqx_gateway/src/coap — message codec
(emqx_coap_message.erl/emqx_coap_frame), transport manager, and the MQTT
resource (emqx_coap_mqtt_handler): PUT/POST `/mqtt/{topic}?c=<clientid>`
publishes the payload; GET with Observe:0 subscribes (notifications arrive
as NON 2.05 responses carrying an incrementing Observe sequence on the same
token); Observe:1 (or DELETE) unsubscribes.
"""

from __future__ import annotations

import asyncio
import struct
from dataclasses import dataclass, field
from typing import Optional
from urllib.parse import parse_qsl, unquote

from emqx_tpu.gateway.ctx import GatewayCtx

# types
CON, NON, ACK, RST = 0, 1, 2, 3
# option numbers
OPT_OBSERVE = 6
OPT_LOCATION_PATH = 8
OPT_URI_PATH = 11
OPT_CONTENT_FORMAT = 12
OPT_MAX_AGE = 14
OPT_URI_QUERY = 15


def code(cls: int, detail: int) -> int:
    return (cls << 5) | detail


GET, POST, PUT, DELETE = 1, 2, 3, 4
CREATED = code(2, 1)
DELETED = code(2, 2)
VALID = code(2, 3)
CHANGED = code(2, 4)
CONTENT = code(2, 5)
BAD_REQUEST = code(4, 0)
UNAUTHORIZED = code(4, 1)
NOT_FOUND = code(4, 4)
METHOD_NOT_ALLOWED = code(4, 5)


@dataclass
class CoapMessage:
    type: int = CON
    code: int = 0
    message_id: int = 0
    token: bytes = b""
    options: list = field(default_factory=list)   # [(number, bytes)]
    payload: bytes = b""

    def opt(self, number: int) -> Optional[bytes]:
        for n, v in self.options:
            if n == number:
                return v
        return None

    def opts(self, number: int) -> list[bytes]:
        return [v for n, v in self.options if n == number]

    @property
    def uri_path(self) -> list[str]:
        return [v.decode("utf-8", "replace")
                for v in self.opts(OPT_URI_PATH)]

    @property
    def uri_query(self) -> dict:
        out = {}
        for v in self.opts(OPT_URI_QUERY):
            k, _, val = v.decode("utf-8", "replace").partition("=")
            out[k] = unquote(val)
        return out


def _ext_len(x: int) -> tuple[int, bytes]:
    if x < 13:
        return x, b""
    if x < 269:
        return 13, bytes([x - 13])
    return 14, struct.pack(">H", x - 269)


def encode(m: CoapMessage) -> bytes:
    out = bytearray()
    out.append(0x40 | (m.type << 4) | len(m.token))
    out.append(m.code)
    out += struct.pack(">H", m.message_id)
    out += m.token
    last = 0
    # stable sort by option number ONLY: repeated options (Uri-Path
    # segments) must keep their relative order (RFC 7252 §3.1)
    for num, val in sorted(m.options, key=lambda kv: kv[0]):
        dnib, dext = _ext_len(num - last)
        lnib, lext = _ext_len(len(val))
        out.append((dnib << 4) | lnib)
        out += dext + lext + val
        last = num
    if m.payload:
        out.append(0xFF)
        out += m.payload
    return bytes(out)


class CoapError(Exception):
    pass


def decode(data: bytes) -> CoapMessage:
    try:
        return _decode(data)
    except (struct.error, IndexError) as e:
        # truncated datagrams surface as CoapError (silent drop upstream)
        raise CoapError(f"truncated message: {e}") from e


def _decode(data: bytes) -> CoapMessage:
    if len(data) < 4 or (data[0] >> 6) != 1:
        raise CoapError("bad version/short header")
    tkl = data[0] & 0x0F
    if tkl > 8:
        raise CoapError("bad TKL")
    m = CoapMessage(type=(data[0] >> 4) & 3, code=data[1],
                    message_id=struct.unpack(">H", data[2:4])[0],
                    token=data[4:4 + tkl])
    i = 4 + tkl
    last = 0
    while i < len(data):
        if data[i] == 0xFF:
            m.payload = data[i + 1:]
            if not m.payload:
                raise CoapError("payload marker with empty payload")
            break
        dnib, lnib = data[i] >> 4, data[i] & 0x0F
        i += 1

        def ext(nib):
            nonlocal i
            if nib == 13:
                v = data[i] + 13
                i += 1
                return v
            if nib == 14:
                v = struct.unpack(">H", data[i:i + 2])[0] + 269
                i += 2
                return v
            if nib == 15:
                raise CoapError("reserved option nibble")
            return nib
        delta = ext(dnib)
        length = ext(lnib)
        last += delta
        m.options.append((last, data[i:i + length]))
        i += length
    return m


class _Observer:
    def __init__(self, gw, addr, token, clientid, topic):
        self.gw = gw
        self.addr = addr
        self.token = token
        self.clientid = clientid
        self.topic = topic
        self.seq = 1
        self.sid: Optional[int] = None

    def deliver(self, topic_filter: str, msg) -> bool:
        self.seq += 1
        self.gw._send(self.addr, CoapMessage(
            type=NON, code=CONTENT, message_id=self.gw._next_mid(),
            token=self.token,
            options=[(OPT_OBSERVE, _obs_bytes(self.seq))],
            payload=msg.payload))
        return True


def _obs_bytes(seq: int) -> bytes:
    if seq < 256:
        return bytes([seq])
    if seq < 65536:
        return struct.pack(">H", seq)
    return struct.pack(">I", seq)[1:]


class CoapGateway(asyncio.DatagramProtocol):
    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        self.conf = conf or {}
        self.ctx = GatewayCtx(node, "coap")
        self.bind = self.conf.get("bind", "127.0.0.1")
        self.port = self.conf.get("port", 5683)
        self.transport = None
        self._mid = 0
        # (addr, token) -> _Observer ; and (addr, topic) for dedup
        self.observers: dict[tuple, _Observer] = {}

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self.transport, _ = await loop.create_datagram_endpoint(
            lambda: self, local_addr=(self.bind, self.port))
        if self.port == 0:
            self.port = self.transport.get_extra_info("sockname")[1]

    async def stop(self) -> None:
        for ob in list(self.observers.values()):
            if ob.sid is not None:
                self.ctx.unregister_subscriber(ob.sid)
        self.observers.clear()
        if self.transport:
            self.transport.close()

    def info(self) -> dict:
        return {"listener": f"udp:{self.bind}:{self.port}",
                "observers": len(self.observers)}

    def _next_mid(self) -> int:
        self._mid = (self._mid + 1) & 0xFFFF
        return self._mid

    def _send(self, addr, msg: CoapMessage) -> None:
        if self.transport:
            self.transport.sendto(encode(msg), addr)

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            msg = decode(data)
        except CoapError:
            return
        if msg.type in (ACK, RST):
            if msg.type == RST:
                self._cancel_all(addr)
            return
        from emqx_tpu.broker.supervise import spawn
        spawn(self._handle(addr, msg), "coap-handle")

    def _reply(self, addr, req: CoapMessage, rcode: int,
               options: Optional[list] = None,
               payload: bytes = b"") -> None:
        self._send(addr, CoapMessage(
            type=ACK if req.type == CON else NON, code=rcode,
            message_id=req.message_id, token=req.token,
            options=options or [], payload=payload))

    async def _handle(self, addr, req: CoapMessage) -> None:
        path = req.uri_path
        if len(path) < 2 or path[0] != "mqtt":
            self._reply(addr, req, NOT_FOUND)
            return
        topic = "/".join(path[1:])
        q = req.uri_query
        clientid = q.get("c") or f"coap-{addr[0]}-{addr[1]}"
        clientinfo = {"clientid": f"coap:{clientid}",
                      "username": q.get("u"), "protocol": "coap",
                      "peername": addr}
        if not await self.ctx.authenticate(clientinfo, q.get("p")):
            self._reply(addr, req, UNAUTHORIZED)
            return
        if req.code in (PUT, POST):
            if not await self.ctx.authorize(clientinfo, "publish", topic):
                self._reply(addr, req, UNAUTHORIZED)
                return
            qos = int(q.get("qos", 0))
            retain = q.get("retain") in ("1", "true")
            self.ctx.publish(clientid, topic, req.payload, qos=qos,
                             retain=retain)
            self._reply(addr, req, CHANGED)
        elif req.code == GET:
            obs = req.opt(OPT_OBSERVE)
            if obs is None:
                self._reply(addr, req, METHOD_NOT_ALLOWED)
                return
            obs_val = int.from_bytes(obs, "big") if obs else 0
            key = (addr, bytes(req.token))
            if obs_val == 0:
                if not await self.ctx.authorize(clientinfo, "subscribe",
                                                topic):
                    self._reply(addr, req, UNAUTHORIZED)
                    return
                prev = self.observers.pop(key, None)
                if prev is not None and prev.sid is not None:
                    # retransmitted observe: the old registration must go
                    self.ctx.unregister_subscriber(prev.sid)
                ob = _Observer(self, addr, bytes(req.token), clientid,
                               topic)
                ob.sid = self.ctx.register_subscriber(ob, clientid)
                self.ctx.subscribe(ob.sid, topic,
                                   {"qos": int(q.get("qos", 0))})
                self.observers[key] = ob
                self._reply(addr, req, CONTENT,
                            options=[(OPT_OBSERVE, _obs_bytes(1))])
            else:   # observe deregister
                ob = self.observers.pop(key, None)
                if ob is not None and ob.sid is not None:
                    self.ctx.unregister_subscriber(ob.sid)
                self._reply(addr, req, CONTENT)
        elif req.code == DELETE:
            self._cancel_all(addr, topic)
            self._reply(addr, req, DELETED)
        else:
            self._reply(addr, req, METHOD_NOT_ALLOWED)

    def _cancel_all(self, addr, topic: Optional[str] = None) -> None:
        for key, ob in list(self.observers.items()):
            if key[0] == addr and (topic is None or ob.topic == topic):
                if ob.sid is not None:
                    self.ctx.unregister_subscriber(ob.sid)
                del self.observers[key]
