"""OMA LwM2M object registry: object/resource definitions + name lookup.

Parity: apps/emqx_gateway/src/lwm2m/emqx_lwm2m_xml_object_db.erl +
emqx_lwm2m_xml_object.erl — the reference loads the OMA DDF XML files
shipped in lwm2m_xml/ into an ets registry and uses it to resolve paths
given by name ("/Device/0/Manufacturer" -> /3/0/0), look up resource
operations, and convert values by resource data type.

Here the core OMA objects (0-7) are compiled in (same definitions the
reference's XML files carry), and `load_xml` accepts OMA DDF XML for
custom objects — stdlib ElementTree, no xmerl analog needed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass(frozen=True)
class ResourceDef:
    rid: int
    name: str
    operations: str        # "R" / "W" / "RW" / "E"
    type: str              # String/Integer/Float/Boolean/Opaque/Time/Objlnk
    multiple: bool = False
    mandatory: bool = False


@dataclass
class ObjectDef:
    oid: int
    name: str
    urn: str = ""
    multiple: bool = False
    resources: dict[int, ResourceDef] = field(default_factory=dict)

    def resource_by_name(self, name: str) -> Optional[ResourceDef]:
        want = name.strip().lower()
        for r in self.resources.values():
            if r.name.lower() == want:
                return r
        return None


def _res(rid, name, ops, rtype, multiple=False, mandatory=False):
    return ResourceDef(rid, name, ops, rtype, multiple, mandatory)


def _obj(oid, name, urn, resources, multiple=False):
    return ObjectDef(oid, name, urn, multiple,
                     {r.rid: r for r in resources})


# Core object definitions per OMA LwM2M TS 1.0 Appendix E (the same set
# the reference ships as lwm2m_xml/*.xml).
_CORE = [
    _obj(0, "LWM2M Security", "urn:oma:lwm2m:oma:0", [
        _res(0, "LWM2M Server URI", "W", "String", mandatory=True),
        _res(1, "Bootstrap Server", "W", "Boolean", mandatory=True),
        _res(2, "Security Mode", "W", "Integer", mandatory=True),
        _res(3, "Public Key or Identity", "W", "Opaque", mandatory=True),
        _res(4, "Server Public Key", "W", "Opaque", mandatory=True),
        _res(5, "Secret Key", "W", "Opaque", mandatory=True),
        _res(6, "SMS Security Mode", "W", "Integer"),
        _res(7, "SMS Binding Key Parameters", "W", "Opaque"),
        _res(8, "SMS Binding Secret Key(s)", "W", "Opaque"),
        _res(9, "LWM2M Server SMS Number", "W", "String"),
        _res(10, "Short Server ID", "W", "Integer"),
        _res(11, "Client Hold Off Time", "W", "Integer"),
    ], multiple=True),
    _obj(1, "LWM2M Server", "urn:oma:lwm2m:oma:1", [
        _res(0, "Short Server ID", "R", "Integer", mandatory=True),
        _res(1, "Lifetime", "RW", "Integer", mandatory=True),
        _res(2, "Default Minimum Period", "RW", "Integer"),
        _res(3, "Default Maximum Period", "RW", "Integer"),
        _res(4, "Disable", "E", "Execute"),
        _res(5, "Disable Timeout", "RW", "Integer"),
        _res(6, "Notification Storing When Disabled or Offline", "RW",
             "Boolean", mandatory=True),
        _res(7, "Binding", "RW", "String", mandatory=True),
        _res(8, "Registration Update Trigger", "E", "Execute",
             mandatory=True),
    ], multiple=True),
    _obj(2, "LWM2M Access Control", "urn:oma:lwm2m:oma:2", [
        _res(0, "Object ID", "R", "Integer", mandatory=True),
        _res(1, "Object Instance ID", "R", "Integer", mandatory=True),
        _res(2, "ACL", "RW", "Integer", multiple=True),
        _res(3, "Access Control Owner", "RW", "Integer", mandatory=True),
    ], multiple=True),
    _obj(3, "Device", "urn:oma:lwm2m:oma:3", [
        _res(0, "Manufacturer", "R", "String"),
        _res(1, "Model Number", "R", "String"),
        _res(2, "Serial Number", "R", "String"),
        _res(3, "Firmware Version", "R", "String"),
        _res(4, "Reboot", "E", "Execute", mandatory=True),
        _res(5, "Factory Reset", "E", "Execute"),
        _res(6, "Available Power Sources", "R", "Integer", multiple=True),
        _res(7, "Power Source Voltage", "R", "Integer", multiple=True),
        _res(8, "Power Source Current", "R", "Integer", multiple=True),
        _res(9, "Battery Level", "R", "Integer"),
        _res(10, "Memory Free", "R", "Integer"),
        _res(11, "Error Code", "R", "Integer", multiple=True,
             mandatory=True),
        _res(12, "Reset Error Code", "E", "Execute"),
        _res(13, "Current Time", "RW", "Time"),
        _res(14, "UTC Offset", "RW", "String"),
        _res(15, "Timezone", "RW", "String"),
        _res(16, "Supported Binding and Modes", "R", "String",
             mandatory=True),
    ]),
    _obj(4, "Connectivity Monitoring", "urn:oma:lwm2m:oma:4", [
        _res(0, "Network Bearer", "R", "Integer", mandatory=True),
        _res(1, "Available Network Bearer", "R", "Integer", multiple=True,
             mandatory=True),
        _res(2, "Radio Signal Strength", "R", "Integer", mandatory=True),
        _res(3, "Link Quality", "R", "Integer"),
        _res(4, "IP Addresses", "R", "String", multiple=True,
             mandatory=True),
        _res(5, "Router IP Addresses", "R", "String", multiple=True),
        _res(6, "Link Utilization", "R", "Integer"),
        _res(7, "APN", "R", "String", multiple=True),
        _res(8, "Cell ID", "R", "Integer"),
        _res(9, "SMNC", "R", "Integer"),
        _res(10, "SMCC", "R", "Integer"),
    ]),
    _obj(5, "Firmware Update", "urn:oma:lwm2m:oma:5", [
        _res(0, "Package", "W", "Opaque", mandatory=True),
        _res(1, "Package URI", "W", "String", mandatory=True),
        _res(2, "Update", "E", "Execute", mandatory=True),
        _res(3, "State", "R", "Integer", mandatory=True),
        _res(4, "Update Supported Objects", "RW", "Boolean"),
        _res(5, "Update Result", "R", "Integer", mandatory=True),
    ]),
    _obj(6, "Location", "urn:oma:lwm2m:oma:6", [
        _res(0, "Latitude", "R", "String", mandatory=True),
        _res(1, "Longitude", "R", "String", mandatory=True),
        _res(2, "Altitude", "R", "String"),
        _res(3, "Uncertainty", "R", "String"),
        _res(4, "Velocity", "R", "Opaque"),
        _res(5, "Timestamp", "R", "Time", mandatory=True),
    ]),
    _obj(7, "Connectivity Statistics", "urn:oma:lwm2m:oma:7", [
        _res(0, "SMS Tx Counter", "R", "Integer"),
        _res(1, "SMS Rx Counter", "R", "Integer"),
        _res(2, "Tx Data", "R", "Integer"),
        _res(3, "Rx Data", "R", "Integer"),
        _res(4, "Max Message Size", "R", "Integer"),
        _res(5, "Average Message Size", "R", "Integer"),
        _res(6, "StartOrReset", "E", "Execute", mandatory=True),
    ]),
]


class ObjectRegistry:
    """Object-definition store with id and name lookup
    (emqx_lwm2m_xml_object_db.erl find_objectid/find_name)."""

    def __init__(self, objects: Optional[list[ObjectDef]] = None):
        self._by_id: dict[int, ObjectDef] = {}
        self._by_name: dict[str, ObjectDef] = {}
        for o in (objects if objects is not None else _CORE):
            self.add(o)

    @classmethod
    def core(cls) -> "ObjectRegistry":
        return cls()

    def add(self, obj: ObjectDef) -> None:
        self._by_id[obj.oid] = obj
        self._by_name[obj.name.lower()] = obj

    def object(self, oid: int) -> Optional[ObjectDef]:
        return self._by_id.get(oid)

    def object_by_name(self, name: str) -> Optional[ObjectDef]:
        return self._by_name.get(name.strip().lower())

    def resource(self, oid: int, rid: int) -> Optional[ResourceDef]:
        o = self._by_id.get(oid)
        return o.resources.get(rid) if o else None

    # ---- path resolution (emqx_lwm2m_cmd_handler path handling) ----
    def resolve_path(self, path: str) -> str:
        """Name segments -> numeric path: "/Device/0/Manufacturer" ->
        "/3/0/0". Numeric segments pass through; raises KeyError when a
        name is unknown."""
        segs = [s for s in str(path).split("/") if s != ""]
        if not segs:
            return "/"
        out: list[str] = []
        obj: Optional[ObjectDef] = None
        if segs[0].isdigit():
            obj = self.object(int(segs[0]))
            out.append(segs[0])
        else:
            obj = self.object_by_name(segs[0])
            if obj is None:
                raise KeyError(f"unknown LwM2M object {segs[0]!r}")
            out.append(str(obj.oid))
        if len(segs) > 1:
            out.append(segs[1])              # instance id is numeric
        if len(segs) > 2:
            if segs[2].isdigit():
                out.append(segs[2])
            else:
                if obj is None:
                    raise KeyError(f"unknown object for {path!r}")
                r = obj.resource_by_name(segs[2])
                if r is None:
                    raise KeyError(
                        f"unknown resource {segs[2]!r} of {obj.name}")
                out.append(str(r.rid))
        out.extend(segs[3:])
        return "/" + "/".join(out)

    def path_name(self, path: str) -> Optional[str]:
        """Numeric path -> "ObjectName/inst/ResourceName" (None when the
        object is unknown)."""
        segs = [s for s in str(path).split("/") if s != ""]
        if not segs or not segs[0].isdigit():
            return None
        obj = self.object(int(segs[0]))
        if obj is None:
            return None
        out = [obj.name]
        if len(segs) > 1:
            out.append(segs[1])
        if len(segs) > 2 and segs[2].isdigit():
            r = obj.resources.get(int(segs[2]))
            out.append(r.name if r else segs[2])
        return "/".join(out)

    def decode_value(self, oid: int, rid: int, raw: Any) -> Any:
        """Convert a text/TLV value by the resource's declared type."""
        r = self.resource(oid, rid)
        if r is None or raw is None:
            return raw
        data = raw
        try:
            if r.type == "Integer" or r.type == "Time":
                if isinstance(data, (bytes, bytearray)):
                    return int.from_bytes(bytes(data), "big",
                                          signed=True) if data else 0
                return int(data)
            if r.type == "Float":
                if isinstance(data, (bytes, bytearray)):
                    import struct as _s
                    if len(data) == 4:
                        return _s.unpack(">f", data)[0]
                    if len(data) == 8:
                        return _s.unpack(">d", data)[0]
                    return 0.0
                return float(data)
            if r.type == "Boolean":
                if isinstance(data, (bytes, bytearray)):
                    return bool(data and data[-1])
                return str(data) in ("1", "true", "True")
            if r.type == "String":
                if isinstance(data, (bytes, bytearray)):
                    return bytes(data).decode("utf-8", "replace")
                return str(data)
        except (ValueError, TypeError):
            return raw
        return raw

    # ---- OMA DDF XML (custom objects; emqx_lwm2m_xml_object_db load) ----
    def load_xml(self, source: str) -> ObjectDef:
        """Parse one OMA DDF XML document (file path or XML string) and
        register the object it defines."""
        import os
        import xml.etree.ElementTree as ET
        if os.path.isfile(source):
            root = ET.parse(source).getroot()
        else:
            root = ET.fromstring(source)
        onode = root.find("Object")
        if onode is None:
            raise ValueError("DDF XML has no <Object> element")
        oid = int(onode.findtext("ObjectID", "0"))
        name = onode.findtext("Name", f"Object{oid}")
        urn = onode.findtext("ObjectURN", "")
        multiple = (onode.findtext("MultipleInstances", "Single")
                    == "Multiple")
        resources = {}
        for item in onode.iter("Item"):
            rid = int(item.get("ID", "0"))
            rname = item.findtext("Name", str(rid))
            ops = item.findtext("Operations", "") or "E"
            rtype = item.findtext("Type", "String") or "String"
            rmult = item.findtext("MultipleInstances", "Single") \
                == "Multiple"
            rmand = item.findtext("Mandatory", "Optional") == "Mandatory"
            resources[rid] = ResourceDef(rid, rname, ops, rtype, rmult,
                                         rmand)
        obj = ObjectDef(oid, name, urn, multiple, resources)
        self.add(obj)
        return obj

    def load_xml_dir(self, dirpath: str) -> int:
        import glob
        import os
        n = 0
        for p in sorted(glob.glob(os.path.join(dirpath, "*.xml"))):
            try:
                self.load_xml(p)
                n += 1
            except (ValueError, OSError):
                continue
        return n
