"""Dashboard: admin users + token auth + overview endpoints.

Parity: apps/emqx_dashboard — admin user table with hashed passwords
(emqx_dashboard_admin.erl: add/remove/change_password/check, default
admin/public seeded at boot), login issuing a bearer token the HTTP layer
accepts, and the overview data the web UI renders (the reference fetches
the static asset bundle at build time — here the landing endpoint serves
the JSON the UI would consume).
"""

from __future__ import annotations

import hashlib
import os
import secrets
import time
from typing import Optional

TOKEN_TTL_S = 3600


def _hash(password: str, salt: bytes) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", password.encode(), salt, 10000)


class DashboardAdmin:
    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        c = dict(node.config.get("dashboard") or {})
        c.update(conf or {})
        self._users: dict[str, dict] = {}
        self._tokens: dict[str, tuple[str, float]] = {}  # tok -> (user, exp)
        self.add_user(c.get("default_username", "admin"),
                      c.get("default_password", "public"),
                      "administrator", replace=True)
        node.dashboard = self

    # ---- users (emqx_dashboard_admin) ----
    def add_user(self, username: str, password: str, desc: str = "",
                 replace: bool = False) -> None:
        if username in self._users and not replace:
            raise ValueError("user already exists")
        salt = os.urandom(16)
        self._users[username] = {"salt": salt,
                                 "hash": _hash(password, salt),
                                 "desc": desc,
                                 "created_at": int(time.time())}

    def remove_user(self, username: str) -> bool:
        if len(self._users) <= 1:
            raise ValueError("cannot remove the last admin")
        return self._users.pop(username, None) is not None

    def change_password(self, username: str, old: str, new: str) -> bool:
        if not self.check(username, old):
            return False
        self.add_user(username, new,
                      self._users[username]["desc"], replace=True)
        return True

    def check(self, username: str, password: str) -> bool:
        u = self._users.get(username)
        if u is None:
            return False
        return secrets.compare_digest(u["hash"],
                                      _hash(password, u["salt"]))

    def users(self) -> list[dict]:
        return [{"username": n, "description": u["desc"]}
                for n, u in self._users.items()]

    # ---- tokens ----
    def sign_token(self, username: str, password: str) -> Optional[str]:
        if not self.check(username, password):
            return None
        tok = secrets.token_urlsafe(32)
        self._tokens[tok] = (username, time.time() + TOKEN_TTL_S)
        return tok

    def verify_token(self, token: str) -> Optional[str]:
        ent = self._tokens.get(token)
        if ent is None:
            return None
        user, exp = ent
        if time.time() > exp:
            del self._tokens[token]
            return None
        return user

    def destroy_token(self, token: str) -> bool:
        return self._tokens.pop(token, None) is not None

    # ---- HTTP auth hook for mgmt HttpServer (basic or bearer) ----
    def auth_check(self, user: str, secret: str) -> bool:
        if user == "__bearer__":
            return self.verify_token(secret) is not None
        return self.check(user, secret)


def register_api(srv, node, admin: DashboardAdmin, mgmt=None) -> None:
    """Mount dashboard endpoints on a mgmt HttpServer."""
    from emqx_tpu.mgmt.httpd import ApiError

    # the web UI itself + login are reachable without credentials (the
    # page drives the token flow); everything else stays behind auth
    srv.auth_exempt = tuple(
        set(srv.auth_exempt) | {"/", "/dashboard", "/api/v5/login"})

    async def index(_req):
        return 200, (_ui_html(), "text/html; charset=utf-8")
    srv.route("GET", "/", index)
    srv.route("GET", "/dashboard", index)

    async def login(req):
        body = req.json() or {}
        tok = admin.sign_token(body.get("username", ""),
                               body.get("password", ""))
        if tok is None:
            raise ApiError(401, "BAD_USERNAME_OR_PWD")
        return {"token": tok, "license": {"edition": "opensource"},
                "version": _version()}
    srv.route("POST", "/api/v5/login", login)

    async def logout(req):
        hdr = req.headers.get("authorization", "")
        if hdr.lower().startswith("bearer "):
            admin.destroy_token(hdr[7:].strip())
        return 204, b""
    srv.route("POST", "/api/v5/logout", logout)

    async def users(_req):
        return admin.users()
    srv.route("GET", "/api/v5/users", users)

    async def add_user(req):
        body = req.json() or {}
        try:
            admin.add_user(body["username"], body["password"],
                           body.get("description", ""))
        except ValueError as e:
            raise ApiError(409, "ALREADY_EXISTS", str(e))
        return 201, {"username": body["username"]}
    srv.route("POST", "/api/v5/users", add_user)

    async def del_user(req):
        try:
            ok = admin.remove_user(req.params["username"])
        except ValueError as e:
            raise ApiError(400, "BAD_REQUEST", str(e))
        if not ok:
            raise ApiError(404, "NOT_FOUND")
        return 204, b""
    srv.route("DELETE", "/api/v5/users/:username", del_user)

    async def change_pwd(req):
        body = req.json() or {}
        if not admin.change_password(req.params["username"],
                                     body.get("old_pwd", ""),
                                     body.get("new_pwd", "")):
            raise ApiError(400, "BAD_USERNAME_OR_PWD")
        return 204, b""
    srv.route("PUT", "/api/v5/users/:username/change_pwd", change_pwd)

    async def overview(_req):
        stats = node.stats.sample()
        return {
            "node": node.name, "version": _version(),
            "uptime": int(time.monotonic()),
            "connections": stats.get("connections.count", 0),
            "topics": stats.get("topics.count", 0),
            "subscriptions": stats.get("subscriptions.count", 0),
            "retained": stats.get("retained.count", 0),
            "received": node.metrics.val("messages.received"),
            "sent": node.metrics.val("messages.sent"),
            # structured views the built-in UI renders
            "stats": stats,
            "metrics": node.metrics.all(),
        }
    srv.route("GET", "/api/v5/overview", overview)


def _version() -> str:
    from emqx_tpu.version import __version__
    return __version__


_UI_CACHE: Optional[bytes] = None


def _ui_html() -> bytes:
    """The single-file web UI (parity: the reference serves a prebuilt
    dashboard bundle, scripts/get-dashboard.sh + emqx_dashboard)."""
    global _UI_CACHE
    if _UI_CACHE is None:
        path = os.path.join(os.path.dirname(__file__), "assets",
                            "dashboard.html")
        with open(path, "rb") as f:
            _UI_CACHE = f.read()
    return _UI_CACHE
