"""Authorization (ACL) sources evaluated in order behind `client.authorize`.

Parity: apps/emqx_authz — sources checked in order; each returns allow /
deny / nomatch (emqx_authz.erl authorize/5); `no_match` config decides the
terminal default; per-client decision cache (emqx_authz_cache.erl).

Rule format (FileSource) mirrors the reference's acl rules
(emqx_authz_rule.erl): permit allow|deny; who all | {username} |
{clientid} | {ipaddr CIDR}; action publish|subscribe|all; topics are
filters supporting %c/%u placeholders and {"eq": t} literal matching.
"""

from __future__ import annotations

import ipaddress
import time
from collections import OrderedDict
from typing import Awaitable, Callable, Optional

from emqx_tpu.broker.hooks import HP_AUTHZ
from emqx_tpu.utils import topic as T

ALLOW, DENY, NOMATCH = "allow", "deny", "nomatch"


class Rule:
    def __init__(self, permit: str, who="all", action: str = "all",
                 topics: Optional[list] = None):
        if permit not in (ALLOW, DENY):
            raise ValueError(f"bad permit {permit!r}")
        if action not in ("publish", "subscribe", "all"):
            raise ValueError(f"bad action {action!r}")
        self.permit = permit
        self.who = who
        self.action = action
        self.topics = topics if topics is not None else ["#"]

    def _who_match(self, clientinfo: dict) -> bool:
        w = self.who
        if w == "all":
            return True
        if isinstance(w, dict):
            if "username" in w:
                return clientinfo.get("username") == w["username"]
            if "clientid" in w:
                return clientinfo.get("clientid") == w["clientid"]
            if "ipaddr" in w:
                peer = clientinfo.get("peername")
                if not peer:
                    return False
                try:
                    return ipaddress.ip_address(peer[0]) in \
                        ipaddress.ip_network(w["ipaddr"], strict=False)
                except ValueError:
                    return False
            if "and" in w:
                return all(Rule(self.permit, sub)._who_match(clientinfo)
                           for sub in w["and"])
            if "or" in w:
                return any(Rule(self.permit, sub)._who_match(clientinfo)
                           for sub in w["or"])
        return False

    def _topic_match(self, clientinfo: dict, topic: str) -> bool:
        for t in self.topics:
            if isinstance(t, dict) and "eq" in t:
                if topic == t["eq"]:
                    return True
                continue
            filt = (t.replace("%c", clientinfo.get("clientid") or "")
                     .replace("%u", clientinfo.get("username") or ""))
            if T.match(topic, filt):
                return True
        return False

    def check(self, clientinfo: dict, action: str, topic: str) -> str:
        if self.action not in (action, "all"):
            return NOMATCH
        if not self._who_match(clientinfo):
            return NOMATCH
        if not self._topic_match(clientinfo, topic):
            return NOMATCH
        return self.permit


class FileSource:
    """Ordered static rules (the reference's acl.conf file source)."""

    name = "file"

    def __init__(self, rules: list):
        self.rules = [r if isinstance(r, Rule) else Rule(
            r.get("permit", "allow"), r.get("who", "all"),
            r.get("action", "all"), r.get("topics")) for r in rules]

    def authorize(self, clientinfo: dict, action: str, topic: str) -> str:
        for r in self.rules:
            v = r.check(clientinfo, action, topic)
            if v != NOMATCH:
                return v
        return NOMATCH


class ClientAclSource:
    """Per-client ACL granted by the authenticator (JWT acl claim —
    emqx_authn_jwt acl_claim_name)."""

    name = "client_acl"

    def authorize(self, clientinfo: dict, action: str, topic: str) -> str:
        acl = clientinfo.get("acl")
        if not acl:
            return NOMATCH
        key = {"publish": "pub", "subscribe": "sub"}[action]
        for filt in list(acl.get(key, [])) + list(acl.get("all", [])):
            f = (filt.replace("%c", clientinfo.get("clientid") or "")
                     .replace("%u", clientinfo.get("username") or ""))
            if T.match(topic, f):
                return ALLOW
        return DENY      # acl present but no grant → deny (reference)


class HTTPSource:
    """External HTTP ACL service (emqx_authz_http.erl)."""

    name = "http"

    def __init__(self, url: str, method: str = "post",
                 body: Optional[dict] = None,
                 headers: Optional[dict] = None, timeout: float = 5.0,
                 transport: Optional[Callable[..., Awaitable]] = None):
        self.url = url
        self.method = method
        self.body = body or {"username": "%u", "clientid": "%c",
                             "action": "%A", "topic": "%t"}
        self.headers = headers or {}
        self.timeout = timeout
        self._transport = transport

    async def authorize_async(self, clientinfo: dict, action: str,
                              topic: str) -> str:
        from emqx_tpu.utils.http import templated_request
        peer = clientinfo.get("peername")
        subs = {"%u": clientinfo.get("username") or "",
                "%c": clientinfo.get("clientid") or "",
                "%A": action, "%t": topic,
                "%a": str(peer[0]) if peer else ""}
        try:
            resp = await templated_request(
                self.method, self.url, self.body, subs,
                headers=self.headers, timeout=self.timeout,
                transport=self._transport)
        except Exception:
            return NOMATCH
        if resp.status == 204:
            return ALLOW
        if resp.status != 200:
            return NOMATCH
        try:
            result = resp.json().get("result", "allow")
        except Exception:
            return ALLOW
        return {"allow": ALLOW, "deny": DENY}.get(result, NOMATCH)


class AuthzCache:
    """Per-client (action, topic) → decision LRU with TTL
    (emqx_authz_cache.erl / the authz_cache zone config)."""

    def __init__(self, max_size: int = 32, ttl: float = 60.0):
        self.max_size = max_size
        self.ttl = ttl
        self._c: "OrderedDict[tuple, tuple[str, float]]" = OrderedDict()

    def get(self, key: tuple) -> Optional[str]:
        ent = self._c.get(key)
        if ent is None:
            return None
        verdict, ts = ent
        if time.monotonic() - ts > self.ttl:
            del self._c[key]
            return None
        self._c.move_to_end(key)
        return verdict

    def put(self, key: tuple, verdict: str) -> None:
        if key in self._c:
            self._c.move_to_end(key)
        self._c[key] = (verdict, time.monotonic())
        while len(self._c) > self.max_size:
            self._c.popitem(last=False)

    def drain(self) -> None:
        self._c.clear()


class Authz:
    """The `client.authorize` hook: folds sources in order."""

    def __init__(self, node, sources: Optional[list] = None,
                 no_match: Optional[str] = None,
                 cache_enable: bool = True):
        self.node = node
        conf = node.config.get("authz") or {}
        self.no_match = no_match or conf.get("no_match", "allow")
        self.sources = list(sources or [])
        self.cache_enable = cache_enable
        self._caches: dict[str, AuthzCache] = {}

    def load(self) -> "Authz":
        self.node.hooks.add("client.authorize", self.on_authorize,
                            priority=HP_AUTHZ, tag="authz")
        # drain the per-client cache when its channel goes away, else the
        # cache dict grows one entry per clientid ever seen
        self.node.hooks.add("client.disconnected", self._on_disconnected,
                            tag="authz")
        return self

    def unload(self) -> None:
        self.node.hooks.delete("client.authorize", "authz")
        self.node.hooks.delete("client.disconnected", "authz")

    def _on_disconnected(self, clientinfo: dict, reason) -> None:
        self.drop_cache(clientinfo.get("clientid", ""))

    def add_source(self, s, front: bool = False) -> None:
        if front:
            self.sources.insert(0, s)
        else:
            self.sources.append(s)

    def _cache(self, clientid: str) -> AuthzCache:
        c = self._caches.get(clientid)
        if c is None:
            c = self._caches[clientid] = AuthzCache()
        return c

    def drop_cache(self, clientid: str) -> None:
        self._caches.pop(clientid, None)

    async def on_authorize(self, clientinfo: dict, action: str, topic: str,
                           acc):
        if not self.sources:
            return ("ok", acc)
        cid = clientinfo.get("clientid", "")
        cache = self._cache(cid) if self.cache_enable else None
        if cache is not None:
            hit = cache.get((action, topic))
            if hit is not None:
                self.node.metrics.inc("client.authorize.cache_hit")
                return ("stop", hit)
        verdict = self.no_match
        for s in self.sources:
            if hasattr(s, "authorize_async"):
                v = await s.authorize_async(clientinfo, action, topic)
            else:
                v = s.authorize(clientinfo, action, topic)
            if v != NOMATCH:
                verdict = v
                break
        if cache is not None:
            cache.put((action, topic), verdict)
        return ("stop", verdict)
