"""Authentication chain: ordered authenticators behind `client.authenticate`.

Parity: apps/emqx_authn — a per-node chain of authenticators evaluated in
order (emqx_authn.erl authenticate/2): each returns `ok` (accept, possibly
with is_superuser/mountpoint), `deny`, or `ignore` (fall through). If the
chain is enabled and every authenticator ignores, the client is denied
(the reference's terminal `{error, not_authorized}`).

Authenticators:
- `BuiltinDB`  — username/clientid + hashed password store
  (simple_authn/emqx_authn_mnesia.erl)
- `JWTAuthenticator` — HS256/384/512 JWT in the password field with claim
  checks (emqx_authn_jwt.erl)
- `HTTPAuthenticator` — POST/GET to an external service
  (emqx_authn_http.erl); async transport is injectable for tests
"""

from __future__ import annotations

import base64
import hashlib
import hmac as _hmac
import json
import time
from typing import Awaitable, Callable, Optional

from emqx_tpu.broker.hooks import HP_AUTHN
from emqx_tpu.mqtt import constants as C
from emqx_tpu.utils import passwd as PW

OK, IGNORE, DENY = "ok", "ignore", "deny"


class BuiltinDB:
    """Username(or clientid)/password store with per-user salt.

    Parity: emqx_authn_mnesia.erl — user_id_type username|clientid,
    password_hash_algorithm, add/delete/update/lookup user API.
    """

    name = "password_based:built_in_database"

    def __init__(self, user_id_type: str = "username",
                 algorithm: str = "sha256",
                 salt_position: str = "prefix"):
        self.user_id_type = user_id_type
        self.algorithm = algorithm
        self.salt_position = salt_position
        self._users: dict[str, dict] = {}

    # ---- user management (emqx_authn_mnesia add_user/...) ----
    def add_user(self, user_id: str, password: str,
                 is_superuser: bool = False) -> None:
        salt = "" if self.algorithm == "plain" else PW.gen_salt()
        self._users[user_id] = {
            "password_hash": PW.hash_password(
                self.algorithm, password.encode(), salt, self.salt_position),
            "salt": salt, "is_superuser": is_superuser}

    def delete_user(self, user_id: str) -> bool:
        return self._users.pop(user_id, None) is not None

    def lookup_user(self, user_id: str) -> Optional[dict]:
        u = self._users.get(user_id)
        return dict(u, user_id=user_id) if u else None

    def list_users(self) -> list[str]:
        return list(self._users)

    def update_user(self, user_id: str, password: Optional[str] = None,
                    is_superuser: Optional[bool] = None) -> bool:
        if user_id not in self._users:
            return False
        if password is not None:
            self.add_user(user_id, password,
                          self._users[user_id]["is_superuser"])
        if is_superuser is not None:
            self._users[user_id]["is_superuser"] = is_superuser
        return True

    # ---- chain interface ----
    def authenticate(self, clientinfo: dict, password: Optional[bytes]):
        uid = (clientinfo.get("username") if self.user_id_type == "username"
               else clientinfo.get("clientid"))
        if not uid:
            return IGNORE, {}
        u = self._users.get(uid)
        if u is None:
            return IGNORE, {}
        if PW.check_password(self.algorithm, u["password_hash"], password,
                             u["salt"], self.salt_position):
            return OK, {"is_superuser": u["is_superuser"]}
        return DENY, {}


def _b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


class JWTAuthenticator:
    """HMAC-signed JWT carried in the MQTT password field.

    Parity: emqx_authn_jwt.erl — algorithm hmac-based, `verify_claims`
    pairs where the expected value supports %u (username) and %c
    (clientid) substitution; exp/nbf always enforced.
    """

    name = "jwt"
    _ALGOS = {"HS256": hashlib.sha256, "HS384": hashlib.sha384,
              "HS512": hashlib.sha512}

    def __init__(self, secret: str, algorithm: str = "HS256",
                 verify_claims: Optional[dict] = None,
                 acl_claim_name: str = "acl"):
        if algorithm not in self._ALGOS:
            raise ValueError(f"unsupported jwt algorithm {algorithm}")
        self.secret = secret.encode()
        self.algorithm = algorithm
        self.verify_claims = dict(verify_claims or {})
        self.acl_claim_name = acl_claim_name

    def _verify(self, token: str) -> Optional[dict]:
        try:
            head_s, payload_s, sig_s = token.split(".")
            header = json.loads(_b64url_decode(head_s))
            if header.get("alg") != self.algorithm:
                return None
            digest = self._ALGOS[self.algorithm]
            expect = _hmac.new(self.secret,
                               f"{head_s}.{payload_s}".encode(),
                               digest).digest()
            if not _hmac.compare_digest(expect, _b64url_decode(sig_s)):
                return None
            claims = json.loads(_b64url_decode(payload_s))
            # a validly-signed scalar/array payload is still not a claims
            # object — treat as unusable, not as a crash
            return claims if isinstance(claims, dict) else None
        except Exception:
            return None

    def authenticate(self, clientinfo: dict, password: Optional[bytes]):
        if not password:
            return IGNORE, {}
        claims = self._verify(password.decode("utf-8", "replace"))
        if claims is None:
            return IGNORE, {}
        now = time.time()
        if "exp" in claims and now >= float(claims["exp"]):
            return DENY, {}
        if "nbf" in claims and now < float(claims["nbf"]):
            return DENY, {}
        for name, expected in self.verify_claims.items():
            want = (str(expected)
                    .replace("%u", clientinfo.get("username") or "")
                    .replace("%c", clientinfo.get("clientid") or ""))
            if str(claims.get(name)) != want:
                return DENY, {}
        extra = {"is_superuser": bool(claims.get("is_superuser", False))}
        if self.acl_claim_name in claims:
            extra["acl"] = claims[self.acl_claim_name]
        return OK, extra


class HTTPAuthenticator:
    """External HTTP service decides; body carries %-substituted params.

    Parity: emqx_authn_http.erl — result read from the response JSON
    `result` field (allow/deny/ignore) or the HTTP status (200 allow,
    204 allow, 4xx ignore).
    """

    name = "password_based:http"

    def __init__(self, url: str, method: str = "post",
                 body: Optional[dict] = None,
                 headers: Optional[dict] = None,
                 timeout: float = 5.0,
                 transport: Optional[Callable[..., Awaitable]] = None):
        self.url = url
        self.method = method
        self.body = body or {"username": "%u", "clientid": "%c",
                             "password": "%P"}
        self.headers = headers or {}
        self.timeout = timeout
        self._transport = transport

    async def authenticate_async(self, clientinfo: dict,
                                 password: Optional[bytes]):
        from emqx_tpu.utils.http import templated_request
        peer = clientinfo.get("peername")
        subs = {"%u": clientinfo.get("username") or "",
                "%c": clientinfo.get("clientid") or "",
                "%P": (password or b"").decode("utf-8", "replace"),
                "%a": str(peer[0]) if peer else "",
                "%p": str(peer[1]) if peer else ""}
        try:
            resp = await templated_request(
                self.method, self.url, self.body, subs,
                headers=self.headers, timeout=self.timeout,
                transport=self._transport)
        except Exception:
            return IGNORE, {}
        if resp.status == 204:
            return OK, {}
        if resp.status != 200:
            return IGNORE, {}
        try:
            data = resp.json()
        except Exception:
            return OK, {}
        result = data.get("result", "allow")
        if result in ("allow", "ok"):
            extra = {"is_superuser": bool(data.get("is_superuser", False))}
            return OK, extra
        if result == "ignore":
            return IGNORE, {}
        return DENY, {}

    def authenticate(self, clientinfo: dict, password: Optional[bytes]):
        # sync path (hook context): HTTP authn needs the async pipeline;
        # the chain calls authenticate_async when available
        return IGNORE, {}


class AuthnChain:
    """The `client.authenticate` hook: folds authenticators in order."""

    def __init__(self, node, authenticators: Optional[list] = None,
                 enable: Optional[bool] = None):
        self.node = node
        conf = node.config.get("authn") or {}
        self.enable = conf.get("enable", False) if enable is None else enable
        self.authenticators = list(authenticators or [])

    def load(self) -> "AuthnChain":
        self.node.hooks.add("client.authenticate", self.on_authenticate,
                            priority=HP_AUTHN, tag="authn")
        for a in self.authenticators:
            self._register_enhanced(a)
        return self

    def unload(self) -> None:
        self.node.hooks.delete("client.authenticate", "authn")
        for a in self.authenticators:
            if getattr(a, "mechanism", None):
                getattr(self.node, "enhanced_authn", {}) \
                    .pop(a.mechanism, None)

    def _register_enhanced(self, a) -> None:
        """Authenticators with a `mechanism` (SCRAM) also serve the MQTT5
        AUTH-packet exchange; the channel finds them by method name."""
        mech = getattr(a, "mechanism", None)
        if mech:
            if not hasattr(self.node, "enhanced_authn"):
                self.node.enhanced_authn = {}
            self.node.enhanced_authn[mech] = a

    def add_authenticator(self, a) -> None:
        self.authenticators.append(a)
        self._register_enhanced(a)

    def remove_authenticator(self, name: str) -> bool:
        n = len(self.authenticators)
        removed = [a for a in self.authenticators if a.name == name]
        self.authenticators = [a for a in self.authenticators
                               if a.name != name]
        for a in removed:       # also stop serving its AUTH exchanges
            if getattr(a, "mechanism", None):
                getattr(self.node, "enhanced_authn", {}) \
                    .pop(a.mechanism, None)
        return len(self.authenticators) < n

    async def on_authenticate(self, clientinfo: dict, acc):
        if not self.enable or not self.authenticators:
            return ("ok", acc)
        password = (acc or {}).get("password")
        for a in self.authenticators:
            if hasattr(a, "authenticate_async"):
                verdict, extra = await a.authenticate_async(clientinfo,
                                                            password)
            else:
                verdict, extra = a.authenticate(clientinfo, password)
            if verdict == OK:
                self.node.metrics.inc("client.auth.success")
                return ("stop", dict({"ok": True}, **extra))
            if verdict == DENY:
                self.node.metrics.inc("client.auth.failure")
                return ("stop", {"ok": False,
                                 "rc": C.RC_BAD_USER_NAME_OR_PASSWORD})
        self.node.metrics.inc("client.auth.failure")
        return ("stop", {"ok": False, "rc": C.RC_NOT_AUTHORIZED})
