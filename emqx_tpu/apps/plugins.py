"""Plugin system: load/unload external feature modules.

Parity: emqx_plugins.erl — app-based plugins loaded at boot from a config
list (`plugins.load/0` emqx_plugins.erl:44-47), load/unload at runtime,
state listed by CLI/API. A plugin is a Python module (import path) exposing
`load(node, conf) -> instance` and the instance exposing `unload()` — the
shape of the reference's plugin-template application callbacks
(lib-extra/emqx_plugin_template).
"""

from __future__ import annotations

import importlib
import logging
from typing import Any, Optional

log = logging.getLogger("emqx_tpu.plugins")


class Plugins:
    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        c = dict(node.config.get("plugins") or {})
        c.update(conf or {})
        # [{"name": ..., "module": "pkg.mod", "config": {...},
        #   "enabled": true}]
        self.declared = list(c.get("load", []))
        self._loaded: dict[str, Any] = {}
        node.plugins = self

    def load_all(self) -> int:
        """Boot-time load of every enabled declared plugin."""
        n = 0
        for decl in self.declared:
            if decl.get("enabled", True):
                try:
                    self.load(decl["name"], decl["module"],
                              decl.get("config"))
                    n += 1
                except Exception:  # noqa: BLE001 — one bad plugin never
                    log.exception("plugin %s failed to load",
                                  decl.get("name"))   # blocks the boot
        return n

    def load(self, name: str, module_path: str,
             conf: Optional[dict] = None) -> Any:
        if name in self._loaded:
            raise ValueError(f"plugin {name} already loaded")
        mod = importlib.import_module(module_path)
        if not hasattr(mod, "load"):
            raise ValueError(f"{module_path} has no load(node, conf)")
        inst = mod.load(self.node, conf or {})
        self._loaded[name] = inst
        log.info("plugin %s loaded from %s", name, module_path)
        return inst

    def unload(self, name: str) -> bool:
        inst = self._loaded.pop(name, None)
        if inst is None:
            return False
        unload = getattr(inst, "unload", None)
        if unload is not None:
            try:
                unload()
            except Exception:  # noqa: BLE001
                log.exception("plugin %s unload failed", name)
        return True

    def unload_all(self) -> None:
        for name in list(self._loaded):
            self.unload(name)

    def is_loaded(self, name: str) -> bool:
        return name in self._loaded

    def list(self) -> list[dict]:
        out = []
        seen = set()
        for decl in self.declared:
            name = decl["name"]
            seen.add(name)
            out.append({"name": name, "module": decl["module"],
                        "enabled": name in self._loaded})
        for name in self._loaded:
            if name not in seen:
                out.append({"name": name, "module": "?",
                            "enabled": True})
        return out
