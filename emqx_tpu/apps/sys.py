"""$SYS broker: periodic heartbeat/stats/metrics publishes + alarm topics.

Parity: apps/emqx/src/emqx_sys.erl — `$SYS/brokers` node list,
`$SYS/brokers/<node>/{version,uptime,datetime,sysdescr}` heartbeats
(emqx_sys.erl:56-67,83-91), `$SYS/brokers/<node>/stats/<name>` and
`.../metrics/<name>` interval publishes; alarm transitions republished on
`$SYS/brokers/<node>/alarms/{activate,deactivate}` (emqx_alarm handler).
"""

from __future__ import annotations

import json
import time
from typing import Optional

from emqx_tpu.broker.message import make
from emqx_tpu.version import __version__


class SysBroker:
    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        c = dict(node.config.get("broker") or {})
        c.update(conf or {})
        self.heartbeat_interval = float(c.get("sys_heartbeat_interval", 30))
        self.msg_interval = float(c.get("sys_msg_interval", 60))
        self.started_at = time.monotonic()
        self._last_heartbeat = 0.0
        self._last_msg = 0.0

    def load(self) -> "SysBroker":
        self.node.hooks.add("alarm.activated", self.on_alarm_activated,
                            tag="sys")
        self.node.hooks.add("alarm.deactivated", self.on_alarm_deactivated,
                            tag="sys")
        return self

    def unload(self) -> None:
        self.node.hooks.delete("alarm.activated", "sys")
        self.node.hooks.delete("alarm.deactivated", "sys")

    # ---- publishing ----
    def _pub(self, suffix: str, payload: bytes) -> None:
        self.node.broker.publish(make(
            "", 0, f"$SYS/brokers/{self.node.name}/{suffix}", payload,
            flags={"sys": True}))

    def uptime(self) -> float:
        return time.monotonic() - self.started_at

    def publish_heartbeat(self) -> None:
        self.node.broker.publish(make(
            "", 0, "$SYS/brokers", self.node.name.encode(),
            flags={"sys": True}))
        self._pub("version", __version__.encode())
        self._pub("uptime", str(int(self.uptime())).encode())
        self._pub("datetime",
                  time.strftime("%Y-%m-%d %H:%M:%S").encode())
        self._pub("sysdescr", b"emqx_tpu broker")

    def publish_stats_metrics(self) -> None:
        for name, val in self.node.stats.sample().items():
            self._pub(f"stats/{name}", str(val).encode())
        for name, val in self.node.metrics.all().items():
            self._pub(f"metrics/{name}", str(val).encode())
        self.publish_pipeline()

    def publish_pipeline(self) -> None:
        """$SYS/brokers/<node>/pipeline/# — the device-path telemetry
        snapshot, piecewise: one JSON payload per stage
        (`pipeline/stages/<stage>`), per occupancy class
        (`pipeline/occupancy/<class>`), plus `pipeline/compiles`,
        `pipeline/decisions` and — when the relevant layer has traffic —
        `pipeline/match_cache` / `pipeline/dedup` / `pipeline/readback`
        (dense-vs-compact device→host transfer bytes, ISSUE 3) /
        `pipeline/rebuild` / `pipeline/deliver` (delivery-lane egress
        stage, ISSUE 5) / `pipeline/supervise` (fault-domain
        supervision: breaker states, ladder rung, ISSUE 6) /
        `pipeline/trace` (window-causal flight recorder: ring state +
        dispatch↔materialize overlap + bubble attribution, ISSUE 7) /
        `pipeline/ingress` (columnar PUBLISH ingress: burst sizes,
        columnar-vs-fallback frames, per-acceptor-lane accepts,
        ISSUE 11) /
        `pipeline/memory` (HBM ledger: per-category device bytes, pin
        ages, backend memory_stats cross-check, ISSUE 8) /
        `pipeline/program_costs` (jit-program cost registry: compile
        wall per class, flops/bytes where analyzed, ISSUE 8) /
        `pipeline/latency` (end-to-end latency SLO observatory:
        per-(qos, path) ingress→routed / ingress→delivered
        percentiles, SLO burn rates, breach exemplars, ISSUE 13) /
        `pipeline/overload` (adaptive overload governor: grade, armed
        shed actions, signal readings, shed counters, ISSUE 14)."""
        tele = getattr(self.node, "pipeline_telemetry", None)
        if tele is None:
            return
        snap = tele.snapshot()
        for stage, row in snap["stages"].items():
            self._pub(f"pipeline/stages/{stage}",
                      json.dumps(row).encode())
        for cls, row in snap["occupancy"].items():
            self._pub(f"pipeline/occupancy/{cls}",
                      json.dumps(row).encode())
        self._pub("pipeline/compiles",
                  json.dumps(snap["compiles"]).encode())
        self._pub("pipeline/decisions",
                  json.dumps(snap["decisions"]).encode())
        for section in ("match_cache", "dedup", "readback", "rebuild",
                        "deliver", "supervise", "trace", "ingress",
                        "memory", "program_costs", "latency",
                        "overload"):
            if section in snap:
                self._pub(f"pipeline/{section}",
                          json.dumps(snap[section]).encode())

    # ---- alarms → $SYS ----
    def on_alarm_activated(self, alarm: dict) -> None:
        self._pub("alarms/activate", json.dumps(alarm).encode())

    def on_alarm_deactivated(self, alarm: dict) -> None:
        self._pub("alarms/deactivate", json.dumps(alarm).encode())

    # ---- timer (Node.sweep) ----
    def tick(self) -> None:
        now = time.monotonic()
        if now - self._last_heartbeat >= self.heartbeat_interval:
            self._last_heartbeat = now
            self.publish_heartbeat()
        if now - self._last_msg >= self.msg_interval:
            self._last_msg = now
            self.publish_stats_metrics()

    def info(self) -> dict:
        """emqx_mgmt broker info surface."""
        return {"node": self.node.name, "version": __version__,
                "uptime": int(self.uptime()),
                "datetime": time.strftime("%Y-%m-%d %H:%M:%S"),
                "sysdescr": "emqx_tpu broker"}
