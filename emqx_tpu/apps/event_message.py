"""Client/session lifecycle events republished as `$event/...` messages.

Parity: apps/emqx_modules/src/emqx_event_message.erl — hook callbacks build
JSON payloads and publish them to `$event/client_connected`,
`$event/client_disconnected`, `$event/session_subscribed`,
`$event/session_unsubscribed`, `$event/message_delivered`,
`$event/message_acked`, `$event/message_dropped`, each individually
config-gated.
"""

from __future__ import annotations

import json
from typing import Optional

from emqx_tpu.broker.message import Message, base62_encode, make, now_ms

EVENTS = ("client_connected", "client_disconnected", "session_subscribed",
          "session_unsubscribed", "message_delivered", "message_acked",
          "message_dropped")


def _payload(d: dict) -> bytes:
    return json.dumps(d, default=repr).encode()


class EventMessage:
    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        c = dict(node.config.get("event_message") or {})
        c.update(conf or {})
        self.enabled = {e for e in EVENTS if c.get(e, False)}

    def load(self) -> "EventMessage":
        h = self.node.hooks
        if "client_connected" in self.enabled:
            h.add("client.connected", self.on_client_connected, tag="event_msg")
        if "client_disconnected" in self.enabled:
            h.add("client.disconnected", self.on_client_disconnected,
                  tag="event_msg")
        if "session_subscribed" in self.enabled:
            h.add("session.subscribed", self.on_session_subscribed,
                  tag="event_msg")
        if "session_unsubscribed" in self.enabled:
            h.add("session.unsubscribed", self.on_session_unsubscribed,
                  tag="event_msg")
        if "message_delivered" in self.enabled:
            h.add("message.delivered", self.on_message_delivered,
                  tag="event_msg")
        if "message_acked" in self.enabled:
            h.add("message.acked", self.on_message_acked, tag="event_msg")
        if "message_dropped" in self.enabled:
            h.add("message.dropped", self.on_message_dropped, tag="event_msg")
        return self

    def unload(self) -> None:
        for h in ("client.connected", "client.disconnected",
                  "session.subscribed", "session.unsubscribed",
                  "message.delivered", "message.acked", "message.dropped"):
            self.node.hooks.delete(h, "event_msg")

    def _publish(self, event: str, payload: dict) -> None:
        msg = make("", 0, f"$event/{event}", _payload(payload),
                   flags={"sys": True})
        self.node.broker.publish(msg)

    @staticmethod
    def _skip(topic: str) -> bool:
        return topic.startswith("$event/") or topic.startswith("$SYS/")

    # ---- hooks ----
    def on_client_connected(self, clientinfo: dict, conninfo: dict):
        self._publish("client_connected", {
            "clientid": clientinfo.get("clientid"),
            "username": clientinfo.get("username"),
            "keepalive": clientinfo.get("keepalive"),
            "proto_ver": clientinfo.get("proto_ver"),
            "clean_start": clientinfo.get("clean_start"),
            "connected_at": clientinfo.get("connected_at"),
            "ts": now_ms()})

    def on_client_disconnected(self, clientinfo: dict, reason):
        self._publish("client_disconnected", {
            "clientid": clientinfo.get("clientid"),
            "username": clientinfo.get("username"),
            "reason": str(reason), "disconnected_at": now_ms(),
            "ts": now_ms()})

    def on_session_subscribed(self, clientinfo: dict, topic: str,
                              subopts: dict):
        if self._skip(topic):
            return
        self._publish("session_subscribed", {
            "clientid": clientinfo.get("clientid"),
            "username": clientinfo.get("username"),
            "topic": topic, "subopts": {k: v for k, v in subopts.items()
                                        if k != "is_new"},
            "ts": now_ms()})

    def on_session_unsubscribed(self, clientinfo: dict, topic: str):
        if self._skip(topic):
            return
        self._publish("session_unsubscribed", {
            "clientid": clientinfo.get("clientid"),
            "username": clientinfo.get("username"),
            "topic": topic, "ts": now_ms()})

    def on_message_delivered(self, clientid, msg: Message):
        if self._skip(msg.topic):
            return
        self._publish("message_delivered", self._msg_map(msg,
                                                         clientid=clientid))

    def on_message_acked(self, clientinfo, msg: Message):
        if self._skip(msg.topic):
            return
        cid = clientinfo.get("clientid") if isinstance(clientinfo, dict) \
            else clientinfo
        self._publish("message_acked", self._msg_map(msg, clientid=cid))

    def on_message_dropped(self, msg: Optional[Message], reason=None):
        if msg is None or self._skip(msg.topic):
            return
        self._publish("message_dropped",
                      self._msg_map(msg, reason=str(reason)))

    @staticmethod
    def _msg_map(msg: Message, **extra) -> dict:
        d = {"id": base62_encode(msg.id), "from": msg.from_,
             "topic": msg.topic, "qos": msg.qos, "retain": msg.retain,
             "payload": msg.payload.decode("utf-8", "replace"),
             "publish_received_at": msg.ts, "ts": now_ms()}
        d.update(extra)
        return d
