"""Database-backed authorization (ACL) sources.

Parity: apps/emqx_authz/src/emqx_authz_{mysql,pgsql,redis,mongo}.erl —
each source queries rule rows for the requesting client and folds them
through the same rule matcher as the file source; `nomatch` on empty
results or query errors so evaluation falls through to the next source.

Row shapes (the reference's):
- SQL:   columns (permission, action, topic) per row, params %u/%c/%a
- Redis: a flat [topic, action, topic, action, ...] reply (HGETALL) with
         permission implied allow
- Mongo: documents {topics: [...], permission, action}
"""

from __future__ import annotations

import re
from typing import Optional

from emqx_tpu.apps.authz import ALLOW, DENY, NOMATCH, Rule

_SQL_VAR_RE = re.compile(r"'(%[uca])'")


def _sql_params(query: str, clientinfo: dict) -> Optional[tuple[str, list]]:
    """Replace quoted '%u'/'%c'/'%a' markers with ? params, one param per
    occurrence in order (emqx_authz_mysql replvar over the param list)."""
    params: list = []
    for m in _SQL_VAR_RE.finditer(query):
        v = m.group(1)
        if v == "%u":
            val = clientinfo.get("username")
        elif v == "%c":
            val = clientinfo.get("clientid")
        else:
            peer = clientinfo.get("peername")
            val = str(peer[0]) if peer else None
        if val is None:
            return None
        params.append(val)
    return _SQL_VAR_RE.sub("?", query), params


def _match_row(clientinfo: dict, action: str, topic: str,
               permission: str, row_action: str, topics: list) -> str:
    try:
        rule = Rule(permission or ALLOW, "all", row_action or "all", topics)
    except ValueError:
        return NOMATCH
    return rule.check(clientinfo, action, topic)


class _SqlSource:
    style = "mysql"

    def __init__(self, resource, query: str, timeout: float = 5.0):
        self.resource = resource
        self.query = query
        self.timeout = timeout

    async def authorize_async(self, clientinfo: dict, action: str,
                              topic: str) -> str:
        prepared = _sql_params(self.query, clientinfo)
        if prepared is None:
            return NOMATCH
        sql, params = prepared
        if self.style == "pgsql":
            for i in range(len(params)):
                sql = sql.replace("?", f"${i + 1}", 1)
        try:
            columns, rows = await self.resource.query(("sql", sql, params))
        except Exception:  # noqa: BLE001
            return NOMATCH
        for row in rows:
            r = dict(zip(columns, row))
            v = _match_row(clientinfo, action, topic,
                           str(r.get("permission") or ALLOW),
                           str(r.get("action") or "all"),
                           [str(r.get("topic") or "#")])
            if v != NOMATCH:
                return v
        return NOMATCH


class MysqlSource(_SqlSource):
    name = "mysql"
    style = "mysql"


class PgsqlSource(_SqlSource):
    name = "pgsql"
    style = "pgsql"


class RedisSource:
    """cmd like "HGETALL mqtt_acl:%u"; reply pairs topic -> action
    (emqx_authz_redis do_authorize: rows are [TopicFilter, Action | ...],
    permission allow)."""

    name = "redis"

    def __init__(self, resource, cmd: str, timeout: float = 5.0):
        self.resource = resource
        self.cmd = cmd
        self.timeout = timeout

    async def authorize_async(self, clientinfo: dict, action: str,
                              topic: str) -> str:
        peer = clientinfo.get("peername")
        # split FIRST, substitute per token: a username containing spaces
        # must not change the command arity (argument injection)
        args = [t.replace("%u", clientinfo.get("username") or "")
                 .replace("%c", clientinfo.get("clientid") or "")
                 .replace("%a", str(peer[0]) if peer else "")
                for t in self.cmd.split(" ")]
        try:
            reply = await self.resource.query(args)
        except Exception:  # noqa: BLE001
            return NOMATCH
        if not reply:
            return NOMATCH
        flat = [x.decode("utf-8", "replace") if isinstance(x, bytes)
                else str(x) for x in reply]
        for filt, act in zip(flat[0::2], flat[1::2]):
            v = _match_row(clientinfo, action, topic, ALLOW, act, [filt])
            if v != NOMATCH:
                return v
        return NOMATCH


class MongoSource:
    """Documents {topics, permission, action} selected per client
    (emqx_authz_mongo.erl)."""

    name = "mongo"

    def __init__(self, resource, collection: str = "mqtt_acl",
                 selector: Optional[dict] = None, timeout: float = 5.0):
        self.resource = resource
        self.collection = collection
        self.selector = selector or {"username": "%u"}
        self.timeout = timeout

    async def authorize_async(self, clientinfo: dict, action: str,
                              topic: str) -> str:
        peer = clientinfo.get("peername")
        sel = {}
        for k, v in self.selector.items():
            if isinstance(v, str):
                v = (v.replace("%u", clientinfo.get("username") or "")
                      .replace("%c", clientinfo.get("clientid") or "")
                      .replace("%a", str(peer[0]) if peer else ""))
            sel[k] = v
        try:
            docs = await self.resource.query(("find", self.collection, sel))
        except Exception:  # noqa: BLE001
            return NOMATCH
        for doc in docs:
            topics = doc.get("topics") or [doc.get("topic") or "#"]
            v = _match_row(clientinfo, action, topic,
                           str(doc.get("permission") or ALLOW),
                           str(doc.get("action") or "all"), list(topics))
            if v != NOMATCH:
                return v
        return NOMATCH


__all__ = ["MysqlSource", "PgsqlSource", "RedisSource", "MongoSource",
           "ALLOW", "DENY", "NOMATCH"]
