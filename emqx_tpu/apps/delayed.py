"""Delayed publish: `$delayed/<Seconds>/<RealTopic>` interception.

Parity: apps/emqx_modules/src/emqx_delayed.erl — a `message.publish` hook
intercepts `$delayed/...` topics, stops the chain with `allow_publish=false`
(so the broker does not route the wrapper), stores the message keyed by its
fire time (the reference's mnesia ordered_set + timer), and republishes the
unwrapped message when due. `tick()` is the timer callback; `start()` runs
it on the node's event loop.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
from typing import Optional

from emqx_tpu.broker.message import Message, now_ms

PREFIX = "$delayed/"
MAX_DELAYED_INTERVAL = 4294967          # s (reference ?MAX_INTERVAL)


class DelayedPublish:
    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        c = dict(node.config.get("delayed") or {})
        c.update(conf or {})
        self.enable = c.get("enable", True)
        self.max_delayed = int(c.get("max_delayed_messages", 0))
        self._heap: list[tuple[int, int, Message]] = []  # (fire_ms, seq, msg)
        self._cancelled: set[int] = set()                # seq ids deleted
        self._seq = itertools.count()
        self._task: Optional[asyncio.Task] = None

    # ---- app lifecycle ----
    def load(self) -> "DelayedPublish":
        # high priority: runs before retainer/rule hooks so the wrapper
        # topic never reaches them
        self.node.hooks.add("message.publish", self.on_message_publish,
                            priority=500, tag="delayed")
        return self

    def unload(self) -> None:
        self.node.hooks.delete("message.publish", "delayed")
        if self._task:
            self._task.cancel()

    # ---- hook ----
    def on_message_publish(self, msg: Message):
        if not self.enable or not msg.topic.startswith(PREFIX):
            return ("ok", msg)
        rest = msg.topic[len(PREFIX):]
        secs_s, sep, real = rest.partition("/")
        try:
            secs = int(secs_s)
        except ValueError:
            secs = -1
        if not sep or not real or secs < 0 or secs > MAX_DELAYED_INTERVAL:
            # malformed wrapper: drop (reference logs + drops)
            self.node.metrics.inc("messages.delayed.dropped")
            return ("stop", msg.set_header("allow_publish", False))
        if self.max_delayed and self.count() >= self.max_delayed:
            self.node.metrics.inc("messages.delayed.dropped")
            return ("stop", msg.set_header("allow_publish", False))
        inner = msg.copy()
        inner.topic = real
        inner.headers.pop("allow_publish", None)
        heapq.heappush(self._heap,
                       (msg.ts + secs * 1000, next(self._seq), inner))
        self.node.metrics.inc("messages.delayed")
        return ("stop", msg.set_header("allow_publish", False))

    # ---- timer ----
    def tick(self, now: Optional[int] = None) -> int:
        """Publish every message whose fire time has passed; returns count."""
        now = now if now is not None else now_ms()
        n = 0
        while self._heap and self._heap[0][0] <= now:
            _, seq, msg = heapq.heappop(self._heap)
            if seq in self._cancelled:
                self._cancelled.discard(seq)
                continue
            self.node.broker.publish(msg)
            n += 1
        return n

    async def _run(self, interval: float) -> None:
        while True:
            await asyncio.sleep(interval)
            self.tick()

    def start(self, interval: float = 0.25) -> None:
        self._task = asyncio.ensure_future(self._run(interval))

    # ---- checkpoint/resume (broker.persistence) ----
    def pending(self) -> list[tuple[int, int, Message]]:
        """Live (fire_ms, seq, msg) entries, cancelled ones excluded."""
        return [(fire, seq, m) for fire, seq, m in sorted(self._heap)
                if seq not in self._cancelled]

    def restore(self, msg: Message, fire_at_ms: int) -> None:
        heapq.heappush(self._heap, (fire_at_ms, next(self._seq), msg))

    # ---- mgmt API (emqx_delayed:list/delete) ----
    def list(self) -> list[dict]:
        return [{"seq": seq, "publish_at": fire, "topic": m.topic,
                 "qos": m.qos, "from": m.from_}
                for fire, seq, m in sorted(self._heap)
                if seq not in self._cancelled]

    def delete(self, seq: int) -> bool:
        live = {s for _, s, _ in self._heap}
        if seq in live and seq not in self._cancelled:
            self._cancelled.add(seq)
            return True
        return False

    def count(self) -> int:
        return len(self._heap) - len(self._cancelled)
