"""Per-client / per-topic debug tracing to file.

Parity: apps/emqx/src/emqx_tracer.erl — `start_trace({clientid,C}|{topic,T},
Level, File)` installs a filtered handler capturing matching publish and
client lifecycle events (emqx_tracer.erl:66-75+); `stop_trace` removes it,
`lookup_traces` lists active traces. The OTP-logger-filter mechanism
becomes hook callbacks writing formatted lines.

Slow-batch tracing: pipeline telemetry fires the `batch.slow` hook when a
publish batch's oldest-enqueue→completion span exceeds the configurable
`broker.slow_batch_threshold_ms`; the tracer logs every such event at
WARNING and mirrors it into any `start_trace("slow_batch", ...)` files —
the stage-level flight recorder a dead bench round needs.
"""

from __future__ import annotations

import logging
import time
from typing import Optional, TextIO

log = logging.getLogger("emqx_tpu.tracer")

from emqx_tpu.broker.message import Message
from emqx_tpu.utils import topic as T


class Trace:
    def __init__(self, kind: str, value: str, path: str):
        if kind not in ("clientid", "topic", "slow_batch"):
            raise ValueError(f"bad trace kind {kind!r}")
        self.kind = kind
        self.value = value
        self.path = path
        self._fh: Optional[TextIO] = open(path, "a")

    def matches_msg(self, msg: Message) -> bool:
        if self.kind == "slow_batch":
            return False
        if self.kind == "clientid":
            return msg.from_ == self.value
        return T.match(msg.topic, self.value)

    def matches_client(self, clientid: str) -> bool:
        return self.kind == "clientid" and clientid == self.value

    def write(self, line: str) -> None:
        if self._fh:
            ts = time.strftime("%Y-%m-%d %H:%M:%S")
            self._fh.write(f"{ts} {line}\n")
            self._fh.flush()

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None


class Tracer:
    def __init__(self, node):
        self.node = node
        self._traces: dict[tuple[str, str], Trace] = {}
        # (monotonic ts, overlap, top bubbles) — the slow-batch causal
        # context is re-analyzed at most once per _SLOW_CTX_TTL_S
        self._slow_ctx: Optional[tuple] = None

    _SLOW_CTX_TTL_S = 5.0

    def load(self) -> "Tracer":
        h = self.node.hooks
        h.add("message.publish", self.on_message_publish, priority=-500,
              tag="tracer")
        h.add("client.connected", self.on_client_connected, tag="tracer")
        h.add("client.disconnected", self.on_client_disconnected,
              tag="tracer")
        h.add("session.subscribed", self.on_session_subscribed, tag="tracer")
        h.add("batch.slow", self.on_batch_slow, tag="tracer")
        h.add("pipeline.pin_stale", self.on_pin_stale, tag="tracer")
        h.add("latency.breach", self.on_latency_breach, tag="tracer")
        h.add("overload.shed", self.on_overload_shed, tag="tracer")
        return self

    def unload(self) -> None:
        for hp in ("message.publish", "client.connected",
                   "client.disconnected", "session.subscribed",
                   "batch.slow", "pipeline.pin_stale",
                   "latency.breach", "overload.shed"):
            self.node.hooks.delete(hp, "tracer")
        for t in self._traces.values():
            t.close()
        self._traces.clear()

    # ---- mgmt API (emqx_tracer:start_trace/stop_trace/lookup_traces) ----
    def start_trace(self, kind: str, value: str, path: str) -> bool:
        key = (kind, value)
        if key in self._traces:
            return False
        self._traces[key] = Trace(kind, value, path)
        return True

    def stop_trace(self, kind: str, value: str) -> bool:
        t = self._traces.pop((kind, value), None)
        if t is None:
            return False
        t.close()
        return True

    def lookup_traces(self) -> list[dict]:
        return [{"type": k, "value": v, "path": t.path}
                for (k, v), t in self._traces.items()]

    # ---- hooks ----
    def on_message_publish(self, msg: Message):
        for t in self._traces.values():
            if t.matches_msg(msg):
                t.write(f"PUBLISH from={msg.from_} topic={msg.topic} "
                        f"qos={msg.qos} retain={int(msg.retain)} "
                        f"payload={msg.payload[:128]!r}")
        return ("ok", msg)

    def on_client_connected(self, clientinfo: dict, conninfo) -> None:
        cid = clientinfo.get("clientid", "")
        for t in self._traces.values():
            if t.matches_client(cid):
                t.write(f"CONNECTED clientid={cid} "
                        f"username={clientinfo.get('username')} "
                        f"peer={clientinfo.get('peername')}")

    def on_client_disconnected(self, clientinfo: dict, reason) -> None:
        cid = clientinfo.get("clientid", "")
        for t in self._traces.values():
            if t.matches_client(cid):
                t.write(f"DISCONNECTED clientid={cid} reason={reason}")

    def on_batch_slow(self, info: dict) -> None:
        """`batch.slow` hook (broker.telemetry.record_total): a publish
        batch exceeded the slow-batch threshold — always logged, and
        mirrored into active slow_batch trace files. With the ISSUE-7
        flight recorder on, the line carries the causal context the
        triage order reads first: the dispatch↔materialize overlap and
        the top bubble attribution of the recent windows (so a slow
        batch names WHERE its time went before anyone opens a metric
        dashboard)."""
        line = ("SLOW_BATCH " +
                " ".join(f"{k}={info[k]}" for k in sorted(info)))
        rec = getattr(self.node, "flight_recorder", None)
        if rec is not None:
            try:
                # a degraded pipeline makes EVERY batch slow — the
                # full-ring analysis runs on the event loop, so reuse
                # the last one for _SLOW_CTX_TTL_S instead of paying
                # O(ring) per batch exactly when the broker is slow
                now = time.monotonic()
                ctx = self._slow_ctx
                if ctx is None or now - ctx[0] > self._SLOW_CTX_TTL_S:
                    a = rec.analyze(per_window=1)
                    ctx = self._slow_ctx = (
                        now,
                        (a.get("overlap") or {}).get(
                            "dispatch_materialize"),
                        (a.get("bubbles") or {}).get("top") or [])
                _ts, ov, top = ctx
                if ov is not None:
                    line += f" overlap={ov}"
                if top:
                    line += " top_bubble=%s:%.3fs" % tuple(top[0])
            except Exception:  # noqa: BLE001 — context is best-effort
                pass
        log.warning("%s", line)
        for t in self._traces.values():
            if t.kind == "slow_batch":
                t.write(line)

    def on_latency_breach(self, ex: dict) -> None:
        """`latency.breach` hook (broker.latency, ISSUE 13): a message
        exceeded the ingress→routed SLO objective. The exemplar carries
        its window's flight-recorder trace id, so the log line names
        the CAUSAL CHAIN of the exact slow message — queue wait vs
        dispatch vs materialize vs lane backpressure — not an
        aggregate. The observatory throttles the hook to one fire per
        second, so a degraded pipeline (every message breaching) logs
        one chain per second, never one per message."""
        line = ("SLO_BREACH " +
                " ".join(f"{k}={ex[k]}" for k in sorted(ex)))
        rec = getattr(self.node, "flight_recorder", None)
        tid = ex.get("trace_id")
        if rec is not None and tid:
            try:
                spans = sorted(
                    (s for s in rec.spans()
                     if s.trace_id == tid and s.t1 > s.t0
                     and s.name not in ("window", "message")),
                    key=lambda s: s.t0)
                chain = ",".join(f"{s.name}:{s.dur * 1000:.1f}ms"
                                 for s in spans[:12])
                if chain:
                    line += f" chain={chain}"
            except Exception:  # noqa: BLE001 — context is best-effort
                pass
        log.warning("%s", line)

    def on_overload_shed(self, info: dict) -> None:
        """`overload.shed` hook (broker.overload, ISSUE 14): the
        governor armed (or unwound) a shed action — or disconnected a
        top-offender connection. One WARNING line per transition (arms
        are grade-change-edge-triggered, never per-message), so the
        log reads as the ladder's movement history."""
        log.warning("OVERLOAD_SHED %s",
                    " ".join(f"{k}={info[k]}" for k in sorted(info)))

    def on_pin_stale(self, info: dict) -> None:
        """`pipeline.pin_stale` hook (broker.hbm_ledger, ISSUE 8): a
        dispatch handle has pinned its snapshot longer than
        EMQX_TPU_PIN_WARN_WINDOWS prepared windows — stale pins
        silently block snapshot swaps AND hold the old snapshot's
        HBM, so the leak is logged the moment it crosses the
        threshold instead of surfacing as a mystery rebuild stall."""
        log.warning("STALE_PIN %s",
                    " ".join(f"{k}={info[k]}" for k in sorted(info)))

    def on_session_subscribed(self, clientinfo: dict, topic: str,
                              subopts: dict) -> None:
        cid = clientinfo.get("clientid", "")
        for t in self._traces.values():
            if t.matches_client(cid) or (t.kind == "topic"
                                         and T.match(topic, t.value)):
                t.write(f"SUBSCRIBE clientid={cid} topic={topic} "
                        f"qos={subopts.get('qos', 0)}")
