"""Feature apps layered over the broker core via hooks.

Parity: the reference's per-feature OTP applications (emqx_retainer,
emqx_modules' delayed/rewrite/topic_metrics/event_message, emqx_rule_engine,
emqx_authn/authz, ...). Each app is a plain object constructed with the
`Node`, installing its hook callbacks in `load()` and removing them in
`unload()` — the hook registry is the only coupling, exactly as in the
reference (apps/emqx/src/emqx_hooks.erl call sites).
"""
