"""Telemetry reporter.

Parity: apps/emqx_modules/src/emqx_telemetry.erl — periodic anonymized
usage report (uuid, version, license/edition, os info, nodes/active
plugins/metrics totals) posted to a collection endpoint; opt-in gated and
disabled by default, with the report inspectable locally (`get_telemetry`).
"""

from __future__ import annotations

import asyncio
import json
import logging
import platform
import uuid
from typing import Optional

from emqx_tpu.version import __version__

log = logging.getLogger("emqx_tpu.telemetry")

DEFAULT_INTERVAL_S = 7 * 24 * 3600


class Telemetry:
    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        c = dict(node.config.get("telemetry") or {})
        c.update(conf or {})
        self.enabled = bool(c.get("enable", False))
        self.url = c.get("url")
        self.interval = c.get("interval", DEFAULT_INTERVAL_S)
        self.uuid = c.get("uuid") or str(uuid.uuid4())
        self._task: Optional[asyncio.Task] = None

    def load(self) -> "Telemetry":
        self.node.telemetry = self
        if self.enabled and self.url:
            self._task = asyncio.get_running_loop().create_task(
                self._loop())
        return self

    def unload(self) -> None:
        if self._task:
            self._task.cancel()
        if getattr(self.node, "telemetry", None) is self:
            self.node.telemetry = None

    def get_telemetry(self) -> dict:
        """The report body (emqx_telemetry:get_telemetry/0)."""
        node = self.node
        active_plugins = []
        plugins = getattr(node, "plugins", None)
        if plugins is not None:
            active_plugins = [p["name"] for p in plugins.list()
                              if p["enabled"]]
        m = node.metrics
        return {
            "emqx_version": __version__,
            "license": {"edition": "opensource"},
            "uuid": self.uuid,
            "os_name": platform.system(),
            "os_version": platform.release(),
            "otp_version": platform.python_version(),
            "nodes_uuid": [],
            "active_plugins": active_plugins,
            "num_clients": node.cm.count(),
            "messages_received": m.val("messages.received"),
            "messages_sent": m.val("messages.sent"),
        }

    async def report_once(self) -> bool:
        if not self.url:
            return False
        from emqx_tpu.utils.http import request
        try:
            resp = await request(
                "POST", self.url,
                headers={"content-type": "application/json"},
                body=json.dumps(self.get_telemetry()).encode(),
                timeout=10)
            return resp.status < 300
        except Exception as e:  # noqa: BLE001
            log.debug("telemetry report failed: %s", e)
            return False

    async def _loop(self) -> None:
        while True:
            await self.report_once()
            await asyncio.sleep(self.interval)
