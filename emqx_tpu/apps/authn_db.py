"""Database-backed authenticators + SCRAM enhanced auth.

Parity: apps/emqx_authn/src/simple_authn/emqx_authn_{mysql,pgsql,mongodb}.erl
and enhanced_authn/emqx_enhanced_authn_scram_mnesia.erl. Each
password-based authenticator resolves `${mqtt-username}` /
`${mqtt-clientid}` / `${mqtt-password}` / `${ip-address}` / `${cert-*}`
placeholders in a configured query/selector, fetches the stored
password_hash (+salt, is_superuser) through a db resource, and verifies
with the configured hash algorithm — returning `ignore` on empty results
or query errors so the chain can fall through, `deny` on a bad password
(the reference's bad_username_or_password).
"""

from __future__ import annotations

import re
from typing import Optional

from emqx_tpu.utils import passwd as PW
from emqx_tpu.utils.scram import ScramError, ScramServer, make_credentials

OK, IGNORE, DENY = "ok", "ignore", "deny"

_PLACEHOLDER_RE = re.compile(r"\$\{([a-zA-Z0-9\-_]+)\}")


def resolve_placeholder(name: str, clientinfo: dict,
                        password: Optional[bytes]) -> Optional[str]:
    """emqx_authn_utils:replace_placeholder/2 variable set."""
    if name == "mqtt-username":
        return clientinfo.get("username")
    if name == "mqtt-clientid":
        return clientinfo.get("clientid")
    if name == "mqtt-password":
        return (password or b"").decode("utf-8", "replace")
    if name == "ip-address":
        peer = clientinfo.get("peername")
        return str(peer[0]) if peer else None
    if name == "cert-subject":
        return clientinfo.get("dn")
    if name == "cert-common-name":
        return clientinfo.get("cn")
    return None


def parse_query(query: str, style: str) -> tuple[str, list[str]]:
    """Extract ${...} placeholders; rewrite to `?` (mysql) or `$n` (pgsql)
    parameter markers (emqx_authn_mysql/pgsql parse_query)."""
    names: list[str] = []

    def _sub(m: re.Match) -> str:
        names.append(m.group(1))
        return "?" if style == "mysql" else f"${len(names)}"

    return _PLACEHOLDER_RE.sub(_sub, query), names


def _fill_params(names: list[str], clientinfo: dict,
                 password: Optional[bytes]) -> Optional[list]:
    params = []
    for n in names:
        v = resolve_placeholder(n, clientinfo, password)
        if v is None:
            return None          # cannot_get_variable → ignore
        params.append(v)
    return params


class _SqlAuthenticator:
    """Shared SELECT-row authenticator over a sql resource
    (emqx_authn_mysql.erl / emqx_authn_pgsql.erl check_password)."""

    style = "mysql"

    def __init__(self, resource, query: str,
                 algorithm: str = "sha256", salt_position: str = "prefix",
                 query_timeout: float = 5.0):
        self.resource = resource
        self.query, self.placeholders = parse_query(query, self.style)
        self.algorithm = algorithm
        self.salt_position = salt_position
        self.query_timeout = query_timeout

    async def authenticate_async(self, clientinfo: dict,
                                 password: Optional[bytes]):
        params = _fill_params(self.placeholders, clientinfo, password)
        if params is None:
            return IGNORE, {}
        try:
            columns, rows = await self.resource.query(
                ("sql", self.query, params))
        except Exception:  # noqa: BLE001
            return IGNORE, {}
        if not rows:
            return IGNORE, {}
        selected = dict(zip(columns, rows[0]))
        return _check_selected(selected, password, self.algorithm,
                               self.salt_position)


def _check_selected(selected: dict, password: Optional[bytes],
                    algorithm: str, salt_position: str):
    stored = selected.get("password_hash")
    if stored is None:
        return DENY, {}
    ok = PW.check_password(algorithm, str(stored), password,
                           str(selected.get("salt") or ""), salt_position)
    if not ok:
        return DENY, {}
    return OK, {"is_superuser": _truthy(selected.get("is_superuser"))}


def _truthy(v) -> bool:
    if isinstance(v, str):
        return v not in ("", "0", "false", "False")
    return bool(v)


class MysqlAuthenticator(_SqlAuthenticator):
    name = "password_based:mysql"
    style = "mysql"


class PgsqlAuthenticator(_SqlAuthenticator):
    name = "password_based:postgresql"
    style = "pgsql"


class MongoAuthenticator:
    """Selector-doc authenticator (emqx_authn_mongodb.erl)."""

    name = "password_based:mongodb"

    def __init__(self, resource, collection: str = "mqtt_user",
                 selector: Optional[dict] = None,
                 password_hash_field: str = "password_hash",
                 salt_field: str = "salt",
                 is_superuser_field: str = "is_superuser",
                 algorithm: str = "sha256", salt_position: str = "prefix"):
        self.resource = resource
        self.collection = collection
        self.selector = selector or {"username": "${mqtt-username}"}
        self.password_hash_field = password_hash_field
        self.salt_field = salt_field
        self.is_superuser_field = is_superuser_field
        self.algorithm = algorithm
        self.salt_position = salt_position

    def _render_selector(self, clientinfo: dict,
                         password: Optional[bytes]) -> Optional[dict]:
        out = {}
        for k, v in self.selector.items():
            if isinstance(v, str):
                m = _PLACEHOLDER_RE.fullmatch(v)
                if m:
                    rv = resolve_placeholder(m.group(1), clientinfo,
                                             password)
                    if rv is None:
                        return None
                    v = rv
            out[k] = v
        return out

    async def authenticate_async(self, clientinfo: dict,
                                 password: Optional[bytes]):
        sel = self._render_selector(clientinfo, password)
        if sel is None:
            return IGNORE, {}
        try:
            docs = await self.resource.query(("find", self.collection, sel))
        except Exception:  # noqa: BLE001
            return IGNORE, {}
        if not docs:
            return IGNORE, {}
        doc = docs[0]
        stored = doc.get(self.password_hash_field)
        if stored is None:
            return DENY, {}
        selected = {"password_hash": stored,
                    "salt": doc.get(self.salt_field) or "",
                    "is_superuser": doc.get(self.is_superuser_field, False)}
        return _check_selected(selected, password, self.algorithm,
                               self.salt_position)


class ScramAuthenticator:
    """MQTT5 enhanced authentication, mechanism SCRAM-SHA-1/256/512.

    Parity: emqx_enhanced_authn_scram_mnesia.erl — local user store of
    (stored_key, server_key, salt) credentials; the channel drives the
    AUTH-packet exchange through begin_/continue_enhanced_auth. The
    authenticate() chain entry ignores password-based credentials so it
    composes with other authenticators in one chain.
    """

    def __init__(self, algorithm: str = "sha256",
                 iteration_count: int = 4096):
        self.algorithm = algorithm
        self.iteration_count = iteration_count
        self._users: dict[str, dict] = {}

    @property
    def name(self) -> str:
        return "scram:built_in_database"

    @property
    def mechanism(self) -> str:
        return f"SCRAM-SHA-{'1' if self.algorithm == 'sha1' else self.algorithm[3:]}"

    # ---- user management (add_user/delete_user/lookup_user API) ----
    def add_user(self, username: str, password: str,
                 is_superuser: bool = False) -> None:
        cred = make_credentials(password, self.algorithm,
                                self.iteration_count)
        cred["is_superuser"] = is_superuser
        self._users[username] = cred

    def delete_user(self, username: str) -> bool:
        return self._users.pop(username, None) is not None

    def lookup_user(self, username: str) -> Optional[dict]:
        u = self._users.get(username)
        return dict(u, username=username) if u else None

    def list_users(self) -> list[str]:
        return list(self._users)

    # ---- enhanced-auth surface driven by the channel ----
    def begin_enhanced_auth(self, auth_data: bytes) -> tuple[bytes, object]:
        """client-first -> (server-first challenge, opaque state)."""
        server = ScramServer(self._users.get, self.algorithm)
        challenge = server.challenge(auth_data.decode("utf-8", "replace"))
        return challenge.encode(), server

    def continue_enhanced_auth(self, auth_data: bytes,
                               state: object) -> tuple[bytes, dict]:
        """client-final -> (server-final, extra) or raises ScramError."""
        server: ScramServer = state
        server_final = server.finish(auth_data.decode("utf-8", "replace"))
        cred = self._users.get(server.username) or {}
        extra = {"is_superuser": bool(cred.get("is_superuser", False)),
                 "username": server.username}
        return server_final.encode(), extra

    # ---- chain interface: not a password authenticator ----
    def authenticate(self, clientinfo: dict, password: Optional[bytes]):
        return IGNORE, {}


class LdapAuthenticator:
    """LDAP bind authentication (round-2 VERDICT item 9): resolve the
    client to a DN by a filter search, then attempt a simple bind with
    the presented password — success authenticates. Parity: the
    reference's eldap-backed authn (emqx_connector_ldap.erl providing the
    transport; the search+bind flow is the classic LDAP auth pattern its
    deployments use).

    filter_tmpl supports `(attr=${placeholder})` and `(&(..)(..)...)`
    with the same placeholder set as the SQL authenticators
    (resolve_placeholder). Search runs on a service connection (bound as
    `bind_dn` when given); the credential check binds on a FRESH
    connection so the service bind is never downgraded.
    """

    name = "password_based:ldap"

    def __init__(self, host: str = "127.0.0.1", port: int = 389,
                 base_dn: str = "",
                 filter_tmpl: str = "(uid=${mqtt-username})",
                 bind_dn: Optional[str] = None, bind_password: str = "",
                 superuser_attr: str = "isSuperuser", ssl=None,
                 timeout: float = 5.0):
        self.host = host
        self.port = port
        self.base_dn = base_dn
        self.filter_tmpl = filter_tmpl
        self.bind_dn = bind_dn
        self.bind_password = bind_password
        self.superuser_attr = superuser_attr
        self.ssl = ssl
        self.timeout = timeout

    def _client(self):
        from emqx_tpu.connectors.ldap import LdapClient
        return LdapClient(host=self.host, port=self.port, ssl=self.ssl,
                          connect_timeout=self.timeout)

    def _build_filter(self, clientinfo: dict,
                      password: Optional[bytes]) -> Optional[bytes]:
        from emqx_tpu.connectors import ldap as L

        def build(expr: str) -> Optional[bytes]:
            expr = expr.strip()
            if not (expr.startswith("(") and expr.endswith(")")):
                raise ValueError(f"bad LDAP filter {expr!r}")
            inner = expr[1:-1]
            if inner.startswith("&"):
                parts, depth, start = [], 0, None
                for i, ch in enumerate(inner):
                    if ch == "(":
                        if depth == 0:
                            start = i
                        depth += 1
                    elif ch == ")":
                        depth -= 1
                        if depth == 0:
                            parts.append(inner[start:i + 1])
                subs = [build(p) for p in parts]
                if any(s is None for s in subs):
                    return None
                return L.f_and(*subs)
            attr, _, val = inner.partition("=")
            m = re.fullmatch(r"\$\{([^}]+)\}", val.strip())
            if m:
                rv = resolve_placeholder(m.group(1), clientinfo, password)
                if rv is None:
                    return None
                val = rv if isinstance(rv, str) else rv.decode()
            return L.f_eq(attr.strip(), val)

        return build(self.filter_tmpl)

    async def authenticate_async(self, clientinfo: dict,
                                 password: Optional[bytes]):
        from emqx_tpu.connectors import ldap as L
        if not password:
            return IGNORE, {}
        try:
            filt = self._build_filter(clientinfo, password)
        except ValueError:
            return IGNORE, {}
        if filt is None:
            return IGNORE, {}
        try:
            svc = self._client()
            await svc.connect()
            try:
                if self.bind_dn:
                    await svc.bind(self.bind_dn, self.bind_password)
                entries = await svc.search(
                    self.base_dn, L.SCOPE_SUB, filt,
                    attributes=[self.superuser_attr], size_limit=1)
            finally:
                await svc.close()
        except Exception:  # noqa: BLE001 — unreachable/refused: next in chain
            return IGNORE, {}
        if not entries:
            return IGNORE, {}
        dn = entries[0]["dn"]
        su_vals = entries[0].get(self.superuser_attr, [])
        try:
            cred = self._client()
            await cred.connect()
            try:
                await cred.bind(dn, password.decode("utf-8", "replace"))
            finally:
                await cred.close()
        except L.LdapError:
            return DENY, {}
        except Exception:  # noqa: BLE001
            return IGNORE, {}
        return OK, {"is_superuser": bool(su_vals)
                    and _truthy(su_vals[0])}


__all__ = ["MysqlAuthenticator", "PgsqlAuthenticator",
           "MongoAuthenticator", "ScramAuthenticator", "LdapAuthenticator",
           "ScramError", "parse_query", "resolve_placeholder"]
