"""Topic rewrite rules for publish and subscribe.

Parity: apps/emqx_modules/src/emqx_rewrite.erl — ordered rules
{action pub|sub|all, source filter, regex, dest template}; a topic that
matches both the MQTT filter and the regex is rewritten to the template
with $1..$N substituted from regex capture groups; rules fold in order,
each seeing the previous rewrite's output. Hooks: `message.publish` (pub),
`client.subscribe` / `client.unsubscribe` (sub).
"""

from __future__ import annotations

import re
from typing import Optional

from emqx_tpu.broker.message import Message
from emqx_tpu.utils import topic as T

_VAR = re.compile(r"\$(\d+)")


class RewriteRule:
    def __init__(self, action: str, source: str, regex: str, dest: str):
        if action not in ("publish", "subscribe", "all"):
            raise ValueError(f"bad rewrite action {action!r}")
        self.action = action
        self.source = source
        self.re = re.compile(regex)
        self.dest = dest

    def apply(self, topic: str) -> Optional[str]:
        if not T.match(topic, self.source):
            return None
        m = self.re.match(topic)
        if m is None:
            return None
        groups = m.groups()

        def sub(v: "re.Match[str]") -> str:
            i = int(v.group(1))
            return groups[i - 1] if 0 < i <= len(groups) else v.group(0)

        return _VAR.sub(sub, self.dest)


class TopicRewrite:
    def __init__(self, node, rules: Optional[list] = None):
        self.node = node
        raw = rules if rules is not None else (
            node.config.get("rewrite") or [])
        self.rules = [r if isinstance(r, RewriteRule) else RewriteRule(
            r.get("action", "all"), r["source"], r["re"], r["dest"])
            for r in raw]

    def load(self) -> "TopicRewrite":
        self.node.hooks.add("message.publish", self.on_message_publish,
                            priority=900, tag="rewrite")
        self.node.hooks.add("client.subscribe", self.on_client_subscribe,
                            tag="rewrite")
        self.node.hooks.add("client.unsubscribe", self.on_client_unsubscribe,
                            tag="rewrite")
        return self

    def unload(self) -> None:
        for h in ("message.publish", "client.subscribe",
                  "client.unsubscribe"):
            self.node.hooks.delete(h, "rewrite")

    def _rewrite(self, topic: str, action: str) -> str:
        for rule in self.rules:
            if rule.action not in (action, "all"):
                continue
            new = rule.apply(topic)
            if new is not None:
                topic = new
        return topic

    # ---- hooks ----
    def on_message_publish(self, msg: Message):
        if msg.topic.startswith("$SYS/"):
            return ("ok", msg)
        new = self._rewrite(msg.topic, "publish")
        if new != msg.topic:
            msg.topic = new
        return ("ok", msg)

    def _rewrite_filter(self, tf: str) -> str:
        """Rewrite the real part, preserving any $share/$queue prefix."""
        try:
            real, opts = T.parse(tf)
        except T.TopicError:
            return tf
        new = self._rewrite(real, "subscribe")
        if new == real:
            return tf
        group = opts.get("share")
        if group == "$queue":
            return f"$queue/{new}"
        if group:
            return f"$share/{group}/{new}"
        return new

    def on_client_subscribe(self, clientinfo, props, filters):
        return ("ok", [(self._rewrite_filter(tf), o) for tf, o in filters])

    def on_client_unsubscribe(self, clientinfo, props, filters):
        return ("ok", [self._rewrite_filter(tf) for tf in filters])
