"""Prometheus exporter.

Parity: apps/emqx_prometheus — collector turning broker metrics/stats/VM
info into the Prometheus text exposition format, a REST endpoint
(`GET /api/v5/prometheus/stats`), and an optional push-gateway timer
(emqx_prometheus.erl push mode).
"""

from __future__ import annotations

import asyncio
import logging
import resource
import time
from typing import Optional

log = logging.getLogger("emqx_tpu.prometheus")


def _san(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


def _lbl(value: str) -> str:
    """Escape a label VALUE per the exposition format (backslash first,
    then quote and newline)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_le(bound: float) -> str:
    """Prometheus `le` label rendering: +Inf for the overflow bucket,
    shortest-repr floats otherwise."""
    if bound == float("inf"):
        return "+Inf"
    return repr(bound)


def collect(node) -> str:
    """Render the node's counters/gauges/histograms in text exposition
    format. Each metric family declares `# TYPE` exactly once (a family
    with several samples — labeled rule metrics, histogram bucket
    series — shares the one declaration), histogram buckets are
    cumulative and end in `+Inf`, and label values are escaped."""
    out: list[str] = []
    declared: set[str] = set()

    def declare(name: str, kind: str, help_: str = "") -> None:
        if name in declared:
            return
        declared.add(name)
        if help_:
            out.append(f"# HELP {name} {help_}")
        out.append(f"# TYPE {name} {kind}")

    def emit(name: str, value, kind: str = "counter",
             help_: str = "") -> None:
        declare(name, kind, help_)
        out.append(f"{name} {value}")

    for name, val in sorted(node.metrics.all().items()):
        emit(f"emqx_{_san(name)}", val, "counter")
    for name, val in sorted(node.stats.sample().items()):
        emit(f"emqx_{_san(name)}", val, "gauge")
    # pipeline (and any other) histograms: _bucket{le}/_sum/_count series
    for name, h in sorted(node.metrics.histograms().items()):
        fam = f"emqx_{_san(name)}"
        declare(fam, "histogram")
        # one cumulative() pass is the scrape's consistent view: _count
        # must equal the +Inf bucket even when an executor thread
        # observes mid-collect (reading h.count separately could exceed
        # the bucket series and fail ingester consistency checks)
        cum = h.cumulative()
        for bound, c in cum:
            out.append(f'{fam}_bucket{{le="{_fmt_le(bound)}"}} {c}')
        out.append(f"{fam}_sum {h.sum}")
        out.append(f"{fam}_count {cum[-1][1]}")
    ru = resource.getrusage(resource.RUSAGE_SELF)
    emit("emqx_vm_used_memory_kb", ru.ru_maxrss, "gauge",
         "resident set size")
    emit("emqx_vm_cpu_time_seconds",
         round(ru.ru_utime + ru.ru_stime, 3), "counter")
    eng = getattr(node, "rule_engine", None)
    if eng is not None:
        # group by FAMILY first: the exposition format requires all
        # samples of one family consecutive under its single TYPE line
        # (per-rule emission interleaved families when >1 rule existed)
        fams: dict[str, list[str]] = {}
        for r in eng.list_rules():
            rid = _lbl(_san(r.id))
            for k, v in r.metrics.counters.items():
                fams.setdefault(f"emqx_rule_{_san(k)}", []).append(
                    f'{{rule="{rid}"}} {v}')
        for fam in sorted(fams):
            declare(fam, "counter")
            out.extend(fam + s for s in fams[fam])
    return "\n".join(out) + "\n"


class PrometheusApp:
    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        c = dict(node.config.get("prometheus") or {})
        c.update(conf or {})
        self.push_gateway = c.get("push_gateway_server")  # http://host:port
        self.interval = c.get("interval", 15.0)
        self.job_name = c.get("job_name", "emqx_tpu")
        self._task: Optional[asyncio.Task] = None

    def load(self) -> "PrometheusApp":
        self.node.prometheus = self
        if self.push_gateway:
            self._task = asyncio.get_running_loop().create_task(
                self._push_loop())
        return self

    def unload(self) -> None:
        if self._task:
            self._task.cancel()
        if getattr(self.node, "prometheus", None) is self:
            self.node.prometheus = None

    def collect_text(self) -> str:
        return collect(self.node)

    async def _push_loop(self) -> None:
        from emqx_tpu.utils.http import request
        url = (f"{self.push_gateway}/metrics/job/{self.job_name}"
               f"/instance/{self.node.name}")
        while True:
            await asyncio.sleep(self.interval)
            try:
                await request("POST", url,
                              headers={"content-type": "text/plain"},
                              body=self.collect_text().encode(),
                              timeout=5)
            except Exception as e:  # noqa: BLE001
                log.debug("prometheus push failed: %s", e)


def register_api(srv, node) -> None:
    """Mount GET /api/v5/prometheus/stats on the mgmt HTTP server."""
    async def prom_stats(_req):
        return 200, collect(node).encode()
    srv.route("GET", "/api/v5/prometheus/stats", prom_stats)
    srv.route("GET", "/metrics", prom_stats)   # standard scrape path
