"""StatsD exporter.

Parity: apps/emqx_statsd — periodic UDP push of broker metrics (counters
as deltas `|c`) and stats (gauges `|g`) to a StatsD daemon.
"""

from __future__ import annotations

import asyncio
import logging
import socket
from typing import Optional

log = logging.getLogger("emqx_tpu.statsd")


class StatsdApp:
    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        c = dict(node.config.get("statsd") or {})
        c.update(conf or {})
        self.host = c.get("host", "127.0.0.1")
        self.port = c.get("port", 8125)
        self.prefix = c.get("prefix", "emqx")
        self.interval = c.get("interval", 10.0)
        self.batch_bytes = c.get("batch_bytes", 1400)
        self._last: dict[str, int] = {}
        self._task: Optional[asyncio.Task] = None
        self._sock: Optional[socket.socket] = None

    def load(self) -> "StatsdApp":
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        self.node.statsd = self
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    def unload(self) -> None:
        if self._task:
            self._task.cancel()
        if self._sock:
            self._sock.close()
            self._sock = None
        if getattr(self.node, "statsd", None) is self:
            self.node.statsd = None

    def render(self) -> list[str]:
        """Metric lines for one flush: counter deltas + stat gauges."""
        lines = []
        for name, val in sorted(self.node.metrics.all().items()):
            delta = val - self._last.get(name, 0)
            self._last[name] = val
            if delta:
                lines.append(f"{self.prefix}.{name}:{delta}|c")
        for name, val in sorted(self.node.stats.sample().items()):
            lines.append(f"{self.prefix}.{name}:{val}|g")
        return lines

    def flush(self) -> int:
        """Send one batch now; returns datagrams sent."""
        if self._sock is None:
            return 0
        sent = 0
        batch: list[str] = []
        size = 0
        for line in self.render():
            if size + len(line) + 1 > self.batch_bytes and batch:
                self._send("\n".join(batch))
                sent += 1
                batch, size = [], 0
            batch.append(line)
            size += len(line) + 1
        if batch:
            self._send("\n".join(batch))
            sent += 1
        return sent

    def _send(self, payload: str) -> None:
        try:
            self._sock.sendto(payload.encode(), (self.host, self.port))
        except OSError as e:
            log.debug("statsd send failed: %s", e)

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            self.flush()
