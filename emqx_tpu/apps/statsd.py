"""StatsD exporter.

Parity: apps/emqx_statsd — periodic UDP push of broker metrics (counters
as deltas `|c`) and stats (gauges `|g`) to a StatsD daemon. Pipeline
latency histograms ride as `|ms` timers: each flush sends the interval's
mean latency with a StatsD sample rate of 1/new_observations, so the
daemon reconstructs both magnitude and volume without one packet per
observation; ratio histograms (batch occupancy) flush as interval-mean
gauges. The final interval flushes on `unload()` — a stopping node no
longer silently drops its last deltas.
"""

from __future__ import annotations

import asyncio
import logging
import socket
from typing import Optional

log = logging.getLogger("emqx_tpu.statsd")


class StatsdApp:
    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        c = dict(node.config.get("statsd") or {})
        c.update(conf or {})
        self.host = c.get("host", "127.0.0.1")
        self.port = c.get("port", 8125)
        self.prefix = c.get("prefix", "emqx")
        self.interval = c.get("interval", 10.0)
        self.batch_bytes = c.get("batch_bytes", 1400)
        self._last: dict[str, int] = {}
        self._last_hist: dict[str, tuple[int, float]] = {}
        self._task: Optional[asyncio.Task] = None
        self._sock: Optional[socket.socket] = None

    def load(self) -> "StatsdApp":
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        self.node.statsd = self
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    def unload(self) -> None:
        if self._task:
            self._task.cancel()
        if self._sock:
            try:
                # final flush: the deltas accumulated since the last
                # interval tick must not vanish when the node stops
                self.flush()
            except Exception as e:  # noqa: BLE001 — teardown best-effort
                log.debug("statsd final flush failed: %s", e)
            self._sock.close()
            self._sock = None
        if getattr(self.node, "statsd", None) is self:
            self.node.statsd = None

    def render(self) -> list[str]:
        """Metric lines for one flush: counter deltas + stat gauges."""
        lines = []
        for name, val in sorted(self.node.metrics.all().items()):
            delta = val - self._last.get(name, 0)
            self._last[name] = val
            if delta:
                lines.append(f"{self.prefix}.{name}:{delta}|c")
        for name, val in sorted(self.node.stats.sample().items()):
            lines.append(f"{self.prefix}.{name}:{val}|g")
        # histograms: latency-unit ones (pipeline stage spans) as |ms
        # timers — one sampled line per flush carrying the interval mean
        # with rate=1/new_count, so aggregate latency AND volume survive
        # the UDP budget (StatsD's documented sampling semantics);
        # ratio-unit ones (batch occupancy) as interval-mean gauges
        for name, h in sorted(self.node.metrics.histograms().items()):
            lc, ls = self._last_hist.get(name, (0, 0.0))
            dc, ds = h.count - lc, h.sum - ls
            self._last_hist[name] = (h.count, h.sum)
            if dc <= 0:
                continue
            if h.unit == "seconds":
                # clamp: >2M observations per interval would render as
                # the invalid zero rate |@0.000000
                rate = f"|@{max(1.0 / dc, 1e-6):.6f}" if dc > 1 else ""
                lines.append(
                    f"{self.prefix}.{name}:{ds / dc * 1000.0:.3f}|ms"
                    f"{rate}")
            else:
                lines.append(f"{self.prefix}.{name}:{ds / dc:.4f}|g")
        return lines

    def flush(self) -> int:
        """Send one batch now; returns datagrams sent."""
        if self._sock is None:
            return 0
        sent = 0
        batch: list[str] = []
        size = 0
        for line in self.render():
            if size + len(line) + 1 > self.batch_bytes and batch:
                self._send("\n".join(batch))
                sent += 1
                batch, size = [], 0
            batch.append(line)
            size += len(line) + 1
        if batch:
            self._send("\n".join(batch))
            sent += 1
        return sent

    def _send(self, payload: str) -> None:
        try:
            self._sock.sendto(payload.encode(), (self.host, self.port))
        except OSError as e:
            log.debug("statsd send failed: %s", e)

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            self.flush()
