"""Per-topic message counters and rates.

Parity: apps/emqx_modules/src/emqx_topic_metrics.erl — operator registers
topic filters; hooks count messages.in/out/dropped and per-QoS variants for
matching topics; `tick()` computes rolling rates the way the reference's
speed timer does.
"""

from __future__ import annotations

import time
from typing import Optional

from emqx_tpu.broker.message import Message
from emqx_tpu.utils import topic as T

METRICS = ("messages.in", "messages.out", "messages.dropped",
           "messages.qos0.in", "messages.qos1.in", "messages.qos2.in",
           "messages.qos0.out", "messages.qos1.out", "messages.qos2.out")
MAX_TOPICS = 512                         # reference ?MAX_TOPICS


class TopicMetrics:
    def __init__(self, node, topics: Optional[list[str]] = None):
        self.node = node
        self._m: dict[str, dict[str, int]] = {}
        self._rates: dict[str, dict[str, float]] = {}
        self._last: dict[str, dict[str, int]] = {}
        self._last_ts = time.monotonic()
        for t in (topics if topics is not None
                  else node.config.get("topic_metrics") or []):
            self.register(t)

    def load(self) -> "TopicMetrics":
        self.node.hooks.add("message.publish", self.on_message_publish,
                            priority=-100, tag="topic_metrics")
        self.node.hooks.add("message.delivered", self.on_message_delivered,
                            tag="topic_metrics")
        self.node.hooks.add("message.dropped", self.on_message_dropped,
                            tag="topic_metrics")
        return self

    def unload(self) -> None:
        for h in ("message.publish", "message.delivered", "message.dropped"):
            self.node.hooks.delete(h, "topic_metrics")

    # ---- registry ----
    def register(self, topic: str) -> bool:
        if topic in self._m:
            return False
        if len(self._m) >= MAX_TOPICS:
            raise ValueError("quota_exceeded")
        self._m[topic] = {k: 0 for k in METRICS}
        self._last[topic] = {k: 0 for k in METRICS}
        self._rates[topic] = {k: 0.0 for k in METRICS}
        return True

    def deregister(self, topic: str) -> bool:
        ok = self._m.pop(topic, None) is not None
        self._last.pop(topic, None)
        self._rates.pop(topic, None)
        return ok

    def topics(self) -> list[str]:
        return list(self._m)

    def _inc(self, topic: str, metric: str, qos_metric: Optional[str] = None):
        for filt, counters in self._m.items():
            if T.match(topic, filt):
                counters[metric] += 1
                if qos_metric:
                    counters[qos_metric] += 1

    # ---- hooks ----
    def on_message_publish(self, msg: Message):
        self._inc(msg.topic, "messages.in", f"messages.qos{msg.qos}.in")
        return ("ok", msg)

    def on_message_delivered(self, clientid, msg: Message):
        self._inc(msg.topic, "messages.out", f"messages.qos{msg.qos}.out")

    def on_message_dropped(self, msg: Optional[Message], reason=None):
        if msg is not None:
            self._inc(msg.topic, "messages.dropped")

    # ---- rates ----
    def tick(self) -> None:
        now = time.monotonic()
        dt = max(now - self._last_ts, 1e-9)
        for t, counters in self._m.items():
            for k, v in counters.items():
                self._rates[t][k] = (v - self._last[t][k]) / dt
                self._last[t][k] = v
        self._last_ts = now

    def val(self, topic: str, metric: str) -> int:
        return self._m.get(topic, {}).get(metric, 0)

    def rate(self, topic: str, metric: str) -> float:
        return self._rates.get(topic, {}).get(metric, 0.0)

    def metrics(self, topic: Optional[str] = None) -> dict:
        if topic is not None:
            return dict(self._m.get(topic, {}))
        return {t: dict(c) for t, c in self._m.items()}
