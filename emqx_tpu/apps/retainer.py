"""Retained-message store with wildcard read on subscribe.

Parity: apps/emqx_retainer — `message.publish` hook stores/clears retained
messages (emqx_retainer.erl on_message_publish), `session.subscribed` hook
dispatches matching retained messages to the new subscriber honoring the
MQTT5 Retain-Handling subopt (emqx_retainer.erl dispatch/2), expiry via the
v5 Message-Expiry-Interval property or the configured default
(emqx_retainer_mnesia.erl expiry scan), and max_retained_messages /
max_payload_size limits (emqx_retainer.erl:enabled checks).

The reference's mnesia index-read for wildcard subscribe becomes a host
nested-level trie over retained topic *names* (exact topics, so the walk is
filter-driven); the bulk device matcher is not involved because retained
reads are off the publish hot path.
"""

from __future__ import annotations

from typing import Iterator, Optional

from emqx_tpu.broker.hooks import HP_RETAINER
from emqx_tpu.broker.message import Message, now_ms
from emqx_tpu.utils import topic as T


class TopicIndex:
    """Nested-dict trie over exact topic names; lookup by wildcard filter."""

    _LEAF = object()

    def __init__(self):
        self._root: dict = {}
        self._count = 0

    def insert(self, topic: str) -> bool:
        node = self._root
        for w in T.tokens(topic):
            node = node.setdefault(w, {})
        if TopicIndex._LEAF in node:
            return False
        node[TopicIndex._LEAF] = topic
        self._count += 1
        return True

    def delete(self, topic: str) -> bool:
        path = []
        node = self._root
        for w in T.tokens(topic):
            nxt = node.get(w)
            if nxt is None:
                return False
            path.append((node, w))
            node = nxt
        if node.pop(TopicIndex._LEAF, None) is None:
            return False
        self._count -= 1
        for parent, w in reversed(path):
            if parent[w]:
                break
            del parent[w]
        return True

    def __len__(self) -> int:
        return self._count

    def match(self, filt: str) -> Iterator[str]:
        """All stored topic names matching the filter (MQTT semantics incl.
        the `$`-topic root-wildcard exclusion, emqx_topic.erl:66-69)."""
        fw = T.tokens(filt)
        exclude_dollar = fw[0] in (T.PLUS, T.HASH)

        def walk(node: dict, i: int, depth: int):
            if i == len(fw):
                t = node.get(TopicIndex._LEAF)
                if t is not None:
                    yield t
                return
            w = fw[i]
            if w == T.HASH:
                # '#' matches remaining levels including zero
                yield from collect(node, depth)
                return
            if w == T.PLUS:
                for k, child in node.items():
                    if k is TopicIndex._LEAF:
                        continue
                    if depth == 0 and exclude_dollar and k.startswith("$"):
                        continue
                    yield from walk(child, i + 1, depth + 1)
                return
            child = node.get(w)
            if child is not None:
                yield from walk(child, i + 1, depth + 1)

        def collect(node: dict, depth: int):
            for k, child in node.items():
                if k is TopicIndex._LEAF:
                    yield child
                    continue
                if depth == 0 and exclude_dollar and k.startswith("$"):
                    continue
                yield from collect(child, depth + 1)

        yield from walk(self._root, 0, 0)


class RetainerStorage:
    """Pluggable retained-message store (emqx_retainer_mnesia.erl:49-55
    behaviour analog: the reference selects mnesia ram/disc/disc_only
    copies; here a backend object with this interface).

    Entries are (Message, expire_at_ms | None); expiry policy lives in
    Retainer — backends only store and match.
    """

    def insert(self, topic: str, msg: Message,
               expire_at: Optional[int]) -> None:
        raise NotImplementedError

    def delete(self, topic: str) -> bool:
        raise NotImplementedError

    def get(self, topic: str):
        """-> (Message, expire_at) or None."""
        raise NotImplementedError

    def match_topics(self, filt: str) -> list[str]:
        raise NotImplementedError

    def items(self):
        """-> iterable of (topic, Message, expire_at)."""
        raise NotImplementedError

    def clear(self) -> int:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass


class RamStorage(RetainerStorage):
    """In-memory backend (the reference's ram_copies default)."""

    def __init__(self):
        self._store: dict[str, tuple[Message, Optional[int]]] = {}
        self._index = TopicIndex()

    def insert(self, topic, msg, expire_at):
        if topic not in self._store:
            self._index.insert(topic)
        self._store[topic] = (msg, expire_at)

    def delete(self, topic):
        if self._store.pop(topic, None) is None:
            return False
        self._index.delete(topic)
        return True

    def get(self, topic):
        return self._store.get(topic)

    def match_topics(self, filt):
        return list(self._index.match(filt))

    def items(self):
        return [(t, m, exp) for t, (m, exp) in self._store.items()]

    def clear(self):
        n = len(self._store)
        self._store.clear()
        self._index = TopicIndex()
        return n

    def __len__(self):
        return len(self._store)


class DiscStorage(RamStorage):
    """Write-through disk backend (the reference's disc_copies — ram reads
    + durable writes; `disc_only` maps here too, the distinction in mnesia
    is memory residency, not semantics). A JSONL journal of set/del
    records replays on open and compacts when it grows past 4x the live
    entry count."""

    def __init__(self, dirpath: str):
        super().__init__()
        import os
        os.makedirs(dirpath, exist_ok=True)
        self.path = os.path.join(dirpath, "retained.jsonl")
        self._journal_lines = 0
        self._fh = None
        self._load()
        self._fh = open(self.path, "a", encoding="utf-8")

    def _load(self) -> None:
        import json
        import os

        from emqx_tpu.broker.persistence import _dec_deep
        if not os.path.exists(self.path):
            return
        with open(self.path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ent = _dec_deep(json.loads(line))
                except ValueError:
                    continue        # torn tail write: ignore
                self._journal_lines += 1
                if ent.get("op") == "del":
                    super().delete(ent["topic"])
                elif ent.get("op") == "set":
                    msg = Message.from_wire(ent["msg"])
                    super().insert(msg.topic, msg, ent.get("expire_at"))

    def _append(self, ent: dict) -> None:
        import json

        from emqx_tpu.broker.persistence import _enc
        self._fh.write(json.dumps(ent, default=_enc) + "\n")
        self._fh.flush()
        self._journal_lines += 1
        if self._journal_lines > max(64, 4 * len(self._store)):
            self._compact()

    def _compact(self) -> None:
        import json
        import os

        from emqx_tpu.broker.persistence import _enc
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for t, (m, exp) in self._store.items():
                f.write(json.dumps({"op": "set", "msg": m.to_wire(),
                                    "expire_at": exp}, default=_enc) + "\n")
        self._fh.close()
        os.replace(tmp, self.path)
        self._fh = open(self.path, "a", encoding="utf-8")
        self._journal_lines = len(self._store)

    def insert(self, topic, msg, expire_at):
        super().insert(topic, msg, expire_at)
        self._append({"op": "set", "msg": msg.to_wire(),
                      "expire_at": expire_at})

    def delete(self, topic):
        if not super().delete(topic):
            return False
        self._append({"op": "del", "topic": topic})
        return True

    def clear(self):
        n = super().clear()
        self._compact()
        return n

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def make_storage(conf) -> RetainerStorage:
    """Config -> backend: "ram" (default) | {"type": "disc"|"disc_only",
    "dir": path}."""
    if isinstance(conf, RetainerStorage):
        return conf
    if conf in (None, "ram"):
        return RamStorage()
    if isinstance(conf, str):
        conf = {"type": conf}
    stype = conf.get("type", "ram")
    if stype == "ram":
        return RamStorage()
    if stype in ("disc", "disc_only"):
        return DiscStorage(conf.get("dir", "data/retainer"))
    raise ValueError(f"unknown retainer storage type {stype!r}")


class Retainer:
    def __init__(self, node, conf: Optional[dict] = None,
                 storage: Optional[RetainerStorage] = None):
        self.node = node
        c = dict(node.config.get("retainer") or {})
        c.update(conf or {})
        self.enable = c.get("enable", True)
        self.max_retained = int(c.get("max_retained_messages", 0))
        self.max_payload = int(c.get("max_payload_size", 1024 * 1024))
        self.default_expiry = int(c.get("msg_expiry_interval", 0))  # s, 0=∞
        self.storage = make_storage(storage
                                    if storage is not None
                                    else c.get("storage"))
        # replays parked by the overload governor's defer_retained
        # shed action (ISSUE 14), drained by tick() on recovery
        self._deferred: list = []

    # ---- app lifecycle ----
    def load(self) -> "Retainer":
        self.node.hooks.add("message.publish", self.on_message_publish,
                            priority=HP_RETAINER, tag="retainer")
        self.node.hooks.add("session.subscribed", self.on_session_subscribed,
                            tag="retainer")
        self.node.stats.register_stats_fun(self.stats_fun)
        return self

    def unload(self) -> None:
        self.node.hooks.delete("message.publish", "retainer")
        self.node.hooks.delete("session.subscribed", "retainer")

    # ---- hooks ----
    def on_message_publish(self, msg: Message):
        if not self.enable or not msg.retain or msg.topic.startswith("$SYS/"):
            return ("ok", msg)
        if not msg.payload:
            self.delete(msg.topic)
            # empty retained publish clears the store and is NOT routed
            # further with retain semantics; the message itself still
            # propagates (spec: treated as normal publish w/o retention)
            return ("ok", msg)
        self._insert(msg)
        return ("ok", msg)

    # overload defer_retained bound (ISSUE 14): replays parked while
    # the governor sheds; beyond this the OLDEST parked replays drop
    # (counted) — retained replay is best-effort convenience, and an
    # unbounded parking lot under a flood would be its own overload
    _DEFER_CAP = 1024

    def on_session_subscribed(self, clientinfo: dict, topic: str,
                              subopts: dict):
        if not self.enable:
            return
        rh = int(subopts.get("rh", 0))
        is_new = bool(subopts.get("is_new", True))
        if rh == 2 or (rh == 1 and not is_new):
            return
        if subopts.get("share"):
            return      # shared subscriptions get no retained replay (spec)
        gov = getattr(self.node, "overload_governor", None)
        if gov is not None and gov.retained_deferred:
            # overload defer_retained action (ISSUE 14): a wildcard
            # retained read + fan-out is pure extra load mid-flood —
            # park the replay (bounded) and run it on the first
            # housekeeping tick after the governor recovers
            self._deferred.append((dict(clientinfo), topic,
                                   dict(subopts)))
            gov.count_retained_deferred()
            while len(self._deferred) > self._DEFER_CAP:
                self._deferred.pop(0)
                self.node.metrics.inc("messages.retained.dropped")
            return
        self._dispatch_retained(clientinfo, topic, subopts)

    def _dispatch_retained(self, clientinfo: dict, topic: str,
                           subopts: dict) -> None:
        chan = self.node.cm.lookup_channel(clientinfo.get("clientid", ""))
        if chan is None:
            return
        opts = {k: v for k, v in subopts.items() if k != "is_new"}
        for m in self.match(topic):
            d = m.copy()
            d.set_flag("retained", True)
            d.headers["subopts"] = opts
            chan.deliver(topic, d)

    # ---- store ----
    def _expire_at(self, msg: Message) -> Optional[int]:
        exp = msg.expiry_interval()
        if exp is None:
            exp = self.default_expiry or None
        return None if exp is None else msg.ts + exp * 1000

    def _insert(self, msg: Message) -> bool:
        t = msg.topic
        if len(msg.payload) > self.max_payload:
            self.node.metrics.inc("messages.retained.dropped")
            return False
        if (self.max_retained and self.storage.get(t) is None
                and len(self.storage) >= self.max_retained):
            self.node.metrics.inc("messages.retained.dropped")
            return False
        self.storage.insert(t, msg.copy(), self._expire_at(msg))
        self.node.metrics.inc("messages.retained")
        return True

    def delete(self, topic: str) -> bool:
        return self.storage.delete(topic)

    def lookup(self, topic: str) -> Optional[Message]:
        ent = self.storage.get(topic)
        if ent is None:
            return None
        msg, exp = ent
        if exp is not None and now_ms() > exp:
            self.delete(topic)
            return None
        return msg

    def match(self, filt: str) -> list[Message]:
        """All live retained messages matching a filter (wildcard read)."""
        out = []
        for t in self.storage.match_topics(filt):
            m = self.lookup(t)
            if m is not None:
                out.append(m)
        return out

    def clean(self, filt: Optional[str] = None) -> int:
        """Purge retained messages (all, or those matching a filter) —
        emqx_retainer:clean/0, emqx_mgmt:clean_retained."""
        if filt is None:
            return self.storage.clear()
        gone = self.storage.match_topics(filt)
        for t in gone:
            self.delete(t)
        return len(gone)

    def clean_expired(self) -> int:
        now = now_ms()
        stale = [t for t, _m, exp in self.storage.items()
                 if exp is not None and now > exp]
        for t in stale:
            self.delete(t)
        return len(stale)

    def tick(self) -> None:
        """Housekeeping hook (Node.sweep): expiry scan + replay of
        retained dispatches the overload governor deferred (runs after
        the governor's own poll in the sweep, so the first healthy
        tick drains the parking lot)."""
        self.clean_expired()
        if self._deferred:
            gov = getattr(self.node, "overload_governor", None)
            if gov is None or not gov.retained_deferred:
                parked, self._deferred = self._deferred, []
                for clientinfo, topic, subopts in parked:
                    self._dispatch_retained(clientinfo, topic, subopts)

    def retained_count(self) -> int:
        return len(self.storage)

    def stats_fun(self, stats) -> None:
        stats.setstat("retained.count", len(self.storage), "retained.max")
