"""Retained-message store with wildcard read on subscribe.

Parity: apps/emqx_retainer — `message.publish` hook stores/clears retained
messages (emqx_retainer.erl on_message_publish), `session.subscribed` hook
dispatches matching retained messages to the new subscriber honoring the
MQTT5 Retain-Handling subopt (emqx_retainer.erl dispatch/2), expiry via the
v5 Message-Expiry-Interval property or the configured default
(emqx_retainer_mnesia.erl expiry scan), and max_retained_messages /
max_payload_size limits (emqx_retainer.erl:enabled checks).

The reference's mnesia index-read for wildcard subscribe becomes a host
nested-level trie over retained topic *names* (exact topics, so the walk is
filter-driven); the bulk device matcher is not involved because retained
reads are off the publish hot path.
"""

from __future__ import annotations

from typing import Iterator, Optional

from emqx_tpu.broker.hooks import HP_RETAINER
from emqx_tpu.broker.message import Message, now_ms
from emqx_tpu.utils import topic as T


class TopicIndex:
    """Nested-dict trie over exact topic names; lookup by wildcard filter."""

    _LEAF = object()

    def __init__(self):
        self._root: dict = {}
        self._count = 0

    def insert(self, topic: str) -> bool:
        node = self._root
        for w in T.tokens(topic):
            node = node.setdefault(w, {})
        if TopicIndex._LEAF in node:
            return False
        node[TopicIndex._LEAF] = topic
        self._count += 1
        return True

    def delete(self, topic: str) -> bool:
        path = []
        node = self._root
        for w in T.tokens(topic):
            nxt = node.get(w)
            if nxt is None:
                return False
            path.append((node, w))
            node = nxt
        if node.pop(TopicIndex._LEAF, None) is None:
            return False
        self._count -= 1
        for parent, w in reversed(path):
            if parent[w]:
                break
            del parent[w]
        return True

    def __len__(self) -> int:
        return self._count

    def match(self, filt: str) -> Iterator[str]:
        """All stored topic names matching the filter (MQTT semantics incl.
        the `$`-topic root-wildcard exclusion, emqx_topic.erl:66-69)."""
        fw = T.tokens(filt)
        exclude_dollar = fw[0] in (T.PLUS, T.HASH)

        def walk(node: dict, i: int, depth: int):
            if i == len(fw):
                t = node.get(TopicIndex._LEAF)
                if t is not None:
                    yield t
                return
            w = fw[i]
            if w == T.HASH:
                # '#' matches remaining levels including zero
                yield from collect(node, depth)
                return
            if w == T.PLUS:
                for k, child in node.items():
                    if k is TopicIndex._LEAF:
                        continue
                    if depth == 0 and exclude_dollar and k.startswith("$"):
                        continue
                    yield from walk(child, i + 1, depth + 1)
                return
            child = node.get(w)
            if child is not None:
                yield from walk(child, i + 1, depth + 1)

        def collect(node: dict, depth: int):
            for k, child in node.items():
                if k is TopicIndex._LEAF:
                    yield child
                    continue
                if depth == 0 and exclude_dollar and k.startswith("$"):
                    continue
                yield from collect(child, depth + 1)

        yield from walk(self._root, 0, 0)


class Retainer:
    def __init__(self, node, conf: Optional[dict] = None):
        self.node = node
        c = dict(node.config.get("retainer") or {})
        c.update(conf or {})
        self.enable = c.get("enable", True)
        self.max_retained = int(c.get("max_retained_messages", 0))
        self.max_payload = int(c.get("max_payload_size", 1024 * 1024))
        self.default_expiry = int(c.get("msg_expiry_interval", 0))  # s, 0=∞
        self._store: dict[str, tuple[Message, Optional[int]]] = {}
        self._index = TopicIndex()

    # ---- app lifecycle ----
    def load(self) -> "Retainer":
        self.node.hooks.add("message.publish", self.on_message_publish,
                            priority=HP_RETAINER, tag="retainer")
        self.node.hooks.add("session.subscribed", self.on_session_subscribed,
                            tag="retainer")
        self.node.stats.register_stats_fun(self.stats_fun)
        return self

    def unload(self) -> None:
        self.node.hooks.delete("message.publish", "retainer")
        self.node.hooks.delete("session.subscribed", "retainer")

    # ---- hooks ----
    def on_message_publish(self, msg: Message):
        if not self.enable or not msg.retain or msg.topic.startswith("$SYS/"):
            return ("ok", msg)
        if not msg.payload:
            self.delete(msg.topic)
            # empty retained publish clears the store and is NOT routed
            # further with retain semantics; the message itself still
            # propagates (spec: treated as normal publish w/o retention)
            return ("ok", msg)
        self._insert(msg)
        return ("ok", msg)

    def on_session_subscribed(self, clientinfo: dict, topic: str,
                              subopts: dict):
        if not self.enable:
            return
        rh = int(subopts.get("rh", 0))
        is_new = bool(subopts.get("is_new", True))
        if rh == 2 or (rh == 1 and not is_new):
            return
        if subopts.get("share"):
            return      # shared subscriptions get no retained replay (spec)
        chan = self.node.cm.lookup_channel(clientinfo.get("clientid", ""))
        if chan is None:
            return
        opts = {k: v for k, v in subopts.items() if k != "is_new"}
        for m in self.match(topic):
            d = m.copy()
            d.set_flag("retained", True)
            d.headers["subopts"] = opts
            chan.deliver(topic, d)

    # ---- store ----
    def _expire_at(self, msg: Message) -> Optional[int]:
        exp = msg.expiry_interval()
        if exp is None:
            exp = self.default_expiry or None
        return None if exp is None else msg.ts + exp * 1000

    def _insert(self, msg: Message) -> bool:
        t = msg.topic
        if len(msg.payload) > self.max_payload:
            self.node.metrics.inc("messages.retained.dropped")
            return False
        if (self.max_retained and t not in self._store
                and len(self._store) >= self.max_retained):
            self.node.metrics.inc("messages.retained.dropped")
            return False
        if t not in self._store:
            self._index.insert(t)
        self._store[t] = (msg.copy(), self._expire_at(msg))
        self.node.metrics.inc("messages.retained")
        return True

    def delete(self, topic: str) -> bool:
        if self._store.pop(topic, None) is None:
            return False
        self._index.delete(topic)
        return True

    def lookup(self, topic: str) -> Optional[Message]:
        ent = self._store.get(topic)
        if ent is None:
            return None
        msg, exp = ent
        if exp is not None and now_ms() > exp:
            self.delete(topic)
            return None
        return msg

    def match(self, filt: str) -> list[Message]:
        """All live retained messages matching a filter (wildcard read)."""
        out = []
        for t in list(self._index.match(filt)):
            m = self.lookup(t)
            if m is not None:
                out.append(m)
        return out

    def clean(self, filt: Optional[str] = None) -> int:
        """Purge retained messages (all, or those matching a filter) —
        emqx_retainer:clean/0, emqx_mgmt:clean_retained."""
        if filt is None:
            n = len(self._store)
            self._store.clear()
            self._index = TopicIndex()
            return n
        gone = list(self._index.match(filt))
        for t in gone:
            self.delete(t)
        return len(gone)

    def clean_expired(self) -> int:
        now = now_ms()
        stale = [t for t, (_, exp) in self._store.items()
                 if exp is not None and now > exp]
        for t in stale:
            self.delete(t)
        return len(stale)

    def tick(self) -> None:
        """Housekeeping hook (Node.sweep): expiry scan."""
        self.clean_expired()

    def retained_count(self) -> int:
        return len(self._store)

    def stats_fun(self, stats) -> None:
        stats.setstat("retained.count", len(self._store), "retained.max")
